"""Composer semantics tests (reference semmerge/compose.py behavior)."""
from semantic_merge_tpu.core.compose import compose_oplogs
from semantic_merge_tpu.core.ops import Op, Target


def mk(op_type, sym, params=None, ts="2024-01-01T00:00:00Z", op_id=None, addr=None):
    return Op.new(op_type, Target(symbolId=sym, addressId=addr),
                  params=params or {}, provenance={"timestamp": ts},
                  op_id=op_id)


def test_move_decl_rewrites_own_target_address():
    move = mk("moveDecl", "sym-1", {"newAddress": "new-addr"}, addr="old-addr")
    composed, conflicts = compose_oplogs([move], [])
    assert conflicts == []
    (op,) = composed
    assert op.target.addressId == "new-addr"
    assert op.params["newAddress"] == "new-addr"


def test_rename_from_a_move_from_b_compose_cleanly():
    # The flagship scenario (reference tests/e2e_rename_move_decl.sh):
    # A renames foo→bar in src/util.ts, B moves src/util.ts→lib/util.ts.
    rename = mk("renameSymbol", "sym-1",
                {"oldName": "foo", "newName": "bar", "file": "src/util.ts"},
                op_id="a" * 32)
    move = mk("moveDecl", "sym-1",
              {"oldFile": "src/util.ts", "newFile": "lib/util.ts",
               "oldAddress": "src/util.ts::foo::0", "newAddress": "lib/util.ts::foo::0"},
              op_id="b" * 32)
    composed, conflicts = compose_oplogs([rename], [move])
    assert conflicts == []
    assert [o.type for o in composed] == ["moveDecl", "renameSymbol"]
    # The move chain rewrote the rename's file to the moved location.
    rename_out = composed[1]
    assert rename_out.params["file"] == "lib/util.ts"
    assert rename_out.params["newFile"] == "lib/util.ts"
    assert rename_out.target.addressId == "lib/util.ts::foo::0"


def test_divergent_rename_head_vs_head_conflict():
    ra = mk("renameSymbol", "s", {"newName": "x"}, op_id="1" * 32)
    rb = mk("renameSymbol", "s", {"newName": "y"}, op_id="2" * 32)
    composed, conflicts = compose_oplogs([ra], [rb])
    assert composed == []
    assert len(conflicts) == 1
    conf = conflicts[0]
    assert conf.category == "DivergentRename"
    # A's op is always reported as opA regardless of which side sorted first.
    assert conf.opA["id"] == ra.id
    assert conf.opB["id"] == rb.id


def test_divergent_rename_opA_is_side_A_even_when_B_sorts_first():
    ra = mk("renameSymbol", "s", {"newName": "x"}, op_id="9" * 32)
    rb = mk("renameSymbol", "s", {"newName": "y"}, op_id="1" * 32)
    _, conflicts = compose_oplogs([ra], [rb])
    assert conflicts[0].opA["id"] == ra.id
    assert conflicts[0].suggestions[0]["label"] == "Rename to x"


def test_same_rename_both_sides_is_not_a_conflict():
    ra = mk("renameSymbol", "s", {"newName": "x"}, op_id="1" * 32)
    rb = mk("renameSymbol", "s", {"newName": "x"}, op_id="2" * 32)
    composed, conflicts = compose_oplogs([ra], [rb])
    assert conflicts == []
    assert len(composed) == 2


def test_interleaved_op_masks_divergent_rename_reference_quirk():
    # Conflict detection is head-vs-head only: if an unrelated B op sorts
    # *between* the two divergent renames, A's rename is consumed while
    # B's head is still the unrelated op, and B's rename is consumed after
    # A is exhausted — the conflict is masked. Reference behavior
    # (semmerge/compose.py:60-70), kept bit-for-bit in parity mode.
    ra = mk("renameSymbol", "s", {"newName": "x"}, op_id="1" * 32)
    other_b = mk("renameSymbol", "unrelated", {"newName": "n"}, op_id="2" * 32)
    rb = mk("renameSymbol", "s", {"newName": "y"}, op_id="3" * 32)
    composed, conflicts = compose_oplogs([ra], [other_b, rb])
    assert conflicts == []  # masked!
    assert len(composed) == 3


def test_id_never_decides_cross_stream_order():
    # Cross-stream ties compare (precedence, timestamp) only, A first —
    # op ids are hashes here, and letting them interleave the streams
    # would make merge results a coin flip (see core/compose.py
    # docstring). B's smaller id must NOT promote early_b ahead of ra:
    # ra is consumed against head early_b, so the divergent rename on
    # "s" is masked — the same masking the reference exhibits when left
    # ops carry earlier wall-clock timestamps than right ops.
    ra = mk("renameSymbol", "s", {"newName": "x"}, op_id="2" * 32)
    early_b = mk("renameSymbol", "unrelated", {"newName": "n"}, op_id="1" * 32)
    rb = mk("renameSymbol", "s", {"newName": "y"}, op_id="3" * 32)
    composed, conflicts = compose_oplogs([ra], [early_b, rb])
    assert conflicts == []
    assert len(composed) == 3


def test_adjacent_divergent_rename_detected_with_earlier_timestamped_b_op():
    # With a genuinely earlier timestamp, B's unrelated op is consumed
    # first; then the heads are ra vs rb simultaneously → conflict.
    ra = mk("renameSymbol", "s", {"newName": "x"}, op_id="2" * 32)
    early_b = mk("renameSymbol", "unrelated", {"newName": "n"},
                 ts="2023-01-01T00:00:00Z", op_id="1" * 32)
    rb = mk("renameSymbol", "s", {"newName": "y"}, op_id="3" * 32)
    composed, conflicts = compose_oplogs([ra], [early_b, rb])
    assert len(conflicts) == 1
    assert len(composed) == 1


def test_rename_context_attached_to_other_ops():
    rename = mk("renameSymbol", "s", {"newName": "bar"}, op_id="1" * 32)
    edit = mk("editStmtBlock", "s", {}, op_id="2" * 32)
    composed, _ = compose_oplogs([rename, edit], [])
    edit_out = [o for o in composed if o.type == "editStmtBlock"][0]
    assert edit_out.params["renameContext"] == "bar"
    rename_out = [o for o in composed if o.type == "renameSymbol"][0]
    assert "renameContext" not in rename_out.params


def test_move_chain_merges_address_and_file_separately():
    m1 = mk("moveDecl", "s", {"newAddress": "addr1"}, op_id="1" * 32)
    m2 = mk("moveDecl", "s", {"newFile": "f2.ts"}, op_id="2" * 32)
    composed, _ = compose_oplogs([m1, m2], [])
    last = composed[-1]
    # Second move inherits the first move's address through the chain.
    assert last.params["newAddress"] == "addr1"
    assert last.params["newFile"] == "f2.ts"


def test_ties_prefer_side_a():
    a = mk("addDecl", "s1", {"file": "a.ts"}, op_id="5" * 32)
    b = mk("addDecl", "s2", {"file": "b.ts"}, op_id="5" * 32)
    composed, _ = compose_oplogs([a], [b])
    assert composed[0].target.symbolId == "s1"


def test_sort_by_precedence_then_timestamp_then_id():
    late_move = mk("moveDecl", "m", {"newAddress": "x"}, ts="2025-01-01T00:00:00Z")
    early_add = mk("addDecl", "a", {"file": "f.ts"}, ts="2020-01-01T00:00:00Z")
    composed, _ = compose_oplogs([early_add, late_move], [])
    # moveDecl (prec 10) composes before addDecl (prec 30) despite timestamps.
    assert [o.type for o in composed] == ["moveDecl", "addDecl"]


def test_input_ops_not_mutated():
    move = mk("moveDecl", "s", {"newAddress": "new"}, addr="old")
    compose_oplogs([move], [])
    assert move.target.addressId == "old"


class TestCrossStreamOrdering:
    """Cross-stream ties order A before B — never by hash id.

    Regression: side A's rename also emits a spurious moveDecl (the
    addressId embeds the name), which collides with side B's genuine
    file move in the move chain. Whichever materializes last wins, so
    the pick must be deterministic and reference-shaped (left log
    lifted first → B's move lands last) for EVERY seed.
    """

    def test_rename_plus_move_composes_to_moved_file_any_seed(self):
        from semantic_merge_tpu.backends.ts_host import HostTSBackend
        from semantic_merge_tpu.frontend.snapshot import Snapshot

        base = Snapshot(files=[{"path": "src/util.ts",
                                "content": "export function foo(n: number): number { return n; }\n"}])
        left = Snapshot(files=[{"path": "src/util.ts",
                                "content": "export function bar(n: number): number { return n; }\n"}])
        right = Snapshot(files=[{"path": "lib/util.ts",
                                 "content": "export function foo(n: number): number { return n; }\n"}])
        host = HostTSBackend()
        for seed in ("a", "b", "xyz", "0", "deadbeef"):
            res = host.build_and_diff(base, left, right, seed=seed, timestamp="t")
            composed, conflicts = compose_oplogs(res.op_log_left, res.op_log_right)
            assert conflicts == []
            renames = [o for o in composed if o.type == "renameSymbol"]
            assert len(renames) == 1, seed
            assert renames[0].params["file"] == "lib/util.ts", seed
