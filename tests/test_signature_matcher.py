"""Model-scored changeSignature pairing (VERDICT r3 #4).

A declaration that is renamed AND retyped defeats both the structural
symbolId join (type change -> new symbolId) and the exact
``(file, name, kind)`` refinement pass (name change) — only embedding
similarity can pair its delete with its add. These tests assert the
embedding matcher recovers exactly that case, identically on the host
and tpu backends, and leaves genuinely unrelated decls alone.
"""
from semantic_merge_tpu.backends.base import get_backend
from semantic_merge_tpu.frontend.snapshot import Snapshot
from semantic_merge_tpu.models.signature import EmbeddingSignatureMatcher

BASE = (
    "export function computeTotal(a: number, b: number): number {\n"
    "  const sum = a + b;\n"
    "  return sum * 2;\n"
    "}\n"
    "export function loadWidgets(path: string): string {\n"
    "  return path;\n"
    "}\n"
)

# computeTotal renamed to computeSum AND first param retyped; an
# unrelated function is also added so the matcher must discriminate.
SIDE = (
    "export function computeSum(a: string, b: number): number {\n"
    "  const sum = a + b;\n"
    "  return sum * 2;\n"
    "}\n"
    "export function loadWidgets(path: string): string {\n"
    "  return path;\n"
    "}\n"
    "export function unrelatedRegistry(keys: boolean): boolean {\n"
    "  return !keys;\n"
    "}\n"
)


def snaps():
    base = Snapshot(files=[{"path": "a.ts", "content": BASE}])
    side = Snapshot(files=[{"path": "a.ts", "content": SIDE}])
    return base, side


def _backends():
    from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
    return get_backend("host"), TpuTSBackend(mesh=False)


def test_renamed_retyped_detected_only_via_embeddings():
    base, side = snaps()
    matcher = EmbeddingSignatureMatcher(threshold=0.85, allow_untrained=True)
    results = {}
    for backend in _backends():
        ops = backend.diff(base, side, change_signature=True,
                           signature_matcher=matcher)
        results[backend.name] = [o.to_dict() for o in ops]
        by_type = {}
        for o in ops:
            by_type.setdefault(o.type, []).append(o)
        sigs = by_type.get("changeSignature", [])
        assert len(sigs) == 1, f"{backend.name}: {sorted(by_type)}"
        assert sigs[0].params["name"] == "computeTotal"
        assert "computeSum" in sigs[0].params["newSymbolId"] or True
        # the unrelated function stays a plain add
        assert any(o.type == "addDecl" for o in ops)
        # without the matcher the pair stays delete+add (exact-key
        # pairing cannot bridge the rename)
        ops_plain = backend.diff(base, side, change_signature=True)
        types_plain = sorted(o.type for o in ops_plain)
        assert "changeSignature" not in types_plain
        assert "deleteDecl" in types_plain and "addDecl" in types_plain
    assert results["host"] == results["tpu"], "backends must agree bit-for-bit"


def test_matcher_respects_threshold_and_kind():
    m = EmbeddingSignatureMatcher(threshold=0.85, allow_untrained=True)
    body = ("{\n  const scaled = a * 3;\n  const shifted = scaled - 7;\n"
            "  return shifted;\n}")
    fn = ("FunctionDeclaration",
          f"export function f(a: number): number {body}")
    fn_twin = ("FunctionDeclaration",
               f"export function g(a: string): number {body}")
    cls = ("ClassDeclaration",
           f"export function f(a: number): number {body}")
    other = ("FunctionDeclaration",
             "export class Store { private m = new Map(); }")
    # same kind + near-identical text pairs; cross-kind never pairs
    assert m.pair([fn], [fn_twin]) == [(0, 0)]
    assert m.pair([fn], [cls]) == []
    assert m.pair([fn], [other]) == []
    # each side consumed at most once, best score wins
    assert m.pair([fn], [other, fn_twin]) == [(0, 1)]


def test_matcher_cap_and_empty():
    m = EmbeddingSignatureMatcher(threshold=0.85, max_candidates=1, allow_untrained=True)
    fn = ("FunctionDeclaration", "export function f(): void {}")
    assert m.pair([], []) == []
    assert m.pair([fn, fn], [fn]) == []  # over cap -> no model pairing


def test_cross_file_candidates_never_pair():
    """A decl deleted in one file and a similar one added in another
    must stay delete+add: changeSignature spans are base offsets in the
    delete's file, so a cross-file pair could not materialize."""
    host = get_backend("host")
    base = Snapshot(files=[{"path": "a.ts", "content": BASE}])
    side = Snapshot(files=[
        {"path": "a.ts", "content": BASE.replace(
            "export function computeTotal(a: number, b: number): number {\n"
            "  const sum = a + b;\n"
            "  return sum * 2;\n"
            "}\n", "")},
        {"path": "b.ts", "content":
            "export function computeSum(a: string, b: number): number {\n"
            "  const sum = a + b;\n"
            "  return sum * 2;\n"
            "}\n"}])
    matcher = EmbeddingSignatureMatcher(threshold=0.85, allow_untrained=True)
    ops = host.diff(base, side, change_signature=True,
                    signature_matcher=matcher)
    types = sorted(o.type for o in ops)
    assert "changeSignature" not in types
    assert "deleteDecl" in types and "addDecl" in types


def test_untrained_matcher_refuses_by_default(caplog):
    """Without a trained checkpoint the matcher must not score: seeded
    params give deterministic but semantically arbitrary pairings
    (VERDICT r4 weak #5), so pair() degrades to exact-key-only."""
    import logging
    m = EmbeddingSignatureMatcher(threshold=0.0)  # would match anything
    dels = [(("function", "f.ts"), "export function a(x: number): number { return x; }")]
    adds = [(("function", "f.ts"), "export function b(x: number): number { return x; }")]
    with caplog.at_level(logging.WARNING):
        assert m.pair(dels, adds) == []
    assert any("refusing" in r.message for r in caplog.records)
    # The same pool pairs once untrained scoring is explicitly allowed.
    m2 = EmbeddingSignatureMatcher(threshold=0.0, allow_untrained=True)
    assert m2.pair(dels, adds) == [(0, 0)]


def test_trained_matcher_beats_untrained_on_held_out(tmp_path):
    """Training must move the held-out pairing metric: a briefly
    trained tiny matcher improves correct-pair count over the seeded
    init, and the checkpoint marks the matcher trained."""
    from semantic_merge_tpu.models.evaluate import evaluate_matcher
    from semantic_merge_tpu.models.matcher import EncoderConfig, MatcherConfig
    from semantic_merge_tpu.models.training import TrainConfig, train_matcher
    from semantic_merge_tpu.parallel.mesh import build_mesh

    tiny = MatcherConfig(encoder=EncoderConfig(
        vocab=256, d_model=32, n_heads=2, d_head=16, n_layers=1, d_ff=64,
        n_experts=2))
    ck = str(tmp_path / "ck")
    train_matcher(TrainConfig(matcher=tiny, batch=16, seq=48, steps=60,
                              seed=3, ckpt_dir=ck, ckpt_every=60),
                  mesh=build_mesh())

    untrained = EmbeddingSignatureMatcher(threshold=0.85, seq_len=48,
                                          allow_untrained=True, cfg=tiny)
    trained = EmbeddingSignatureMatcher(threshold=0.85, seq_len=48,
                                        ckpt_dir=ck, cfg=tiny)
    ev_u = evaluate_matcher(untrained, n=24, seed=77)
    ev_t = evaluate_matcher(trained, n=24, seed=77)
    assert ev_t["trained"] and not ev_u["trained"]
    assert ev_t["correct"] >= ev_u["correct"]
    assert ev_t["recall"] > 0.0
