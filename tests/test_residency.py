"""Warm snapshot residency (``service/residency.py``).

The residency cache hands repeat merges of the same base tree the
already-encoded decl tensor (skipping scan+encode+h2d); these tests pin
the invalidation matrix that keeps the shortcut byte-safe: a changed
tree oid misses, a GC'd repository evicts (``stale-tree``), an epoch
bump — the fleet-failover hook — evicts (``stale-epoch``), an interner
replacement evicts (``stale-interner``), and both the byte budget and
the daemon's RSS hard watermark evict. In EVERY case the merge output
stays byte-identical to a cold run.
"""
from __future__ import annotations

import shutil
import subprocess

import pytest

import bench
from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
from semantic_merge_tpu.core.ops import OpLog
from semantic_merge_tpu.frontend.snapshot import annotate_residency
from semantic_merge_tpu.obs import metrics as obs_metrics
from semantic_merge_tpu.service import residency

TS = "2026-01-01T00:00:00Z"


@pytest.fixture(autouse=True)
def _residency_on(monkeypatch):
    monkeypatch.setenv("SEMMERGE_RESIDENCY_CACHE", "on")
    monkeypatch.setenv("SEMMERGE_MESH", "off")
    residency.cache().reset()
    yield
    residency.cache().reset()


def outcome_total(outcome: str) -> float:
    return obs_metrics.REGISTRY.counter(
        "snapshot_residency_hits_total").value(outcome=outcome)


def eviction_total(reason: str) -> float:
    return obs_metrics.REGISTRY.counter(
        "snapshot_residency_evictions_total").value(reason=reason)


def merge_bytes(backend, snaps, *, annotate=None):
    """One fused merge; returns the byte-comparable payload triple.
    ``annotate=(root, oid)`` keys the base into the residency cache the
    way the CLI does (fresh snapshot objects each call — the residency
    hit must not depend on object identity)."""
    base, left, right = snaps
    if annotate is not None:
        annotate_residency(base, annotate[0], annotate[1])
    res, composed, conflicts = backend.merge(
        base, left, right, base_rev="bench", seed="bench", timestamp=TS)
    return (OpLog(res.op_log_left).to_json_bytes(),
            OpLog(res.op_log_right).to_json_bytes(),
            [op.to_dict() for op in composed],
            [c.to_dict() for c in conflicts])


def fresh_snaps(divergent=True, n=30):
    return bench.synth_repo(n, 4, divergent=divergent)


def test_repeat_base_hits_and_stays_byte_identical():
    backend = TpuTSBackend(mesh=False)
    cold = merge_bytes(backend, fresh_snaps(), annotate=("", "oid-a"))
    assert cold[3], "divergent workload must produce conflicts"
    before = outcome_total("hit")
    warm = merge_bytes(backend, fresh_snaps(), annotate=("", "oid-a"))
    assert warm == cold
    assert outcome_total("hit") == before + 1
    stats = residency.cache().stats()
    assert stats["entries"] == 1 and stats["bytes"] > 0


def test_tree_oid_change_misses_and_stays_byte_identical():
    backend = TpuTSBackend(mesh=False)
    merge_bytes(backend, fresh_snaps(), annotate=("", "oid-a"))
    # Same repo key, new tree oid (base advanced): must MISS — never
    # serve the old tree's encoding — and produce identical bytes to a
    # cold merge of the same content.
    unannotated = merge_bytes(TpuTSBackend(mesh=False), fresh_snaps())
    before_hit, before_miss = outcome_total("hit"), outcome_total("miss")
    got = merge_bytes(backend, fresh_snaps(), annotate=("", "oid-b"))
    assert got == unannotated
    assert outcome_total("hit") == before_hit
    assert outcome_total("miss") == before_miss + 1
    assert residency.cache().stats()["entries"] == 2


def _git(args, cwd):
    subprocess.run(["git", *args], cwd=cwd, check=True,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def test_repo_gc_mid_residency_evicts_stale_tree(tmp_path):
    repo = tmp_path / "repo"
    repo.mkdir()
    _git(["init", "-q"], repo)
    (repo / "a.ts").write_text("export function a(): number "
                               "{ return 1; }\n")
    _git(["add", "."], repo)
    _git(["-c", "user.email=t@t", "-c", "user.name=t",
          "commit", "-q", "-m", "seed"], repo)
    oid = subprocess.run(
        ["git", "rev-parse", "HEAD^{tree}"], cwd=repo, check=True,
        stdout=subprocess.PIPE, text=True).stdout.strip()

    backend = TpuTSBackend(mesh=False)
    key = (str(repo), oid)
    cold = merge_bytes(backend, fresh_snaps(), annotate=key)
    warm = merge_bytes(backend, fresh_snaps(), annotate=key)
    assert warm == cold

    # GC the repository out from under the resident entry: the tree
    # object is gone, so the next lookup must evict (stale-tree) and
    # re-encode — byte-identically.
    shutil.rmtree(repo / ".git")
    _git(["init", "-q"], repo)  # a repo with no such tree
    before = outcome_total("stale-tree")
    regone = merge_bytes(backend, fresh_snaps(), annotate=key)
    assert regone == cold
    assert outcome_total("stale-tree") == before + 1
    assert eviction_total("stale") >= 1


def test_rss_hard_watermark_clear_evicts_and_reencodes():
    backend = TpuTSBackend(mesh=False)
    cold = merge_bytes(backend, fresh_snaps(), annotate=("", "oid-a"))
    assert residency.cache().stats()["entries"] == 1
    # The daemon's pressure monitor makes exactly this call at the RSS
    # hard watermark (service/daemon.py _pressure_monitor).
    before = eviction_total("rss-hard")
    dropped = residency.cache().clear(reason="rss-hard")
    assert dropped == 1
    assert eviction_total("rss-hard") == before + 1
    assert residency.cache().stats()["entries"] == 0
    assert residency.cache().stats()["bytes"] == 0
    regone = merge_bytes(backend, fresh_snaps(), annotate=("", "oid-a"))
    assert regone == cold


def test_fleet_failover_epoch_bump_evicts_stale_epoch():
    backend = TpuTSBackend(mesh=False)
    cold = merge_bytes(backend, fresh_snaps(), annotate=("", "oid-a"))
    # The fleet router makes exactly this call when a membership change
    # moves keys (fleet/router.py _set_ring): a rehashed member must
    # not trust any resident handle from the previous routing epoch.
    residency.cache().bump_epoch()
    before = outcome_total("stale-epoch")
    regone = merge_bytes(backend, fresh_snaps(), annotate=("", "oid-a"))
    assert regone == cold
    assert outcome_total("stale-epoch") == before + 1
    # The re-encode repopulated under the new epoch: next lookup hits.
    before_hit = outcome_total("hit")
    warm = merge_bytes(backend, fresh_snaps(), annotate=("", "oid-a"))
    assert warm == cold
    assert outcome_total("hit") == before_hit + 1


def test_fresh_backend_shares_interner_and_hits():
    # The daemon builds a fresh backend per request (get_backend is not
    # memoized); under residency every backend must adopt the
    # process-shared interner or no daemon request could ever hit.
    backend = TpuTSBackend(mesh=False)
    cold = merge_bytes(backend, fresh_snaps(), annotate=("", "oid-a"))
    other = TpuTSBackend(mesh=False)
    assert other._interner is backend._interner
    before = outcome_total("hit")
    got = merge_bytes(other, fresh_snaps(), annotate=("", "oid-a"))
    assert got == cold
    assert outcome_total("hit") == before + 1


def test_growth_guard_swap_evicts_stale_interner():
    # The growth guard is the one remaining interner-replacement path:
    # it must swap the process-shared instance (so later backends adopt
    # the replacement), and entries encoded under the dead token must
    # never be served — the next lookup evicts (stale-interner) and
    # re-encodes byte-identically.
    from semantic_merge_tpu.backends import ts_tpu
    from semantic_merge_tpu.core.encode import Interner
    backend = TpuTSBackend(mesh=False)
    cold = merge_bytes(backend, fresh_snaps(), annotate=("", "oid-a"))
    old = backend._interner
    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(Interner, "__len__", lambda self: 4_000_001)
        backend._maybe_reset_interner()
    assert backend._interner is not old
    assert backend._interner.shared
    assert ts_tpu._SHARED_INTERNER is backend._interner
    assert TpuTSBackend(mesh=False)._interner is backend._interner
    before = outcome_total("stale-interner")
    got = merge_bytes(backend, fresh_snaps(), annotate=("", "oid-a"))
    assert got == cold
    assert outcome_total("stale-interner") == before + 1


def test_byte_budget_evicts_lru(monkeypatch):
    # A ~zero budget admits nothing; a small budget evicts the oldest
    # entry when a second is admitted.
    backend = TpuTSBackend(mesh=False)
    monkeypatch.setenv("SEMMERGE_RESIDENCY_CACHE_MB", "0.00001")
    merge_bytes(backend, fresh_snaps(), annotate=("", "oid-a"))
    assert residency.cache().stats()["entries"] == 0
    monkeypatch.setenv("SEMMERGE_RESIDENCY_CACHE_MB", "0.06")
    merge_bytes(backend, fresh_snaps(n=60), annotate=("", "oid-a"))
    assert residency.cache().stats()["entries"] == 1
    before = eviction_total("lru")
    merge_bytes(backend, fresh_snaps(n=60), annotate=("", "oid-b"))
    stats = residency.cache().stats()
    assert stats["entries"] == 1, "budget admits one ~52K entry, not two"
    assert eviction_total("lru") > before


def test_scope_participates_in_key():
    backend = TpuTSBackend(mesh=False)
    base, left, right = fresh_snaps()
    annotate_residency(base, "", "oid-a", scope=["src/a.ts"])
    backend.merge(base, left, right, base_rev="bench", seed="bench",
                  timestamp=TS)
    base2, left2, right2 = fresh_snaps()
    annotate_residency(base2, "", "oid-a", scope=["src/b.ts"])
    before = outcome_total("hit")
    backend.merge(base2, left2, right2, base_rev="bench", seed="bench",
                  timestamp=TS)
    # Different scope, same tree: a restricted encoding must not be
    # served for a differently-restricted request.
    assert outcome_total("hit") == before
    assert residency.cache().stats()["entries"] == 2


def test_posture_off_bypasses_cache(monkeypatch):
    monkeypatch.setenv("SEMMERGE_RESIDENCY_CACHE", "off")
    backend = TpuTSBackend(mesh=False)
    merge_bytes(backend, fresh_snaps(), annotate=("", "oid-a"))
    merge_bytes(backend, fresh_snaps(), annotate=("", "oid-a"))
    assert residency.cache().stats()["entries"] == 0


def test_daemon_status_reports_residency():
    from semantic_merge_tpu.service import daemon as daemon_mod
    d = daemon_mod.Daemon.__new__(daemon_mod.Daemon)
    # Only status() is exercised; give it the minimal state it reads.
    import threading
    import time as _time
    d._state_lock = threading.Lock()
    d._in_flight, d._served = 0, 0
    d._t0 = _time.time()
    d._queue = __import__("queue").Queue()
    d._socket_path = "-"
    d._workers_n = 0
    d._draining = False
    d._fleet_member = False
    d._joined_as = d._join_addr = d._advertise = None
    d._capacity, d._join_epoch = 1, 0
    d._repo_locks = {}
    d._telemetry = None
    d._slo = None
    d._pressure = 0
    d._soft_mb = d._hard_mb = 0.0
    d._exec_ewma = 0.0
    d._idem = {}
    d._projected_wait = lambda: 0.0
    from semantic_merge_tpu.obs import agg as obs_agg
    from semantic_merge_tpu.obs import anomaly as obs_anomaly
    from semantic_merge_tpu.obs import sampling as obs_sampling
    d._window = obs_agg.WindowAggregator()
    d._sampler = obs_sampling.SamplingPolicy()
    d._anomaly = obs_anomaly.AnomalyTriage()
    d._trace_store = None
    status = d.status()
    res = status["residency"]
    assert set(res) >= {"enabled", "entries", "bytes", "budget_bytes",
                        "hit_rate", "evictions"}
