"""Differential fuzzing: Python scanner vs native C++ scanner.

The native frontend claims bit-parity with the Python oracle; the
golden corpus pins 20 hand-picked cases. This fuzzer generates
hundreds of randomized snapshots weighted toward the scanner's tricky
paths — type-annotation shapes (unions, tuples, object literals,
generics, qualified names, arrays-of-parenthesized-unions), expression
positions, nesting, modifiers, multi-decl var statements, ``.tsx`` —
and requires identical decl records from both implementations.
"""
import random

import pytest

from semantic_merge_tpu.frontend import native
from semantic_merge_tpu.frontend.scanner import scan_snapshot_py

TYPES = ["number", "string", "boolean", "void", "any", "unknown",
         "Foo", "ns.Thing", "JSX.Element", "string[]", "number[][]",
         "(string | number)", "string | boolean", "A & B",
         "[string, number]", "[Foo, boolean,]", "{ x: number; y: string }",
         "Map<string, number>", "Promise<void>", "(a: number) => string"]

NAME_POOL = ["alpha", "beta", "gamma", "delta", "Foo", "runIt", "fetchAll",
             "Widget", "Panel", "handler", "m1", "m2"]


def gen_decl(rng: random.Random, i: int) -> str:
    roll = rng.random()
    name = f"{rng.choice(NAME_POOL)}{i}"
    if roll < 0.45:
        n_params = rng.randrange(0, 4)
        params = ", ".join(
            f"p{k}{'?' if rng.random() < 0.2 else ''}: {rng.choice(TYPES)}"
            for k in range(n_params))
        ret = f": {rng.choice(TYPES)}" if rng.random() < 0.8 else ""
        mods = rng.choice(["export ", "", "export async ", "declare "])
        body = "{ return undefined as any; }" if "declare" not in mods else ";"
        return f"{mods}function {name}({params}){ret} {body}"
    if roll < 0.6:
        members = " ".join(f"m{k}(): void {{}}" for k in range(rng.randrange(0, 3)))
        mods = rng.choice(["export ", "", "export abstract "])
        return f"{mods}class {name} {{ {members} }}"
    if roll < 0.7:
        fields = "; ".join(f"f{k}: {rng.choice(TYPES)}"
                           for k in range(rng.randrange(1, 3)))
        return f"export interface {name} {{ {fields} }}"
    if roll < 0.78:
        variants = ", ".join(f"V{k}" for k in range(rng.randrange(1, 4)))
        return f"export enum {name} {{ {variants} }}"
    if roll < 0.9:
        n_vars = rng.randrange(1, 3)
        decls = ", ".join(
            f"v{k}{i}" + (f": {rng.choice(TYPES)}" if rng.random() < 0.5 else "")
            + (f" = {rng.randrange(9)}" if rng.random() < 0.7 else "")
            for k in range(n_vars))
        return f"{rng.choice(['const', 'let', 'var'])} {decls};"
    # Expression positions that must NOT index.
    return rng.choice([
        f"export const {name} = function inner(a: number): number {{ return a; }};",
        f"export const {name} = (b: string): string => b;",
        f"const K{i} = class Named{i} {{}};",
        f"export function {name}(): void {{\n"
        f"  for (let i = 0; i < 2; i++) {{}}\n"
        f"  function nested(q: {rng.choice(TYPES)}): void {{}}\n"
        f"}}",
    ])


def node_tuple(n):
    return (n.symbolId, n.addressId, n.kind, n.name, n.file, n.pos, n.end,
            n.signature)


@pytest.mark.parametrize("seed", range(8))
def test_differential_python_vs_native(seed):
    if native.try_scan_snapshot([{"path": "probe.ts",
                                  "content": "export function p(): void {}\n"}]) is None:
        pytest.skip("native scanner unavailable")
    rng = random.Random(1000 + seed)
    files = []
    for f in range(rng.randrange(1, 6)):
        lines = [gen_decl(rng, f * 10 + d) for d in range(rng.randrange(1, 6))]
        ext = ".tsx" if rng.random() < 0.2 else ".ts"
        files.append({"path": f"src/f{f}{ext}", "content": "\n".join(lines) + "\n"})
    py_nodes = scan_snapshot_py(files)
    native_nodes = native.try_scan_snapshot(files)
    assert native_nodes is not None
    assert [node_tuple(n) for n in native_nodes] == \
        [node_tuple(n) for n in py_nodes], \
        f"seed {seed}: native scanner diverged from Python oracle"
