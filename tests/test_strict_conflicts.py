"""Strict conflict detection ([CFR-002] categories).

The reference requires six categories (reference ``requirements.md:93-99``)
but implements only head-vs-head DivergentRename; strict mode implements
every category expressible over the extracted op vocabulary and is immune
to the interleaving that masks the reference's detection.
"""
from semantic_merge_tpu.core.compose import compose_oplogs
from semantic_merge_tpu.core.ops import Op, Target
from semantic_merge_tpu.core.strict_conflicts import detect_conflicts_strict

TS = "2026-01-01T00:00:00Z"


def _op(op_type, sym, params, op_id, ts=TS):
    return Op.new(op_type, Target(symbolId=sym, addressId=f"f.ts::{sym}::0"),
                  params=params, guards={}, effects={},
                  provenance={"rev": "base", "timestamp": ts}, op_id=op_id)


def test_divergent_rename_detected_despite_interleaving():
    # Unrelated ops between the two renames mask the reference's
    # head-vs-head walk; the strict join still finds the conflict.
    a = [_op("moveDecl", "other1", {"oldAddress": "x", "newAddress": "y",
                                    "oldFile": "x.ts", "newFile": "y.ts"}, "a1"),
         _op("renameSymbol", "sym", {"oldName": "f", "newName": "g", "file": "f.ts"}, "a2")]
    b = [_op("renameSymbol", "sym", {"oldName": "f", "newName": "h", "file": "f.ts"}, "b1")]
    kept_a, kept_b, conflicts = detect_conflicts_strict(a, b)
    assert [c.category for c in conflicts] == ["DivergentRename"]
    assert len(kept_a) == 1 and kept_a[0].id == "a1"
    assert kept_b == []
    # The residual streams compose cleanly.
    composed, walk_conflicts = compose_oplogs(kept_a, kept_b)
    assert walk_conflicts == [] and len(composed) == 1


def test_divergent_move():
    a = [_op("moveDecl", "sym", {"oldAddress": "f.ts::s::0", "newAddress": "a.ts::s::0",
                                 "oldFile": "f.ts", "newFile": "a.ts"}, "a1")]
    b = [_op("moveDecl", "sym", {"oldAddress": "f.ts::s::0", "newAddress": "b.ts::s::0",
                                 "oldFile": "f.ts", "newFile": "b.ts"}, "b1")]
    _, _, conflicts = detect_conflicts_strict(a, b)
    assert [c.category for c in conflicts] == ["DivergentMove"]
    assert conflicts[0].addressIds == {"A": "a.ts::s::0", "B": "b.ts::s::0",
                                       "base": "f.ts::s::0"}


def test_same_destination_move_is_not_a_conflict():
    a = [_op("moveDecl", "sym", {"oldAddress": "o", "newAddress": "n",
                                 "oldFile": "f.ts", "newFile": "g.ts"}, "a1")]
    b = [_op("moveDecl", "sym", {"oldAddress": "o", "newAddress": "n",
                                 "oldFile": "f.ts", "newFile": "g.ts"}, "b1")]
    kept_a, kept_b, conflicts = detect_conflicts_strict(a, b)
    assert conflicts == [] and len(kept_a) == 1 and len(kept_b) == 1


def test_incompatible_signature_change():
    a = [_op("changeSignature", "sym", {"oldSignature": "fn(int)->int",
                                        "newSignature": "fn(long)->int"}, "a1")]
    b = [_op("changeSignature", "sym", {"oldSignature": "fn(int)->int",
                                        "newSignature": "fn(str)->int"}, "b1")]
    _, _, conflicts = detect_conflicts_strict(a, b)
    assert [c.category for c in conflicts] == ["IncompatibleSignatureChange"]


def test_delete_vs_edit_both_directions():
    del_a = [_op("deleteDecl", "sym", {"file": "f.ts"}, "a1")]
    ren_b = [_op("renameSymbol", "sym", {"oldName": "f", "newName": "g",
                                         "file": "f.ts"}, "b1")]
    kept_a, kept_b, conflicts = detect_conflicts_strict(del_a, ren_b)
    assert [c.category for c in conflicts] == ["DeleteVsEdit"]
    assert kept_a == [] and kept_b == []
    assert {s["id"] for s in conflicts[0].suggestions} == {"keepDelete", "keepEdit"}

    kept_a, kept_b, conflicts = detect_conflicts_strict(ren_b, del_a)
    assert [c.category for c in conflicts] == ["DeleteVsEdit"]
    assert kept_a == [] and kept_b == []


def test_unrelated_symbols_untouched():
    a = [_op("renameSymbol", "s1", {"oldName": "a", "newName": "b", "file": "f.ts"}, "a1")]
    b = [_op("deleteDecl", "s2", {"file": "g.ts"}, "b1")]
    kept_a, kept_b, conflicts = detect_conflicts_strict(a, b)
    assert conflicts == [] and len(kept_a) == 1 and len(kept_b) == 1


def test_cli_strict_mode_end_to_end(tmp_path, monkeypatch):
    """--strict-conflicts surfaces DeleteVsEdit, which parity mode merges
    silently (the delete wins and the rename dangles)."""
    import json
    import subprocess

    def git(*args):
        subprocess.run(["git", *args], cwd=tmp_path, check=True,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    (tmp_path / "a.ts").write_text(
        "export function foo(n: number): number { return n; }\n")
    git("init", "-q", "-b", "main")
    git("config", "user.email", "t@e")
    git("config", "user.name", "t")
    git("add", "-A")
    git("commit", "-qm", "base")
    git("branch", "basebr")
    git("checkout", "-qb", "ba")
    (tmp_path / "a.ts").write_text(
        "export function bar(n: number): number { return n; }\n")
    git("commit", "-qam", "rename")
    git("checkout", "-q", "main")
    git("checkout", "-qb", "bb")
    (tmp_path / "a.ts").write_text("export const unrelated = 1;\n")
    git("commit", "-qam", "delete")
    git("checkout", "-q", "main")

    monkeypatch.chdir(tmp_path)
    from semantic_merge_tpu.cli import main
    rc = main(["semmerge", "basebr", "ba", "bb", "--backend", "host",
               "--strict-conflicts"])
    assert rc == 1
    payload = json.loads((tmp_path / ".semmerge-conflicts.json").read_text())
    assert any(c["category"] == "DeleteVsEdit" for c in payload)


def test_config_rejects_bad_conflict_mode(tmp_path, monkeypatch):
    (tmp_path / ".semmerge.toml").write_text('[engine]\nconflict_mode = "Strict"\n')
    monkeypatch.chdir(tmp_path)
    import pytest as _pytest
    from semantic_merge_tpu.config import load_config
    with _pytest.raises(ValueError, match="conflict_mode"):
        load_config()


def test_concurrent_stmt_edit_conflict():
    a = [_op("editStmtBlock", "sym", {"file": "f.ts", "oldBodyHash": "h0",
                                      "newBodyHash": "hA",
                                      "oldBody": "x", "newBody": "yA"}, "a1")]
    b = [_op("editStmtBlock", "sym", {"file": "f.ts", "oldBodyHash": "h0",
                                      "newBodyHash": "hB",
                                      "oldBody": "x", "newBody": "yB"}, "b1")]
    kept_a, kept_b, conflicts = detect_conflicts_strict(a, b)
    assert [c.category for c in conflicts] == ["ConcurrentStmtEdit"]
    assert kept_a == [] and kept_b == []
    # [CFR-003]: minimal slice carries the disputed body.
    assert conflicts[0].to_dict()["minimalSlice"]["code"] == "x"


def test_identical_stmt_edits_agree():
    a = [_op("editStmtBlock", "sym", {"file": "f.ts", "oldBodyHash": "h0",
                                      "newBodyHash": "hSame",
                                      "oldBody": "x", "newBody": "y"}, "a1")]
    b = [_op("editStmtBlock", "sym", {"file": "f.ts", "oldBodyHash": "h0",
                                      "newBodyHash": "hSame",
                                      "oldBody": "x", "newBody": "y"}, "b1")]
    kept_a, kept_b, conflicts = detect_conflicts_strict(a, b)
    assert conflicts == []
    assert len(kept_a) == 1 and len(kept_b) == 1


def test_delete_vs_stmt_edit():
    a = [_op("deleteDecl", "sym", {"file": "f.ts"}, "a1")]
    b = [_op("editStmtBlock", "sym", {"file": "f.ts", "oldBodyHash": "h0",
                                      "newBodyHash": "hB",
                                      "oldBody": "x", "newBody": "y"}, "b1")]
    _, _, conflicts = detect_conflicts_strict(a, b)
    assert [c.category for c in conflicts] == ["DeleteVsEdit"]


def test_cli_concurrent_stmt_edit_end_to_end(tmp_path, monkeypatch):
    """Strict mode implies statement-op extraction: divergent body
    edits of one function conflict (ConcurrentStmtEdit), while parity
    mode merges silently (body-only changes emit no ops there)."""
    import json
    import subprocess

    def git(*args):
        subprocess.run(["git", *args], cwd=tmp_path, check=True,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    (tmp_path / "a.ts").write_text(
        "export function foo(n: number): number { return 0; }\n")
    git("init", "-q", "-b", "main")
    git("config", "user.email", "t@e")
    git("config", "user.name", "t")
    git("add", "-A")
    git("commit", "-qm", "base")
    git("branch", "basebr")
    git("checkout", "-qb", "ba")
    (tmp_path / "a.ts").write_text(
        "export function foo(n: number): number { return 1; }\n")
    git("commit", "-qam", "edit A")
    git("checkout", "-q", "main")
    git("checkout", "-qb", "bb")
    (tmp_path / "a.ts").write_text(
        "export function foo(n: number): number { return 2; }\n")
    git("commit", "-qam", "edit B")
    git("checkout", "-q", "main")

    monkeypatch.chdir(tmp_path)
    from semantic_merge_tpu.cli import main
    rc = main(["semmerge", "basebr", "ba", "bb", "--backend", "host",
               "--strict-conflicts"])
    assert rc == 1
    payload = json.loads((tmp_path / ".semmerge-conflicts.json").read_text())
    assert any(c["category"] == "ConcurrentStmtEdit" for c in payload)


def test_cli_stmt_edit_applies_to_merge(tmp_path, monkeypatch):
    """A one-sided body edit lands in the merged tree via the
    editStmtBlock applier handler (text fallback would also patch it;
    disabling it proves the op path does the splice)."""
    import subprocess

    def git(*args):
        subprocess.run(["git", *args], cwd=tmp_path, check=True,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    (tmp_path / "a.ts").write_text(
        "export function foo(n: number): number { return 0; }\n")
    (tmp_path / "b.ts").write_text(
        "export function other(s: string): string { return s; }\n")
    (tmp_path / ".semmerge.toml").write_text(
        "[engine]\nstatement_ops = true\ntext_fallback = false\n")
    git("init", "-q", "-b", "main")
    git("config", "user.email", "t@e")
    git("config", "user.name", "t")
    git("add", "-A")
    git("commit", "-qm", "base")
    git("branch", "basebr")
    git("checkout", "-qb", "ba")
    (tmp_path / "a.ts").write_text(
        "export function foo(n: number): number { return 42; }\n")
    git("commit", "-qam", "edit A")
    git("checkout", "-q", "main")
    git("checkout", "-qb", "bb")
    (tmp_path / "b.ts").write_text(
        "export function other2(s: string): string { return s; }\n")
    git("commit", "-qam", "rename B")
    git("checkout", "-q", "main")

    monkeypatch.chdir(tmp_path)
    from semantic_merge_tpu.cli import main
    rc = main(["semmerge", "basebr", "ba", "bb", "--backend", "host",
               "--inplace"])
    assert rc == 0
    assert "return 42" in (tmp_path / "a.ts").read_text()
    assert "other2" in (tmp_path / "b.ts").read_text()


def test_conflict_order_by_first_involved_a_op_position():
    """The documented output contract: conflicts sort by the first
    involved A-op's stream position, even though ExtractVsInline is
    DETECTED first (the motion pass runs before the per-symbol loops).
    Here the divergent rename involves A's op 0 and the motion pair
    A's op 1 — the rename must come out first."""
    ext = _op("extractMethod", "host",
              {"file": "f.ts", "newName": "helper", "newAddress": "na",
               "newSymbol": "hsym", "fromFile": "f.ts",
               "blockHash": "bh"}, "a2")
    a = [_op("renameSymbol", "s2", {"oldName": "f", "newName": "g",
                                    "file": "f.ts"}, "a1"),
         ext]
    inl = _op("inlineMethod", "hostB",
              {"file": "g.ts", "methodName": "helper", "oldAddress": "oa",
               "oldSymbol": "hsym", "blockHash": "bh"}, "b2")
    b = [inl,
         _op("renameSymbol", "s2", {"oldName": "f", "newName": "h",
                                    "file": "f.ts"}, "b1")]
    _, _, conflicts = detect_conflicts_strict(a, b)
    assert [c.category for c in conflicts] == ["DivergentRename",
                                               "ExtractVsInline"]
    # Swapping A's stream order swaps the output order too.
    _, _, conflicts = detect_conflicts_strict(list(reversed(a)), b)
    assert [c.category for c in conflicts] == ["ExtractVsInline",
                                               "DivergentRename"]
