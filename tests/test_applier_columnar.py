"""Columnar-vs-object applier parity (the tentpole contract of the
columnar apply path): the same composed stream applied through the
columnar dispatch loop and through the object-handler oracle
(``SEMMERGE_OBJECT_APPLY=1``) must produce byte-identical working
trees, and the op-log/notes payloads serialized from the columnar
views must be byte-identical to the object serialization — including
conflict-patched streams, CRDT reorder ops, and empty streams."""
import os
import pathlib
import random
import tempfile

import pytest

import bench
from semantic_merge_tpu.backends.base import get_backend, run_merge
from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
from semantic_merge_tpu.core.ops import Op, OpLog, Target, dumps_canonical
from semantic_merge_tpu.runtime.applier import (apply_ops, consume_stream,
                                                touched_paths,
                                                _normalize_relpath)

KW = dict(base_rev="r", seed="s", timestamp="2026-01-01T00:00:00Z")


def fused_backend():
    return TpuTSBackend(mesh=False)


def mk_tree(snap) -> pathlib.Path:
    root = pathlib.Path(tempfile.mkdtemp(prefix="semmerge_base_"))
    for f in snap.files:
        p = root / f["path"]
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(f["content"], encoding="utf-8")
    return root


def tree_bytes(root) -> dict:
    root = pathlib.Path(root)
    return {str(p.relative_to(root)): p.read_bytes()
            for p in sorted(root.rglob("*")) if p.is_file()}


def object_touched(ops) -> set:
    """The object-comprehension oracle for the touched-path set."""
    return {str(_normalize_relpath(v))
            for op in ops
            for k in ("file", "oldFile", "newFile", "oldPath", "newPath")
            if isinstance((v := op.params.get(k)), str) and v}


def apply_both_ways(base_snap, composed, monkeypatch):
    """(columnar tree bytes, object-oracle tree bytes) for one stream."""
    tree = mk_tree(base_snap)
    monkeypatch.delenv("SEMMERGE_OBJECT_APPLY", raising=False)
    out_col = apply_ops(tree, composed)
    monkeypatch.setenv("SEMMERGE_OBJECT_APPLY", "1")
    out_obj = apply_ops(tree, composed)
    monkeypatch.delenv("SEMMERGE_OBJECT_APPLY", raising=False)
    return tree_bytes(out_col), tree_bytes(out_obj)


def test_apply_parity_fuzz(monkeypatch):
    """Property test: random synthetic workloads (clean and
    DivergentRename — the latter exercises conflict-patched views whose
    dropped rows and rename-context writes must not change the tree),
    applied through both dispatch paths, plus the host oracle's
    composed list, all byte-identical. Tiny tail shards force multiple
    apply shards so shard-boundary stitching is covered; notes payloads
    and touched-path sets are checked against their object oracles on
    every trial."""
    monkeypatch.setenv("SEMMERGE_TAIL_SHARD_ROWS", "16")
    host = get_backend("host")
    rng = random.Random(7)
    for trial in range(4):
        n = rng.randrange(15, 45)
        divergent = bool(trial % 2)
        base, left, right = bench.synth_repo(n, 3, divergent=divergent)
        tpu = fused_backend()
        res_t, comp_t, conf_t = run_merge(tpu, base, left, right, **KW)
        res_h, comp_h, conf_h = run_merge(host, base, left, right, **KW)
        assert comp_t.supports_columns, trial
        if divergent:
            assert conf_t, "divergent trial produced no conflicts"

        a, b = apply_both_ways(base, comp_t, monkeypatch)
        assert a == b, f"columnar vs object tree diverged (trial {trial})"
        tree = mk_tree(base)
        assert tree_bytes(apply_ops(tree, comp_h)) == a, \
            f"columnar tree diverged from host-composed tree (trial {trial})"

        # Notes payloads: the columnar op-stream serialization must be
        # byte-identical to the object OpLog serialization.
        for view, ops in ((res_t.op_log_left, res_h.op_log_left),
                          (res_t.op_log_right, res_h.op_log_right)):
            assert OpLog(view).to_json_bytes() == dumps_canonical(
                [o.to_dict() for o in ops]).encode("utf-8"), trial

        # Touched-path scope: columnar columns vs object comprehension.
        assert touched_paths(comp_t) == object_touched(list(comp_t)), trial
        # The bench's consumption endpoint counts exactly the
        # actionable rows the object stream carries.
        assert consume_stream(comp_t) == sum(
            op.type in ("renameSymbol", "moveDecl") for op in comp_h), trial


def test_apply_parity_empty_stream(monkeypatch):
    """An empty composed stream (three identical snapshots) must apply
    to an unchanged copy of the base tree on both paths."""
    base, _, _ = bench.synth_repo(6, 2)
    tpu = fused_backend()
    _, composed, conflicts = run_merge(tpu, base, base, base, **KW)
    assert len(composed) == 0 and not conflicts
    a, b = apply_both_ways(base, composed, monkeypatch)
    assert a == b == tree_bytes(mk_tree(base))
    assert touched_paths(composed) == set()
    assert consume_stream(composed) == 0


def test_apply_parity_one_sided_stream(monkeypatch):
    """One side identical to base (that op-stream column is empty):
    the merged gathers must not index into the empty stream, and both
    dispatch paths stay byte-identical."""
    base, left, right = bench.synth_repo(12, 2)
    tpu = fused_backend()
    for snaps in ((base, base, right), (base, left, base)):
        _, composed, _ = run_merge(tpu, *snaps, **KW)
        assert len(composed) > 0
        assert min(len(composed.left), len(composed.right)) == 0
        a, b = apply_both_ways(base, composed, monkeypatch)
        assert a == b
        assert touched_paths(composed) == object_touched(list(composed))


def test_apply_crdt_reorder_unaffected(monkeypatch):
    """reorderImports (the CRDT-ordered handler) only ever arrives in
    object streams — the columnar vocabulary is the four diff kinds —
    and must behave identically whether or not the object oracle is
    forced: the env flag gates dispatch, not semantics."""
    order = [
        {"value": 'import b from "b";', "anchor": "", "t": 1,
         "author": "x", "opid": "1"},
        {"value": 'import a from "a";', "anchor": "", "t": 2,
         "author": "y", "opid": "2"},
    ]
    op = Op.new("reorderImports", Target(symbolId="s"),
                params={"file": "a.ts", "order": order})
    rename = Op.new("renameSymbol", Target(symbolId="s2"),
                    params={"file": "a.ts", "oldName": "foo",
                            "newName": "bar"})
    root = pathlib.Path(tempfile.mkdtemp())
    (root / "a.ts").write_text(
        'import a from "a";\nimport b from "b";\nconst foo = 1;\n')
    monkeypatch.delenv("SEMMERGE_OBJECT_APPLY", raising=False)
    out1 = tree_bytes(apply_ops(root, [op, rename]))
    monkeypatch.setenv("SEMMERGE_OBJECT_APPLY", "1")
    out2 = tree_bytes(apply_ops(root, [op, rename]))
    assert out1 == out2
    assert out1["a.ts"].startswith(b'import b from "b";\nimport a from "a";')
    assert b"const bar = 1;" in out1["a.ts"]


def test_device_compose_view_applies_like_eager_list():
    """The device composer now hands a lazy (object-backed) view
    through instead of a materialized list; applying it must equal
    applying the host composer's eager list."""
    host = get_backend("host")
    tpu = fused_backend()
    base, left, right = bench.synth_repo(12, 2)
    res = tpu.build_and_diff(base, left, right, **KW)
    comp_view, _ = tpu.compose(list(res.op_log_left),
                               list(res.op_log_right))
    comp_list, _ = host.compose(list(res.op_log_left),
                                list(res.op_log_right))
    assert [o.to_dict() for o in comp_view] == \
        [o.to_dict() for o in comp_list]
    tree = mk_tree(base)
    assert tree_bytes(apply_ops(tree, comp_view)) == \
        tree_bytes(apply_ops(tree, comp_list))


@pytest.mark.parametrize("split", ["0", "1"])
def test_apply_parity_split_fetch_modes(monkeypatch, split):
    """Both fetch schedules (one-buffer packed and split/deferred
    chains) must feed the columnar applier identically — the split
    path's chain decode happens shard-wise inside the apply walk."""
    monkeypatch.setenv("SEMMERGE_SPLIT_FETCH", split)
    monkeypatch.setenv("SEMMERGE_TAIL_SHARD_ROWS", "8")
    base, left, right = bench.synth_repo(20, 2)
    tpu = fused_backend()
    _, composed, _ = run_merge(tpu, base, left, right, **KW)
    a, b = apply_both_ways(base, composed, monkeypatch)
    assert a == b
