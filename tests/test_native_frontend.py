"""Parity tests: C++ native scanner vs the Python oracle.

The native library (``native/semmerge_native.cpp``) must reproduce the
Python scanner's output bit-for-bit on ASCII snapshots — every field of
every DeclNode, in order. These cases cover the indexing semantics the
reference worker defines (reference ``workers/ts/src/sast.ts``) plus
the tokenizer edge cases the scan depends on.
"""
from __future__ import annotations

import pytest

from semantic_merge_tpu.frontend import native
from semantic_merge_tpu.frontend.scanner import scan_snapshot_py

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native frontend unavailable (no compiler?)")


def assert_parity(files):
    got = native.try_scan_snapshot(files)
    want = scan_snapshot_py(files)
    assert got is not None
    assert [n.to_dict() for n in got] == [n.to_dict() for n in want]
    assert [n.signature for n in got] == [n.signature for n in want]


CASES = {
    "functions": """
export function add(a: number, b: number): number { return a + b; }
function noTypes(x, y) { return x; }
async function fetchIt(url: string): Promise<string> { return url; }
export default function (x: number) { return x; }
function overload(a: string): void;
function overload(a: number): void;
function* gen(n: number): Iterator { yield n; }
declare function ambient(q: boolean): void;
""",
    "expressions_not_indexed": """
const f = function (x: number) { return x; };
const g = (x: number) => x * 2;
let h = class { m() {} };
new (class {})();
(function iife() {})();
const obj = { method: function named() {} };
""",
    "classes": """
export class Point {
  x: number = 0;
  y: number = 0;
  constructor(x: number, y: number) { this.x = x; this.y = y; }
  dist(): number { return Math.sqrt(this.x ** 2 + this.y ** 2); }
  static origin = new Point(0, 0);
  ;
}
abstract class Shape extends Point implements Printable {
  abstract area(): number
  get name(): string { return "shape" }
}
class Empty {}
""",
    "interfaces_enums": """
interface Printable {
  print(): void;
  label: string,
  [key: string]: unknown;
}
enum Color { Red, Green = 2, Blue }
const enum Flags {
  A = 1 << 0,
  B = 1 << 1,
}
enum Empty {}
interface One { only: number }
""",
    "variables": """
const a = 1;
let b: string = "x", c = 2;
var d;
export const e: number[] = [1, 2, 3];
const [x, y] = [1, 2];
const { p, q } = { p: 1, q: 2 };
for (let i = 0; i < 10; i++) {}
for (const item of [1, 2]) {}
for (var k in {}) {}
""",
    "types": """
class Model {}
type Alias = Model | null;
function f1(m: Model): Model[] { return [m]; }
function f2(u: string | number, v: Model & Printable): (string | null)[] { return []; }
function f3(g: Array<Model>, h: Promise<number>): Map<string, Model> { return null as any; }
function f4(lit: "on" | "off", num: 42 | -1): 'ok' { return 'ok'; }
function f5(opt?: boolean, def: number = 3, ...rest: string[]): void {}
function f6(fn: (a: number) => string, tup: [string, number]): { k: string } { return { k: "" }; }
interface Printable { print(): void }
""",
    "tokenizer_edges": """
const re = /ab[/]c/g;
const div = a / b / c;
const s = 'it\\'s';
const t = `tmpl ${ { brace: `${nested}` } } end`;
// line comment with function fake() {}
/* block
   comment class Fake {} */
function real(x: number): number { return x; }
const weird = x ?? y ?? z;
label: for (;;) { break label; }
""",
    "nesting": """
function outer(a: number): void {
  function inner(b: string): string { return b; }
  class Local { m(): void {} }
  const localVar = 1;
}
namespace NS {
  export function nsFn(q: boolean): boolean { return q; }
  export class NsClass { a: number; }
}
""",
    "asi": """
class C {
  a = 1
  b = 2
  m() { return this.a }
  get v() { return 3 }
}
const x = 1
const y = 2
let z
""",
    "modifiers": """
export declare class DC { m(): void; }
export abstract class AC { abstract n(): number; }
export default class Main { run(): void {} }
export async function af(t: number): Promise<void> {}
""",
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_case_parity(name):
    assert_parity([{"path": f"{name}.ts", "content": CASES[name]}])


def test_all_cases_one_snapshot():
    """Cross-file type resolution: declared names from every file are
    visible to every other file's annotations."""
    files = [{"path": f"src/{name}.ts", "content": src}
             for name, src in sorted(CASES.items())]
    assert_parity(files)


def test_path_normalization():
    src = "export function p(a: number): number { return a; }\n"
    assert_parity([
        {"path": "./rel.ts", "content": src},
        {"path": "/abs.ts", "content": src},
        {"path": "win\\path.ts", "content": src},
    ])


def test_empty_and_trivial_files():
    assert_parity([
        {"path": "empty.ts", "content": ""},
        {"path": "ws.ts", "content": "   \n\t\n"},
        {"path": "comment.ts", "content": "// nothing here\n"},
        {"path": "one.ts", "content": "const one = 1;"},
    ])


def test_non_ascii_falls_back():
    files = [{"path": "u.ts", "content": "const s = 'héllo';\nfunction f(x: number): number { return x; }\n"}]
    assert native.try_scan_snapshot(files) is None  # Python path must handle it
    nodes = scan_snapshot_py(files)
    assert [n.name for n in nodes] == [None, "f"]


def test_synthetic_repo_parity():
    """The bench workload (hundreds of files) produces identical node
    streams on both frontends."""
    import bench
    base, left, right = bench.synth_repo(24, 6)
    for snap in (base, left, right):
        assert_parity(snap.files)


def test_unbalanced_sources():
    """Malformed inputs must not crash either frontend, and must agree."""
    cases = [
        "function broken(a: number { return a; }",
        "class Unclosed { m() {",
        "const s = 'unterminated",
        "interface I { x: ",
        "enum E { A,",
        "((((",
        "}}}}",
        "function ;",
        "const = 5;",
    ]
    files = [{"path": f"bad{i}.ts", "content": c} for i, c in enumerate(cases)]
    assert_parity(files)
