"""End-to-end request tracing (ISSUE 10 tentpole).

The contracts under test:

- **Isolation** — N concurrent daemon merges with ``--trace`` produce N
  per-request artifacts, each carrying its own non-empty ``trace_id``
  and no span stamped with another request's id
  (``check_trace_schema.validate_request_traces``), with the merged
  trees byte-equivalent to the one-shot path.
- **Flight recorder** — a fault-injected strict daemon merge with NO
  ``--trace`` flag still leaves ``.semmerge-postmortem/<trace_id>.json``
  in the repo, keyed by the same trace id the client's error line
  shows, validated by ``validate_postmortem`` (in-process and via the
  script CLI, as tier-1 wires it).
- **Drain flush** — a SIGTERM'd daemon writes its metrics registry
  (``SEMMERGE_METRICS``) and a ``daemon-drain`` bundle
  (``SEMMERGE_POSTMORTEM_DIR``) from the drain handler, not an atexit
  hook that signal shutdowns skip.
- **Live telemetry** — the ``metrics`` wire verb and the loopback HTTP
  listener serve the same registry/health payloads.
- **Attribution** — ``semmerge trace analyze`` buckets one request's
  wall time into the documented critical-path splits.
"""
import hashlib
import importlib.util
import json
import os
import pathlib
import re
import signal
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import pytest

from semantic_merge_tpu.errors import ParseFault

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
_SCRIPT = REPO_ROOT / "scripts" / "check_trace_schema.py"

ARTIFACTS = {".semmerge-conflicts.json", ".semmerge-trace.json",
             ".semmerge-events.jsonl", ".semmerge-journal.json",
             ".semmerge-postmortem"}

MERGE_ARGV = ["semmerge", "basebr", "brA", "brB",
              "--inplace", "--backend", "host"]


@pytest.fixture(scope="module")
def schema():
    spec = importlib.util.spec_from_file_location("check_trace_schema",
                                                  _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def git(args, cwd):
    subprocess.run(["git", *args], cwd=cwd, check=True,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def commit_all(root, msg):
    git(["add", "-A"], root)
    env = {"GIT_AUTHOR_DATE": "2024-01-01T00:00:00Z",
           "GIT_COMMITTER_DATE": "2024-01-01T00:00:00Z"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        git(["commit", "-q", "-m", msg], root)
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.update({k: v})


def build_repo(root: pathlib.Path) -> pathlib.Path:
    """The test_service repo shape (pinned dates: bit-identical repos
    at any path, so cross-repo tree comparisons are meaningful)."""
    root.mkdir(parents=True)
    git(["init", "-q", "-b", "main"], root)
    git(["config", "user.email", "t@example.com"], root)
    git(["config", "user.name", "t"], root)
    (root / "src").mkdir()
    (root / "src/util.ts").write_text(
        "export function foo(n: number): number {\n  return n;\n}\n")
    (root / "notes.txt").write_text("hello\n")
    commit_all(root, "base")
    git(["branch", "basebr"], root)
    git(["checkout", "-qb", "brA"], root)
    (root / "src/util.ts").write_text(
        "export function bar(n: number): number {\n  return n;\n}\n")
    commit_all(root, "rename foo->bar")
    git(["checkout", "-q", "main"], root)
    git(["checkout", "-qb", "brB"], root)
    (root / "extra.ts").write_text(
        "export function extra(s: string): string { return s; }\n")
    (root / "notes.txt").write_text("hello\nworld\n")
    commit_all(root, "add extra + edit notes")
    git(["checkout", "-q", "main"], root)
    return root


def tree_state(root: pathlib.Path) -> dict:
    from semantic_merge_tpu.runtime import inplace
    out = {}
    for p in sorted(root.rglob("*")):
        if not p.is_file():
            continue
        rel = p.relative_to(root).as_posix()
        if rel.startswith(".git/") or rel.split("/")[0] in ARTIFACTS \
                or rel.startswith(inplace.STAGE_DIR + "/"):
            continue
        out[rel] = hashlib.sha256(p.read_bytes()).hexdigest()
    return out


def client_env(sock: str, **extra) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    env["SEMMERGE_DAEMON"] = "require"
    env["SEMMERGE_SERVICE_SOCKET"] = sock
    env.pop("SEMMERGE_FAULT", None)
    env.pop("SEMMERGE_STRICT", None)
    env.update(extra)
    return env


def oneshot_subprocess_env(**extra) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    env["SEMMERGE_DAEMON"] = "off"
    env.pop("SEMMERGE_FAULT", None)
    env.pop("SEMMERGE_STRICT", None)
    env.update(extra)
    return env


def run_client(repo: pathlib.Path, env: dict, *argv, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "semantic_merge_tpu",
         *(argv or MERGE_ARGV)],
        cwd=repo, capture_output=True, text=True, env=env, timeout=timeout)


# ---------------------------------------------------------------------------
# Concurrent per-request span isolation
# ---------------------------------------------------------------------------

def test_concurrent_daemon_merges_have_isolated_traces(
        tmp_path, service_daemon, schema):
    """Three concurrent ``--trace`` merges through one daemon: each repo
    gets its own ``.semmerge-trace.json`` whose ``trace_id`` is unique
    and whose spans never carry a foreign id — and every merged tree is
    byte-equivalent to the one-shot result."""
    n = 3
    repos = [build_repo(tmp_path / f"repo{i}") for i in range(n)]
    results = [None] * n

    def work(i):
        results[i] = run_client(repos[i], client_env(service_daemon),
                                *MERGE_ARGV, "--trace")

    threads = [threading.Thread(target=work, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    for i, proc in enumerate(results):
        assert proc is not None and proc.returncode == 0, \
            f"repo{i}: {proc and proc.stderr}"

    traces = []
    for repo in repos:
        artifact = repo / ".semmerge-trace.json"
        assert artifact.exists(), "--trace through the daemon must leave " \
                                  "the per-request artifact in the repo"
        traces.append(json.loads(artifact.read_text()))
    assert schema.validate_request_traces(traces) == []
    for trace in traces:
        assert trace["spans"], "a traced daemon merge must record spans"

    # The script CLI path tier-1 uses is the same validator.
    ok = subprocess.run(
        [sys.executable, str(_SCRIPT), "validate_request_traces",
         *(str(r / ".semmerge-trace.json") for r in repos)],
        capture_output=True, text=True, timeout=60)
    assert ok.returncode == 0, ok.stderr

    # Byte parity vs one-shot: requests traced concurrently must not
    # change what gets merged.
    oneshot = build_repo(tmp_path / "oneshot")
    proc = run_client(oneshot, oneshot_subprocess_env(),
                      *MERGE_ARGV, "--trace")
    assert proc.returncode == 0, proc.stderr
    expected = tree_state(oneshot)
    for i, repo in enumerate(repos):
        assert tree_state(repo) == expected, \
            f"repo{i}: daemon-traced merge diverged from one-shot"


# ---------------------------------------------------------------------------
# Flight recorder: postmortem bundle without --trace
# ---------------------------------------------------------------------------

def test_fault_escape_writes_postmortem_keyed_by_client_trace_id(
        tmp_path, service_daemon, schema):
    """A strict fault-injected daemon merge with NO ``--trace`` flag:
    the client error line carries ``[trace <id>]``, and the repo gains
    ``.semmerge-postmortem/<id>.json`` — a validated bundle whose fault
    names the failing stage and whose ring rows carry the same id."""
    repo = build_repo(tmp_path / "repo")
    proc = run_client(repo, client_env(service_daemon,
                                       SEMMERGE_FAULT="scan:fault",
                                       SEMMERGE_STRICT="1"))
    assert proc.returncode == ParseFault.exit_code, proc.stderr
    m = re.search(r"\[trace ([^\]]+)\]", proc.stderr)
    assert m, f"client error must carry the trace id: {proc.stderr!r}"
    tid = m.group(1)

    bundle = repo / ".semmerge-postmortem" / f"{tid}.json"
    assert bundle.exists(), \
        f"fault escape must dump {bundle}, got " \
        f"{list((repo / '.semmerge-postmortem').glob('*')) if (repo / '.semmerge-postmortem').is_dir() else 'no dir'}"
    data = json.loads(bundle.read_text())
    assert schema.validate_postmortem(data) == []
    assert data["trace_id"] == tid
    assert data["reason"] == "fault-escape"
    assert data["fault"]["type"] == "ParseFault"
    assert data["fault"]["stage"] == "scan"
    assert data["fault"]["exit_code"] == ParseFault.exit_code
    assert data["fault_chain"], "the fault chain must not be empty"
    own = [row for row in data["spans"] if row["trace_id"] == tid]
    assert own, "the flight ring must hold spans of the failing request"

    # Tier-1 wires the same check through the script CLI.
    ok = subprocess.run([sys.executable, str(_SCRIPT),
                         "validate_postmortem", str(bundle)],
                        capture_output=True, text=True, timeout=60)
    assert ok.returncode == 0, ok.stderr

    # The daemon survived the fault and serves the next request.
    proc2 = run_client(repo, client_env(service_daemon))
    assert proc2.returncode == 0, proc2.stderr
    assert "bar" in (repo / "src/util.ts").read_text()


# ---------------------------------------------------------------------------
# Drain flush: SIGTERM'd daemon persists metrics + flight ring
# ---------------------------------------------------------------------------

def test_sigterm_drain_flushes_metrics_and_flight(tmp_path, daemon_factory,
                                                  schema):
    """Metrics used to evaporate when the supervisor (or an operator)
    SIGTERM'd the daemon: the atexit dump never ran. The drain handler
    now writes both ``SEMMERGE_METRICS`` and a ``daemon-drain``
    postmortem bundle before the process exits."""
    sock = str(tmp_path / "daemon.sock")
    metrics_path = tmp_path / "daemon-metrics.json"
    pm_dir = tmp_path / "postmortem"
    proc = daemon_factory(sock, extra_env={
        "SEMMERGE_METRICS": str(metrics_path),
        "SEMMERGE_POSTMORTEM_DIR": str(pm_dir),
    })

    repo = build_repo(tmp_path / "repo")
    merged = run_client(repo, client_env(sock))
    assert merged.returncode == 0, merged.stderr

    proc.send_signal(signal.SIGTERM)
    proc.wait(timeout=60)

    assert metrics_path.exists(), \
        "a SIGTERM'd daemon must flush its registry from the drain handler"
    registry = json.loads(metrics_path.read_text())
    assert schema.validate_metrics(registry) == []
    assert "service_requests_total" in registry.get("counters", {}), \
        "the flushed registry must contain the served request"

    bundles = sorted(pm_dir.glob("*.json"))
    assert bundles, "the drain handler must dump the flight ring when a " \
                    "postmortem dir is configured"
    drained = [json.loads(b.read_text()) for b in bundles]
    drain = [d for d in drained if d.get("reason") == "daemon-drain"]
    assert drain, f"expected a daemon-drain bundle, got " \
                  f"{[d.get('reason') for d in drained]}"
    assert schema.validate_postmortem(drain[0]) == []
    assert drain[0]["spans"], \
        "the drained ring must hold the served request's spans"


# ---------------------------------------------------------------------------
# Live telemetry: wire verb + loopback HTTP listener
# ---------------------------------------------------------------------------

def test_metrics_wire_verb(service_daemon, schema):
    """``metrics`` control verb: live Prometheus text + registry dict +
    health payload without waiting for process exit."""
    from semantic_merge_tpu.service import client as service_client
    res = service_client.call_control("metrics", path=service_daemon)
    assert isinstance(res.get("prometheus"), str)
    assert schema.validate_metrics(res["metrics"]) == []
    health = res["health"]
    assert "queue_depth" in health
    assert "metrics_port" in health


def test_http_telemetry_listener_serves_metrics_and_healthz(tmp_path):
    """The loopback listener (``SEMMERGE_METRICS_PORT``): ``/metrics``
    answers Prometheus text, ``/healthz`` the health JSON, unknown
    paths 404. Ephemeral-port binding (port 0) is what daemons under
    test use, so exercise exactly that."""
    from semantic_merge_tpu.obs import metrics as obs_metrics
    from semantic_merge_tpu.service.telemetry import TelemetryServer
    obs_metrics.REGISTRY.counter("telemetry_probe_total", "t").inc(1)
    server = TelemetryServer(0, lambda: {"queue_depth": 0, "ok": True})
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as resp:
            assert resp.status == 200
            body = resp.read().decode("utf-8")
        assert "telemetry_probe_total" in body
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as resp:
            assert resp.status == 200
            health = json.loads(resp.read().decode("utf-8"))
        assert health["queue_depth"] == 0
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/nope", timeout=10)
        assert err.value.code == 404
    finally:
        server.stop()


def test_daemon_reports_bound_metrics_port(tmp_path, daemon_factory):
    """A daemon started with ``SEMMERGE_METRICS_PORT=0`` binds an
    ephemeral loopback port and reports it through ``status`` so
    operators can discover the scrape endpoint."""
    from semantic_merge_tpu.service import client as service_client
    sock = str(tmp_path / "daemon.sock")
    daemon_factory(sock, extra_env={"SEMMERGE_METRICS_PORT": "0"})
    status = service_client.call_control("status", path=sock)
    port = status.get("metrics_port")
    assert isinstance(port, int) and port > 0
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                                timeout=10) as resp:
        assert resp.status == 200
        health = json.loads(resp.read().decode("utf-8"))
    assert health.get("metrics_port") == port


# ---------------------------------------------------------------------------
# Latency attribution: semmerge trace analyze
# ---------------------------------------------------------------------------

def _span(name, layer, seconds, span_id, **meta):
    return {"name": name, "layer": layer, "t_start": 0.0,
            "seconds": seconds, "depth": 0, "span_id": span_id,
            "parent_id": -1, "thread": "t", "status": "ok",
            "error": None, "meta": meta}


def _synthetic_trace(tid: str, scale: float = 1.0) -> dict:
    return {
        "schema": 1, "trace_id": tid, "total_seconds": 0.05 * scale,
        "phases": [], "counters": {}, "device": None,
        "spans": [
            _span("service.queue_wait", "service", 0.010 * scale, 1,
                  verb="semmerge"),
            _span("merge", "cli", 0.030 * scale, 2),
            _span("kernel", "ops", 0.020 * scale, 3),
            _span("fetch", "ops", 0.005 * scale, 4),
            _span("materialize", "cli", 0.004 * scale, 5),
        ],
    }


def test_trace_analyze_buckets_one_request(tmp_path, capsys):
    from semantic_merge_tpu.cli import main
    artifact = tmp_path / "trace.json"
    artifact.write_text(json.dumps(_synthetic_trace("req-1")))
    rc = main(["trace", "analyze", str(artifact), "--json"])
    assert rc == 0
    result = json.loads(capsys.readouterr().out)
    assert result["trace_id"] == "req-1"
    buckets = result["buckets"]
    assert buckets["queue_wait"] == pytest.approx(0.010)
    assert buckets["kernel"] == pytest.approx(0.020)
    assert buckets["host_tail"] == pytest.approx(0.005)
    assert buckets["apply"] == pytest.approx(0.004)
    # total = cli wall + queue wait; "merge" (0.030) wraps kernel+fetch
    # and must not be double-counted as its own bucket.
    assert result["total_seconds"] == pytest.approx(0.044)
    assert result["other_seconds"] == pytest.approx(0.005)


def test_trace_analyze_directory_percentiles(tmp_path, capsys):
    from semantic_merge_tpu.cli import main
    outdir = tmp_path / "bundles"
    outdir.mkdir()
    for i, scale in enumerate((1.0, 2.0, 3.0)):
        (outdir / f"req-{i}.json").write_text(
            json.dumps(_synthetic_trace(f"req-{i}", scale)))
    (outdir / "not-a-trace.json").write_text(json.dumps({"schema": 1}))
    rc = main(["trace", "analyze", str(outdir), "--json"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["requests"] == 3
    assert summary["p50"]["queue_wait"] == pytest.approx(0.020)
    assert summary["p99"]["queue_wait"] == pytest.approx(0.030)
    assert summary["p99"]["total_seconds"] == pytest.approx(0.132)


def test_trace_analyze_rejects_non_artifacts(tmp_path, capsys):
    from semantic_merge_tpu.cli import main
    bogus = tmp_path / "bogus.json"
    bogus.write_text("not json")
    assert main(["trace", "analyze", str(bogus)]) == 1
    assert main(["trace", "analyze", str(tmp_path / "missing.json")]) == 1
    capsys.readouterr()


def test_trace_analyze_reads_real_daemon_artifact(tmp_path, service_daemon,
                                                  capsys):
    """End to end: a real traced daemon merge's artifact feeds the
    analyzer — queue wait is attributed and the totals are positive."""
    from semantic_merge_tpu.cli import main
    repo = build_repo(tmp_path / "repo")
    proc = run_client(repo, client_env(service_daemon),
                      *MERGE_ARGV, "--trace")
    assert proc.returncode == 0, proc.stderr
    rc = main(["trace", "analyze", str(repo / ".semmerge-trace.json"),
               "--json"])
    assert rc == 0
    result = json.loads(capsys.readouterr().out)
    assert result["trace_id"], "daemon trace artifact must carry its id"
    assert result["total_seconds"] > 0
    assert set(result["buckets"]) == set(
        ("queue_wait", "batch_window", "pack", "kernel", "host_tail",
         "apply"))
