"""Chaos/soak coverage for the supervised service (ISSUE 9 tentpole).

Tier-1 runs the smoke: 200 mixed merges (clean / fault-degrade /
strict-typed / resolver-enabled conflict merges) from 8 concurrent
workers against a ``semmerge serve --supervise`` daemon, with 2
randomized SIGKILLs of the daemon child mid-soak. The harness (``scripts/chaos_soak.py``) asserts the full
invariant set — byte-exact settled trees with no journal/lock debris,
documented exit codes only, supervisor respawns observable, RSS under
the hard watermark — and returns a report; the test checks the report
plus the schedule actually exercised what it claims (kills landed, the
breaker tripped, every shape ran).

The slow-marked soak triples the traffic and kill count.
"""
import importlib.util
import pathlib

import pytest

_SCRIPT = (pathlib.Path(__file__).resolve().parent.parent
           / "scripts" / "chaos_soak.py")


def _load():
    spec = importlib.util.spec_from_file_location("chaos_soak", _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def chaos_soak():
    return _load()


def _check_report(report, *, requests, kills):
    assert report["errors"] == [], "\n".join(report["errors"])
    assert report["ok"] is True
    # Every request resolved to a documented outcome, none dropped.
    total = sum(sum(per_code.values())
                for per_code in report["outcomes"].values())
    assert total == requests
    assert set(report["outcomes"]) == {
        "clean", "degrade-scan", "degrade-apply", "strict-scan",
        "resolve"}
    # Resolver-enabled traffic stayed on documented outcomes: exit 0
    # (resolver's verified suggestion applied) or exit 1 (textual-rung
    # conflict-as-result while the host breaker was open) — and the
    # surviving daemon recorded accepted resolutions, at minimum from
    # the resolver-settled conflict repos.
    assert set(report["outcomes"]["resolve"]) <= {"0", "1"}
    assert report["resolutions_total"] is not None
    assert report["resolutions_total"] >= 1
    # The kill schedule landed and self-healing was observable: a new
    # daemon pid appeared and the supervisor counted its respawns.
    assert report["kills"] == kills
    assert report["daemon_pids_seen"] >= 2
    assert report["supervisor_restarts"] >= 1
    # Requests in flight during a kill rode through on retries.
    assert report["transport_retries"] >= 1
    assert report["final_rss_mb"] < 4096.0


def test_chaos_smoke(chaos_soak, tmp_path):
    report = chaos_soak.run_soak(
        tmp_path / "soak", requests=200, repos=8, concurrency=8,
        kills=2, seed=1, hard_mb=4096.0)
    _check_report(report, requests=200, kills=2)
    # The fault-injected traffic keeps failing the host rung, so the
    # breaker must have tripped in the surviving daemon's lifetime
    # (strict requests then surface exit 12 instead of 10).
    assert report["breaker_transitions"] is not None
    assert report["breaker_transitions"] >= 1
    assert report["breakers"] is not None


@pytest.mark.slow
def test_chaos_full_soak(chaos_soak, tmp_path):
    report = chaos_soak.run_soak(
        tmp_path / "soak", requests=600, repos=12, concurrency=12,
        kills=5, seed=7, hard_mb=4096.0)
    _check_report(report, requests=600, kills=5)
    assert report["breaker_transitions"] >= 1


def test_fleet_chaos_smoke(chaos_soak, tmp_path):
    """The ISSUE 14 kill-drill plus the ISSUE 19 cross-host legs: a
    fleet of 3 supervised members and one standalone member joined
    over real TCP, under byte-exact traffic with one member SIGKILL
    and one router SIGKILL mid-stream, one elastic TCP join + one
    drain (churn), and one SIGSTOP partition of the TCP member — a
    half-open link only the application heartbeat can eject, counted
    as a reason="partition" failover. Every request settles byte-exact
    with documented exits, the healed member rejoins, and the full
    journal history accounts for each effect exactly once."""
    report = chaos_soak.run_fleet_soak(
        tmp_path / "fleet", requests=24, repos=4, concurrency=4,
        members=3, member_kills=1, router_kills=1, seed=3,
        tcp_members=1, partitions=1, churn=True)
    assert report["errors"] == [], "\n".join(report["errors"])
    assert report["ok"] is True
    total = sum(sum(per_code.values())
                for per_code in report["outcomes"].values())
    assert total == 24
    # Kills landed and the fleet healed: failovers counted, a
    # replacement router pid appeared, the ring refilled (3 supervised
    # + the healed TCP member; the churn member stays drained).
    assert report["member_kills"] == 1
    assert report["router_kills"] == 1
    assert report["failovers_total"] >= 1
    assert report["router_pids_seen"] >= 2
    assert report["members_up"] == 4
    # The cross-host legs all landed: the partition was ejected by
    # heartbeat (not a dial failure), the churn drain was a deliberate
    # leave, and both TCP members were admitted via the join verb.
    assert report["partitions"] == 1
    assert report["partition_failovers"] >= 1
    assert report["churn_joins"] == 1
    assert report["churn_drains"] == 1
    assert report["drain_failovers"] >= 1
    assert report["joins_total"] >= 2
    # Exactly-once accounting: nothing left open in the journal.
    assert report["wal_open"] == 0


@pytest.mark.slow
def test_fleet_chaos_full_drill(chaos_soak, tmp_path):
    report = chaos_soak.run_fleet_soak(
        tmp_path / "fleet", requests=120, repos=8, concurrency=8,
        members=3, member_kills=3, router_kills=2, seed=11,
        tcp_members=2, partitions=2, churn=True)
    assert report["errors"] == [], "\n".join(report["errors"])
    assert report["member_kills"] == 3
    assert report["router_kills"] == 2
    assert report["failovers_total"] >= 3
    assert report["partitions"] == 2
    assert report["partition_failovers"] >= 1
    assert report["wal_open"] == 0


def test_cli_entrypoint_smoke(chaos_soak, tmp_path, capsys):
    """The standalone CLI path: tiny run, human-readable summary."""
    rc = chaos_soak.main(["--requests", "8", "--repos", "2",
                          "--concurrency", "2", "--kills", "0",
                          "--workdir", str(tmp_path / "mini")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "OK" in out
