"""Pallas flash-attention chunk kernel: parity with the einsum path.

Runs in interpret mode on the CPU mesh (the compiled path needs a real
TPU; the bench harness exercises it there). Parity target: the kernel's
partial softmax statistics must merge to the same attention output as
the dense reference, and the full ring-attention path with the kernel
enabled must match the einsum ring path bit-for-close.
"""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from semantic_merge_tpu.parallel.flash import flash_chunk_attention  # noqa: E402
from semantic_merge_tpu.parallel.mesh import build_mesh  # noqa: E402
from semantic_merge_tpu.parallel.ring import (_chunk_stats_einsum,  # noqa: E402
                                              ring_attention)


def _rand(shape, seed):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


def test_chunk_kernel_matches_einsum_stats():
    b, lq, lk, h, dh = 2, 16, 24, 3, 8
    q = jnp.asarray(_rand((b, lq, h, dh), 0))
    k = jnp.asarray(_rand((b, lk, h, dh), 1))
    v = jnp.asarray(_rand((b, lk, h, dh), 2))
    mask = np.random.RandomState(3).rand(b, lk) > 0.3
    mask[:, 0] = True
    mask = jnp.asarray(mask)

    pv_p, m_p, l_p = flash_chunk_attention(q, k, v, mask, block_q=8,
                                           block_k=8, interpret=True)
    pv_e, m_e, l_e = _chunk_stats_einsum(q, k, v, mask, dh ** -0.5)

    # m may differ between paths (blockwise vs global row max); the
    # normalised attention they imply must agree.
    out_p = np.asarray(pv_p) / np.asarray(l_p).transpose(0, 2, 1)[..., None]
    out_e = np.asarray(pv_e) / np.asarray(l_e).transpose(0, 2, 1)[..., None]
    np.testing.assert_allclose(out_p, out_e, rtol=1e-5, atol=1e-5)
    # And so must the raw sums once rebased to a common max.
    scale_p = np.exp(np.asarray(m_p) - np.asarray(m_e))
    np.testing.assert_allclose(np.asarray(l_p) * scale_p, np.asarray(l_e),
                               rtol=1e-5, atol=1e-5)


def test_chunk_kernel_ragged_shapes():
    # Lengths that do not divide the block sizes exercise the padding path.
    b, lq, lk, h, dh = 1, 13, 27, 2, 16
    q = jnp.asarray(_rand((b, lq, h, dh), 4))
    k = jnp.asarray(_rand((b, lk, h, dh), 5))
    v = jnp.asarray(_rand((b, lk, h, dh), 6))
    mask = jnp.ones((b, lk), bool)
    pv_p, m_p, l_p = flash_chunk_attention(q, k, v, mask, block_q=8,
                                           block_k=8, interpret=True)
    pv_e, m_e, l_e = _chunk_stats_einsum(q, k, v, mask, dh ** -0.5)
    out_p = np.asarray(pv_p) / np.asarray(l_p).transpose(0, 2, 1)[..., None]
    out_e = np.asarray(pv_e) / np.asarray(l_e).transpose(0, 2, 1)[..., None]
    np.testing.assert_allclose(out_p, out_e, rtol=1e-5, atol=1e-5)


def test_ring_attention_pallas_matches_einsum():
    b, l, h, dh = 4, 16, 4, 8
    q = jnp.asarray(_rand((b, l, h, dh), 7))
    k = jnp.asarray(_rand((b, l, h, dh), 8))
    v = jnp.asarray(_rand((b, l, h, dh), 9))
    mask = np.random.RandomState(10).rand(b, l) > 0.2
    mask[:, 0] = True
    mask = jnp.asarray(mask)
    mesh = build_mesh(dp=2, pp=1, sp=2, tp=2, ep=1)
    out_pallas = ring_attention(q, k, v, mask, mesh.mesh, pallas="interpret")
    out_einsum = ring_attention(q, k, v, mask, mesh.mesh, pallas=None)
    np.testing.assert_allclose(np.asarray(out_pallas), np.asarray(out_einsum),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_matches_dense_and_ring():
    from semantic_merge_tpu.parallel.ulysses import ulysses_attention
    b, l, h, dh = 4, 16, 4, 8
    q = jnp.asarray(_rand((b, l, h, dh), 11))
    k = jnp.asarray(_rand((b, l, h, dh), 12))
    v = jnp.asarray(_rand((b, l, h, dh), 13))
    mask = np.random.RandomState(14).rand(b, l) > 0.2
    mask[:, 0] = True
    mask = jnp.asarray(mask)
    mesh = build_mesh(dp=2, pp=1, sp=2, tp=2, ep=1)
    out_u = ulysses_attention(q, k, v, mask, mesh.mesh)
    out_r = ring_attention(q, k, v, mask, mesh.mesh, pallas=None)
    np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_r),
                               rtol=2e-4, atol=2e-4)


def test_encoder_ulysses_mode_runs():
    from dataclasses import replace

    from semantic_merge_tpu.models.encoder import (EncoderConfig,
                                                   encoder_forward,
                                                   init_encoder)
    from semantic_merge_tpu.models.features import encode_batch
    cfg = EncoderConfig(vocab=256, d_model=32, n_heads=4, d_head=8,
                        n_layers=1, d_ff=64, n_experts=2, attn_mode="ulysses")
    mesh = build_mesh(dp=2, pp=1, sp=2, tp=2, ep=1)
    params = init_encoder(jax.random.PRNGKey(0), cfg)
    toks, mask = encode_batch(["export function f(x: number): number { return x; }"] * 4,
                              256, 16)
    out = encoder_forward(params, jnp.asarray(toks), jnp.asarray(mask), cfg, mesh)
    assert out.shape == (4, 16, 32)
    assert np.isfinite(np.asarray(out, dtype=np.float32)).all()
