"""Merge service daemon (ISSUE 7 tentpole): one-shot parity, warm-path
fallback, and concurrency semantics.

The bar the daemon must clear:

- **Parity** — a request served by the daemon produces the same exit
  code, the same work-tree bytes, the same conflicts artifact, and the
  same git notes as the identical one-shot invocation. Byte-for-byte,
  across clean merges, conflicts, and strict-mode typed faults.
- **Never worse than one-shot** — under ``SEMMERGE_DAEMON=auto``, a
  daemon SIGKILLed mid-request (or one that cannot bind/spawn at all)
  must not fail a merge the one-shot path would complete: the client
  falls back in-process and the tree matches the one-shot result.
- **Admission/locking** — same-repo ``--inplace`` requests serialize
  (their ``service.execute`` windows are disjoint); different-repo
  requests overlap on the executor pool.
"""
import contextlib
import hashlib
import json
import os
import pathlib
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

from semantic_merge_tpu.cli import CONFLICTS_ARTIFACT, main
from semantic_merge_tpu.errors import ApplyFault, ParseFault, WorkerFault
from semantic_merge_tpu.runtime import inplace
from semantic_merge_tpu.utils import faults

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent

ARTIFACTS = {".semmerge-conflicts.json", ".semmerge-trace.json",
             ".semmerge-events.jsonl", ".semmerge-journal.json",
             ".semmerge-postmortem"}

MERGE_ARGV = ["semmerge", "basebr", "brA", "brB",
              "--inplace", "--backend", "host"]


def git(args, cwd):
    subprocess.run(["git", *args], cwd=cwd, check=True,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def commit_all(root, msg):
    git(["add", "-A"], root)
    env = {"GIT_AUTHOR_DATE": "2024-01-01T00:00:00Z",
           "GIT_COMMITTER_DATE": "2024-01-01T00:00:00Z"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        git(["commit", "-q", "-m", msg], root)
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.update({k: v})


def build_repo(root: pathlib.Path, conflict: bool = False) -> pathlib.Path:
    """The test_faults repo shape, buildable at any path (parity needs
    two bit-identical repos — pinned dates make the commit shas equal,
    so notes comparisons line up too). ``conflict=True`` adds opposing
    edits to the same ``notes.txt`` line: a guaranteed textual conflict
    (exit 1) while the semantic .ts merge still succeeds."""
    root.mkdir(parents=True)
    git(["init", "-q", "-b", "main"], root)
    git(["config", "user.email", "t@example.com"], root)
    git(["config", "user.name", "t"], root)
    (root / "src").mkdir()
    (root / "src/util.ts").write_text(
        "export function foo(n: number): number {\n  return n;\n}\n")
    (root / "notes.txt").write_text("hello\n")
    commit_all(root, "base")
    git(["branch", "basebr"], root)
    git(["checkout", "-qb", "brA"], root)
    (root / "src/util.ts").write_text(
        "export function bar(n: number): number {\n  return n;\n}\n")
    if conflict:
        (root / "notes.txt").write_text("hello-from-A\n")
    commit_all(root, "rename foo->bar")
    git(["checkout", "-q", "main"], root)
    git(["checkout", "-qb", "brB"], root)
    (root / "extra.ts").write_text(
        "export function extra(s: string): string { return s; }\n")
    (root / "notes.txt").write_text(
        "hello-from-B\n" if conflict else "hello\nworld\n")
    commit_all(root, "add extra + edit notes")
    git(["checkout", "-q", "main"], root)
    return root


def build_resolve_repo(root: pathlib.Path, tie: bool = False) -> pathlib.Path:
    """A DivergentRename repo for resolver parity. Default shape carries
    asymmetric reference evidence (brA rewrote the call site) so the
    search resolver accepts ``keepA`` and the merge exits 0; ``tie=True``
    renames the declaration only on BOTH sides — symmetric evidence,
    scoring tie, conflict-as-result exit 1 with a rejected audit row."""
    root.mkdir(parents=True)
    git(["init", "-q", "-b", "main"], root)
    git(["config", "user.email", "t@example.com"], root)
    git(["config", "user.name", "t"], root)
    (root / "src").mkdir()
    (root / "src/util.ts").write_text(
        "export function foo(n: number): number {\n  return n;\n}\n"
        "export function use(s: string): number {\n"
        "  return foo(s.length);\n}\n")
    commit_all(root, "base")
    git(["branch", "basebr"], root)
    git(["checkout", "-qb", "brA"], root)
    call_a = "foo" if tie else "bar"
    (root / "src/util.ts").write_text(
        "export function bar(n: number): number {\n  return n;\n}\n"
        "export function use(s: string): number {\n"
        f"  return {call_a}(s.length);\n}}\n")
    commit_all(root, "rename foo->bar")
    git(["checkout", "-q", "main"], root)
    git(["checkout", "-qb", "brB"], root)
    (root / "src/util.ts").write_text(
        "export function baz(n: number): number {\n  return n;\n}\n"
        "export function use(s: string): number {\n"
        "  return foo(s.length);\n}\n")
    commit_all(root, "rename foo->baz decl-only")
    git(["checkout", "-q", "main"], root)
    return root


def tree_state(root: pathlib.Path) -> dict:
    out = {}
    for p in sorted(root.rglob("*")):
        if not p.is_file():
            continue
        rel = p.relative_to(root).as_posix()
        if rel.startswith(".git/") or rel.split("/")[0] in ARTIFACTS \
                or rel.startswith(inplace.STAGE_DIR + "/"):
            continue
        out[rel] = hashlib.sha256(p.read_bytes()).hexdigest()
    return out


def semmerge_notes(root: pathlib.Path) -> dict:
    """``git notes --ref semmerge`` payloads for both merged heads —
    ``(rc, stdout)`` so "no note" (rc 1) compares equal too."""
    out = {}
    for rev in ("brA", "brB"):
        proc = subprocess.run(
            ["git", "notes", "--ref", "semmerge", "show", rev],
            cwd=root, capture_output=True, text=True)
        out[rev] = (proc.returncode, proc.stdout)
    return out


@contextlib.contextmanager
def oneshot_env(cwd: pathlib.Path, extra: dict):
    """Run the in-process one-shot CLI exactly as a fresh shell would:
    chdir into the repo, daemon mode off, scenario env applied, fault
    counters reset — and everything restored afterwards."""
    keys = {"SEMMERGE_DAEMON", "SEMMERGE_FAULT", "SEMMERGE_STRICT"} \
        | set(extra)
    saved = {k: os.environ.get(k) for k in keys}
    old_cwd = os.getcwd()
    os.chdir(cwd)
    os.environ["SEMMERGE_DAEMON"] = "off"
    os.environ.pop("SEMMERGE_FAULT", None)
    os.environ.pop("SEMMERGE_STRICT", None)
    os.environ.update(extra)
    faults.reset()
    try:
        yield
    finally:
        faults.reset()
        os.chdir(old_cwd)
        for k, v in saved.items():
            os.environ.pop(k, None) if v is None else os.environ.update({k: v})


def client_env(sock: str, **extra) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    env["SEMMERGE_DAEMON"] = "require"
    env["SEMMERGE_SERVICE_SOCKET"] = sock
    env.pop("SEMMERGE_FAULT", None)
    env.pop("SEMMERGE_STRICT", None)
    env.update(extra)
    return env


def run_client(repo: pathlib.Path, env: dict, *argv, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "semantic_merge_tpu",
         *(argv or MERGE_ARGV)],
        cwd=repo, capture_output=True, text=True, env=env, timeout=timeout)


# ---------------------------------------------------------------------------
# Byte parity: daemon ≡ one-shot
# ---------------------------------------------------------------------------

PARITY_SCENARIOS = [
    # (repo shape, request env, documented exit code)
    pytest.param("clean", {}, 0, id="clean-merge-exit0"),
    pytest.param("conflict", {}, 1, id="textual-conflict-exit1"),
    pytest.param("clean",
                 {"SEMMERGE_FAULT": "scan:fault", "SEMMERGE_STRICT": "1"},
                 ParseFault.exit_code, id="strict-parse-fault-exit10"),
    pytest.param("clean",
                 {"SEMMERGE_FAULT": "apply:fault", "SEMMERGE_STRICT": "1"},
                 ApplyFault.exit_code, id="strict-apply-fault-exit13"),
]


@pytest.mark.parametrize("shape,extra_env,expected", PARITY_SCENARIOS)
def test_daemon_matches_one_shot(tmp_path, service_daemon, shape,
                                 extra_env, expected):
    """The acceptance bar: same exit code, same tree bytes, same
    conflicts artifact, same notes — whether the merge ran one-shot or
    through the warm daemon (request env overlay carrying the scenario's
    fault/strict posture)."""
    one = build_repo(tmp_path / "oneshot", conflict=shape == "conflict")
    two = build_repo(tmp_path / "daemon", conflict=shape == "conflict")
    with oneshot_env(one, extra_env):
        rc_one = main(MERGE_ARGV)
    assert rc_one == expected

    proc = run_client(two, client_env(service_daemon, **extra_env))
    assert proc.returncode == rc_one, \
        f"daemon exit {proc.returncode} != one-shot {rc_one}: {proc.stderr}"
    assert tree_state(one) == tree_state(two), \
        "daemon and one-shot must produce byte-identical work trees"
    art_one = one / CONFLICTS_ARTIFACT
    art_two = two / CONFLICTS_ARTIFACT
    assert art_one.exists() == art_two.exists()
    if art_one.exists():
        assert json.loads(art_one.read_text()) == \
            json.loads(art_two.read_text())
    assert semmerge_notes(one) == semmerge_notes(two)


def _normalized_artifact(path: pathlib.Path):
    """The conflicts artifact with per-gate wall-clock stripped — gate
    timings are the only nondeterministic field in the audit trail."""
    payload = json.loads(path.read_text())
    if isinstance(payload, dict):
        for rec in payload.get("resolutions", []):
            for gate in rec.get("gates", []):
                gate.pop("ms", None)
    return payload


@pytest.mark.parametrize("tie,expected", [
    pytest.param(False, 0, id="resolve-accepted-exit0"),
    pytest.param(True, 1, id="resolve-tie-exit1"),
])
def test_daemon_resolve_posture_parity(tmp_path, service_daemon, tie,
                                       expected):
    """``SEMMERGE_RESOLVE`` rides the request env overlay: the daemon's
    resolver-enabled merge matches the one-shot run byte-for-byte —
    exit code, work tree, v2 conflicts artifact (audit trail included),
    git notes — for both an accepted resolution and a tie fallback."""
    one = build_resolve_repo(tmp_path / "oneshot", tie=tie)
    two = build_resolve_repo(tmp_path / "daemon", tie=tie)
    extra = {"SEMMERGE_RESOLVE": "auto"}
    with oneshot_env(one, extra):
        rc_one = main(MERGE_ARGV)
    assert rc_one == expected

    proc = run_client(two, client_env(service_daemon, **extra))
    assert proc.returncode == rc_one, \
        f"daemon exit {proc.returncode} != one-shot {rc_one}: {proc.stderr}"
    assert tree_state(one) == tree_state(two), \
        "daemon and one-shot resolver runs must produce identical trees"
    art_one = one / CONFLICTS_ARTIFACT
    art_two = two / CONFLICTS_ARTIFACT
    assert art_one.exists() and art_two.exists(), \
        "a resolver-tier run must always leave the audited artifact"
    pay_one = _normalized_artifact(art_one)
    pay_two = _normalized_artifact(art_two)
    assert pay_one == pay_two
    assert pay_one["schema_version"] == 2
    statuses = {r["status"] for r in pay_one["resolutions"]}
    assert statuses == ({"rejected"} if tie else {"accepted"})
    assert semmerge_notes(one) == semmerge_notes(two)


# ---------------------------------------------------------------------------
# auto mode: never worse than one-shot
# ---------------------------------------------------------------------------


def test_sigkill_daemon_mid_request_auto_falls_back(tmp_path,
                                                    daemon_factory):
    """SIGKILL the daemon while it holds the request wedged inside
    ``service:execute`` (hang fault): the auto-mode client must detect
    the dead transport, fall back in-process, and complete the merge
    with the exact one-shot tree — the dead daemon never touched it."""
    repo = build_repo(tmp_path / "repo")
    ref = build_repo(tmp_path / "ref")
    sock = str(tmp_path / "kill.sock")
    daemon_proc = daemon_factory(sock)

    from semantic_merge_tpu.service import client as svc
    client = subprocess.Popen(
        [sys.executable, "-m", "semantic_merge_tpu", *MERGE_ARGV],
        cwd=repo, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True,
        env=client_env(sock, SEMMERGE_DAEMON="auto",
                       SEMMERGE_FAULT="service:execute:hang=120"))
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if svc.call_control("status", path=sock)["in_flight"] >= 1:
            break
        time.sleep(0.1)
    else:
        client.kill()
        pytest.fail("request never reached the daemon's execute stage")
    os.kill(daemon_proc.pid, signal.SIGKILL)

    _out, err = client.communicate(timeout=300)
    assert client.returncode == 0, \
        f"auto mode must fall back to a clean one-shot merge: {err}"
    with oneshot_env(ref, {}):
        assert main(MERGE_ARGV) == 0
    assert tree_state(repo) == tree_state(ref), \
        "fallback tree must match the one-shot result"
    assert not (repo / ".semmerge-journal.json").exists()


def test_auto_mode_spawns_daemon_when_absent(tmp_path):
    """auto with no daemon on the socket spawns one (handshake-gated),
    runs the merge warm, and leaves the daemon serving."""
    from semantic_merge_tpu.service import client as svc
    repo = build_repo(tmp_path / "repo")
    sock = str(tmp_path / "auto.sock")
    pid = None
    try:
        proc = run_client(repo, client_env(sock, SEMMERGE_DAEMON="auto"))
        assert proc.returncode == 0, proc.stderr
        assert "bar" in (repo / "src/util.ts").read_text()
        st = svc.call_control("status", path=sock)
        pid = st["pid"]
        assert st["served_total"] >= 1
    finally:
        with contextlib.suppress(Exception):
            svc.call_control("shutdown", path=sock)
        if pid is not None:
            for _ in range(150):
                try:
                    os.kill(pid, 0)
                except OSError:
                    break
                time.sleep(0.1)
            else:
                with contextlib.suppress(OSError):
                    os.kill(pid, signal.SIGKILL)


def test_require_mode_without_daemon_exits_worker_code(tmp_path,
                                                       monkeypatch):
    """Client postures, in-process (spawn stubbed to an immediate
    failure): require → WorkerFault exit; auto → ``None`` (fall back);
    non-verb invocations never delegate."""
    from semantic_merge_tpu.service import client as svc
    assert svc._REQUIRE_FAILED_EXIT == WorkerFault.exit_code
    monkeypatch.setenv("SEMMERGE_SERVICE_SOCKET",
                       str(tmp_path / "absent.sock"))

    class _DeadProc:
        returncode = 1

        def poll(self):
            return self.returncode

    monkeypatch.setattr(svc, "_spawn_daemon", lambda path: _DeadProc())
    monkeypatch.setenv("SEMMERGE_DAEMON", "require")
    assert svc.delegate(["semmerge", "basebr", "brA", "brB"]) == \
        WorkerFault.exit_code
    monkeypatch.setenv("SEMMERGE_DAEMON", "auto")
    assert svc.delegate(["semmerge", "basebr", "brA", "brB"]) is None
    monkeypatch.setenv("SEMMERGE_DAEMON", "require")
    assert svc.delegate(["stats"]) is None


# ---------------------------------------------------------------------------
# Admission control: same-repo serialize, different-repo overlap
# ---------------------------------------------------------------------------


def _fire_requests(sock: str, requests: list) -> list:
    """Issue protocol requests concurrently; return response frames."""
    from semantic_merge_tpu.service import client as svc
    frames = [None] * len(requests)

    def _one(i, params):
        frames[i] = svc.call_verb("semmerge", params, path=sock,
                                  timeout=240)

    threads = [threading.Thread(target=_one, args=(i, p))
               for i, p in enumerate(requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    return frames


def _execute_windows(frames: list) -> list:
    metas = []
    for frame in frames:
        assert frame is not None, "request thread did not complete"
        result = frame.get("result")
        assert result is not None, f"unexpected error frame: {frame}"
        assert result["exit_code"] == 0, result["stderr"]
        assert result["meta"]["queue_wait_s"] >= 0.0
        metas.append(result["meta"])
    return sorted(metas, key=lambda m: m["t_execute_start"])


def test_same_repo_inplace_requests_serialize(tmp_path, service_daemon):
    """Two concurrent ``--inplace`` requests against ONE repo take the
    per-repo lock: their ``service.execute`` windows (opened after the
    lock) must be disjoint. The 1s hang fault makes each window long
    enough that accidental serialization can't explain the result."""
    repo = build_repo(tmp_path / "repo")
    params = {
        "argv": MERGE_ARGV[1:],
        "cwd": str(repo),
        "env": {"SEMMERGE_FAULT": "service:execute:hang=1"},
    }
    first, second = _execute_windows(
        _fire_requests(service_daemon, [dict(params), dict(params)]))
    assert first["t_execute_end"] <= second["t_execute_start"], \
        "same-repo --inplace execute windows must not overlap"
    assert first["t_execute_end"] - first["t_execute_start"] >= 1.0
    assert "bar" in (repo / "src/util.ts").read_text()
    assert not (repo / ".semmerge-journal.json").exists()


def test_different_repo_requests_overlap(tmp_path, service_daemon):
    """Requests against different repos (no --inplace → no repo lock)
    run on the executor pool concurrently: with each request wedged
    1.5s inside execute, the windows must overlap."""
    repos = [build_repo(tmp_path / f"repo{i}") for i in range(2)]
    requests = [{
        "argv": ["basebr", "brA", "brB", "--backend", "host"],
        "cwd": str(repo),
        "env": {"SEMMERGE_FAULT": "service:execute:hang=1.5"},
    } for repo in repos]
    first, second = _execute_windows(
        _fire_requests(service_daemon, requests))
    assert second["t_execute_start"] < first["t_execute_end"], \
        "different-repo requests must execute concurrently"


# ---------------------------------------------------------------------------
# Socket lifecycle units (in-process, no daemon subprocess)
# ---------------------------------------------------------------------------


def test_stale_socket_replaced_live_socket_respected(tmp_path):
    from semantic_merge_tpu.service.daemon import Daemon
    path = str(tmp_path / "svc.sock")
    dead = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    dead.bind(path)
    dead.close()  # the file remains, nothing listens: a stale socket
    assert os.path.exists(path)

    listener = Daemon(socket_path=path)._bind()
    assert listener is not None, "a stale socket must be replaced"
    try:
        probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        probe.connect(path)  # genuinely listening now
        probe.close()
        # A second daemon probing a LIVE socket steps aside.
        assert Daemon(socket_path=path)._bind() is None
    finally:
        listener.close()
