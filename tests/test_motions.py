"""Body-motion extraction (extractMethod / inlineMethod) and the
[CFR-002] ExtractVsInline conflict + [RES-004] extract dedup.

The reference names extract/inline in its op vocabulary (reference
``requirements.md:52``) and gates a conflict category on them
(``requirements.md:98``) but its worker emits neither; detection here
is ``core.difflift.body_motions`` over the already-lifted evidence
(added/deleted decls whose normalized brace block moved into or out of
a body-edited decl). Fixtures keep each decl in its own file: position
shifts would add the reference's spurious ``moveDecl`` quirk ops,
which are orthogonal to what these tests pin.
"""
import json
import subprocess

from semantic_merge_tpu.backends.base import get_backend
from semantic_merge_tpu.core.strict_conflicts import detect_conflicts_strict
from semantic_merge_tpu.frontend.snapshot import Snapshot

TS = "2026-01-01T00:00:00Z"
KW = dict(base_rev="r", seed="s", timestamp=TS, statement_ops=True)

# Bodies avoid inner variable statements: the scanner indexes those as
# decls too (reference buildIndex recursion), and a block moving between
# functions would add the reference's spurious moveDecl for them.
BIG = ("export function big(s: string): string"
       " { return s.trim() + '!'; }\n")
BIG_CALLS = "export function big(s: string): string { return helper(s, 0); }\n"
# helper takes an extra param so its structural symbolId cannot collide
# with big's (name-free signatures collide on shape, SURVEY §3.4).
HELPER = ("export function helper(s: string, pad: number): string"
          " { return s.trim() + '!'; }\n")

UTIL = "export function util(s: string): string { return s.trim(); }\n"
CALLER = ("export function caller(s: string, n: number): string"
          " { return util(s); }\n")
CALLER_INLINED = ("export function caller(s: string, n: number): string"
                  " { return s.trim(); }\n")


def _snap(**files):
    return Snapshot(files=[{"path": p + ".ts", "content": c}
                           for p, c in sorted(files.items())])


BASE_EXTRACT = _snap(big=BIG)
SIDE_EXTRACT = _snap(big=BIG_CALLS, helper=HELPER)

BASE_INLINE = _snap(caller=CALLER, util=UTIL)
SIDE_INLINE = _snap(caller=CALLER_INLINED, util="")


def test_extract_detected():
    ops = get_backend("host").diff(BASE_EXTRACT, SIDE_EXTRACT, **KW)
    by_type = {o.type: o for o in ops}
    assert set(by_type) == {"addDecl", "editStmtBlock", "extractMethod"}
    ext = by_type["extractMethod"]
    # The motion targets the SOURCE decl (big) and names the new one.
    assert ext.target.symbolId == by_type["editStmtBlock"].target.symbolId
    assert ext.params["newName"] == "helper"
    assert ext.params["newAddress"] == by_type["addDecl"].target.addressId
    assert ext.params["blockHash"]


def test_inline_detected():
    ops = get_backend("host").diff(BASE_INLINE, SIDE_INLINE, **KW)
    by_type = {o.type: o for o in ops}
    assert set(by_type) == {"deleteDecl", "editStmtBlock", "inlineMethod"}
    inl = by_type["inlineMethod"]
    assert inl.target.symbolId == by_type["editStmtBlock"].target.symbolId
    assert inl.params["methodName"] == "util"
    assert inl.params["oldAddress"] == by_type["deleteDecl"].target.addressId


def test_motion_ids_deterministic():
    a = get_backend("host").diff(BASE_EXTRACT, SIDE_EXTRACT, **KW)
    b = get_backend("host").diff(BASE_EXTRACT, SIDE_EXTRACT, **KW)
    assert [o.to_dict() for o in a] == [o.to_dict() for o in b]


def test_no_motion_without_body_match():
    # The added decl's body never lived in the edited decl: no marker.
    side = _snap(
        big="export function big(s: string): string { return 'x'; }\n",
        helper=("export function helper(s: string, pad: number): string"
                " { return 'fresh'; }\n"))
    ops = get_backend("host").diff(BASE_EXTRACT, side, **KW)
    assert not [o for o in ops if o.type == "extractMethod"]


BLOCK = "{ return s.trim(); }"
CVI_BASE = _snap(
    big="export function big(s: string): string " + BLOCK + "\n",
    util=("export function util(s: string, n: number): string "
          + BLOCK + "\n"),
    caller=("export function caller(s: string, n: number, b: boolean):"
            " string { return util(s, 0); }\n"))
# Branch A: extract big's block into a new decl (new file, no shifts).
CVI_A = _snap(
    big="export function big(s: string): string { return ex(s, 0, 0); }\n",
    ex=("export function ex(s: string, x: number, y: number): string "
        + BLOCK + "\n"),
    util=("export function util(s: string, n: number): string "
          + BLOCK + "\n"),
    caller=("export function caller(s: string, n: number, b: boolean):"
            " string { return util(s, 0); }\n"))
# Branch B: inline util (same block text) into caller, delete util.
CVI_B = _snap(
    big="export function big(s: string): string " + BLOCK + "\n",
    util="",
    caller=("export function caller(s: string, n: number, b: boolean):"
            " string " + BLOCK + "\n"))


def test_extract_vs_inline_conflict():
    bk = get_backend("host")
    res = bk.build_and_diff(CVI_BASE, CVI_A, CVI_B, **KW)
    assert [o.type for o in res.op_log_left].count("extractMethod") == 1
    assert [o.type for o in res.op_log_right].count("inlineMethod") == 1
    kept_a, kept_b, conflicts = detect_conflicts_strict(
        res.op_log_left, res.op_log_right)
    assert [c.category for c in conflicts] == ["ExtractVsInline"]
    # The conflict consumes the motions AND their text-level companions;
    # nothing about either motion leaks into the residual streams.
    assert kept_a == [] and kept_b == []
    d = conflicts[0].to_dict()
    assert {s["id"] for s in d["suggestions"]} == {"keepExtract", "keepInline"}


def test_res004_dedup_identical_extracts():
    bk = get_backend("host")
    res = bk.build_and_diff(BASE_EXTRACT, SIDE_EXTRACT, SIDE_EXTRACT, **KW)
    kept_a, kept_b, conflicts = detect_conflicts_strict(
        res.op_log_left, res.op_log_right)
    assert conflicts == []
    # A keeps its declaration; B's duplicate addDecl and marker drop.
    assert [o.type for o in kept_a].count("addDecl") == 1
    assert [o.type for o in kept_b].count("addDecl") == 0
    assert [o.type for o in kept_b].count("extractMethod") == 0
    # Identical residual body edits agree and pass through on both sides.
    assert [o.type for o in kept_b].count("editStmtBlock") == 1


def test_block_match_requires_identifier_boundaries():
    # `return x + 1;` must not "match" inside `return max + 1;` — a raw
    # substring check would mint a motion for code that never moved.
    base = _snap(big=("export function big(m: number): number"
                      " { const max = m; return max + 1; }\n"))
    side = _snap(
        big="export function big(m: number): number { return m; }\n",
        helper=("export function helper(x: number, pad: number): number"
                " { return x + 1; }\n"))
    ops = get_backend("host").diff(base, side, **KW)
    assert not [o for o in ops if o.type == "extractMethod"]


def test_differently_named_extracts_do_not_dedup():
    # Same block, same source decl, DIFFERENT new names: not duplicates.
    # B's declaration must survive (its residual body calls it); the
    # differing residual edits surface as ConcurrentStmtEdit instead of
    # B's helper silently vanishing.
    side_b = _snap(
        big="export function big(s: string): string { return other(s, 0); }\n",
        other=("export function other(s: string, pad: number): string"
               " { return s.trim() + '!'; }\n"))
    bk = get_backend("host")
    res = bk.build_and_diff(BASE_EXTRACT, SIDE_EXTRACT, side_b, **KW)
    kept_a, kept_b, conflicts = detect_conflicts_strict(
        res.op_log_left, res.op_log_right)
    assert [o.type for o in kept_a].count("addDecl") == 1
    assert [o.type for o in kept_b].count("addDecl") == 1
    assert any(c.category == "ConcurrentStmtEdit" for c in conflicts)


def test_different_bodies_keep_both():
    # [RES-004] second clause: concurrent extracts with DIFFERENT
    # bodies keep both declarations — no dedup, no ExtractVsInline.
    side_b = _snap(
        big="export function big(s: string): string { return helper(s, 1); }\n",
        helper=("export function helper(s: string, pad: number): string"
                " { return s.trim(); }\n"))
    bk = get_backend("host")
    res = bk.build_and_diff(BASE_EXTRACT, SIDE_EXTRACT, side_b, **KW)
    kept_a, kept_b, conflicts = detect_conflicts_strict(
        res.op_log_left, res.op_log_right)
    assert not [c for c in conflicts if c.category == "ExtractVsInline"]
    assert [o.type for o in kept_a].count("addDecl") == 1
    assert [o.type for o in kept_b].count("addDecl") == 1


def test_backend_parity_motions():
    """Host and TPU backends emit identical motion markers (shared
    lift_statements tail)."""
    import pytest
    pytest.importorskip("jax")
    rh = get_backend("host").diff(BASE_EXTRACT, SIDE_EXTRACT, **KW)
    rt = get_backend("tpu").diff(BASE_EXTRACT, SIDE_EXTRACT, **KW)
    assert [o.to_dict() for o in rh] == [o.to_dict() for o in rt]


def test_cli_extract_vs_inline_end_to_end(tmp_path, monkeypatch):
    def git(*args):
        subprocess.run(["git", *args], cwd=tmp_path, check=True,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    def write_snapshot(snap):
        for f in snap.files:
            (tmp_path / f["path"]).write_text(f["content"])

    write_snapshot(CVI_BASE)
    git("init", "-q", "-b", "main")
    git("config", "user.email", "t@e")
    git("config", "user.name", "t")
    git("add", "-A")
    git("commit", "-qm", "base")
    git("branch", "basebr")
    git("checkout", "-qb", "ba")
    write_snapshot(CVI_A)
    git("add", "-A")
    git("commit", "-qam", "extract")
    git("checkout", "-q", "main")
    git("checkout", "-qb", "bb")
    write_snapshot(CVI_B)  # util.ts emptied: scanner sees no decls
    git("commit", "-qam", "inline")
    git("checkout", "-q", "main")

    monkeypatch.chdir(tmp_path)
    from semantic_merge_tpu.cli import main
    rc = main(["semmerge", "basebr", "ba", "bb", "--backend", "host",
               "--strict-conflicts"])
    assert rc == 1
    payload = json.loads((tmp_path / ".semmerge-conflicts.json").read_text())
    assert any(c["category"] == "ExtractVsInline" for c in payload)


def test_trivial_blocks_are_not_motion_evidence():
    """A trivial shared block (the bare `return null;` class) must not
    mint motion markers: content-only blockHash would otherwise join
    opposite-side trivial "motions" into a false ExtractVsInline abort
    of a clean merge (ADVICE round 5). The gate is
    core.difflift._block_significant: ≥2 statements or >15 chars."""
    base = _snap(
        big="export function big(s: string): string { return null; }\n",
        util=("export function util(s: string, n: number): string"
              " { return null; }\n"))
    # A "extracts" big's trivial block; B "inlines" util's — both
    # coincidences, neither a motion.
    side_a = _snap(
        big="export function big(s: string): string { return ex(s); }\n",
        ex=("export function ex(s: string, x: number): string"
            " { return null; }\n"),
        util=("export function util(s: string, n: number): string"
              " { return null; }\n"))
    side_b = _snap(
        big="export function big(s: string): string { return null; }\n",
        util="")
    bk = get_backend("host")
    assert not [o for o in bk.diff(BASE_EXTRACT, side_a, **KW)
                if o.type == "extractMethod"]
    res = bk.build_and_diff(base, side_a, side_b, **KW)
    assert not [o for o in res.op_log_left
                if o.type in ("extractMethod", "inlineMethod")]
    assert not [o for o in res.op_log_right
                if o.type in ("extractMethod", "inlineMethod")]
    kept_a, kept_b, conflicts = detect_conflicts_strict(
        res.op_log_left, res.op_log_right)
    assert not [c for c in conflicts if c.category == "ExtractVsInline"]


def test_two_trivial_statements_are_motion_evidence():
    """The statement-count arm of the gate: two short statements pass
    even when the char arm alone would not."""
    from semantic_merge_tpu.core.difflift import _block_significant
    assert not _block_significant("return null;")
    assert _block_significant("a();b();")       # 2 statements, 8 chars
    assert _block_significant("return s.trim();")  # 16 chars > 15
