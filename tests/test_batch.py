"""Continuous-batching parity matrix (ISSUE 8 tentpole).

Parity is the hard gate: every merge coalesced into a fused
multi-merge dispatch must produce byte-identical observable output —
op logs, composed op stream, conflict artifacts — to the same merge
run unbatched. The matrix covers requests straddling bucket-ladder
rungs, empty merges, conflict-bearing merges, mixed repos sharing one
batch window, and one member degrading mid-flight while its co-batched
neighbours complete normally. Posture semantics (``SEMMERGE_BATCH`` =
off / auto / require) are exercised both in-process and over the
service wire, where the client's posture rides the request env
overlay.
"""
import contextlib
import hashlib
import json
import os
import pathlib
import subprocess
import sys
import threading

import pytest

from semantic_merge_tpu import batch
from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
from semantic_merge_tpu.errors import BatchFault
from semantic_merge_tpu.obs import metrics as obs_metrics
from semantic_merge_tpu.frontend.snapshot import Snapshot
from semantic_merge_tpu.utils import faults, reqenv

from bench import synth_repo

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------

def fingerprint(merge_result):
    """Byte-comparable form of everything a merge observably produces:
    both op logs, the composed stream, and the conflict artifacts."""
    result, composed, conflicts = merge_result
    return (
        [op.to_dict() for op in result.op_log_left],
        [op.to_dict() for op in result.op_log_right],
        [op.to_dict() for op in composed],
        [c.to_dict() for c in conflicts],
    )


def baseline(snaps):
    """Unbatched reference run on a fresh single-device backend (no
    scheduler is active when this is called)."""
    assert batch.current() is None
    return fingerprint(TpuTSBackend(mesh=False).merge(*snaps))


@contextlib.contextmanager
def active_batching(**kwargs):
    batch.activate(**kwargs)
    try:
        yield batch.current()
    finally:
        batch.deactivate()


def run_concurrent(jobs):
    """Run ``jobs`` — a list of ``(snapshots, overlay_env_or_None)`` —
    concurrently, one thread per job, released together so they land in
    the same batch window. Each thread owns a fresh backend (pre-warmed
    through the bypass posture so the measured merge's host phases are
    fast enough to co-batch). Returns per-job fingerprints; re-raises
    the first per-thread error."""
    n = len(jobs)
    results = [None] * n
    errors = [None] * n
    barrier = threading.Barrier(n)

    def work(i, snaps, env):
        try:
            be = TpuTSBackend(mesh=False)
            with reqenv.overlay({batch.ENV_POSTURE: "off"}):
                be.merge(*snaps)  # warm caches off the batched path
            barrier.wait()
            with reqenv.overlay(env or {}):
                results[i] = fingerprint(be.merge(*snaps))
        except BaseException as exc:  # noqa: BLE001 - surfaced below
            errors[i] = exc
            with contextlib.suppress(threading.BrokenBarrierError):
                barrier.abort()

    threads = [threading.Thread(target=work, args=(i, snaps, env))
               for i, (snaps, env) in enumerate(jobs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    for exc in errors:
        if exc is not None:
            raise exc
    return results


def outcome_total(outcome: str) -> float:
    return obs_metrics.REGISTRY.counter(
        "batch_requests_total").value(outcome=outcome)


@pytest.fixture
def single_device(monkeypatch):
    """Pin the batch-eligible engine shape: the test mesh (8 virtual
    CPU devices, conftest) would otherwise auto-shard every backend and
    make each merge batch-ineligible."""
    monkeypatch.setenv("SEMMERGE_MESH", "off")
    faults.reset()
    yield
    batch.deactivate()
    faults.reset()


# ---------------------------------------------------------------------------
# Co-batched parity
# ---------------------------------------------------------------------------

def test_cobatched_same_shape_parity(single_device):
    """Four identically-shaped concurrent merges coalesce into fused
    multi-merge dispatches and stay byte-identical to unbatched runs."""
    snaps = synth_repo(4, 2)
    want = baseline(snaps)
    with active_batching(window_ms=100.0) as sched:
        got = run_concurrent([(snaps, None)] * 4)
        stats = sched.stats()
    for i, fp in enumerate(got):
        assert fp == want, f"request {i} diverged from the unbatched run"
    assert stats["requests_batched"] == 4
    assert stats["mean_batch_size"] > 1.0, \
        "identically-shaped concurrent requests must co-batch"


def test_bucket_ladder_straddle_parity(single_device):
    """Requests straddling bucket-ladder rungs — plus an empty merge
    and a conflict-bearing one — share a window; each lands in its own
    shape group and every result matches its unbatched run."""
    base, _, _ = synth_repo(4, 2)
    scenarios = [
        synth_repo(3, 2),                   # small rung
        synth_repo(6, 3),                   # middle rung
        synth_repo(12, 2),                  # straddles the next rung
        (base, base, base),                 # empty merge: zero ops
        synth_repo(6, 2, divergent=True),   # conflict-bearing
    ]
    want = [baseline(s) for s in scenarios]
    assert want[3][2] == [], "identical snapshots must compose to no ops"
    assert want[4][3], "the divergent scenario must carry a conflict"
    with active_batching(window_ms=100.0) as sched:
        got = run_concurrent([(s, None) for s in scenarios])
        stats = sched.stats()
    for i, fp in enumerate(got):
        assert fp == want[i], f"scenario {i} diverged from its unbatched run"
    assert stats["requests_batched"] == len(scenarios)


def test_mixed_repos_one_window_parity(single_device):
    """Two DIFFERENT repos whose encoded shapes share a co-batch key
    ride the same batched dispatch; rows scatter back to the right
    request (the scope-collision hazard of cross-repo batching)."""
    snaps_a = synth_repo(4, 2)

    def relocate(snap: Snapshot) -> Snapshot:
        return Snapshot(files=[{**f, "path": "pkg/" + f["path"]}
                               for f in snap.files], project=snap.project)

    snaps_b = tuple(relocate(s) for s in snaps_a)
    want_a, want_b = baseline(snaps_a), baseline(snaps_b)
    assert want_a != want_b, "relocation must change the observable ops"
    with active_batching(window_ms=100.0) as sched:
        got = run_concurrent([(snaps_a, None), (snaps_b, None),
                              (snaps_a, None), (snaps_b, None)])
        stats = sched.stats()
    assert got[0] == want_a and got[2] == want_a
    assert got[1] == want_b and got[3] == want_b
    assert stats["requests_batched"] == 4
    assert stats["mean_batch_size"] > 1.0, \
        "same-shape merges from different repos must co-batch"


# ---------------------------------------------------------------------------
# Mid-flight degradation: affected request only
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("stage", ["batch:pack", "batch:dispatch",
                                   "batch:scatter"])
def test_midflight_fault_degrades_only_affected_request(single_device, stage):
    """A batching fault on ONE member of a window degrades that request
    to the inline unbatched dispatch; its co-batched neighbour completes
    normally. Both results stay byte-identical to the unbatched run.
    (The fourth request-side stage, ``batch:mesh``, is drilled end-to-
    end in test_faults.py — same degradation contract plus the
    fallback-counter increment.)"""
    snaps = synth_repo(4, 2)
    want = baseline(snaps)
    degraded_before = outcome_total("degraded")
    batched_before = outcome_total("batched")
    with active_batching(window_ms=100.0):
        got = run_concurrent([
            (snaps, {"SEMMERGE_FAULT": f"{stage}:fault"}),
            (snaps, None),
        ])
    assert got[0] == want, "the degraded request must still merge correctly"
    assert got[1] == want, "the co-batched neighbour must be untouched"
    assert outcome_total("degraded") >= degraded_before + 1
    assert outcome_total("batched") >= batched_before + 1


# ---------------------------------------------------------------------------
# Posture semantics (in-process)
# ---------------------------------------------------------------------------

def test_posture_off_bypasses_subsystem(single_device):
    """``SEMMERGE_BATCH=off`` routes around the scheduler entirely:
    no batch is formed and the run matches the unbatched result."""
    snaps = synth_repo(4, 2)
    want = baseline(snaps)
    bypass_before = outcome_total("bypass")
    with active_batching(window_ms=20.0) as sched:
        with reqenv.overlay({batch.ENV_POSTURE: "off"}):
            got = fingerprint(TpuTSBackend(mesh=False).merge(*snaps))
        stats = sched.stats()
    assert got == want
    assert stats["requests_batched"] == 0, \
        "off posture must never enqueue into the scheduler"
    assert outcome_total("bypass") >= bypass_before + 1


def test_posture_require_without_scheduler_raises():
    """``require`` with no active scheduler is unsatisfiable — a typed
    BatchFault (exit 16), never a silent inline run."""
    assert batch.current() is None
    with reqenv.overlay({batch.ENV_POSTURE: "require"}):
        with pytest.raises(BatchFault) as exc_info:
            batch.plan_for_request(eligible=True)
    assert exc_info.value.exit_code == 16


def test_posture_require_ineligible_engine_raises(single_device):
    """``require`` on a mesh-sharded (batch-ineligible) engine is
    unsatisfiable too; ``auto`` quietly bypasses instead."""
    with active_batching(window_ms=20.0):
        with reqenv.overlay({batch.ENV_POSTURE: "require"}):
            with pytest.raises(BatchFault):
                batch.plan_for_request(eligible=False)
        assert batch.plan_for_request(eligible=False) is None


# ---------------------------------------------------------------------------
# Posture semantics over the service wire (satellite: reqenv overlay)
# ---------------------------------------------------------------------------

def _git(args, cwd):
    subprocess.run(["git", *args], cwd=cwd, check=True,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def _make_repo(root: pathlib.Path) -> pathlib.Path:
    """basebr/brA/brB repo whose semantic merge equals its textual
    merge (disjoint edits) — the shared fault-matrix shape."""
    root.mkdir()
    _git(["init", "-q", "-b", "main"], root)
    _git(["config", "user.email", "t@example.com"], root)
    _git(["config", "user.name", "t"], root)
    (root / "src").mkdir()
    (root / "src/util.ts").write_text(
        "export function foo(n: number): number {\n  return n;\n}\n")
    _git(["add", "-A"], root)
    _git(["commit", "-q", "-m", "base"], root)
    _git(["branch", "basebr"], root)
    _git(["checkout", "-qb", "brA"], root)
    (root / "src/util.ts").write_text(
        "export function bar(n: number): number {\n  return n;\n}\n")
    _git(["add", "-A"], root)
    _git(["commit", "-q", "-m", "rename"], root)
    _git(["checkout", "-q", "main"], root)
    _git(["checkout", "-qb", "brB"], root)
    (root / "extra.ts").write_text(
        "export function extra(s: string): string { return s; }\n")
    _git(["add", "-A"], root)
    _git(["commit", "-q", "-m", "add extra"], root)
    _git(["checkout", "-q", "main"], root)
    return root


def _wire_env(sock: str, **extra) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    env["SEMMERGE_DAEMON"] = "require"
    env["SEMMERGE_SERVICE_SOCKET"] = sock
    env.pop("SEMMERGE_FAULT", None)
    env.update(extra)
    return env


def test_wire_postures_honored_inside_daemon(tmp_path, daemon_factory):
    """The client's ``SEMMERGE_BATCH`` posture rides the request env
    overlay into the daemon: ``require`` merges on the batched path,
    ``off`` bypasses the scheduler — both visible in daemon status."""
    from semantic_merge_tpu.service import client as service_client
    sock = str(tmp_path / "batch.sock")
    daemon_factory(sock, extra_env={
        # Pin the daemon's engine to the batch-eligible single-device
        # shape despite the test harness's 8-device XLA_FLAGS.
        "SEMMERGE_MESH": "off",
        "SEMMERGE_BATCH_WINDOW_MS": "5",
    })

    def merge_in(repo: pathlib.Path, posture: str) -> None:
        proc = subprocess.run(
            [sys.executable, "-m", "semantic_merge_tpu", "semmerge",
             "basebr", "brA", "brB", "--inplace", "--backend", "tpu"],
            cwd=repo, capture_output=True, text=True,
            env=_wire_env(sock, SEMMERGE_BATCH=posture))
        assert proc.returncode == 0, \
            f"{posture} posture over the wire failed: {proc.stderr}"
        assert "bar" in (repo / "src/util.ts").read_text()
        assert (repo / "extra.ts").exists()

    def wire_outcome(status: dict, outcome: str) -> float:
        series = (status["metrics"].get("counters", {})
                  .get("batch_requests_total", {}).get("series", []))
        return sum(s["value"] for s in series
                   if s.get("labels", {}).get("outcome") == outcome)

    merge_in(_make_repo(tmp_path / "require_repo"), "require")
    status = service_client.call_control("status", path=sock)
    assert status["batch"] is not None, "daemon must expose batch stats"
    batched_after_require = status["batch"]["requests_batched"]
    assert batched_after_require >= 1, \
        "require posture must land on the batched path"
    assert wire_outcome(status, "batched") >= 1

    merge_in(_make_repo(tmp_path / "off_repo"), "off")
    status = service_client.call_control("status", path=sock)
    assert wire_outcome(status, "bypass") >= 1, \
        "off posture must bypass the scheduler inside the daemon"
    assert status["batch"]["requests_batched"] == batched_after_require, \
        "off posture must never enqueue into the scheduler"


def _make_resolve_repo(root: pathlib.Path) -> pathlib.Path:
    """DivergentRename with asymmetric evidence (brA rewrote the call
    site): the search resolver accepts ``keepA`` and the merge exits 0.
    Commit dates are pinned so two builds are sha-identical and their
    conflicts artifacts compare equal."""
    root.mkdir()
    _git(["init", "-q", "-b", "main"], root)
    _git(["config", "user.email", "t@example.com"], root)
    _git(["config", "user.name", "t"], root)
    env = dict(os.environ,
               GIT_AUTHOR_DATE="2024-01-01T00:00:00Z",
               GIT_COMMITTER_DATE="2024-01-01T00:00:00Z")

    def commit(msg):
        subprocess.run(["git", "add", "-A"], cwd=root, check=True,
                       stdout=subprocess.DEVNULL)
        subprocess.run(["git", "commit", "-q", "-m", msg], cwd=root,
                       check=True, env=env, stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL)

    (root / "src").mkdir()
    (root / "src/util.ts").write_text(
        "export function foo(n: number): number {\n  return n;\n}\n"
        "export function use(s: string): number {\n"
        "  return foo(s.length);\n}\n")
    commit("base")
    _git(["branch", "basebr"], root)
    _git(["checkout", "-qb", "brA"], root)
    (root / "src/util.ts").write_text(
        "export function bar(n: number): number {\n  return n;\n}\n"
        "export function use(s: string): number {\n"
        "  return bar(s.length);\n}\n")
    commit("rename foo->bar")
    _git(["checkout", "-q", "main"], root)
    _git(["checkout", "-qb", "brB"], root)
    (root / "src/util.ts").write_text(
        "export function baz(n: number): number {\n  return n;\n}\n"
        "export function use(s: string): number {\n"
        "  return foo(s.length);\n}\n")
    commit("rename foo->baz decl-only")
    _git(["checkout", "-q", "main"], root)
    return root


def _normalized_artifact(path: pathlib.Path):
    """Conflicts artifact with per-gate wall-clock stripped — gate
    timings are the only nondeterministic field in the audit trail."""
    payload = json.loads(path.read_text())
    if isinstance(payload, dict):
        for rec in payload.get("resolutions", []):
            for gate in rec.get("gates", []):
                gate.pop("ms", None)
    return payload


def test_wire_resolve_parity_on_batched_path(tmp_path, daemon_factory):
    """``SEMMERGE_RESOLVE`` rides the request env overlay onto the
    BATCHED daemon path: the same conflict repo merged one-shot
    (unbatched) and through a batch-require daemon yields byte-identical
    trees and audited conflicts artifacts, and the daemon's batch stats
    prove the request actually took the batched dispatch."""
    from semantic_merge_tpu.service import client as service_client
    sock = str(tmp_path / "resolve.sock")
    daemon_factory(sock, extra_env={
        "SEMMERGE_MESH": "off",
        "SEMMERGE_BATCH_WINDOW_MS": "5",
    })
    one = _make_resolve_repo(tmp_path / "oneshot")
    two = _make_resolve_repo(tmp_path / "batched")
    argv = [sys.executable, "-m", "semantic_merge_tpu", "semmerge",
            "basebr", "brA", "brB", "--inplace", "--backend", "tpu"]

    env_one = dict(os.environ)
    env_one.update({"PYTHONPATH": str(REPO_ROOT), "JAX_PLATFORMS": "cpu",
                    "SEMMERGE_DAEMON": "off", "SEMMERGE_MESH": "off",
                    "SEMMERGE_RESOLVE": "auto"})
    env_one.pop("SEMMERGE_FAULT", None)
    proc = subprocess.run(argv, cwd=one, capture_output=True, text=True,
                          env=env_one)
    assert proc.returncode == 0, f"one-shot resolve failed: {proc.stderr}"

    proc = subprocess.run(argv, cwd=two, capture_output=True, text=True,
                          env=_wire_env(sock, SEMMERGE_BATCH="require",
                                        SEMMERGE_RESOLVE="auto"))
    assert proc.returncode == 0, \
        f"batched resolve over the wire failed: {proc.stderr}"

    want = (one / "src/util.ts").read_text()
    assert "bar(s.length)" in want and "baz" not in want
    assert (two / "src/util.ts").read_text() == want, \
        "batched and one-shot resolver runs must produce identical trees"
    pay_one = _normalized_artifact(one / ".semmerge-conflicts.json")
    pay_two = _normalized_artifact(two / ".semmerge-conflicts.json")
    assert pay_one == pay_two
    assert pay_one["schema_version"] == 2
    assert {r["status"] for r in pay_one["resolutions"]} == {"accepted"}

    status = service_client.call_control("status", path=sock)
    assert status["batch"]["requests_batched"] >= 1, \
        "require posture must land the resolver merge on the batched path"


# ---------------------------------------------------------------------------
# Mesh-sharded dispatch (ISSUE 13 tentpole): byte parity vs single-device
# ---------------------------------------------------------------------------

@pytest.fixture
def mesh_batching(monkeypatch):
    """Mesh posture ON for the dispatcher while every backend stays
    batch-eligible: the test backends are built ``mesh=False``
    explicitly, so the 8 virtual devices (conftest) belong to the
    batch mesh alone."""
    monkeypatch.delenv("SEMMERGE_MESH", raising=False)
    faults.reset()
    yield monkeypatch
    batch.deactivate()
    faults.reset()


@pytest.mark.slow
def test_mesh_cobatch_parity(mesh_batching):
    """The mesh-sharded batched program is byte-identical to the
    unbatched single-device run for a padding-heavy co-batch (2 same-
    shape merges on an 8-chip mesh pad to 8 rows) and a conflict-
    bearing one. Run under ``require`` — the posture that faults
    rather than silently narrowing, so a mesh that failed to form
    cannot fake parity. ``auto`` takes the identical code path once
    the mesh forms; its fallback branches are covered by
    test_mesh_require_unsatisfiable_on_single_chip,
    test_mesh_posture_parsing, and the test_faults.py mesh drill.
    Bucket-straddling and resolver-active meshed co-batches live in
    the slow tier (the wire tests below)."""
    posture = "require"
    mesh_batching.setenv("SEMMERGE_MESH", posture)
    scenarios = [
        synth_repo(4, 2), synth_repo(4, 2),
        synth_repo(6, 2, divergent=True),    # conflict-bearing
    ]
    want = [baseline(s) for s in scenarios]
    assert want[2][3], "the divergent scenario must carry a conflict"
    with active_batching(window_ms=100.0) as sched:
        got = run_concurrent([(s, None) for s in scenarios])
        stats = sched.stats()
    for i, fp in enumerate(got):
        assert fp == want[i], \
            f"scenario {i} diverged from its unbatched run under {posture}"
    mesh = stats["mesh"]
    assert mesh["mesh_dispatches"] >= 1, \
        "the packed merge axis must actually shard across the chips"
    assert mesh["last_shape"] == "batch=8"
    assert sum(mesh["last_chip_rows"]) >= 1
    assert stats["requests_batched"] == len(scenarios)
    occupancy = obs_metrics.REGISTRY.gauge(
        "batch_mesh_occupancy_ratio").value()
    assert 0.0 < occupancy <= 1.0


@pytest.mark.slow
def test_wire_mesh_resolver_parity(tmp_path, daemon_factory):
    """An ACTIVE search resolver rides the mesh-sharded batched path
    byte-identically: rows scatter per request, so the resolver tier
    runs on the request thread exactly as it does single-device — same
    merged tree, same audited conflicts artifact."""
    from semantic_merge_tpu.service import client as service_client
    sock = str(tmp_path / "meshres.sock")
    daemon_factory(sock, extra_env={
        "SEMMERGE_MESH": "require",
        "SEMMERGE_BATCH_WINDOW_MS": "5",
    })
    one = _make_resolve_repo(tmp_path / "oneshot")
    two = _make_resolve_repo(tmp_path / "meshed")
    argv = [sys.executable, "-m", "semantic_merge_tpu", "semmerge",
            "basebr", "brA", "brB", "--inplace", "--backend", "tpu"]

    env_one = dict(os.environ)
    env_one.update({"PYTHONPATH": str(REPO_ROOT), "JAX_PLATFORMS": "cpu",
                    "SEMMERGE_DAEMON": "off", "SEMMERGE_MESH": "off",
                    "SEMMERGE_RESOLVE": "auto"})
    env_one.pop("SEMMERGE_FAULT", None)
    proc = subprocess.run(argv, cwd=one, capture_output=True, text=True,
                          env=env_one)
    assert proc.returncode == 0, f"one-shot resolve failed: {proc.stderr}"

    proc = subprocess.run(argv, cwd=two, capture_output=True, text=True,
                          env=_wire_env(sock, SEMMERGE_BATCH="require",
                                        SEMMERGE_MESH="require",
                                        SEMMERGE_RESOLVE="auto"))
    assert proc.returncode == 0, \
        f"mesh resolve over the wire failed: {proc.stderr}"
    assert (two / "src/util.ts").read_text() == \
        (one / "src/util.ts").read_text()
    assert _normalized_artifact(two / ".semmerge-conflicts.json") == \
        _normalized_artifact(one / ".semmerge-conflicts.json")
    status = service_client.call_control("status", path=sock)
    assert status["batch"]["mesh"]["mesh_dispatches"] >= 1


def test_mesh_require_unsatisfiable_on_single_chip(mesh_batching):
    """Leader-side planning: a 1-chip host under ``require`` raises
    the typed MeshFault (exit 18); ``auto`` falls back to the
    single-device program and counts the fallback."""
    from semantic_merge_tpu.batch import dispatcher
    from semantic_merge_tpu.errors import MeshFault
    from semantic_merge_tpu.parallel import mesh as mesh_mod
    mesh_batching.setattr(mesh_mod, "batch_mesh_shards",
                          lambda devices=None: 1)
    fallbacks = obs_metrics.REGISTRY.counter("batch_mesh_fallbacks_total")
    before = fallbacks.value(reason="single-device")
    with pytest.raises(MeshFault) as exc_info:
        dispatcher._plan_mesh("require")
    assert exc_info.value.exit_code == 18
    assert dispatcher._plan_mesh("auto") == (None, 1)
    assert fallbacks.value(reason="single-device") >= before + 2


def test_mesh_posture_parsing(mesh_batching):
    """One posture definition: env overlay wins over the configured
    value, legacy off-aliases keep working, unknown values read as
    ``auto``."""
    from semantic_merge_tpu.parallel.mesh import mesh_posture
    assert mesh_posture() == "auto"
    assert mesh_posture("require") == "require"
    assert mesh_posture("off") == "off"
    for alias in ("none", "single", "0"):
        mesh_batching.setenv("SEMMERGE_MESH", alias)
        assert mesh_posture() == "off", f"legacy alias {alias!r}"
        assert mesh_posture("require") == "off", "env must beat config"
    mesh_batching.setenv("SEMMERGE_MESH", "bogus")
    assert mesh_posture() == "auto"
    with reqenv.overlay({"SEMMERGE_MESH": "require"}):
        assert mesh_posture("off") == "require", \
            "the per-request overlay must win over config"


@pytest.mark.slow
def test_wire_mesh_parity_and_status(tmp_path, daemon_factory):
    """Over-the-wire mesh parity: the same repo merged one-shot
    (mesh off) and through a SEMMERGE_MESH=require daemon on the
    batched path yields byte-identical trees, and the daemon status
    exposes the mesh shape, per-chip occupancy and fallback counts."""
    from semantic_merge_tpu.service import client as service_client
    sock = str(tmp_path / "mesh.sock")
    daemon_factory(sock, extra_env={
        "SEMMERGE_MESH": "require",
        "SEMMERGE_BATCH_WINDOW_MS": "5",
    })
    one = _make_repo(tmp_path / "oneshot_repo")
    two = _make_repo(tmp_path / "mesh_repo")
    argv = [sys.executable, "-m", "semantic_merge_tpu", "semmerge",
            "basebr", "brA", "brB", "--inplace", "--backend", "tpu"]

    env_one = dict(os.environ)
    env_one.update({"PYTHONPATH": str(REPO_ROOT), "JAX_PLATFORMS": "cpu",
                    "SEMMERGE_DAEMON": "off", "SEMMERGE_MESH": "off"})
    env_one.pop("SEMMERGE_FAULT", None)
    proc = subprocess.run(argv, cwd=one, capture_output=True, text=True,
                          env=env_one)
    assert proc.returncode == 0, f"one-shot merge failed: {proc.stderr}"

    proc = subprocess.run(argv, cwd=two, capture_output=True, text=True,
                          env=_wire_env(sock, SEMMERGE_BATCH="require",
                                        SEMMERGE_MESH="require"))
    assert proc.returncode == 0, \
        f"mesh-require merge over the wire failed: {proc.stderr}"

    for rel in ("src/util.ts", "extra.ts"):
        assert (two / rel).read_bytes() == (one / rel).read_bytes(), \
            f"{rel}: mesh and single-device trees must be byte-identical"

    status = service_client.call_control("status", path=sock)
    mesh = status["batch"]["mesh"]
    assert mesh["posture"] == "require"
    assert mesh["mesh_dispatches"] >= 1, \
        "require posture must land on the mesh-sharded program"
    assert mesh["last_shape"] == "batch=8"
    assert sum(mesh["last_chip_rows"]) >= 1
    assert "dispatch-error" not in mesh["fallbacks"], \
        "the mesh program must not silently fall back per dispatch"


# ---------------------------------------------------------------------------
# Device-scale fuzz (slow: real windows at service concurrency)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_batchserve_scale_parity(single_device):
    """Concurrency-16 fuzz at bench-preset shapes: parity holds for
    every request and batches actually form (mean size > 1)."""
    shapes = [(4, 2), (6, 3), (12, 2), (6, 2)]
    scenarios = [synth_repo(*shapes[i % len(shapes)],
                            divergent=(i % 5 == 0)) for i in range(16)]
    want = [baseline(s) for s in scenarios]
    with active_batching(window_ms=100.0) as sched:
        got = run_concurrent([(s, None) for s in scenarios])
        stats = sched.stats()
    for i, fp in enumerate(got):
        assert fp == want[i], f"request {i} diverged at concurrency 16"
    assert stats["requests_batched"] == 16
    assert stats["mean_batch_size"] > 1.0
    assert 0.0 <= stats["padding_waste_ratio"] <= 1.0
    assert stats["program_cache"]["programs"] >= 1
