"""Perf-regression sentinel (ISSUE 11): the obs.perf core, the
``semmerge perf record|compare`` CLI, and the standalone
``scripts/perf_gate.py`` CI gate.

Direction rules under test: ``*/sec`` units are higher-better, wall
units (``ms``/``seconds``/``pct``) lower-better, phase walls always
lower-better with a noise floor; new snapshots without a baseline
entry report but never fail the gate; ``--record`` (re)generates the
committed ``PERF_BASELINE.json``.
"""
import json
import pathlib
import subprocess
import sys

import pytest

from semantic_merge_tpu.obs import perf as obs_perf

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
GATE = REPO_ROOT / "scripts" / "perf_gate.py"


def snapshot(value=1000.0, unit="files/sec", phases=None, **extra):
    rec = {"metric": "files merged/sec/chip (synthetic)", "value": value,
           "unit": unit, "vs_baseline": 1.0}
    if phases is not None:
        rec["phases_ms"] = phases
    rec.update(extra)
    return rec


def write_snapshot(path, **kwargs):
    path.write_text(json.dumps(snapshot(**kwargs)) + "\n")
    return path


def run_gate(*argv, cwd=None):
    return subprocess.run(
        [sys.executable, str(GATE), *map(str, argv)],
        capture_output=True, text=True, timeout=120, cwd=cwd)


# ---------------------------------------------------------------------------
# Core: normalization + direction-aware comparison


def test_record_key_strips_bench_prefix():
    assert obs_perf.record_key("BENCH_r05.json") == "r05"
    assert obs_perf.record_key(pathlib.Path("/x/BENCH_tpu_rung5.json")) \
        == "tpu_rung5"
    assert obs_perf.record_key("MULTICHIP_r01.json") == "MULTICHIP_r01"


def test_higher_is_better_by_unit():
    assert obs_perf.higher_is_better("files/sec")
    assert obs_perf.higher_is_better("merges/s")
    assert not obs_perf.higher_is_better("ms")
    assert not obs_perf.higher_is_better("seconds")
    assert not obs_perf.higher_is_better("pct")


def test_normalize_record_keeps_comparable_surface():
    entry = obs_perf.normalize_record(
        snapshot(phases={"kernel": 12.0, "scan_encode": 3.0},
                 error="degraded"), source="BENCH_x.json")
    assert entry["value"] == 1000.0 and entry["unit"] == "files/sec"
    assert entry["phases_ms"] == {"kernel": 12.0, "scan_encode": 3.0}
    assert entry["error"] == "degraded"
    assert entry["source"] == "BENCH_x.json"
    assert "vs_baseline" not in entry


def test_throughput_drop_is_a_regression_gain_is_not():
    base = obs_perf.normalize_record(snapshot(value=1000.0))
    findings = obs_perf.compare_entry(
        "k", obs_perf.normalize_record(snapshot(value=850.0)), base)
    assert findings[0]["regression"] is True  # -15% throughput
    findings = obs_perf.compare_entry(
        "k", obs_perf.normalize_record(snapshot(value=1500.0)), base)
    assert findings[0]["regression"] is False  # +50% is an improvement
    findings = obs_perf.compare_entry(
        "k", obs_perf.normalize_record(snapshot(value=950.0)), base)
    assert findings[0]["regression"] is False  # -5% within 10% tolerance


def test_latency_increase_is_a_regression():
    base = obs_perf.normalize_record(snapshot(value=100.0, unit="ms"))
    findings = obs_perf.compare_entry(
        "k", obs_perf.normalize_record(snapshot(value=120.0, unit="ms")),
        base)
    assert findings[0]["regression"] is True
    findings = obs_perf.compare_entry(
        "k", obs_perf.normalize_record(snapshot(value=60.0, unit="ms")),
        base)
    assert findings[0]["regression"] is False


def test_phase_bands_and_noise_floor():
    base = obs_perf.normalize_record(snapshot(
        phases={"kernel": 100.0, "tiny": 1.0}))
    cur = obs_perf.normalize_record(snapshot(
        phases={"kernel": 140.0, "tiny": 50.0}))
    findings = obs_perf.compare_entry("k", cur, base)
    by_field = {f["field"]: f for f in findings}
    # kernel +40% > 25% phase tolerance -> regression.
    assert by_field["phases_ms.kernel"]["regression"] is True
    # tiny is under the 5ms noise floor in the baseline -> not compared.
    assert "phases_ms.tiny" not in by_field


def test_compare_many_missing_baseline_never_fails():
    baseline = {"schema": 1, "entries": {}}
    ok, findings = obs_perf.compare_many(
        {"new": obs_perf.normalize_record(snapshot())}, baseline)
    assert ok is True
    assert findings[0]["note"] == "missing-baseline"
    assert findings[0]["regression"] is False


def test_daemon_entry_prefers_slo_window_quantiles():
    status = {"slo": {"window_quantiles": {
        "semmerge": {"p50_ms": 120.0, "p99_ms": 450.0, "count": 9,
                     "errors": 0},
        "semdiff": {"p50_ms": 10.0, "p99_ms": 30.0, "count": 4,
                    "errors": 0},
    }}}
    entry = obs_perf.daemon_entry(status)
    assert entry["value"] == pytest.approx(450.0)
    assert entry["unit"] == "ms"
    assert entry["source"] == "slo-window"
    assert entry["phases_ms"]["semmerge_p99"] == pytest.approx(450.0)
    assert entry["phases_ms"]["semdiff_p50"] == pytest.approx(10.0)


def test_daemon_entry_falls_back_to_cumulative_histogram():
    status = {"metrics": {"histograms": {"service_request_seconds": {
        "buckets": [0.1, 1.0, 10.0],
        "series": [{"labels": {"verb": "semmerge"},
                    "counts": [0, 8, 2, 0], "count": 10, "sum": 6.0}],
    }}}}
    entry = obs_perf.daemon_entry(status)
    assert entry["source"] == "cumulative-histogram"
    assert entry["phases_ms"]["semmerge_p99"] > \
        entry["phases_ms"]["semmerge_p50"] > 0


def test_append_trajectory_env_override(tmp_path, monkeypatch):
    traj = tmp_path / "custom" / "traj.jsonl"
    monkeypatch.setenv(obs_perf.ENV_TRAJECTORY, str(traj))
    p1 = obs_perf.append_trajectory(snapshot(), preset="rung5")
    p2 = obs_perf.append_trajectory(snapshot(value=2.0))
    assert p1 == p2 == traj
    rows = [json.loads(l) for l in traj.read_text().splitlines()]
    assert len(rows) == 2
    assert rows[0]["preset"] == "rung5" and "ts" in rows[0]
    assert "preset" not in rows[1]


# ---------------------------------------------------------------------------
# scripts/perf_gate.py exit codes


def test_gate_passes_on_baseline_and_fails_on_regression(tmp_path):
    snap = write_snapshot(tmp_path / "BENCH_x.json", value=1000.0)
    baseline = tmp_path / obs_perf.BASELINE_NAME
    rec = run_gate(snap, "--baseline", baseline, "--record")
    assert rec.returncode == 0, rec.stderr
    assert baseline.is_file()

    ok = run_gate(snap, "--baseline", baseline)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert "REGRESSION" not in ok.stdout

    write_snapshot(tmp_path / "BENCH_x.json", value=500.0)
    bad = run_gate(snap, "--baseline", baseline, "--json")
    assert bad.returncode == 1
    out = json.loads(bad.stdout)
    assert out["ok"] is False
    assert any(f["regression"] for f in out["findings"])


def test_gate_usage_errors_exit_2(tmp_path):
    snap = write_snapshot(tmp_path / "BENCH_x.json")
    missing = run_gate(snap, "--baseline", tmp_path / "absent.json")
    assert missing.returncode == 2
    assert "no baseline" in missing.stderr

    garbled = tmp_path / "BENCH_bad.json"
    garbled.write_text("{not json")
    bad = run_gate(garbled, "--baseline", tmp_path / "absent.json")
    assert bad.returncode == 2


def test_gate_new_snapshot_reports_but_passes(tmp_path):
    known = write_snapshot(tmp_path / "BENCH_known.json")
    baseline = tmp_path / obs_perf.BASELINE_NAME
    assert run_gate(known, "--baseline", baseline,
                    "--record").returncode == 0
    fresh = write_snapshot(tmp_path / "BENCH_fresh.json")
    out = run_gate(known, fresh, "--baseline", baseline)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "no baseline entry" in out.stdout


def test_gate_defaults_cover_committed_snapshots():
    """The committed PERF_BASELINE.json must gate the checked-in
    BENCH_*.json snapshots cleanly — the exact tier-1/CI invocation."""
    proc = run_gate()
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# semmerge perf record|compare CLI


def test_perf_cli_record_then_compare(tmp_path, capsys):
    from semantic_merge_tpu.cli import main

    snap = write_snapshot(tmp_path / "BENCH_cli.json", value=200.0)
    baseline = tmp_path / "PERF_BASELINE.json"
    assert main(["perf", "record", str(snap),
                 "--baseline", str(baseline)]) == 0
    capsys.readouterr()
    assert json.loads(baseline.read_text())["entries"]["cli"]["value"] \
        == 200.0

    assert main(["perf", "compare", str(snap),
                 "--baseline", str(baseline)]) == 0
    capsys.readouterr()

    write_snapshot(tmp_path / "BENCH_cli.json", value=100.0)
    assert main(["perf", "compare", str(snap),
                 "--baseline", str(baseline), "--json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] is False

    # Improvements re-recorded under a custom key.
    assert main(["perf", "record", str(snap), "--key", "custom",
                 "--baseline", str(baseline)]) == 0
    capsys.readouterr()
    entries = json.loads(baseline.read_text())["entries"]
    assert set(entries) == {"cli", "custom"}


def test_perf_cli_compare_missing_baseline_exits_2(tmp_path, capsys):
    from semantic_merge_tpu.cli import main

    snap = write_snapshot(tmp_path / "BENCH_cli.json")
    assert main(["perf", "compare", str(snap),
                 "--baseline", str(tmp_path / "absent.json")]) == 2
    capsys.readouterr()


@pytest.mark.slow
def test_perf_cli_daemon_record(tmp_path, service_daemon, capsys,
                                monkeypatch):
    from semantic_merge_tpu.cli import main

    monkeypatch.setenv("SEMMERGE_SERVICE_SOCKET", service_daemon)
    baseline = tmp_path / "PERF_BASELINE.json"
    assert main(["perf", "record", "--daemon",
                 "--baseline", str(baseline)]) == 0
    capsys.readouterr()
    entry = json.loads(baseline.read_text())["entries"]["daemon"]
    assert entry["unit"] == "ms"
    assert entry["source"] in ("slo-window", "cumulative-histogram")
