"""Overload-hardened self-healing (ISSUE 9): circuit breakers on the
degradation ladder, daemon admission control and load shedding with
``retry_after_ms``, idempotent replay, bounded program caches, worker
respawn accounting, supervised restart, and the repo-lock stale-break
race.

The bar:

- A rung whose breaker is open is skipped *without* paying a failed
  attempt, and a half-open probe restores it when the fault clears.
- An overloaded daemon rejects with a typed fault carrying
  ``retry_after_ms``; ``require`` clients surface the documented exit
  code, ``auto`` clients fall back in-process and still merge.
- A SIGKILLed daemon under ``serve --supervise`` comes back on the
  same socket; supervision ends cleanly on SIGTERM.
- A dead-PID ``--inplace`` lock is broken **exactly once** across
  concurrent contenders, and mutual exclusion holds throughout.
"""
import json
import os
import pathlib
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

from semantic_merge_tpu.cli import main
from semantic_merge_tpu.obs import metrics as obs_metrics
from semantic_merge_tpu.runtime import inplace
from semantic_merge_tpu.service import protocol, resilience
from semantic_merge_tpu.service.resilience import CircuitBreaker, breakers
from semantic_merge_tpu.utils import faults

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


def counter_series(name: str, **labels) -> float:
    """Sum of a counter's series whose labels include ``labels``."""
    data = obs_metrics.REGISTRY.to_dict()
    metric = data.get("counters", {}).get(name, {})
    total = 0.0
    for s in metric.get("series", []):
        got = s.get("labels") or {}
        if all(got.get(k) == v for k, v in labels.items()):
            total += s["value"]
    return total


def gauge_value(name: str, **labels):
    data = obs_metrics.REGISTRY.to_dict()
    metric = data.get("gauges", {}).get(name, {})
    for s in metric.get("series", []):
        if (s.get("labels") or {}) == labels:
            return s["value"]
    return None


def git(args, cwd):
    subprocess.run(["git", *args], cwd=cwd, check=True,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def commit_all(root, msg):
    git(["add", "-A"], root)
    env = {"GIT_AUTHOR_DATE": "2024-01-01T00:00:00Z",
           "GIT_COMMITTER_DATE": "2024-01-01T00:00:00Z"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        git(["commit", "-q", "-m", msg], root)
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.update({k: v})


def build_repo(root: pathlib.Path) -> pathlib.Path:
    """The test_faults repo shape: semantic result == textual result,
    so every rung converges on the same bytes."""
    root.mkdir(parents=True, exist_ok=True)
    git(["init", "-q", "-b", "main"], root)
    git(["config", "user.email", "t@example.com"], root)
    git(["config", "user.name", "t"], root)
    (root / "src").mkdir()
    (root / "src/util.ts").write_text(
        "export function foo(n: number): number {\n  return n;\n}\n")
    (root / "notes.txt").write_text("hello\n")
    commit_all(root, "base")
    git(["branch", "basebr"], root)
    git(["checkout", "-qb", "brA"], root)
    (root / "src/util.ts").write_text(
        "export function bar(n: number): number {\n  return n;\n}\n")
    commit_all(root, "rename foo->bar")
    git(["checkout", "-q", "main"], root)
    git(["checkout", "-qb", "brB"], root)
    (root / "extra.ts").write_text(
        "export function extra(s: string): string { return s; }\n")
    (root / "notes.txt").write_text("hello\nworld\n")
    commit_all(root, "add extra + edit notes")
    git(["checkout", "-q", "main"], root)
    return root


def raw_conn(sock_path: str, timeout: float = 60.0):
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(timeout)
    s.connect(sock_path)
    return (s, s.makefile("r", encoding="utf-8"),
            s.makefile("w", encoding="utf-8"))


def raw_close(conn) -> None:
    s, rfile, wfile = conn
    for h in (rfile, wfile, s):
        try:
            h.close()
        except OSError:
            pass


def send_merge(conn, cwd: str, env=None, req_id=1, argv=None,
               idem_key=None) -> None:
    params = {"argv": argv or ["basebr", "brA", "brB", "--backend", "host"],
              "cwd": cwd, "env": env or {}}
    if idem_key:
        params["idempotency_key"] = idem_key
    protocol.write_message(conn[2], {"id": req_id, "method": "semmerge",
                                     "params": params})


# ---------------------------------------------------------------------------
# Circuit breaker unit behavior
# ---------------------------------------------------------------------------

def test_breaker_opens_after_threshold_and_recovers():
    br = CircuitBreaker("x", window_s=30.0, threshold=3, cooldown_s=0.05)
    for _ in range(2):
        br.record_failure()
    assert br.state == "closed" and br.allow()
    br.record_failure()
    assert br.state == "open" and not br.allow()
    time.sleep(0.06)
    assert br.allow()                      # the half-open probe
    assert br.state == "half-open"
    assert not br.allow()                  # one probe at a time
    br.record_failure()                    # probe failed: re-open
    assert br.state == "open" and not br.allow()
    time.sleep(0.06)
    assert br.allow()
    br.record_success()
    assert br.state == "closed" and br.allow()


def test_breaker_window_prunes_old_failures():
    br = CircuitBreaker("y", window_s=0.05, threshold=3, cooldown_s=1.0)
    br.record_failure()
    br.record_failure()
    time.sleep(0.08)
    br.record_failure()                    # the first two aged out
    assert br.state == "closed"


def test_breaker_board_noop_outside_daemon(monkeypatch):
    monkeypatch.delenv("SEMMERGE_BREAKER", raising=False)
    monkeypatch.delenv("_SEMMERGE_IN_DAEMON", raising=False)
    board = resilience.BreakerBoard()
    for _ in range(10):
        board.record_failure("fused")
    assert board.allow("fused")
    assert board.snapshot() == {}


# ---------------------------------------------------------------------------
# Breaker on the degradation ladder (end to end, in process)
# ---------------------------------------------------------------------------

@pytest.fixture
def repo(tmp_path, monkeypatch):
    root = build_repo(tmp_path / "repo")
    monkeypatch.chdir(root)
    faults.reset()
    yield root
    faults.reset()


def test_ladder_skips_open_rung_and_half_open_restores(repo, monkeypatch):
    """Two faulted merges trip the host rung's breaker; the third merge
    skips the rung *without an attempt* (degradation fault is the
    breaker's WorkerFault, not the injected ParseFault); after the
    cooldown with the fault cleared, the half-open probe restores the
    rung."""
    monkeypatch.setenv("SEMMERGE_BREAKER", "on")
    monkeypatch.setenv("SEMMERGE_BREAKER_THRESHOLD", "2")
    monkeypatch.setenv("SEMMERGE_BREAKER_COOLDOWN", "0.2")
    breakers().reset()
    monkeypatch.setenv("SEMMERGE_FAULT", "scan:raise")
    try:
        skip0 = counter_series("merge_degradations_total",
                               fault="WorkerFault", to="text")
        for _ in range(2):
            faults.reset()
            assert main(["semmerge", "basebr", "brA", "brB", "--inplace",
                         "--backend", "host"]) == 0
        assert breakers().snapshot().get("host") == "open"
        assert gauge_value("breaker_state", rung="host") == 1
        # Breaker open: the rung is skipped without an attempt.
        faults.reset()
        assert main(["semmerge", "basebr", "brA", "brB", "--inplace",
                     "--backend", "host"]) == 0
        assert counter_series("merge_degradations_total",
                              fault="WorkerFault", to="text") == skip0 + 1
        assert breakers().snapshot().get("host") == "open"
        # Fault clears; the cooled-down breaker admits one probe,
        # which succeeds and closes it.
        monkeypatch.delenv("SEMMERGE_FAULT")
        faults.reset()
        time.sleep(0.25)
        assert main(["semmerge", "basebr", "brA", "brB", "--inplace",
                     "--backend", "host"]) == 0
        assert breakers().snapshot().get("host") == "closed"
        assert gauge_value("breaker_state", rung="host") == 0
        assert counter_series("merge_degradations_total",
                              fault="WorkerFault", to="text") == skip0 + 1
    finally:
        breakers().reset()


def test_strict_mode_breaker_open_is_typed_exit(repo, monkeypatch):
    """``--no-degrade`` + open breaker: the skip is a fail-fast typed
    WorkerFault (exit 12), tree untouched — not a silent degrade."""
    monkeypatch.setenv("SEMMERGE_BREAKER", "on")
    monkeypatch.setenv("SEMMERGE_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("SEMMERGE_BREAKER_COOLDOWN", "60")
    breakers().reset()
    try:
        monkeypatch.setenv("SEMMERGE_FAULT", "scan:raise")
        assert main(["semmerge", "basebr", "brA", "brB", "--inplace",
                     "--backend", "host"]) == 0
        assert breakers().snapshot().get("host") == "open"
        monkeypatch.delenv("SEMMERGE_FAULT")
        faults.reset()
        rc = main(["semmerge", "basebr", "brA", "brB", "--inplace",
                   "--backend", "host", "--no-degrade"])
        assert rc == 12
    finally:
        breakers().reset()


# ---------------------------------------------------------------------------
# Daemon admission control and load shedding
# ---------------------------------------------------------------------------

def test_queue_full_rejection_carries_retry_after(tmp_path, daemon_factory):
    sock = str(tmp_path / "q.sock")
    daemon_factory(sock, extra_env={"SEMMERGE_SERVICE_WORKERS": "1",
                                    "SEMMERGE_SERVICE_QUEUE": "1"})
    hang = raw_conn(sock)
    queued = raw_conn(sock)
    rejected = raw_conn(sock)
    try:
        # Wedge the single executor, then fill the queue of one.
        send_merge(hang, "/", env={"SEMMERGE_FAULT":
                                   "service:execute:hang=60"})
        time.sleep(0.5)
        send_merge(queued, "/")
        time.sleep(0.3)
        send_merge(rejected, "/")
        resp = protocol.read_message(rejected[1])
        err = resp.get("error")
        assert err, f"expected a typed rejection, got {resp}"
        assert err["fault"] == "WorkerFault" and err["exit_code"] == 12
        assert "queue full" in err["message"]
        assert isinstance(err.get("retry_after_ms"), int)
        assert 100 <= err["retry_after_ms"] <= 5000
    finally:
        for c in (hang, queued, rejected):
            raw_close(c)


def test_hard_watermark_sheds_and_auto_client_falls_back(tmp_path,
                                                         daemon_factory):
    """A daemon whose RSS exceeds the hard watermark sheds everything:
    raw requests get a typed overload rejection with ``retry_after_ms``,
    ``require`` clients exit 12 after their bounded retries, ``auto``
    clients fall back in-process and still complete the merge."""
    sock = str(tmp_path / "rss.sock")
    daemon_factory(sock, extra_env={"SEMMERGE_RSS_HARD_MB": "1"})
    from semantic_merge_tpu.service import client as service_client
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        status = service_client.call_control("status", path=sock)
        if status["resilience"]["pressure"] == 2:
            break
        time.sleep(0.2)
    else:
        pytest.fail("pressure monitor never reached the hard watermark")

    conn = raw_conn(sock)
    try:
        send_merge(conn, "/")
        err = protocol.read_message(conn[1]).get("error")
        assert err and err["exit_code"] == 12
        assert "hard watermark" in err["message"]
        assert isinstance(err.get("retry_after_ms"), int)
    finally:
        raw_close(conn)

    repo = build_repo(tmp_path / "repo")

    def run_client(posture):
        env = dict(os.environ)
        env.update({"PYTHONPATH": str(REPO_ROOT), "JAX_PLATFORMS": "cpu",
                    "SEMMERGE_DAEMON": posture,
                    "SEMMERGE_SERVICE_SOCKET": sock,
                    "SEMMERGE_SERVICE_RETRIES": "1"})
        env.pop("SEMMERGE_FAULT", None)
        return subprocess.run(
            [sys.executable, "-m", "semantic_merge_tpu", "semmerge",
             "basebr", "brA", "brB", "--inplace", "--backend", "host"],
            cwd=repo, capture_output=True, text=True, env=env, timeout=300)

    strict = run_client("require")
    assert strict.returncode == 12, strict.stderr
    fallback = run_client("auto")
    assert fallback.returncode == 0, fallback.stderr
    assert (repo / "extra.ts").exists()  # the merge really landed

    status = service_client.call_control("status", path=sock)
    shed = status["metrics"]["counters"]["service_shed_total"]["series"]
    assert sum(s["value"] for s in shed
               if s["labels"].get("reason") == "rss-hard") >= 3


def test_idempotent_replay_returns_cached_response(tmp_path,
                                                   service_daemon):
    """Same idempotency key twice: the second answer is served from the
    daemon's replay cache (counted), byte-identical modulo the id."""
    from semantic_merge_tpu.service import client as service_client
    repo = build_repo(tmp_path / "repo")
    key = "test-idem-0001"
    c1 = raw_conn(service_daemon)
    try:
        send_merge(c1, str(repo), req_id=7, idem_key=key)
        first = protocol.read_message(c1[1])
    finally:
        raw_close(c1)
    assert first.get("result", {}).get("exit_code") == 0, first
    before = service_client.call_control("status", path=service_daemon)
    n0 = _replay_total(before)
    c2 = raw_conn(service_daemon)
    try:
        send_merge(c2, str(repo), req_id=9, idem_key=key)
        second = protocol.read_message(c2[1])
    finally:
        raw_close(c2)
    assert second["id"] == 9
    scrub = lambda r: {k: v for k, v in r.items() if k != "id"}  # noqa: E731
    assert scrub(second) == scrub(first)
    after = service_client.call_control("status", path=service_daemon)
    assert _replay_total(after) == n0 + 1


def _replay_total(status: dict) -> float:
    metric = status["metrics"]["counters"].get(
        "service_idempotent_replays_total", {})
    return sum(s["value"] for s in metric.get("series", []))


def _idem_request(req_id, key):
    from semantic_merge_tpu.service import daemon as daemon_mod
    return daemon_mod._Request(req_id, "semmerge",
                               {"idempotency_key": key})


def test_idem_cache_ttl_expires_entry_and_frees_slot(monkeypatch):
    """Replay-cache TTL semantics: a resend *within* the TTL replays
    the cached response; a resend *after* it re-executes as a fresh
    request (deterministic merges + the inplace journal make that
    safe) and the expired entry's slot is freed, not just masked."""
    from semantic_merge_tpu.service import daemon as daemon_mod
    monkeypatch.setenv("SEMMERGE_SERVICE_IDEM_TTL", "0.15")
    d = daemon_mod.Daemon(socket_path="/tmp/idem-ttl-unused.sock")
    first = _idem_request(1, "ttl-key")
    first.response = {"id": 1, "result": {"exit_code": 0, "stdout": "x"}}
    d._idem_store(first)
    replays0 = counter_series("service_idempotent_replays_total")
    hit = d._idem_lookup(_idem_request(2, "ttl-key"))
    assert hit == {"id": 2, "result": {"exit_code": 0, "stdout": "x"}}
    assert counter_series("service_idempotent_replays_total") \
        == replays0 + 1
    time.sleep(0.2)
    assert d._idem_lookup(_idem_request(3, "ttl-key")) is None
    assert "ttl-key" not in d._idem  # slot freed, not replayed-stale
    # The expired miss is NOT a replay: counter unchanged.
    assert counter_series("service_idempotent_replays_total") \
        == replays0 + 1


def test_idem_cache_evict_then_resend_reexecutes(monkeypatch):
    """LRU-cap/TTL interaction for a client resending after
    ``retry_after_ms``: a key evicted by newer entries (or never cached
    because the original attempt was *rejected*, not executed) simply
    re-executes — a cache miss is never an error. The still-resident
    key keeps replaying."""
    from semantic_merge_tpu.service import daemon as daemon_mod
    monkeypatch.setenv("SEMMERGE_SERVICE_IDEM_CACHE", "1")
    monkeypatch.delenv("SEMMERGE_SERVICE_IDEM_TTL", raising=False)
    d = daemon_mod.Daemon(socket_path="/tmp/idem-cap-unused.sock")
    assert d._idem_ttl == 0.0  # default: size-only LRU, no expiry
    r1 = _idem_request(1, "old-key")
    r1.response = {"id": 1, "result": {"exit_code": 0}}
    d._idem_store(r1)
    r2 = _idem_request(2, "new-key")
    r2.response = {"id": 2, "result": {"exit_code": 0}}
    d._idem_store(r2)  # cap=1: evicts old-key
    assert d._idem_lookup(_idem_request(3, "old-key")) is None
    assert len(d._idem) == 1
    hit = d._idem_lookup(_idem_request(4, "new-key"))
    assert hit == {"id": 4, "result": {"exit_code": 0}}
    # A request rejected at admission never reaches _idem_store: its
    # key is absent, so the post-retry_after_ms resend is a fresh
    # execution under the same key.
    rejected = _idem_request(5, "rejected-key")
    assert rejected.response is None
    d._idem_store(rejected)
    assert d._idem_lookup(_idem_request(6, "rejected-key")) is None


# ---------------------------------------------------------------------------
# Supervised restart
# ---------------------------------------------------------------------------

def test_supervisor_respawns_sigkilled_daemon(tmp_path):
    from semantic_merge_tpu.service import client as service_client
    sock = str(tmp_path / "sup.sock")
    dump = tmp_path / "sup-metrics.json"
    env = dict(os.environ)
    env.update({"PYTHONPATH": str(REPO_ROOT), "JAX_PLATFORMS": "cpu",
                "SEMMERGE_DAEMON": "off", "SEMMERGE_METRICS": str(dump),
                "SEMMERGE_SUPERVISE_BACKOFF": "0.1"})
    env.pop("SEMMERGE_FAULT", None)
    log = open(sock + ".log", "ab")
    sup = subprocess.Popen(
        [sys.executable, "-m", "semantic_merge_tpu", "serve",
         "--supervise", "--socket", sock],
        stdin=subprocess.DEVNULL, stdout=log, stderr=log,
        cwd="/", env=env, start_new_session=True)
    log.close()
    try:
        pid1 = _wait_daemon_pid(service_client, sock, sup)
        os.kill(pid1, signal.SIGKILL)
        deadline = time.monotonic() + 120
        pid2 = None
        while time.monotonic() < deadline:
            try:
                status = service_client.call_control("status", path=sock)
                if status["pid"] != pid1:
                    pid2 = status["pid"]
                    break
            except service_client.DaemonUnavailable:
                pass
            time.sleep(0.2)
        assert pid2 is not None, \
            f"supervisor never respawned the daemon (log: {sock}.log)"
        sup.send_signal(signal.SIGTERM)
        assert sup.wait(timeout=60) == 0
    finally:
        if sup.poll() is None:
            sup.kill()
            sup.wait(timeout=10)
    metrics = json.loads(dump.read_text())
    series = metrics["counters"]["supervisor_restarts_total"]["series"]
    assert sum(s["value"] for s in series
               if s["labels"].get("reason") == "signal") >= 1


def _wait_daemon_pid(service_client, sock, sup, timeout=120.0) -> int:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if sup.poll() is not None:
            raise RuntimeError(f"supervisor exited rc={sup.returncode} "
                               f"during startup (log: {sock}.log)")
        try:
            return service_client.call_control("status", path=sock)["pid"]
        except service_client.DaemonUnavailable:
            time.sleep(0.2)
    raise RuntimeError(f"daemon did not come up (log: {sock}.log)")


# ---------------------------------------------------------------------------
# Worker respawn accounting + capped backoff
# ---------------------------------------------------------------------------

def test_worker_respawn_counted_and_backoff_capped(monkeypatch):
    from semantic_merge_tpu.backends.subproc import SubprocessBackend
    monkeypatch.delenv("SEMMERGE_WORKER_KEEPALIVE", raising=False)
    monkeypatch.setenv("SEMMERGE_WORKER_BACKOFF_CAP", "0.5")
    be = SubprocessBackend()
    assert be._retry_backoff_cap == 0.5
    # The cap really clamps the exponential schedule.
    assert min(be._retry_backoff * (2 ** 10), be._retry_backoff_cap) == 0.5
    try:
        assert be._call("ping", {}).get("pong")
        before = counter_series("subprocess_respawns_total",
                                reason="worker-exit")
        proc = be._proc
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        assert be._call("ping", {}).get("pong")
        assert counter_series("subprocess_respawns_total",
                              reason="worker-exit") == before + 1
    finally:
        be.close()


# ---------------------------------------------------------------------------
# Bounded batched-program cache
# ---------------------------------------------------------------------------

def test_batched_program_cache_bounded_lru(monkeypatch):
    fused = pytest.importorskip("semantic_merge_tpu.ops.fused")
    monkeypatch.setattr(fused, "_PROG_CACHE_CAP", 2)
    with fused._batch_prog_lock:
        fused._batch_progs.clear()
    ev0 = fused.batched_program_cache_stats()["evictions"]
    mev0 = counter_series("program_cache_evictions_total", cache="batched")
    fused.batched_fused_program(1, 1, 1, 1, 1)
    fused.batched_fused_program(1, 1, 1, 1, 2)
    fused.batched_fused_program(1, 1, 1, 1, 1)   # refresh key 1
    fused.batched_fused_program(1, 1, 1, 1, 3)   # evicts key 2 (LRU)
    stats = fused.batched_program_cache_stats()
    assert stats["programs"] == 2
    assert stats["evictions"] == ev0 + 1
    with fused._batch_prog_lock:
        # program-cache keys carry the dispatch mesh (None =
        # single-device) since the mesh-sharded batch PR
        assert (1, 1, 1, 1, 2, None) not in fused._batch_progs
        assert (1, 1, 1, 1, 1, None) in fused._batch_progs
    assert counter_series("program_cache_evictions_total",
                          cache="batched") == mev0 + 1


# ---------------------------------------------------------------------------
# Stale --inplace lock: broken exactly once under contention
# ---------------------------------------------------------------------------

def test_stale_lock_broken_exactly_once_under_contention(tmp_path):
    root = tmp_path / "wt"
    root.mkdir()
    lock = root / inplace.LOCKFILE
    ghost = subprocess.Popen([sys.executable, "-c", "pass"])
    ghost.wait(timeout=30)
    with pytest.raises(ProcessLookupError):
        os.kill(ghost.pid, 0)             # the recorded owner is dead
    lock.write_text(f"{ghost.pid} {int(time.time())}\n")
    breaks0 = counter_series("semmerge_inplace_lock_stale_total")
    state = {"active": 0, "max_active": 0, "errors": []}
    guard = threading.Lock()

    def contend():
        try:
            with inplace.repo_lock(root, timeout=30):
                with guard:
                    state["active"] += 1
                    state["max_active"] = max(state["max_active"],
                                              state["active"])
                time.sleep(0.01)
                with guard:
                    state["active"] -= 1
        except BaseException as exc:  # noqa: BLE001 - surfaced below
            state["errors"].append(exc)

    threads = [threading.Thread(target=contend) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not state["errors"], state["errors"]
    assert state["max_active"] == 1, "two contenders held the lock at once"
    assert counter_series("semmerge_inplace_lock_stale_total") \
        == breaks0 + 1, "the stale lock must be broken exactly once"
    assert not lock.exists()
    assert not (root / (inplace.LOCKFILE + ".breaker")).exists(), \
        "no breaker-guard debris may survive"


def test_live_lock_is_not_broken(tmp_path):
    """A fresh lock owned by a live pid survives a breaker's guarded
    recheck — the lock stays, nothing is counted."""
    root = tmp_path / "wt"
    root.mkdir()
    lock = root / inplace.LOCKFILE
    lock.write_text(f"{os.getpid()} {int(time.time())}\n")
    before = counter_series("semmerge_inplace_lock_stale_total")
    assert not inplace._lock_is_stale(lock)
    assert not inplace._break_stale_lock(lock)
    assert lock.exists()
    assert not (root / (inplace.LOCKFILE + ".breaker")).exists()
    assert counter_series("semmerge_inplace_lock_stale_total") == before
    lock.unlink()
