"""Data-contract tests: Op/OpLog/Conflict JSON parity surface."""
import json

from semantic_merge_tpu.core.conflict import divergent_rename_conflict
from semantic_merge_tpu.core.ids import deterministic_op_id, symbol_id_from_signature
from semantic_merge_tpu.core.ops import OP_PRECEDENCE, OP_TYPES, Op, OpLog, Target


def test_op_round_trip():
    op = Op.new(
        "renameSymbol",
        Target(symbolId="abc123", addressId="a.ts::foo::0"),
        params={"oldName": "foo", "newName": "bar", "file": "a.ts"},
        guards={"exists": True},
        effects={"summary": "rename foo→bar"},
        provenance={"rev": "base", "timestamp": "2024-01-01T00:00:00Z"},
    )
    d = op.to_dict()
    assert set(d) == {"id", "schemaVersion", "type", "target", "params",
                      "guards", "effects", "provenance"}
    assert d["target"] == {"symbolId": "abc123", "addressId": "a.ts::foo::0"}
    restored = Op.from_dict(d)
    assert restored == op


def test_oplog_json_round_trip_is_compact():
    op = Op.new("addDecl", Target(symbolId="s1"), params={"file": "a.ts"})
    log = OpLog([op])
    payload = log.to_json()
    # Compact separators — byte-compatible with the reference's orjson output.
    assert ": " not in payload and ", " not in payload
    assert OpLog.from_json(payload).ops == [op]


def test_all_17_op_types_and_precedence():
    assert len(OP_TYPES) == 17
    assert set(OP_PRECEDENCE) == set(OP_TYPES)
    assert OP_PRECEDENCE["moveDecl"] == 10
    assert OP_PRECEDENCE["renameSymbol"] == 11
    assert OP_PRECEDENCE["modifyNamespace"] == 70


def test_sort_key_matches_reference_semantics():
    op = Op.new("moveDecl", Target(symbolId="s"), provenance={})
    prec, ts, _ = op.sort_key()
    assert prec == 10
    assert ts == "1970-01-01T00:00:00Z"  # missing-timestamp default
    unknown = Op.new("notARealOp", Target(symbolId="s"))
    assert unknown.sort_key()[0] == 99


def test_deterministic_ids_are_stable_and_uuid_shaped():
    a = deterministic_op_id("seed", "rev", 0, "renameSymbol")
    b = deterministic_op_id("seed", "rev", 0, "renameSymbol")
    c = deterministic_op_id("seed", "rev", 1, "renameSymbol")
    assert a == b != c
    parts = a.split("-")
    assert [len(p) for p in parts] == [8, 4, 4, 4, 12]


def test_symbol_id_matches_reference_hash_scheme():
    # sha256("fn(number,number)->number")[:16] — the reference's exact
    # symbolId derivation (workers/ts/src/sast.ts:69-71,96).
    import hashlib
    sig = "fn(number,number)->number"
    assert symbol_id_from_signature(sig) == hashlib.sha256(sig.encode()).hexdigest()[:16]
    assert len(symbol_id_from_signature("class{2}")) == 16


def test_divergent_rename_conflict_shape():
    op_a = Op.new("renameSymbol", Target(symbolId="s", addressId="a"),
                  params={"newName": "x"})
    op_b = Op.new("renameSymbol", Target(symbolId="s", addressId="b"),
                  params={"newName": "y"})
    conf = divergent_rename_conflict(op_a, op_b)
    assert conf.category == "DivergentRename"
    assert conf.id == f"conf-{op_a.id[:8]}-{op_b.id[:8]}"
    assert conf.addressIds == {"A": "a", "B": "b", "base": None}
    assert [s["id"] for s in conf.suggestions] == ["keepA", "keepB"]
    assert "Rename to x" == conf.suggestions[0]["label"]
    json.dumps(conf.to_dict())  # serializable


def test_bucket_ladder_invariants():
    """Half-step shape buckets: on-ladder, monotonic, >= n; shard
    buckets additionally divisible by k, >= 8 rows, and equal to
    bucket_size for k = 1."""
    from semantic_merge_tpu.core.encode import bucket_size, shard_bucket

    assert [bucket_size(n) for n in (1, 8, 9, 12, 13, 17, 23000)] == \
        [8, 8, 12, 12, 16, 24, 24576]
    for k in (1, 2, 6, 8):
        prev = 0
        for n in range(1, 2000):
            b = shard_bucket(n, k)
            assert b >= max(n, 8) and b % k == 0 and b >= prev
            prev = b
    for n in range(1, 2000):
        assert shard_bucket(n, 1) == bucket_size(n)
