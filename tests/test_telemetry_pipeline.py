"""Production telemetry pipeline: fleet-consistent tail sampling
(obs/sampling.py), bounded artifact stores, anomaly triage
(obs/anomaly.py), metrics cardinality budget, the hardened trace CLI
surfaces, and the new schema validators."""
import concurrent.futures
import importlib.util
import json
import pathlib
import subprocess
import sys

import pytest

from semantic_merge_tpu.obs import anomaly as obs_anomaly
from semantic_merge_tpu.obs import metrics as obs_metrics
from semantic_merge_tpu.obs import sampling as obs_sampling

_SCRIPT = (pathlib.Path(__file__).resolve().parent.parent
           / "scripts" / "check_trace_schema.py")


@pytest.fixture(scope="module")
def schema():
    spec = importlib.util.spec_from_file_location("check_trace_schema",
                                                  _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean_registry():
    obs_metrics.REGISTRY.reset()
    yield
    obs_metrics.REGISTRY.reset()


def _cli(*args, cwd=None, env=None):
    import os
    full_env = dict(os.environ, JAX_PLATFORMS="cpu",
                    PYTHONPATH=str(_SCRIPT.parent.parent))
    if env:
        full_env.update(env)
    return subprocess.run(
        [sys.executable, "-m", "semantic_merge_tpu", *args],
        capture_output=True, text=True, cwd=cwd, env=full_env,
        timeout=120)


# ---------------------------------------------------------------------------
# Deterministic head sampling & Decision semantics


def test_head_keep_deterministic_across_processes():
    # Pure hash of the id: every process/host agrees with no state.
    assert obs_sampling.head_keep("trace-x", 1) is True
    for tid in ("a", "b", "deadbeef", "trace-123"):
        first = obs_sampling.head_keep(tid, 10)
        assert all(obs_sampling.head_keep(tid, 10) == first
                   for _ in range(20))
    kept = sum(obs_sampling.head_keep(f"t{i}", 10) for i in range(5000))
    assert 350 < kept < 650  # ~1 in 10


def test_head_keep_concurrent_consistency():
    tids = [f"trace-{i}" for i in range(200)]
    expected = {t: obs_sampling.head_keep(t, 7) for t in tids}
    with concurrent.futures.ThreadPoolExecutor(max_workers=8) as pool:
        futs = {t: [pool.submit(obs_sampling.head_keep, t, 7)
                    for _ in range(4)] for t in tids}
        for t, fs in futs.items():
            assert all(f.result() == expected[t] for f in fs)


def test_decision_upgrade_keep_wins_drop_never_downgrades():
    keep = obs_sampling.Decision(True, "error", minted_by="member")
    drop = obs_sampling.Decision(False, obs_sampling.DROP_REASON,
                                 minted_by="router")
    # Router may upgrade a member drop to keep...
    late_keep = obs_sampling.Decision(True, "slow", minted_by="router")
    up = drop.upgrade(late_keep)
    assert up.keep and up.reason == "slow"
    # ...but never downgrade a member keep.
    down = keep.upgrade(drop)
    assert down.keep and down.reason == "error"
    # Earliest minted keep's reason sticks.
    assert keep.upgrade(late_keep).reason == "error"
    assert keep.upgrade(None) is keep


def test_decision_meta_roundtrip():
    d = obs_sampling.Decision(True, "head", minted_by="m0", sample_n=8)
    back = obs_sampling.Decision.from_meta(d.to_meta())
    assert (back.keep, back.reason, back.minted_by, back.sample_n) == \
        (True, "head", "m0", 8)
    assert obs_sampling.Decision.from_meta(None) is None
    assert obs_sampling.Decision.from_meta({"nope": 1}) is None


# ---------------------------------------------------------------------------
# SamplingPolicy


def test_policy_disabled_by_default_keeps_everything(monkeypatch):
    monkeypatch.delenv(obs_sampling.ENV_SAMPLE, raising=False)
    monkeypatch.delenv(obs_sampling.ENV_BUDGET_MB, raising=False)
    policy = obs_sampling.SamplingPolicy()
    assert not policy.enabled
    for i in range(50):
        d = policy.decide(f"t{i}", "semmerge", 0.01,
                          error=False, degraded=False,
                          breaker=False, resolver=False)
        assert d.keep and d.reason == "always"


def test_policy_outcome_keeps_beat_head_drop(monkeypatch):
    monkeypatch.setenv(obs_sampling.ENV_SAMPLE, "1000000")
    policy = obs_sampling.SamplingPolicy()
    assert policy.enabled
    cases = [({"error": True}, "error"), ({"degraded": True}, "degraded"),
             ({"breaker": True}, "breaker"), ({"resolver": True},
                                              "resolver")]
    for flags, reason in cases:
        full = dict(error=False, degraded=False, breaker=False,
                    resolver=False)
        full.update(flags)
        d = policy.decide("tid-any", "semmerge", 0.001, **full)
        assert d.keep and d.reason == reason
    # No outcome flag, astronomically sparse head sample: dropped.
    drops = [policy.decide(f"x{i}", "semmerge", 0.001, error=False,
                           degraded=False, breaker=False,
                           resolver=False) for i in range(50)]
    assert any(not d.keep for d in drops)
    assert all(d.reason == obs_sampling.DROP_REASON
               for d in drops if not d.keep)


def test_policy_slow_keep_via_rolling_p99(monkeypatch):
    monkeypatch.setenv(obs_sampling.ENV_SAMPLE, "1000000")
    policy = obs_sampling.SamplingPolicy()
    # Warm the per-verb window past MIN_SLOW_SAMPLES with fast merges.
    for i in range(obs_sampling.MIN_SLOW_SAMPLES + 10):
        policy.decide(f"warm{i}", "semmerge", 0.010, error=False,
                      degraded=False, breaker=False, resolver=False)
    d = policy.decide("tail", "semmerge", 0.500, error=False,
                      degraded=False, breaker=False, resolver=False)
    assert d.keep and d.reason == "slow"
    stats = policy.stats()
    assert stats["enabled"] and stats["decisions"]["slow"] >= 1
    assert stats["p99_ms"]["semmerge"] > 0


def test_policy_decisions_counted(monkeypatch):
    monkeypatch.setenv(obs_sampling.ENV_SAMPLE, "1000000")
    policy = obs_sampling.SamplingPolicy()
    policy.decide("t", "semmerge", 0.01, error=True, degraded=False,
                  breaker=False, resolver=False)
    dump = obs_metrics.REGISTRY.to_dict()
    series = dump["counters"]["trace_sampling_decisions_total"]["series"]
    assert any(s["labels"] == {"decision": "keep", "reason": "error"}
               for s in series)


# ---------------------------------------------------------------------------
# TraceStore retention


def _write_traces(store, n, errored=()):
    for i in range(n):
        tid = f"trace-{i:04d}"
        reason = "error" if i in errored else "head"
        store.write(tid, {"schema": 1, "kind": "trace", "trace_id": tid,
                          "spans": [{"name": "pad", "seconds": 0.001,
                                     "meta": {"blob": "x" * 2000}}]},
                    decision=obs_sampling.Decision(
                        True, reason, minted_by="test"))


def test_store_stays_under_byte_budget(tmp_path):
    store = obs_sampling.TraceStore(tmp_path / "traces",
                                    budget_mb=0.02)  # ~20 KiB
    _write_traces(store, 40)
    assert store.total_bytes() <= store.budget_bytes
    assert 0 < store.count() < 40


def test_store_protects_errored_traces(tmp_path):
    store = obs_sampling.TraceStore(tmp_path / "traces", budget_mb=0.02)
    errored = {5, 17, 31}
    _write_traces(store, 40, errored=errored)
    kept = {p.stem for p in (tmp_path / "traces").glob("*.json")}
    for i in errored:
        assert f"trace-{i:04d}" in kept  # 100% errored retention
    assert store.total_bytes() <= store.budget_bytes


def test_store_count_cap_evicts_oldest_first(tmp_path):
    store = obs_sampling.TraceStore(tmp_path / "traces", max_count=5)
    _write_traces(store, 12)
    kept = sorted(p.stem for p in (tmp_path / "traces").glob("*.json"))
    assert len(kept) == 5
    assert kept == [f"trace-{i:04d}" for i in range(7, 12)]


def test_prune_dir_two_pass_protection(tmp_path):
    d = tmp_path / "pm"
    d.mkdir()
    for i in range(6):
        (d / f"b{i}.json").write_text(json.dumps({"i": i}))
    protected = {str(d / "b1.json"), str(d / "b4.json")}
    removed = obs_sampling.prune_dir(
        d, max_count=3, max_bytes=None,
        protect=lambda p: str(p) in protected)
    left = {p.name for p in d.glob("*.json")}
    assert removed == 3
    assert {"b1.json", "b4.json"} <= left and len(left) == 3


# ---------------------------------------------------------------------------
# Anomaly triage


def _drive(triage, n, phases, start=0):
    out = []
    for i in range(start, start + n):
        out += triage.observe(f"t{i}", "semmerge", dict(phases),
                              seconds=sum(phases.values()))
    return out


def test_anomaly_fires_exactly_once_per_sustained_breach(tmp_path,
                                                         monkeypatch):
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv(obs_anomaly.ENV_ENABLE, "1")
    triage = obs_anomaly.AnomalyTriage(z_threshold=4.0, min_n=8,
                                       sustain=2)
    base = {"parse": 0.010, "kernel": 0.020, "emit": 0.005}
    slow = {"parse": 0.010, "kernel": 0.200, "emit": 0.005}
    assert _drive(triage, 40, base) == []
    bundles = _drive(triage, 6, slow, start=100)
    assert len(bundles) == 1  # latched after the first fire
    assert triage.stats()["fired"] == 1
    # Recovery: sustained in-band observations unlatch...
    assert _drive(triage, 20, base, start=200) == []
    assert triage.stats()["latched"] == []
    # ...and a second sustained excursion fires exactly once more.
    assert len(_drive(triage, 6, slow, start=300)) == 1
    assert triage.stats()["fired"] == 2


def test_anomaly_bundle_names_injected_phase(tmp_path, monkeypatch,
                                             schema):
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv(obs_anomaly.ENV_ENABLE, "1")
    triage = obs_anomaly.AnomalyTriage(z_threshold=4.0, min_n=8,
                                       sustain=2)
    base = {"parse": 0.010, "kernel": 0.020, "emit": 0.005}
    _drive(triage, 40, base)
    bundles = _drive(
        triage, 6, {"parse": 0.010, "kernel": 0.300, "emit": 0.005},
        start=50)
    assert bundles and bundles[0]["bundle"]
    data = json.loads(pathlib.Path(bundles[0]["bundle"]).read_text())
    assert data["reason"] == "anomaly"
    assert data["triage"]["suspect_phase"] == "kernel"
    assert data["triage"]["baseline"] is not None
    assert schema.validate_triage(data) == []


def test_anomaly_disable_via_env(monkeypatch):
    monkeypatch.setenv(obs_anomaly.ENV_ENABLE, "off")
    triage = obs_anomaly.AnomalyTriage()
    assert not triage.enabled
    assert triage.observe("t", "semmerge", {"kernel": 99.0},
                          seconds=99.0) == []
    assert triage.stats()["fired"] == 0


def test_ewma_detector_breach_not_absorbed():
    det = obs_anomaly.EwmaDetector(z_threshold=4.0, min_n=8, sustain=2)
    for _ in range(30):
        assert det.observe(0.020) in ("warmup", "ok")
    z_before = det.zscore(0.200)
    assert det.observe(0.200) == "breach"
    # The breaching sample must not drag the baseline toward itself.
    assert det.zscore(0.200) == pytest.approx(z_before)
    assert det.observe(0.200) == "fire"
    assert det.observe(0.200) == "latched"


def test_phase_diff_shared_shape():
    diff = obs_anomaly.phase_diff({"a": 0.010, "b": 0.100},
                                  {"a": 0.010, "b": 0.020})
    assert diff["suspect_phase"] == "b"
    assert diff["phases"][0]["phase"] == "b"
    assert diff["phases"][0]["delta_ms"] == pytest.approx(80.0)
    assert diff["phases"][0]["ratio"] == pytest.approx(5.0)
    flat = obs_anomaly.phase_diff({"a": 0.01}, {"a": 0.02})
    assert flat["suspect_phase"] is None


# ---------------------------------------------------------------------------
# Metrics cardinality budget


def test_cardinality_budget_overflow_series(monkeypatch):
    monkeypatch.setenv(obs_metrics.ENV_MAX_SERIES, "3")
    c = obs_metrics.REGISTRY.counter("card_probe_total")
    for i in range(10):
        c.inc(1, key=f"k{i}")
    dump = obs_metrics.REGISTRY.to_dict()
    series = dump["counters"]["card_probe_total"]["series"]
    assert len(series) <= 4  # 3 admitted + the overflow bucket
    overflow = [s for s in series if s["labels"] == {"overflow": "true"}]
    assert overflow and overflow[0]["value"] == 7.0
    dropped = dump["counters"][obs_metrics.SERIES_DROPPED]["series"]
    assert dropped[0]["labels"] == {"metric": "card_probe_total"}
    assert dropped[0]["value"] == 7.0


def test_cardinality_budget_existing_keys_keep_counting(monkeypatch):
    monkeypatch.setenv(obs_metrics.ENV_MAX_SERIES, "2")
    c = obs_metrics.REGISTRY.counter("card_probe2_total")
    c.inc(1, k="a")
    c.inc(1, k="b")
    c.inc(1, k="c")  # over budget -> overflow
    c.inc(5, k="a")  # established series unaffected by the budget
    dump = obs_metrics.REGISTRY.to_dict()
    series = {tuple(sorted(s["labels"].items())): s["value"]
              for s in dump["counters"]["card_probe2_total"]["series"]}
    assert series[(("k", "a"),)] == 6.0
    assert series[(("overflow", "true"),)] == 1.0


def test_cardinality_budget_disabled_with_zero(monkeypatch):
    monkeypatch.setenv(obs_metrics.ENV_MAX_SERIES, "0")
    c = obs_metrics.REGISTRY.counter("card_probe3_total")
    for i in range(600):
        c.inc(1, key=f"k{i}")
    dump = obs_metrics.REGISTRY.to_dict()
    assert len(dump["counters"]["card_probe3_total"]["series"]) == 600


# ---------------------------------------------------------------------------
# CLI surfaces


def test_trace_diff_cli(tmp_path):
    a = {"schema": 1, "trace_id": "A", "spans": [
        {"name": "kernel", "seconds": 0.100},
        {"name": "parse", "seconds": 0.010}]}
    b = {"schema": 1, "trace_id": "B", "spans": [
        {"name": "kernel", "seconds": 0.020},
        {"name": "parse", "seconds": 0.010}]}
    (tmp_path / "a.json").write_text(json.dumps(a))
    (tmp_path / "b.json").write_text(json.dumps(b))
    res = _cli("trace", "diff", "a.json", "b.json", "--json",
               cwd=tmp_path)
    assert res.returncode == 0, res.stderr
    out = json.loads(res.stdout)
    assert out["suspect_phase"] == "kernel"
    assert out["phases"][0]["phase"] == "kernel"
    human = _cli("trace", "diff", "a.json", "b.json", cwd=tmp_path)
    assert human.returncode == 0
    assert "suspect phase: kernel" in human.stdout


def test_trace_diff_cli_rejects_garbage(tmp_path):
    (tmp_path / "a.json").write_text("{not json")
    (tmp_path / "b.json").write_text(json.dumps({"spans": []}))
    res = _cli("trace", "diff", "a.json", "b.json", cwd=tmp_path)
    assert res.returncode == 1
    assert "not a span-shaped trace artifact" in res.stderr


def test_trace_analyze_survives_corrupt_artifacts(tmp_path):
    good = {"schema": 1, "trace_id": "ok", "spans": [
        {"name": "kernel", "seconds": 0.010, "status": "ok",
         "depth": 0, "meta": {}}]}
    (tmp_path / "good.json").write_text(json.dumps(good))
    (tmp_path / "trunc.json").write_text('{"schema": 1, "spans": [')
    (tmp_path / "mixed.jsonl").write_text(
        json.dumps({"name": "emit", "seconds": 0.001, "status": "ok",
                    "depth": 0, "meta": {}}) + "\n"
        + "{corrupt line\n")
    res = _cli("trace", "analyze", str(tmp_path), "--json")
    assert res.returncode == 0, res.stderr
    out = json.loads(res.stdout)
    assert out["requests"] == 2
    assert out["skipped"] >= 1
    assert out["corrupt_lines"] >= 1
    assert "skipped" in res.stderr and "corrupt" in res.stderr


def test_trace_analyze_since_filter(tmp_path):
    import os
    art = {"schema": 1, "trace_id": "old", "spans": [
        {"name": "kernel", "seconds": 0.010, "status": "ok",
         "depth": 0, "meta": {}}]}
    old = tmp_path / "old.json"
    old.write_text(json.dumps(art))
    os.utime(old, (1000, 1000))  # 1970: far outside any window
    new = tmp_path / "new.json"
    new.write_text(json.dumps(dict(art, trace_id="new")))
    res = _cli("trace", "analyze", str(tmp_path), "--since", "1h",
               "--json")
    assert res.returncode == 0, res.stderr
    out = json.loads(res.stdout)
    assert out["requests"] == 1
    assert out["results"][0]["trace_id"] == "new"
    bad = _cli("trace", "analyze", str(tmp_path), "--since", "-3s")
    assert bad.returncode != 0


def test_top_once_unreachable_daemon(tmp_path):
    res = _cli("top", "--once", "--socket",
               str(tmp_path / "nope.sock"))
    assert res.returncode == 1
    assert "error:" in res.stderr


def test_top_render_frame_shapes():
    from semantic_merge_tpu.cli import _render_top_frame
    status = {"pid": 1, "uptime_s": 5.0, "socket": "/tmp/x.sock",
              "queue_depth": 2, "in_flight": 1, "served_total": 9,
              "window": {"1s": {"qps": 3.0, "p50_ms": 4.0,
                                "p99_ms": 9.0, "error_rate": 0.0},
                         "1m": {"qps": 0.5, "p50_ms": 4.5,
                                "p99_ms": 11.0, "error_rate": 0.1}},
              "resilience": {"pressure": 0,
                             "breakers": {"kernel": "open",
                                          "host": "closed"}},
              "residency": {"lookups": 10, "hit_rate": 0.8},
              "sampling": {"enabled": True},
              "trace_store": {"count": 3, "bytes": 1 << 20,
                              "budget_bytes": 16 << 20},
              "anomaly": {"latched": ["kernel"], "fired": 2},
              "slo": {"healthy": False}}
    frame = _render_top_frame({"status": status, "members": None})
    assert "merge daemon pid 1" in frame
    assert "OPEN:kernel" in frame
    assert "residency hit 80.0%" in frame
    assert "ANOMALY latched: kernel" in frame
    assert "BURNING" in frame
    # Fleet shape: member table from the member_status blocks.
    fleet = {"fleet": True, "pid": 2, "uptime_s": 1.0,
             "socket": "tcp://0:1", "in_flight": 0, "served_total": 4,
             "window": {}, "members": [{"id": "m0", "state": "up"}]}
    members = {"m0": {"window": {"1m": {"qps": 1.5, "p99_ms": 7.0}},
                      "queue_depth": 1, "in_flight": 0,
                      "served_total": 4}}
    fframe = _render_top_frame({"status": fleet, "members": members})
    assert "fleet router" in fframe
    assert "m0" in fframe and "up" in fframe


# ---------------------------------------------------------------------------
# Schema validators (wired into tier-1 like the rest of the family)


def test_validate_sampling_real_policy_stats(schema, monkeypatch):
    monkeypatch.setenv(obs_sampling.ENV_SAMPLE, "4")
    policy = obs_sampling.SamplingPolicy()
    for i in range(20):
        policy.decide(f"t{i}", "semmerge", 0.01, error=(i == 3),
                      degraded=False, breaker=False, resolver=False)
    payload = {"sampling": policy.stats(),
               "metrics": obs_metrics.REGISTRY.to_dict()}
    assert schema.validate_sampling(payload) == []


def test_validate_sampling_rejects_drift(schema):
    kept = {"sampling": {"keep": True, "reason": "mystery",
                         "minted_by": "daemon", "sample_n": 4}}
    assert any("mystery" in e for e in schema.validate_sampling(kept))
    dropped = {"sampling": {"keep": False, "reason": "sampled-out",
                            "minted_by": "daemon", "sample_n": 4}}
    assert any("keep=true" in e
               for e in schema.validate_sampling(dropped))
    over = {"trace_store": {"count": 1, "bytes": 999,
                            "budget_bytes": 100}}
    assert any("over budget" in e for e in schema.validate_sampling(over))


def test_validate_sampling_real_kept_artifact(schema, tmp_path):
    store = obs_sampling.TraceStore(tmp_path / "traces")
    d = obs_sampling.Decision(True, "slow", minted_by="daemon",
                              sample_n=10)
    path = store.write("t1", {"schema": 1, "kind": "trace",
                              "trace_id": "t1", "spans": []},
                       decision=d)
    data = json.loads(pathlib.Path(path).read_text())
    assert schema.validate_sampling(data) == []


def test_validate_window_real_aggregator(schema):
    from semantic_merge_tpu.obs import agg as obs_agg
    win = obs_agg.WindowAggregator()
    win.observe("semmerge", 0.012, phases={"kernel": 0.01})
    win.publish(obs_metrics.REGISTRY)
    payload = {"window": win.window(),
               "metrics": obs_metrics.REGISTRY.to_dict()}
    assert schema.validate_window(payload) == []


def test_validate_window_rejects_drift(schema):
    wb = {"span_s": 1.0, "count": 2, "errors": 3, "qps": 2.0,
          "error_rate": 1.0, "p50_ms": 1.0, "p99_ms": 2.0,
          "max_ms": 2.0, "phases_ms": {}, "verbs": {}}
    bad = {"window": {"1s": wb, "1m": dict(wb, span_s=60.0)}}
    assert any("errors > count" in e for e in schema.validate_window(bad))
    unknown = {"window": {"1s": dict(wb, errors=0),
                          "1m": dict(wb, errors=0, span_s=60.0),
                          "5m": dict(wb, errors=0)}}
    assert any("unknown rollup" in e
               for e in schema.validate_window(unknown))


def test_validate_triage_rejects_drift(schema, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv(obs_anomaly.ENV_ENABLE, "1")
    triage = obs_anomaly.AnomalyTriage(z_threshold=4.0, min_n=8,
                                       sustain=2)
    base = {"kernel": 0.020, "emit": 0.005}
    _drive(triage, 40, base)
    bundles = _drive(triage, 6, {"kernel": 0.300, "emit": 0.005},
                     start=50)
    data = json.loads(pathlib.Path(bundles[0]["bundle"]).read_text())
    assert schema.validate_triage(data) == []
    unsorted_diff = json.loads(json.dumps(data))
    unsorted_diff["triage"]["diff"].reverse()
    assert any("not sorted" in e
               for e in schema.validate_triage(unsorted_diff))
    wrong_suspect = json.loads(json.dumps(data))
    wrong_suspect["triage"]["suspect_phase"] = "emit"
    assert any("top positive-delta" in e
               for e in schema.validate_triage(wrong_suspect))
    noreason = json.loads(json.dumps(data))
    noreason["reason"] = "fault-escape"
    assert any("!= 'anomaly'" in e
               for e in schema.validate_triage(noreason))


def test_validator_cli_subcommands(tmp_path, schema):
    store = obs_sampling.TraceStore(tmp_path / "traces")
    path = store.write("t1", {"schema": 1, "trace_id": "t1",
                              "spans": []},
                       decision=obs_sampling.Decision(
                           True, "error", minted_by="daemon"))
    res = subprocess.run(
        [sys.executable, str(_SCRIPT), "validate_sampling", str(path)],
        capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stderr
    assert "ok" in res.stdout
    res2 = subprocess.run(
        [sys.executable, str(_SCRIPT), "validate_window"],
        capture_output=True, text=True, timeout=60)
    assert res2.returncode == 2  # usage
