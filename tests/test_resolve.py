"""Conflict-resolution tier (ISSUE 12 tentpole).

Per-category golden resolutions through the real CLI (the accepted
merge is byte-materialized through the normal pipeline and every verify
gate runs), plus the fallback ladder: gate rejection, tie, strict-mode
inertness, and breaker-open — each leaving a conflict-as-result exit
with the full audit trail in ``.semmerge-conflicts.json``.
"""
import importlib.util
import io
import json
import os
import pathlib
import subprocess
import tarfile

import pytest

from semantic_merge_tpu.cli import main
from semantic_merge_tpu.core.ops import Op, Target
from semantic_merge_tpu.resolve import posture
from semantic_merge_tpu.resolve.base import Candidate, ResolveContext, Resolver
from semantic_merge_tpu.resolve.search import SearchResolver, _merge3_lines
from semantic_merge_tpu.service.resilience import breakers
from semantic_merge_tpu.utils import faults

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


def _schema_module():
    script = REPO_ROOT / "scripts" / "check_trace_schema.py"
    spec = importlib.util.spec_from_file_location("cts_resolve", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def git(args, cwd):
    subprocess.run(["git", *args], cwd=cwd, check=True,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def make_repo(root, base, br_a, br_b):
    """A basebr/brA/brB repo from three {relpath: content} trees."""
    root.mkdir()
    git(["init", "-q", "-b", "main"], root)
    git(["config", "user.email", "t@example.com"], root)
    git(["config", "user.name", "t"], root)

    def write_tree(files):
        for p in root.iterdir():
            if p.name == ".git":
                continue
            if p.is_dir():
                import shutil
                shutil.rmtree(p)
            else:
                p.unlink()
        for rel, content in files.items():
            path = root / rel
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text(content)

    write_tree(base)
    git(["add", "-A"], root)
    git(["commit", "-q", "-m", "base"], root)
    git(["branch", "basebr"], root)
    git(["checkout", "-qb", "brA"], root)
    write_tree(br_a)
    git(["add", "-A"], root)
    git(["commit", "-q", "-m", "A"], root)
    git(["checkout", "-q", "main"], root)
    git(["checkout", "-qb", "brB"], root)
    write_tree(br_b)
    git(["add", "-A"], root)
    git(["commit", "-q", "-m", "B"], root)
    git(["checkout", "-q", "main"], root)
    return root


def run_cli(*extra):
    return main(["semmerge", "basebr", "brA", "brB",
                 "--inplace", "--backend", "host", *extra])


def read_artifact(root):
    return json.loads((root / ".semmerge-conflicts.json").read_text())


UTIL_BASE = ("export function foo(n: number): number {\n  return n;\n}\n"
             "export function use(s: string): number {\n"
             "  return foo(s.length);\n}\n")
UTIL_A_BAR = ("export function bar(n: number): number {\n  return n;\n}\n"
              "export function use(s: string): number {\n"
              "  return bar(s.length);\n}\n")
UTIL_B_BAZ = ("export function baz(n: number): number {\n  return n;\n}\n"
              "export function use(s: string): number {\n"
              "  return foo(s.length);\n}\n")


@pytest.fixture
def rename_repo(tmp_path, monkeypatch):
    """DivergentRename with asymmetric evidence: brA renames foo→bar
    and rewrites the caller; brB renames the declaration only."""
    root = make_repo(tmp_path / "repo", {"src/util.ts": UTIL_BASE},
                     {"src/util.ts": UTIL_A_BAR},
                     {"src/util.ts": UTIL_B_BAZ})
    monkeypatch.chdir(root)
    faults.reset()
    yield root
    faults.reset()


@pytest.fixture(autouse=True)
def _clean_breakers():
    breakers().reset()
    yield
    breakers().reset()


# ---------------------------------------------------------------------------
# Posture plumbing
# ---------------------------------------------------------------------------

def test_posture_defaults_off(monkeypatch):
    monkeypatch.delenv("SEMMERGE_RESOLVE", raising=False)
    assert posture() == "off"
    monkeypatch.setenv("SEMMERGE_RESOLVE", "auto")
    assert posture() == "auto"
    monkeypatch.setenv("SEMMERGE_RESOLVE", "REQUIRE")
    assert posture() == "require"
    monkeypatch.setenv("SEMMERGE_RESOLVE", "bogus")
    assert posture() == "off"


# ---------------------------------------------------------------------------
# Golden resolutions, per category
# ---------------------------------------------------------------------------

def test_divergent_rename_resolved_end_to_end(rename_repo):
    """The reference-rewriting rename wins; the merge succeeds, the
    tree carries the winning name everywhere, and the artifact records
    the accepted audit with all four gates green, in order."""
    rc = run_cli("--resolve")
    assert rc == 0, "the unique-winner rename must merge cleanly"
    text = (rename_repo / "src/util.ts").read_text()
    assert "bar(" in text and "return bar(s.length)" in text
    assert "baz" not in text
    payload = read_artifact(rename_repo)
    assert payload["schema_version"] == 2
    assert [c["category"] for c in payload["conflicts"]] == \
        ["DivergentRename"]
    (rec,) = payload["resolutions"]
    assert rec["status"] == "accepted" and rec["cause"] is None
    assert rec["resolver"] == "search"
    assert rec["candidate"]["id"] == "keepA"
    assert rec["scores"] == {"keepA": 2, "keepB": 1}
    assert [g["gate"] for g in rec["gates"]] == \
        ["recompose", "parity", "typecheck", "format"]
    assert all(g["ok"] for g in rec["gates"])
    assert _schema_module().validate_conflicts(payload) == []


def test_delete_vs_edit_resolved_end_to_end(tmp_path, monkeypatch):
    """Completed-cleanup deletion beats a body edit of the deleted
    symbol: brB removed ``foo`` and its call site, brA only touched
    ``foo``'s body — keepDelete is the unique evidence-backed winner."""
    foo = "export function foo(n: number): number {\n  return n;\n}\n"
    use = ("import { foo } from './foo';\n"
           "export function use(s: string): number {\n"
           "  return foo(s.length);\n}\n")
    root = make_repo(
        tmp_path / "repo",
        {"src/foo.ts": foo, "src/use.ts": use},
        {"src/foo.ts": foo.replace("return n;", "return n + 1;"),
         "src/use.ts": use},
        {"src/foo.ts": "",
         "src/use.ts": "export function use(s: string): number {\n"
                       "  return s.length;\n}\n"})
    monkeypatch.chdir(root)
    rc = run_cli("--resolve", "auto", "--strict-conflicts",
                 "--structured-apply")
    assert rc == 0
    assert "function foo" not in (root / "src/foo.ts").read_text()
    assert "return s.length" in (root / "src/use.ts").read_text()
    payload = read_artifact(root)
    cats = {r["category"]: r for r in payload["resolutions"]}
    rec = cats["DeleteVsEdit"]
    assert rec["status"] == "accepted"
    assert rec["candidate"]["id"] == "keepDelete"
    assert _schema_module().validate_conflicts(payload) == []


def test_concurrent_stmt_edit_resolved_end_to_end(tmp_path, monkeypatch):
    """Disjoint line edits of the same body 3-way-merge into one body
    carrying both changes."""
    base = ("export function calc(n: number): number {\n"
            "  n = n + 1;\n"
            "  n = n * 2;\n"
            "  return n;\n"
            "}\n")
    root = make_repo(
        tmp_path / "repo",
        {"src/calc.ts": base},
        {"src/calc.ts": base.replace("n = n + 1;", "n = n + 3;")},
        {"src/calc.ts": base.replace("n = n * 2;", "n = n * 4;")})
    monkeypatch.chdir(root)
    rc = run_cli("--resolve", "auto", "--strict-conflicts")
    assert rc == 0
    text = (root / "src/calc.ts").read_text()
    assert "n = n + 3;" in text and "n = n * 4;" in text
    payload = read_artifact(root)
    (rec,) = [r for r in payload["resolutions"]
              if r["category"] == "ConcurrentStmtEdit"]
    assert rec["status"] == "accepted"
    assert rec["candidate"]["id"] == "merged3way"
    assert _schema_module().validate_conflicts(payload) == []


def test_overlapping_stmt_edits_fall_back(tmp_path, monkeypatch):
    """The same line edited to different results on both sides: no
    candidate — conflict-as-result, audit says so."""
    root = make_repo(
        tmp_path / "repo",
        {"a.ts": "export function foo(n: number): number { return 0; }\n"},
        {"a.ts": "export function foo(n: number): number { return 1; }\n"},
        {"a.ts": "export function foo(n: number): number { return 2; }\n"})
    monkeypatch.chdir(root)
    rc = run_cli("--resolve", "auto", "--strict-conflicts")
    assert rc == 1
    payload = read_artifact(root)
    (rec,) = [r for r in payload["resolutions"]
              if r["category"] == "ConcurrentStmtEdit"]
    assert rec["status"] == "rejected"
    assert rec["cause"] == "no-candidates"


# ---------------------------------------------------------------------------
# Fallback ladder: tie, gate rejection, strict inertness, breaker-open
# ---------------------------------------------------------------------------

def test_symmetric_renames_tie_and_fall_back(tmp_path, monkeypatch):
    """Both sides rename the declaration only — equal evidence, a tie,
    and the tier refuses to guess. Work tree stays conflicted."""
    base = "export function foo(n: number): number {\n  return n;\n}\n"
    root = make_repo(
        tmp_path / "repo",
        {"src/util.ts": base},
        {"src/util.ts": base.replace("foo", "bar")},
        {"src/util.ts": base.replace("foo", "baz")})
    monkeypatch.chdir(root)
    monkeypatch.setenv("SEMMERGE_RESOLVE", "auto")  # env path, not flag
    rc = run_cli()
    assert rc == 1
    payload = read_artifact(root)
    (rec,) = payload["resolutions"]
    assert rec["status"] == "rejected" and rec["cause"] == "tie"
    assert rec["scores"] == {"keepA": 1, "keepB": 1}
    assert rec["gates"] == []
    assert _schema_module().validate_conflicts(payload) == []


def test_gate_rejection_falls_back_byte_exact(rename_repo, monkeypatch):
    """A candidate that fails a verify gate (here: drops nothing, so
    recompose still sees the divergent renames) is rejected; the tree
    is byte-identical to a resolver-off run and the audit carries the
    failed gate."""

    class NoopResolver(Resolver):
        name = "noop"

        def propose(self, conflict, ctx):
            return [Candidate(id="noop", label="change nothing",
                              rationale="test", score=1)]

    monkeypatch.setenv("SEMMERGE_RESOLVE", "off")
    assert run_cli() == 1
    baseline = {p.relative_to(rename_repo).as_posix(): p.read_bytes()
                for p in sorted(rename_repo.rglob("*.ts"))}
    monkeypatch.setenv("SEMMERGE_RESOLVE", "auto")
    monkeypatch.setattr("semantic_merge_tpu.resolve.engine.SearchResolver",
                        NoopResolver)
    rc = run_cli()
    assert rc == 1
    assert {p.relative_to(rename_repo).as_posix(): p.read_bytes()
            for p in sorted(rename_repo.rglob("*.ts"))} == baseline
    payload = read_artifact(rename_repo)
    (rec,) = payload["resolutions"]
    assert rec["status"] == "rejected"
    assert rec["cause"] == "gate:recompose"
    assert rec["gates"][0]["gate"] == "recompose"
    assert rec["gates"][0]["ok"] is False
    assert "residual" in rec["gates"][0]["detail"]
    assert _schema_module().validate_conflicts(payload) == []


@pytest.mark.parametrize("mode", ["env", "flag"])
def test_strict_mode_keeps_resolver_inert(rename_repo, monkeypatch, mode):
    """``SEMMERGE_STRICT=1`` / ``--no-degrade`` force the tier off even
    when the posture asks for it: legacy bare-array artifact, exit 1."""
    monkeypatch.setenv("SEMMERGE_RESOLVE", "auto")
    if mode == "env":
        monkeypatch.setenv("SEMMERGE_STRICT", "1")
        rc = run_cli()
    else:
        rc = run_cli("--no-degrade")
    assert rc == 1
    payload = read_artifact(rename_repo)
    assert isinstance(payload, list), \
        "strict mode must keep the legacy artifact shape"
    assert "baz" not in (rename_repo / "src/util.ts").read_text() \
        or "bar" not in (rename_repo / "src/util.ts").read_text()


def test_breaker_open_skips_propose(rename_repo, monkeypatch):
    """An open ``resolve:<Category>`` breaker refuses the attempt
    before propose runs: cause ``breaker-open``, conflict-as-result."""
    monkeypatch.setenv("SEMMERGE_BREAKER", "on")
    monkeypatch.setenv("SEMMERGE_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("SEMMERGE_BREAKER_COOLDOWN", "600")
    breakers().record_failure("resolve:DivergentRename")  # opens it
    assert breakers().snapshot()["resolve:DivergentRename"] == "open"
    monkeypatch.setenv("SEMMERGE_RESOLVE", "auto")
    rc = run_cli()
    assert rc == 1
    payload = read_artifact(rename_repo)
    (rec,) = payload["resolutions"]
    assert rec["status"] == "rejected" and rec["cause"] == "breaker-open"
    assert rec["candidates"] == 0 and rec["gates"] == []


def test_require_posture_tie_still_conflict_as_result(tmp_path, monkeypatch):
    """``require`` escalates resolver *faults* to exit 17 (pinned in
    test_faults.py); a clean tie is not a fault — it stays a documented
    conflict exit with the tie recorded in the audit."""
    base = "export function foo(n: number): number {\n  return n;\n}\n"
    root = make_repo(
        tmp_path / "repo",
        {"src/util.ts": base},
        {"src/util.ts": base.replace("foo", "bar")},
        {"src/util.ts": base.replace("foo", "baz")})
    monkeypatch.chdir(root)
    rc = run_cli("--resolve", "require")
    assert rc == 1
    payload = read_artifact(root)
    assert payload["resolutions"][0]["cause"] == "tie"
    from semantic_merge_tpu.errors import ResolveFault
    assert ResolveFault.exit_code == 17


# ---------------------------------------------------------------------------
# SearchResolver unit goldens (synthetic ops + snapshots)
# ---------------------------------------------------------------------------

def _tar(files):
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        for rel, content in files.items():
            data = content.encode()
            info = tarfile.TarInfo(rel)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    return buf.getvalue()


def _op(op_type, sym, params, op_id, addr=None):
    return Op.new(op_type,
                  Target(symbolId=sym,
                         addressId=addr or f"f.ts::{sym}::0"),
                  params=params, op_id=op_id)


def test_merge3_disjoint_and_overlap():
    base = "a\nb\nc\n"
    assert _merge3_lines(base, "A\nb\nc\n", "a\nb\nC\n") == "A\nb\nC\n"
    assert _merge3_lines(base, "A\nb\nc\n", "X\nb\nc\n") is None
    # Both inserting different text at the same point is a guess.
    assert _merge3_lines(base, "a\nnew1\nb\nc\n", "a\nnew2\nb\nc\n") is None
    # Identical edits on both sides dedupe.
    assert _merge3_lines(base, "A\nb\nc\n", "A\nb\nc\n") == "A\nb\nc\n"


def test_extract_vs_inline_unit_golden():
    """keepExtract wins when the extracted helper is actually called;
    the losing inline motion drops together with its companions."""
    ext = _op("extractMethod", "host",
              {"file": "f.ts", "newName": "helper", "blockHash": "h",
               "newAddress": "f.ts::helper::0"}, "a-ext",
              addr="f.ts::host::0")
    ext_edit = _op("editStmtBlock", "host",
                   {"file": "f.ts", "oldBodyHash": "x", "newBodyHash": "y",
                    "oldBody": "body", "newBody": "helper();"}, "a-edit",
                   addr="f.ts::host::0")
    ext_add = _op("addDecl", "helper", {"file": "f.ts"}, "a-add",
                  addr="f.ts::helper::0")
    inl = _op("inlineMethod", "host",
              {"file": "f.ts", "methodName": "callee", "blockHash": "h",
               "oldAddress": "f.ts::callee::0"}, "b-inl",
              addr="f.ts::host::0")
    inl_del = _op("deleteDecl", "callee", {"file": "f.ts"}, "b-del",
                  addr="f.ts::callee::0")
    ctx = ResolveContext(
        [ext, ext_edit, ext_add], [inl, inl_del],
        base_tar=_tar({"f.ts": "function host() { callee(); }\n"
                               "function callee() {}\n"}),
        left_tar=_tar({"f.ts": "function host() { helper(); }\n"
                               "function helper() {}\n"
                               "function callee() {}\n"}),
        right_tar=_tar({"f.ts": "function host() { /* inlined */ }\n"}))
    conflict = {"category": "ExtractVsInline",
                "opA": ext.to_dict(), "opB": inl.to_dict()}
    cands = SearchResolver().propose(conflict, ctx)
    by_id = {c.id: c for c in cands}
    assert by_id["keepExtract"].score == 2  # helper decl + call site
    assert set(by_id["keepExtract"].drops) == {"b-inl", "b-del"}
    assert by_id["keepInline"].score == 1  # one call site cleaned up
    assert set(by_id["keepInline"].drops) == {"a-ext", "a-edit", "a-add"}


def test_delete_vs_edit_unit_tie_without_evidence():
    """No cleanup and no new usage: both scores 0 — the engine will
    treat that as a tie and fall back."""
    op_del = _op("deleteDecl", "sym", {"file": "f.ts"}, "a1")
    op_edit = _op("renameSymbol", "sym",
                  {"oldName": "foo", "newName": "goo", "file": "f.ts"}, "b1")
    src = "export function foo(): void {}\n"
    ctx = ResolveContext([op_del], [op_edit],
                         base_tar=_tar({"f.ts": src}),
                         left_tar=_tar({"f.ts": ""}),
                         right_tar=_tar({"f.ts": src.replace("foo", "goo")}))
    conflict = {"category": "DeleteVsEdit",
                "opA": op_del.to_dict(), "opB": op_edit.to_dict()}
    cands = SearchResolver().propose(conflict, ctx)
    assert {c.id: c.score for c in cands} == {"keepDelete": 0, "keepEdit": 0}


def test_unknown_category_proposes_nothing():
    ctx = ResolveContext([], [], base_tar=_tar({}), left_tar=_tar({}),
                         right_tar=_tar({}))
    assert SearchResolver().propose({"category": "DivergentMove"}, ctx) == []
