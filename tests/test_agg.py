"""Streaming-aggregation layer (obs/agg.py): quantile-sketch accuracy
and merge properties, windowed rollups, and gauge publication."""
import random

import pytest

from semantic_merge_tpu.obs import agg as obs_agg
from semantic_merge_tpu.obs import metrics as obs_metrics


@pytest.fixture(autouse=True)
def _clean_registry():
    obs_metrics.REGISTRY.reset()
    yield
    obs_metrics.REGISTRY.reset()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# QuantileSketch


def test_sketch_relative_error_bound():
    rng = random.Random(7)
    values = [rng.lognormvariate(0.0, 1.0) for _ in range(5000)]
    sk = obs_agg.QuantileSketch(alpha=0.01)
    for v in values:
        sk.observe(v)
    values.sort()
    for q in (0.5, 0.9, 0.99):
        exact = values[int(q * (len(values) - 1))]
        est = sk.quantile(q)
        # Log-bucket guarantee: relative error bounded by alpha (plus
        # a small rank-interpolation slop on the exact quantile).
        assert abs(est - exact) / exact < 3 * sk.alpha


def test_sketch_merge_equals_union_stream():
    rng = random.Random(11)
    a_vals = [rng.uniform(0.001, 1.0) for _ in range(800)]
    b_vals = [rng.uniform(0.5, 10.0) for _ in range(1200)]
    a = obs_agg.QuantileSketch(alpha=0.01)
    b = obs_agg.QuantileSketch(alpha=0.01)
    union = obs_agg.QuantileSketch(alpha=0.01)
    for v in a_vals:
        a.observe(v)
        union.observe(v)
    for v in b_vals:
        b.observe(v)
        union.observe(v)
    merged = a.merge(b)
    assert merged.count == union.count == len(a_vals) + len(b_vals)
    assert merged.sum == pytest.approx(union.sum)
    assert merged.max == union.max
    for q in (0.1, 0.5, 0.9, 0.99):
        # Bucket-wise addition: the merged sketch IS the union sketch.
        assert merged.quantile(q) == union.quantile(q)


def test_sketch_merge_alpha_mismatch_rejected():
    a = obs_agg.QuantileSketch(alpha=0.01)
    b = obs_agg.QuantileSketch(alpha=0.05)
    with pytest.raises(ValueError):
        a.merge(b)


def test_sketch_roundtrip_dict():
    sk = obs_agg.QuantileSketch(alpha=0.02)
    for v in (0.0, 0.001, 0.5, 2.0, 2.0, 9.0):
        sk.observe(v)
    back = obs_agg.QuantileSketch.from_dict(sk.to_dict())
    assert back.count == sk.count
    assert back.zero == sk.zero
    for q in (0.25, 0.5, 0.99):
        assert back.quantile(q) == sk.quantile(q)


def test_sketch_empty_and_zero_heavy():
    sk = obs_agg.QuantileSketch()
    assert sk.quantile(0.5) == 0.0
    for _ in range(99):
        sk.observe(0.0)
    sk.observe(1.0)
    assert sk.quantile(0.5) == 0.0
    assert sk.quantile(1.0) > 0.9


# ---------------------------------------------------------------------------
# WindowAggregator


def test_window_rollups_1s_and_1m():
    clock = FakeClock()
    win = obs_agg.WindowAggregator(clock=clock)
    for _ in range(5):
        win.observe("semmerge", 0.010, phases={"kernel": 0.008})
    win.observe("semdiff", 0.050, error=True, phases={"kernel": 0.04})
    clock.advance(1.0)  # the just-filled slot becomes the closed 1s one
    out = win.window()
    for key in ("1s", "1m"):
        assert out[key]["count"] == 6
        assert out[key]["errors"] == 1
        assert out[key]["error_rate"] == pytest.approx(1 / 6, abs=1e-4)
        assert out[key]["verbs"] == {"semmerge": 5, "semdiff": 1}
        assert out[key]["phases_ms"]["kernel"] > 0
    assert out["1s"]["span_s"] == 1.0
    assert out["1m"]["span_s"] == 60.0
    assert out["1s"]["qps"] == pytest.approx(6.0)
    assert out["1m"]["qps"] == pytest.approx(6.0 / 60.0)
    assert out["1m"]["p99_ms"] >= out["1m"]["p50_ms"] > 0


def test_window_old_slots_age_out():
    clock = FakeClock()
    win = obs_agg.WindowAggregator(clock=clock)
    win.observe("semmerge", 0.010)
    clock.advance(120.0)
    win.observe("semmerge", 0.020)
    clock.advance(1.0)
    out = win.window()
    # The 2-minute-old request is outside both rollup windows.
    assert out["1m"]["count"] == 1
    assert out["1s"]["count"] == 1


def test_window_publish_gauges():
    clock = FakeClock()
    win = obs_agg.WindowAggregator(clock=clock)
    win.observe("semmerge", 0.010)
    clock.advance(1.0)
    win.publish(obs_metrics.REGISTRY)
    dump = obs_metrics.REGISTRY.to_dict()
    qps = dump["gauges"]["semmerge_window_qps"]["series"]
    labels = {tuple(sorted(s["labels"].items())) for s in qps}
    assert (("window", "1s"),) in labels
    assert (("window", "1m"),) in labels
    for name in ("semmerge_window_p50_ms", "semmerge_window_p99_ms",
                 "semmerge_window_error_rate"):
        assert name in dump["gauges"]


def test_window_sketch_for_merges_slots():
    clock = FakeClock()
    win = obs_agg.WindowAggregator(clock=clock)
    for i in range(30):
        win.observe("semmerge", 0.010 + i * 0.001)
        clock.advance(1.0)
    sk = win.sketch_for("1m")
    assert sk.count == 30
    assert sk.quantile(0.5) == pytest.approx(0.0245, rel=0.2)
