"""Host-tail pipeline parity — the BASELINE invariant of the
pipelined-materialization round.

The fused merge's post-kernel tail (chain decode → op materialization →
op-log serialization) runs as row-range shards over a worker pool
(``SEMMERGE_HOST_WORKERS`` / ``[engine] host_workers``), with a
deterministic shard-order merge of per-shard results. These tests pin
the contract: the emitted op-log bytes and the materialized composed
stream are IDENTICAL for every worker count and shard size — including
the concurrent schedule (eager prefetch + sharded serialization), which
single-core hosts skip by default and these tests force on.
"""
from __future__ import annotations

import os

import pytest

from semantic_merge_tpu.backends.base import get_backend, run_merge
from semantic_merge_tpu.core.encode import shard_ranges
from semantic_merge_tpu.core.ops import OpLog
from semantic_merge_tpu.frontend.snapshot import Snapshot

TS = "2026-01-02T03:04:05Z"


def snap(files):
    return Snapshot(files=[{"path": p, "content": c} for p, c in files])


def _workload(n_files=40, conflicts=False):
    """A multi-kind workload big enough to span several tiny shards."""
    base, left, right = [], [], []
    for i in range(n_files):
        path = f"src/m{i:03d}.ts"
        content = (f"export function fn{i}(x: number): number "
                   f"{{ return {i}; }}\n")
        base.append((path, content))
        if i % 2 == 0:
            left.append((path, content.replace(f"fn{i}(", f"renamed{i}(")))
        elif i % 7 == 0:
            left.append((path, content + f"export function extra{i}"
                                         f"(s: string): string "
                                         f"{{ return s; }}\n"))
        else:
            left.append((path, content))
        if conflicts and i % 8 == 0:
            right.append((path, content.replace(f"fn{i}(", f"other{i}(")))
        elif i % 2 == 1:
            right.append((f"lib/m{i:03d}.ts", content))
        else:
            right.append((path, content))
    return snap(base), snap(left), snap(right)


def _merge_outputs(monkeypatch, workers: int, shard_rows: int,
                   base, left, right, force_multicore: bool = True,
                   seed="s", base_rev="r", timestamp=TS):
    """One fused merge under the given pipeline geometry; returns the
    two op-log byte payloads, the composed op dicts, and conflicts."""
    monkeypatch.setenv("SEMMERGE_HOST_WORKERS", str(workers))
    monkeypatch.setenv("SEMMERGE_TAIL_SHARD_ROWS", str(shard_rows))
    if force_multicore:
        # The concurrent schedule (eager shard prefetch + sharded
        # serialization) is gated on multi-core hosts; force it so the
        # parity claim covers the schedule actually used in production.
        monkeypatch.setattr(os, "cpu_count", lambda: 8)
    from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
    tpu = TpuTSBackend(mesh=False)
    res, composed, conflicts = run_merge(tpu, base, left, right, seed=seed,
                                         base_rev=base_rev,
                                         timestamp=timestamp)
    return (OpLog(res.op_log_left).to_json_bytes(),
            OpLog(res.op_log_right).to_json_bytes(),
            [o.to_dict() for o in composed],
            [c.to_dict() for c in conflicts])


@pytest.mark.parametrize("conflicts", [False, True],
                         ids=["clean", "divergent"])
def test_pipelined_oplog_byte_parity_across_worker_counts(
        monkeypatch, conflicts):
    if conflicts:
        # The bench divergent preset is pinned (test_fused) to surface
        # DivergentRename at the compose cursors — hand-rolled
        # interleavings get masked by the reference's cursor-walk quirk.
        import bench
        base, left, right = bench.synth_repo(97, 3, divergent=True)
        kw = dict(seed="bench", base_rev="bench",
                  timestamp="2026-01-01T00:00:00Z")
    else:
        base, left, right = _workload(conflicts=False)
        kw = {}
    # Serial reference: one worker, one shard covering the stream, and
    # no forced multicore — the exact pre-pipeline serial code path.
    ref = _merge_outputs(monkeypatch, 1, 1 << 20, base, left, right,
                         force_multicore=False, **kw)
    if conflicts:
        assert ref[3], "divergent workload must produce conflicts"
    for workers in (1, 4):
        for shard_rows in (7, 64):
            got = _merge_outputs(monkeypatch, workers, shard_rows,
                                 base, left, right, **kw)
            assert got[0] == ref[0], (workers, shard_rows)
            assert got[1] == ref[1], (workers, shard_rows)
            assert got[2] == ref[2], (workers, shard_rows)
            assert got[3] == ref[3], (workers, shard_rows)


def test_pipelined_empty_merge(monkeypatch):
    # Identical snapshots: zero ops, zero shards (shard_ranges(0) is
    # empty) — the pipeline must produce the empty payloads, not choke.
    base, _, _ = _workload(8)
    for workers in (1, 4):
        left_json, right_json, comp, confs = _merge_outputs(
            monkeypatch, workers, 4, base, base, base)
        assert left_json == b"[]" and right_json == b"[]"
        assert comp == [] and confs == []


def test_pipelined_matches_host_oracle(monkeypatch):
    # The sharded pipeline must stay byte-identical to the HOST
    # backend's Op-object serialization (the Node-worker parity
    # surface), not merely self-consistent — conflict drops included.
    import bench
    base, left, right = bench.synth_repo(97, 3, divergent=True)
    got = _merge_outputs(monkeypatch, 4, 37, base, left, right,
                         seed="bench", base_rev="bench",
                         timestamp="2026-01-01T00:00:00Z")
    res_h, comp_h, conf_h = run_merge(get_backend("host"), base, left,
                                      right, seed="bench",
                                      base_rev="bench",
                                      timestamp="2026-01-01T00:00:00Z")
    assert got[0] == OpLog(res_h.op_log_left).to_json_bytes()
    assert got[1] == OpLog(res_h.op_log_right).to_json_bytes()
    assert got[2] == [o.to_dict() for o in comp_h]
    assert got[3] == [c.to_dict() for c in conf_h]


def test_chain_decode_fault_surfaces_typed_not_hung(monkeypatch):
    """A fault injected into the pipelined chain decode (worker thread)
    must surface to the consumer as a typed KernelFault — the pool must
    not swallow it or wedge the shard walk (tentpole: chain-decode
    injection point feeding the degradation ladder)."""
    from semantic_merge_tpu.errors import KernelFault
    from semantic_merge_tpu.ops.fused import TailPipeline, TailPlan
    from semantic_merge_tpu.utils import faults
    faults.reset()
    monkeypatch.setenv("SEMMERGE_FAULT", "chain:fault")
    plan = TailPlan(TailPipeline(workers=2, shard_rows=4), 10,
                    lambda lo, hi: ([], [], []))
    plan.prefetch()
    with pytest.raises(KernelFault) as exc_info:
        plan.decode_all()
    assert exc_info.value.stage == "chain"
    faults.reset()
    monkeypatch.delenv("SEMMERGE_FAULT")
    # A fresh plan over the same pipeline still works (no poisoning).
    plan2 = TailPlan(TailPipeline(workers=2, shard_rows=4), 10,
                     lambda lo, hi: (list(range(lo, hi)), [], []))
    addr, _, _ = plan2.decode_all()
    assert addr == list(range(10))


def test_shard_ranges_contract():
    assert shard_ranges(0, 8) == []
    assert shard_ranges(1, 8) == [(0, 1)]
    assert shard_ranges(8, 8) == [(0, 8)]
    assert shard_ranges(9, 8) == [(0, 8), (8, 9)]
    assert shard_ranges(20, 7) == [(0, 7), (7, 14), (14, 20)]
    # Degenerate shard size clamps to 1 row per shard.
    assert shard_ranges(3, 0) == [(0, 1), (1, 2), (2, 3)]
    # Ranges tile [0, n) exactly — every consumer sees the same plan.
    for n, rows in ((1, 1), (13, 4), (100, 8192)):
        rs = shard_ranges(n, rows)
        assert rs[0][0] == 0 and rs[-1][1] == n
        assert all(a[1] == b[0] for a, b in zip(rs, rs[1:]))


def test_resolve_host_workers_resolution(monkeypatch):
    from semantic_merge_tpu.ops.fused import resolve_host_workers
    monkeypatch.delenv("SEMMERGE_HOST_WORKERS", raising=False)
    assert resolve_host_workers(3) == 3
    assert resolve_host_workers() == min(8, os.cpu_count() or 1)
    monkeypatch.setenv("SEMMERGE_HOST_WORKERS", "5")
    assert resolve_host_workers(3) == 5  # env beats config
    monkeypatch.setenv("SEMMERGE_HOST_WORKERS", "not-a-number")
    assert resolve_host_workers(3) == 3  # invalid env ignored
    monkeypatch.setenv("SEMMERGE_HOST_WORKERS", "0")
    assert resolve_host_workers(3) >= 1  # floor at 1


def test_tail_disjoint_attribution():
    """bench._tail_disjoint: pool-worker ``materialize_overlap`` time
    executing inside a main-thread tail-phase wall window is attributed
    ONCE (to the overlap pool), not twice — summing the tail trio with
    the overlap phase counts every wall instant exactly once."""
    import bench
    from semantic_merge_tpu.obs import spans as obs_spans

    rec = obs_spans.SpanRecorder()
    e = rec.epoch
    # Main-thread tail spans: serialize [1.0, 1.3), then
    # compose_materialize [1.3, 1.7).
    obs_spans.record_into(rec, "serialize", 0.300, t_start=e + 1.0)
    obs_spans.record_into(rec, "compose_materialize", 0.400,
                          t_start=e + 1.3)
    # Worker shards: two adjacent spans merging into [1.10, 1.40) —
    # straddling the serialize/compose boundary — plus one entirely
    # outside any tail window (must subtract nothing).
    obs_spans.record_into(rec, "materialize_overlap", 0.150,
                          t_start=e + 1.10)
    obs_spans.record_into(rec, "materialize_overlap", 0.150,
                          t_start=e + 1.25)
    obs_spans.record_into(rec, "materialize_overlap", 0.100,
                          t_start=e + 2.0)

    phases = {"serialize": 0.300, "compose_materialize": 0.400,
              "materialize_overlap": 0.400, "kernel": 0.100}
    out = bench._tail_disjoint(phases, rec)
    # serialize window [1.0, 1.3) ∩ worker union [1.10, 1.40) = 0.20.
    assert out["serialize"] == pytest.approx(0.100, abs=1e-4)
    # compose_materialize [1.3, 1.7) ∩ [1.10, 1.40) = 0.10.
    assert out["compose_materialize"] == pytest.approx(0.300, abs=1e-4)
    # Overlap pool and non-tail phases are reported as measured.
    assert out["materialize_overlap"] == pytest.approx(0.400)
    assert out["kernel"] == pytest.approx(0.100)
    # The disjoint invariant: tail trio + overlap == total busy wall.
    disjoint_sum = (out["serialize"] + out["compose_materialize"]
                    + out["materialize_overlap"])
    assert disjoint_sum == pytest.approx(0.300 + 0.400 + 0.400 - 0.300,
                                         abs=1e-4)


def test_tail_disjoint_no_workers_is_identity():
    import bench
    from semantic_merge_tpu.obs import spans as obs_spans

    rec = obs_spans.SpanRecorder()
    obs_spans.record_into(rec, "serialize", 0.3, t_start=rec.epoch + 1.0)
    phases = {"serialize": 0.3, "compose_materialize": 0.4}
    assert bench._tail_disjoint(phases, rec) == phases


def test_tail_disjoint_clamps_at_zero():
    """A phase fully covered by worker intervals reports 0, never a
    negative wall (rounding in span_dicts can over-cover by ~1e-6)."""
    import bench
    from semantic_merge_tpu.obs import spans as obs_spans

    rec = obs_spans.SpanRecorder()
    e = rec.epoch
    obs_spans.record_into(rec, "serialize", 0.200, t_start=e + 1.0)
    obs_spans.record_into(rec, "materialize_overlap", 0.500,
                          t_start=e + 0.9)
    out = bench._tail_disjoint({"serialize": 0.200}, rec)
    assert out["serialize"] == pytest.approx(0.0, abs=1e-5)
