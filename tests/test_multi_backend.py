"""Per-language routing (VERDICT r3 #8): a mixed .ts+.java repository
gets semantic merges for BOTH languages in one run."""
import json
import os
import pathlib
import subprocess

import pytest

from semantic_merge_tpu.backends.base import get_backend, run_merge
from semantic_merge_tpu.backends.multi import MultiBackend, route_backends
from semantic_merge_tpu.frontend.snapshot import Snapshot

TS_BASE = "export function tsThing(a: number): number { return a; }\n"
JAVA_BASE = ("public class Box {\n"
             "  public int measure(int w) { return w; }\n"
             "}\n")


def snaps():
    base = Snapshot(files=[{"path": "a.ts", "content": TS_BASE},
                           {"path": "Box.java", "content": JAVA_BASE}])
    # left renames the TS function; right renames the Java method.
    left = Snapshot(files=[
        {"path": "a.ts", "content": TS_BASE.replace("tsThing", "tsRenamed")},
        {"path": "Box.java", "content": JAVA_BASE}])
    right = Snapshot(files=[
        {"path": "a.ts", "content": TS_BASE},
        {"path": "Box.java", "content": JAVA_BASE.replace("measure", "gauge")}])
    return base, left, right


def test_multi_backend_merges_both_languages():
    multi = MultiBackend([get_backend("host"), get_backend("java")])
    base, left, right = snaps()
    result, composed, conflicts = run_merge(multi, base, left, right,
                                            base_rev="r", seed="s")
    assert conflicts == []
    files_l = {op.params.get("file") or op.params.get("newFile")
               for op in result.op_log_left}
    files_r = {op.params.get("file") or op.params.get("newFile")
               for op in result.op_log_right}
    assert any(f and f.endswith(".ts") for f in files_l), \
        "TS rename must be in the left log"
    assert any(f and f.endswith(".java") for f in files_r), \
        "Java rename must be in the right log"
    types = {op.type for op in composed}
    assert "renameSymbol" in types
    renamed = {op.params.get("newName") for op in composed
               if op.type == "renameSymbol"}
    assert {"tsRenamed", "gauge"} <= renamed, renamed


def test_route_backends_from_config(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / ".semmerge.toml").write_text(
        '[engine]\nbackend = "host"\n'
        '[languages.java]\nenabled = true\n')
    from semantic_merge_tpu.config import load_config
    config = load_config()
    primary = get_backend("host")
    multi = route_backends(primary, config)
    assert multi is not None
    assert {b.name for b in multi.backends} == {"host", "java"}
    assert ".java" in multi.extensions and ".ts" in multi.extensions
    # No extra languages -> no composite.
    (tmp_path / ".semmerge.toml").write_text('[engine]\nbackend = "host"\n')
    assert route_backends(primary, load_config()) is None


def test_cli_merges_mixed_repo_end_to_end(tmp_path, monkeypatch):
    repo = tmp_path / "repo"
    repo.mkdir()
    monkeypatch.chdir(repo)

    def git(*args):
        subprocess.run(["git", *args], cwd=repo, check=True,
                       capture_output=True)

    git("init", "-q", "-b", "main")
    git("config", "user.email", "m@e")
    git("config", "user.name", "m")
    (repo / ".semmerge.toml").write_text(
        '[engine]\nbackend = "host"\n[languages.java]\nenabled = true\n')
    (repo / "a.ts").write_text(TS_BASE)
    (repo / "Box.java").write_text(JAVA_BASE)
    git("add", "-A")
    git("commit", "-qm", "base")
    git("branch", "basebr")
    git("checkout", "-qb", "br-a")
    (repo / "a.ts").write_text(TS_BASE.replace("tsThing", "tsRenamed"))
    git("commit", "-qam", "ts-rename")
    git("checkout", "-q", "main")
    git("checkout", "-qb", "br-b")
    (repo / "Box.java").write_text(JAVA_BASE.replace("measure", "gauge"))
    git("commit", "-qam", "java-rename")
    git("checkout", "-q", "main")

    from semantic_merge_tpu.cli import main
    rc = main(["semmerge", "basebr", "br-a", "br-b", "--inplace"])
    assert rc == 0
    assert "tsRenamed" in (repo / "a.ts").read_text()
    assert "gauge" in (repo / "Box.java").read_text(), \
        "the Java rename must merge semantically in the same run"
