"""changeSignature detection (the reference's declared-but-unimplemented
diff kind, reference ``workers/ts/src/diff.ts:3``, TODO at reference
``implementation.md:902``).

Off by default (parity mode keeps the reference's delete+add shape);
enabled via backend kwarg / ``[engine].change_signature`` /
``--change-signature``. Host and TPU backends must agree bit-for-bit.
"""
from __future__ import annotations

import pytest

from semantic_merge_tpu.backends.ts_host import HostTSBackend
from semantic_merge_tpu.core.difflift import (Diff, diff_nodes, lift,
                                              refine_signature_changes)
from semantic_merge_tpu.frontend.scanner import scan_snapshot
from semantic_merge_tpu.frontend.snapshot import Snapshot


def snap(files):
    return Snapshot(files=[{"path": p, "content": c} for p, c in files.items()])


BASE = {"a.ts": "export function f(x: number): number { return x; }\n"
                "export function g(y: string): string { return y; }\n"}
# f's parameter type changes → new symbolId → delete+add in parity mode.
SIDE = {"a.ts": "export function f(x: string): number { return 0; }\n"
                "export function g(y: string): string { return y; }\n"}


def _diffs(base, side):
    return diff_nodes(scan_snapshot(snap(base).files),
                      scan_snapshot(snap(side).files))


class TestRefine:
    def test_delete_add_pair_becomes_change_sig(self):
        diffs = _diffs(BASE, SIDE)
        kinds = sorted(d.kind for d in diffs)
        assert kinds == ["add", "delete"]
        refined = refine_signature_changes(diffs)
        assert [d.kind for d in refined] == ["changeSig"]
        d = refined[0]
        assert d.a.name == "f" and d.b.name == "f"
        assert d.a.signature == "fn(number)->number"
        assert d.b.signature == "fn(string)->number"

    def test_unrelated_delete_add_not_paired(self):
        base = {"a.ts": "export function f(x: number): number { return x; }\n"}
        side = {"a.ts": "export function h(q: boolean): boolean { return q; }\n"}
        refined = refine_signature_changes(_diffs(base, side))
        assert sorted(d.kind for d in refined) == ["add", "delete"]

    def test_cross_file_same_name_not_paired(self):
        base = {"a.ts": "export function f(x: number): number { return x; }\n"}
        side = {"b.ts": "export function f(x: string): number { return 0; }\n"}
        refined = refine_signature_changes(_diffs(base, side))
        assert sorted(d.kind for d in refined) == ["add", "delete"]

    def test_nameless_decls_never_paired(self):
        base = {"a.ts": "const a = 1;\n"}
        side = {"a.ts": "const a = 1, b = 2;\n"}  # vars{1} -> vars{2}
        refined = refine_signature_changes(_diffs(base, side))
        assert sorted(d.kind for d in refined) == ["add", "delete"]

    def test_fifo_pairing_is_deterministic(self):
        # Two same-named overload-style decls changing together: the k-th
        # delete pairs with the k-th add.
        base = {"a.ts": "function f(x: number): void;\n"
                        "function f(x: number, y: number): void;\n"}
        side = {"a.ts": "function f(x: string): void;\n"
                        "function f(x: string, y: string): void;\n"}
        refined = refine_signature_changes(_diffs(base, side))
        assert [d.kind for d in refined] == ["changeSig", "changeSig"]
        assert refined[0].a.signature == "fn(number)->void"
        assert refined[0].b.signature == "fn(string)->void"
        assert refined[1].a.signature == "fn(number,number)->void"
        assert refined[1].b.signature == "fn(string,string)->void"

    def test_positions_and_reindexing(self):
        # The changeSig occupies the delete's stream position; the add is
        # dropped so later ops re-index.
        base = {"a.ts": "export function f(x: number): number { return x; }\n",
                "b.ts": "export function keep(k: boolean): boolean { return k; }\n"}
        side = {"a.ts": "export function f(x: string): number { return 0; }\n",
                "b.ts": "export function keep(k: boolean): boolean { return k; }\n",
                "c.ts": "export function brandNew(z: bigint): bigint { return z; }\n"}
        diffs = _diffs(base, side)
        refined = refine_signature_changes(diffs)
        kinds = [d.kind for d in refined]
        assert kinds == ["changeSig", "add"]
        assert refined[1].b.name == "brandNew"


class TestLift:
    def test_change_signature_op_shape(self):
        refined = refine_signature_changes(_diffs(BASE, SIDE))
        ops = lift("baserev", refined, seed="s", timestamp="2024-01-01T00:00:00Z")
        assert len(ops) == 1
        op = ops[0]
        assert op.type == "changeSignature"
        assert op.params["name"] == "f"
        assert op.params["oldSignature"] == "fn(number)->number"
        assert op.params["newSignature"] == "fn(string)->number"
        assert op.params["file"] == "a.ts"
        assert op.target.symbolId and op.params["newSymbolId"]
        assert op.target.symbolId != op.params["newSymbolId"]
        assert op.guards["addressMatch"] == op.params["oldAddress"]

    def test_deterministic_ids(self):
        refined = refine_signature_changes(_diffs(BASE, SIDE))
        a = lift("r", refined, seed="s", timestamp="t")
        b = lift("r", refined, seed="s", timestamp="t")
        assert [o.to_dict() for o in a] == [o.to_dict() for o in b]


class TestBackends:
    def test_host_backend_flag(self):
        host = HostTSBackend()
        result = host.build_and_diff(snap(BASE), snap(SIDE), snap(BASE),
                                     change_signature=True)
        assert [o.type for o in result.op_log_left] == ["changeSignature"]
        assert result.op_log_right == []
        # Default (parity mode) keeps delete+add.
        parity = host.build_and_diff(snap(BASE), snap(SIDE), snap(BASE))
        assert sorted(o.type for o in parity.op_log_left) == ["addDecl", "deleteDecl"]

    def test_host_tpu_parity_with_change_signature(self):
        from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
        host, tpu = HostTSBackend(), TpuTSBackend()
        base, left = snap(BASE), snap(SIDE)
        right = snap({"a.ts": BASE["a.ts"] + "export function h(n: never): void {}\n"})
        kw = dict(base_rev="r", seed="s", timestamp="t", change_signature=True)
        h = host.build_and_diff(base, left, right, **kw)
        t = tpu.build_and_diff(base, left, right, **kw)
        assert [o.to_dict() for o in h.op_log_left] == [o.to_dict() for o in t.op_log_left]
        assert [o.to_dict() for o in h.op_log_right] == [o.to_dict() for o in t.op_log_right]
        assert any(o.type == "changeSignature" for o in h.op_log_left)

    def test_diff_entrypoint_flag(self):
        host = HostTSBackend()
        ops = host.diff(snap(BASE), snap(SIDE), change_signature=True)
        assert [o.type for o in ops] == ["changeSignature"]


def test_change_signature_fused_when_no_candidates():
    """--change-signature keeps the one-round-trip fused path when no
    delete+add pair could fold (VERDICT r4 #9): the phase split shows
    the fused kernel ran, and the op logs equal the two-program
    refinement output bit-for-bit."""
    from semantic_merge_tpu.backends.base import run_merge
    from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
    from semantic_merge_tpu.frontend.snapshot import Snapshot

    base = Snapshot(files=[
        {"path": "a.ts", "content":
         "export function f(n: number): number { return n; }\n"},
        {"path": "b.ts", "content":
         "export function g(s: string): string { return s; }\n"}])
    left = Snapshot(files=[
        {"path": "a.ts", "content":
         "export function renamed(n: number): number { return n; }\n"},
        base.files[1]])
    right = Snapshot(files=[
        {"path": "lib/b.ts", "content": base.files[1]["content"]},
        base.files[0]])

    kw = dict(base_rev="r", seed="s", timestamp="2026-01-01T00:00:00Z",
              change_signature=True)
    from semantic_merge_tpu.obs import spans as obs_spans
    bk = TpuTSBackend(mesh=False)
    rec = obs_spans.SpanRecorder()
    with obs_spans.activated(rec):
        res_f, comp_f, conf_f = run_merge(bk, base, left, right, **kw)
    assert "kernel" in rec.phase_totals(), "fused path must have been taken"
    # Oracle: the host backend's two-program change_signature path.
    from semantic_merge_tpu.backends.base import get_backend
    res_h, comp_h, conf_h = run_merge(get_backend("host"),
                                      base, left, right, **kw)
    assert [o.to_dict() for o in res_f.op_log_left] == \
        [o.to_dict() for o in res_h.op_log_left]
    assert [o.to_dict() for o in res_f.op_log_right] == \
        [o.to_dict() for o in res_h.op_log_right]
    assert [o.to_dict() for o in comp_f] == [o.to_dict() for o in comp_h]


def test_change_signature_candidates_fall_back_and_refine():
    """A retyped decl (delete+add sharing file/name/kind) must leave
    the fused path and produce the changeSignature op."""
    from semantic_merge_tpu.backends.base import run_merge
    from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
    from semantic_merge_tpu.frontend.snapshot import Snapshot

    base = Snapshot(files=[{"path": "a.ts", "content":
        "export function f(n: number): number { return n; }\n"}])
    left = Snapshot(files=[{"path": "a.ts", "content":
        "export function f(n: string): number { return 0; }\n"}])
    right = Snapshot(files=[{"path": "a.ts", "content":
        "export function f(n: number): number { return n; }\n"}])

    kw = dict(base_rev="r", seed="s", timestamp="2026-01-01T00:00:00Z",
              change_signature=True)
    from semantic_merge_tpu.obs import spans as obs_spans
    bk = TpuTSBackend(mesh=False)
    rec = obs_spans.SpanRecorder()
    with obs_spans.activated(rec):
        res_f, comp_f, conf_f = run_merge(bk, base, left, right, **kw)
    assert "build_and_diff" in rec.phase_totals(), \
        "candidates must force the fallback"
    types = [o.type for o in res_f.op_log_left]
    assert types == ["changeSignature"]
    from semantic_merge_tpu.backends.base import get_backend
    res_h, _, _ = run_merge(get_backend("host"), base, left, right, **kw)
    assert [o.to_dict() for o in res_f.op_log_left] == \
        [o.to_dict() for o in res_h.op_log_left]
