"""Matcher training: loop, orbax checkpointing, preemption resume."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
pytest.importorskip("orbax.checkpoint")

from semantic_merge_tpu.models.encoder import EncoderConfig  # noqa: E402
from semantic_merge_tpu.models.matcher import MatcherConfig  # noqa: E402
from semantic_merge_tpu.models.training import (TrainConfig, synth_pair,  # noqa: E402
                                                train_matcher)
from semantic_merge_tpu.parallel.mesh import build_mesh  # noqa: E402

TINY = MatcherConfig(encoder=EncoderConfig(
    vocab=256, d_model=32, n_heads=2, d_head=16,
    n_layers=1, d_ff=64, n_experts=2))


def _cfg(**kw):
    base = dict(matcher=TINY, batch=8, seq=32, steps=6, seed=0,
                ckpt_every=3)
    base.update(kw)
    return TrainConfig(**base)


def test_synth_pairs_are_related_but_distinct():
    rng = np.random.RandomState(0)
    a, b = synth_pair(rng)
    assert a != b
    assert "export function" in a and "export function" in b
    # Same parameter structure (the name-free signature survives).
    assert a.split("(")[1].split(")")[0] == b.split("(")[1].split(")")[0]


def test_train_decreases_loss_and_runs_all_steps():
    mesh = build_mesh(dp=2, pp=1, sp=2, tp=2, ep=1)
    _, _, loss, ran = train_matcher(_cfg(steps=8), mesh=mesh)
    assert ran == 8
    assert np.isfinite(loss)


def test_checkpoint_resume_matches_uninterrupted(tmp_path):
    mesh = build_mesh(dp=2, pp=1, sp=2, tp=2, ep=1)
    # Uninterrupted 6-step run (no checkpoints).
    p_full, _, loss_full, _ = train_matcher(_cfg(), mesh=mesh)

    # Same run, preempted after step 3 and resumed.
    ck = str(tmp_path / "ck")
    train_matcher(_cfg(steps=3, ckpt_dir=ck), mesh=mesh)
    p_res, _, loss_res, ran = train_matcher(_cfg(steps=6, ckpt_dir=ck), mesh=mesh)
    assert ran == 3  # resumed at 3, ran to 6

    for key in p_full:
        np.testing.assert_allclose(np.asarray(p_full[key]),
                                   np.asarray(p_res[key]),
                                   rtol=2e-4, atol=2e-4, err_msg=key)
    assert np.isclose(loss_full, loss_res, rtol=2e-3)


def test_resume_disabled_restarts(tmp_path):
    mesh = build_mesh(dp=2, pp=1, sp=2, tp=2, ep=1)
    ck = str(tmp_path / "ck")
    train_matcher(_cfg(steps=3, ckpt_dir=ck), mesh=mesh)
    _, _, _, ran = train_matcher(_cfg(steps=4, ckpt_dir=ck), mesh=mesh,
                                 resume=False)
    assert ran == 4  # started from scratch
