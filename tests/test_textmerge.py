"""[FBK-001] text-merge fallback for non-indexed files."""
import json
import pathlib
import subprocess

import pytest

from semantic_merge_tpu.runtime.textmerge import _resolve


def test_resolve_matrix():
    base, a, b = b"base\n", b"side a\n", b"side b\n"
    assert _resolve("f", base, base, base) == (base, None)
    assert _resolve("f", base, a, base) == (a, None)
    assert _resolve("f", base, base, b) == (b, None)
    assert _resolve("f", base, a, a) == (a, None)
    # one-side delete, other unchanged → deletion wins
    assert _resolve("f", base, None, base) == (None, None)
    # delete vs edit → conflict
    content, conflict = _resolve("f", base, None, b)
    assert content is None and conflict.category == "TextMergeConflict"
    # add same on both sides
    assert _resolve("f", None, a, a) == (a, None)


def test_resolve_non_overlapping_edits_merge():
    base = b"line1\nline2\nline3\nline4\nline5\n"
    a = b"LINE1\nline2\nline3\nline4\nline5\n"
    b = b"line1\nline2\nline3\nline4\nLINE5\n"
    merged, conflict = _resolve("f", base, a, b)
    assert conflict is None
    assert merged == b"LINE1\nline2\nline3\nline4\nLINE5\n"


def test_resolve_overlapping_edits_conflict():
    base = b"hello\n"
    merged, conflict = _resolve("f", base, b"hola\n", b"bonjour\n")
    assert merged is None
    assert conflict.category == "TextMergeConflict"
    assert conflict.minimalSlice["path"] == "f"


def test_resolve_binary_both_changed_conflict():
    base = b"\x00\x01\x02"
    merged, conflict = _resolve("f", base, b"\x00\x03", b"\x00\x04")
    assert merged is None and conflict is not None
    # one side unchanged → fine even for binary
    assert _resolve("f", base, base, b"\x00\x05") == (b"\x00\x05", None)


def _git(cwd, *args):
    subprocess.run(["git", *args], cwd=cwd, check=True,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def _setup_repo(tmp_path, base_files, a_edit, b_edit):
    for name, content in base_files.items():
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(content)
    _git(tmp_path, "init", "-q", "-b", "main")
    _git(tmp_path, "config", "user.email", "t@e")
    _git(tmp_path, "config", "user.name", "t")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "base")
    _git(tmp_path, "branch", "basebr")
    _git(tmp_path, "checkout", "-qb", "ba")
    a_edit(tmp_path)
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "a")
    _git(tmp_path, "checkout", "-q", "main")
    _git(tmp_path, "checkout", "-qb", "bb")
    b_edit(tmp_path)
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "b")
    _git(tmp_path, "checkout", "-q", "main")


def test_cli_merges_readme_alongside_ts(tmp_path, monkeypatch):
    """A doc edit on side A and a TS rename on side B both land."""
    _setup_repo(
        tmp_path,
        {"a.ts": "export function foo(n: number): number { return n; }\n",
         "README.md": "# title\n\nintro\n"},
        a_edit=lambda p: (p / "README.md").write_text("# title\n\nintro rewritten\n"),
        b_edit=lambda p: (p / "a.ts").write_text(
            "export function bar(n: number): number { return n; }\n"),
    )
    monkeypatch.chdir(tmp_path)
    from semantic_merge_tpu.cli import main
    rc = main(["semmerge", "basebr", "ba", "bb", "--backend", "host", "--inplace"])
    assert rc == 0
    assert "rewritten" in (tmp_path / "README.md").read_text()
    assert "function bar" in (tmp_path / "a.ts").read_text()


def test_cli_text_conflict_exits_1(tmp_path, monkeypatch):
    _setup_repo(
        tmp_path,
        {"notes.txt": "hello\n"},
        a_edit=lambda p: (p / "notes.txt").write_text("hola\n"),
        b_edit=lambda p: (p / "notes.txt").write_text("bonjour\n"),
    )
    monkeypatch.chdir(tmp_path)
    from semantic_merge_tpu.cli import main
    rc = main(["semmerge", "basebr", "ba", "bb", "--backend", "host"])
    assert rc == 1
    payload = json.loads((tmp_path / ".semmerge-conflicts.json").read_text())
    assert payload[0]["category"] == "TextMergeConflict"
    assert payload[0]["minimalSlice"]["path"] == "notes.txt"


def test_cli_text_fallback_disabled(tmp_path, monkeypatch):
    _setup_repo(
        tmp_path,
        {"notes.txt": "hello\n"},
        a_edit=lambda p: (p / "notes.txt").write_text("hola\n"),
        b_edit=lambda p: (p / "notes.txt").write_text("bonjour\n"),
    )
    (tmp_path / ".semmerge.toml").write_text(
        "[engine]\nbackend = \"host\"\ntext_fallback = false\n")
    monkeypatch.chdir(tmp_path)
    from semantic_merge_tpu.cli import main
    rc = main(["semmerge", "basebr", "ba", "bb"])
    assert rc == 0  # reference-parity posture: non-indexed files stay at base


def test_java_files_text_merge_under_ts_backend(tmp_path, monkeypatch):
    """With the TS backend active, a .java edit must text-merge, not
    silently revert (the gate is the backend's extension set, not the
    global source union)."""
    _setup_repo(
        tmp_path,
        {"a.ts": "export function foo(n: number): number { return n; }\n",
         "Main.java": "class Main { }\n"},
        a_edit=lambda p: (p / "Main.java").write_text("class Main { int x; }\n"),
        b_edit=lambda p: (p / "a.ts").write_text(
            "export function bar(n: number): number { return n; }\n"),
    )
    monkeypatch.chdir(tmp_path)
    from semantic_merge_tpu.cli import main
    rc = main(["semmerge", "basebr", "ba", "bb", "--backend", "host", "--inplace"])
    assert rc == 0
    assert "int x" in (tmp_path / "Main.java").read_text()


def test_inplace_propagates_text_deletions(tmp_path, monkeypatch):
    _setup_repo(
        tmp_path,
        {"a.ts": "export function foo(n: number): number { return n; }\n",
         "notes.txt": "hello\n"},
        a_edit=lambda p: (p / "notes.txt").unlink(),
        b_edit=lambda p: (p / "a.ts").write_text(
            "export function bar(n: number): number { return n; }\n"),
    )
    monkeypatch.chdir(tmp_path)
    from semantic_merge_tpu.cli import main
    rc = main(["semmerge", "basebr", "ba", "bb", "--backend", "host", "--inplace"])
    assert rc == 0
    assert not (tmp_path / "notes.txt").exists()


def test_encoder_rejects_bad_attn_mode():
    import pytest as _pytest
    from semantic_merge_tpu.models.encoder import EncoderConfig
    with _pytest.raises(ValueError, match="attn_mode"):
        EncoderConfig(attn_mode="ulyses")


def _tarb(files):
    """In-memory tar of {path: text} — shared by the added-file tests."""
    import io
    import tarfile
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        for name, data in files.items():
            info = tarfile.TarInfo(name)
            payload = data.encode()
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
    return buf.getvalue()


def test_one_sided_added_indexed_file_materializes(tmp_path):
    """A .ts file added on one side (absent in base and not produced
    by the op applier) must land in the merge via the text layer —
    the op vocabulary has no whole-file add handler (reference
    applier parity), and a standalone semmerge cannot lean on git
    fast-forwarding pure adds."""
    import pathlib

    from semantic_merge_tpu.runtime.textmerge import apply_text_fallback

    merged = tmp_path / "merged"
    merged.mkdir()
    (merged / "a.ts").write_text("export function bar(): void {}\n")

    base = _tarb({"a.ts": "export function foo(): void {}\n"})
    left = _tarb({"a.ts": "export function bar(): void {}\n"})
    right = _tarb({"a.ts": "export function foo(): void {}\n",
                   "b.ts": "export function extra(s: string): string { return s; }\n"})
    conflicts, deleted, written = apply_text_fallback(merged, base, left, right)
    assert conflicts == [] and deleted == []
    assert written == ["b.ts"]
    assert (merged / "b.ts").read_text().startswith("export function extra")
    # Indexed files the op pipeline already owns stay untouched.
    assert (merged / "a.ts").read_text() == "export function bar(): void {}\n"


def test_both_sided_divergent_added_indexed_file_conflicts(tmp_path):
    """Both sides adding the same new .ts path with different content
    is a conflict the text layer must surface, not silently pick."""
    from semantic_merge_tpu.runtime.textmerge import apply_text_fallback

    merged = tmp_path / "merged"
    merged.mkdir()
    base = _tarb({})
    left = _tarb({"n.ts": "export const a = 1;\n"})
    right = _tarb({"n.ts": "export const a = 2;\n"})
    conflicts, _, _ = apply_text_fallback(merged, base, left, right)
    assert conflicts, "divergent both-sided add must conflict"
