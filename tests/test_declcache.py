"""Decl-index cache: exactness, cross-file invalidation, eviction.

The cache implements the reference's designed-but-unbuilt warm-cache
story (reference ``architecture.md:206-208,313``; [NFR-PERF-004]) and
must never change scan results — every test compares against a
cache-disabled oracle scan.
"""
import numpy as np
import pytest

from semantic_merge_tpu.frontend import scanner
from semantic_merge_tpu.frontend.declcache import DeclCache
from semantic_merge_tpu.frontend.scanner import scan_snapshot_py


def _scan_cached(files, cache):
    return [n for _, nodes in scanner._scan_snapshot_cached(files, cache)
            for n in nodes]


def _as_dicts(nodes):
    return [n.to_dict() | {"signature": n.signature} for n in nodes]


FILES = [
    {"path": "src/a.ts", "content":
     "export interface Foo { x: number }\nexport function mk(): Foo { return {x: 1}; }\n"},
    {"path": "src/b.ts", "content":
     "export function use(f: Foo): number { return f.x; }\n"},
]


def test_cached_scan_matches_oracle():
    cache = DeclCache()
    assert _as_dicts(_scan_cached(FILES, cache)) == _as_dicts(scan_snapshot_py(FILES))
    # Second scan is all hits and still identical.
    h0 = cache.hits
    assert _as_dicts(_scan_cached(FILES, cache)) == _as_dicts(scan_snapshot_py(FILES))
    assert cache.hits > h0


def test_cross_file_type_dependency_invalidates():
    """Removing a.ts's interface changes b.ts's signature (Foo resolves
    to any) even though b.ts itself is unchanged — the declared-set hash
    must force a rescan, not serve the stale node."""
    cache = DeclCache()
    full = _scan_cached(FILES, cache)
    use_full = next(n for n in full if n.name == "use")
    assert "Foo" in use_full.signature

    only_b = [FILES[1]]
    partial = _scan_cached(only_b, cache)
    use_partial = next(n for n in partial if n.name == "use")
    assert _as_dicts(partial) == _as_dicts(scan_snapshot_py(only_b))
    assert "Foo" not in use_partial.signature
    assert "any" in use_partial.signature


def test_three_way_sharing_hits():
    """base/left/right share unchanged files — the second and third
    snapshot scans should mostly hit."""
    base = [{"path": f"src/m{i}.ts",
             "content": f"export function f{i}(x: number): number {{ return {i}; }}\n"}
            for i in range(20)]
    left = [dict(f) for f in base]
    left[3] = {"path": "src/m3.ts",
               "content": "export function renamed3(x: number): number { return 3; }\n"}
    cache = DeclCache()
    _scan_cached(base, cache)
    misses_after_base = cache.misses
    out_left = _scan_cached(left, cache)
    # Only the changed file misses the decl layer (plus its type-name entry).
    assert cache.misses - misses_after_base <= 2
    assert _as_dicts(out_left) == _as_dicts(scan_snapshot_py(left))


def test_eviction_respects_cap_and_stays_correct():
    cache = DeclCache(cap_mb=1)
    cache.cap_bytes = 20_000  # force pressure with a small workload
    rng = np.random.RandomState(0)
    for round_ in range(3):
        files = [{"path": f"f{i}.ts",
                  "content": f"export function g{i}_{round_}(x: number): number "
                             f"{{ return {int(rng.randint(100))}; }}\n" + "// pad" * 200}
                 for i in range(50)]
        out = _scan_cached(files, cache)
        assert _as_dicts(out) == _as_dicts(scan_snapshot_py(files))
    assert cache.bytes_used <= cache.cap_bytes
    assert cache.evictions > 0


def test_native_subset_scan_uses_global_declared_set():
    """A cache-miss subset scanned natively must still resolve type
    names declared in files outside the subset (the synthetic-decls
    mechanism)."""
    from semantic_merge_tpu.frontend import native
    if not native.available():
        pytest.skip("native frontend unavailable")
    cache = DeclCache()
    # Prime the cache with a.ts only; b.ts then misses while Foo comes
    # from the already-cached a.ts.
    _scan_cached([FILES[0]], cache)
    out = _scan_cached(FILES, cache)
    use = next(n for n in out if n.name == "use")
    assert "Foo" in use.signature
    assert _as_dicts(out) == _as_dicts(scan_snapshot_py(FILES))


def test_cache_disabled_env(monkeypatch):
    from semantic_merge_tpu.frontend import declcache
    monkeypatch.setenv("SEMMERGE_CACHE", "0")
    assert declcache.global_cache() is None
