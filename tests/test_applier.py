"""Applier/materialization tests (reference semmerge/applier.py behavior)."""
import pathlib

from semantic_merge_tpu.core.ops import Op, Target
from semantic_merge_tpu.runtime.applier import apply_ops


def mk_tree(tmp_path: pathlib.Path, files: dict) -> pathlib.Path:
    root = tmp_path / "tree"
    for rel, content in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(content)
    return root


def test_move_decl_moves_whole_file(tmp_path):
    tree = mk_tree(tmp_path, {"src/util.ts": "export function foo() {}\n"})
    op = Op.new("moveDecl", Target(symbolId="s"),
                params={"oldFile": "src/util.ts", "newFile": "lib/util.ts"})
    out = apply_ops(tree, [op])
    assert not (out / "src/util.ts").exists()
    assert (out / "lib/util.ts").read_text() == "export function foo() {}\n"


def test_rename_symbol_word_boundary(tmp_path):
    tree = mk_tree(tmp_path, {"a.ts": "function foo() { return foofoo + foo; }\n"})
    op = Op.new("renameSymbol", Target(symbolId="s"),
                params={"file": "a.ts", "oldName": "foo", "newName": "bar"})
    out = apply_ops(tree, [op])
    assert (out / "a.ts").read_text() == "function bar() { return foofoo + bar; }\n"


def test_rename_then_move_sequence(tmp_path):
    # Composed order: move first (precedence 10), then rename with file
    # rewritten to the destination — the flagship e2e scenario.
    tree = mk_tree(tmp_path, {"src/util.ts": "export function foo(): void {}\n"})
    move = Op.new("moveDecl", Target(symbolId="s"),
                  params={"oldFile": "src/util.ts", "newFile": "lib/util.ts"})
    rename = Op.new("renameSymbol", Target(symbolId="s"),
                    params={"file": "lib/util.ts", "oldName": "foo", "newName": "bar"})
    out = apply_ops(tree, [move, rename])
    assert (out / "lib/util.ts").read_text() == "export function bar(): void {}\n"


def test_modify_import_literal_replace(tmp_path):
    tree = mk_tree(tmp_path, {"a.ts": 'import { x } from "./old";\n'})
    op = Op.new("modifyImport", Target(symbolId="s"),
                params={"file": "a.ts", "oldImport": "./old", "newImport": "./new"})
    out = apply_ops(tree, [op])
    assert (out / "a.ts").read_text() == 'import { x } from "./new";\n'


def test_move_file_op(tmp_path):
    tree = mk_tree(tmp_path, {"a.ts": "x\n"})
    op = Op.new("moveFile", Target(symbolId="s"),
                params={"oldPath": "a.ts", "newPath": "b/renamed.ts"})
    out = apply_ops(tree, [op])
    assert (out / "b/renamed.ts").exists() and not (out / "a.ts").exists()


def test_missing_sources_skipped_gracefully(tmp_path):
    tree = mk_tree(tmp_path, {"a.ts": "x\n"})
    ops = [
        Op.new("moveDecl", Target(symbolId="s"),
               params={"oldFile": "nope.ts", "newFile": "other.ts"}),
        Op.new("renameSymbol", Target(symbolId="s"),
               params={"file": "nope.ts", "oldName": "a", "newName": "b"}),
        Op.new("addDecl", Target(symbolId="s"), params={"file": "a.ts"}),
    ]
    out = apply_ops(tree, ops)  # must not raise
    assert (out / "a.ts").read_text() == "x\n"


def test_absolute_paths_normalized(tmp_path):
    tree = mk_tree(tmp_path, {"a.ts": "foo\n"})
    op = Op.new("renameSymbol", Target(symbolId="s"),
                params={"file": "/a.ts", "oldName": "foo", "newName": "bar"})
    out = apply_ops(tree, [op])
    assert (out / "a.ts").read_text() == "bar\n"


def test_path_traversal_rejected(tmp_path):
    # Op logs can arrive from fetched git notes (semrebase) — '..' segments
    # must not escape the merge tree.
    tree = mk_tree(tmp_path, {"a.ts": "x\n"})
    escape = tmp_path / "escape.ts"
    op = Op.new("moveDecl", Target(symbolId="s"),
                params={"oldFile": "a.ts", "newFile": "../../escape.ts"})
    out = apply_ops(tree, [op])
    assert not escape.exists()
    # The file went somewhere inside the merged tree instead.
    assert (out / "escape.ts").exists()


def test_reorder_imports_via_crdt(tmp_path):
    tree = mk_tree(tmp_path, {"a.ts": 'import b from "b";\nimport a from "a";\nconst x = 1;\n'})
    order = [
        {"value": 'import a from "a";', "anchor": "0", "t": 1, "author": "u", "opid": "1"},
        {"value": 'import b from "b";', "anchor": "0", "t": 2, "author": "u", "opid": "2"},
    ]
    op = Op.new("reorderImports", Target(symbolId="s"),
                params={"file": "a.ts", "order": order})
    out = apply_ops(tree, [op])
    text = (out / "a.ts").read_text()
    assert text.index('import a') < text.index('import b')
    assert text.endswith("const x = 1;\n")


def test_reorder_imports_device_batch_parity(tmp_path, monkeypatch):
    """The tpu apply path resolves EVERY reorder list in one batched
    device materialization (VERDICT r3 #7) and must produce the same
    tree as the host RGA path."""
    files = {}
    ops = []
    for k in range(3):
        files[f"m{k}.ts"] = (f'import z{k} from "z";\nimport a{k} from "a";\n'
                             f"const v{k} = {k};\n")
        order = [
            {"value": f'import a{k} from "a";', "anchor": "0", "t": 1,
             "author": "u", "opid": f"{k}-1"},
            {"value": f'import z{k} from "z";', "anchor": "0", "t": 2,
             "author": "u", "opid": f"{k}-2"},
        ]
        ops.append(Op.new("reorderImports", Target(symbolId=f"s{k}"),
                          params={"file": f"m{k}.ts", "order": order}))

    calls = {"batch": 0}
    import semantic_merge_tpu.ops.crdt as device_crdt
    real_batch = device_crdt.materialize_batch

    def spy(rgas):
        calls["batch"] += 1
        return real_batch(rgas)

    monkeypatch.setattr(device_crdt, "materialize_batch", spy)

    host_out = apply_ops(mk_tree(tmp_path / "h", files), ops)
    dev_out = apply_ops(mk_tree(tmp_path / "d", files), ops, device_crdt=True)
    assert calls["batch"] == 1, "one batched device call for the whole merge"
    for name in files:
        assert (dev_out / name).read_text() == (host_out / name).read_text()
        text = (dev_out / name).read_text()
        assert text.index("import a") < text.index("import z")
