"""End-to-end tests of the L7 git merge driver (VERDICT r3 #6).

These register ``scripts/semmerge-driver.py`` in a throwaway repository
the way a user would (``.git/config`` + ``.gitattributes``, with the
``%P`` pathname placeholder the reference driver forgot — reference
``scripts/semmerge-driver.py:46-49`` copies a temp file onto itself),
run REAL ``git merge`` invocations, and assert on the driver-specific
artifacts: merged working tree, semmerge notes, the conflict report,
and the stale-latch recovery path.
"""
import json
import os
import pathlib
import subprocess
import sys
import time

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
DRIVER = REPO_ROOT / "scripts" / "semmerge-driver.py"

BASE_TS = (
    "export function greet(name: string): string {\n"
    "  return name;\n"
    "}\n"
    "export function count(xs: number[]): number {\n"
    "  return xs.length;\n"
    "}\n"
)


def git(args, cwd, check=True, env=None):
    proc = subprocess.run(["git", *args], cwd=cwd, capture_output=True,
                          text=True, env=env)
    if check and proc.returncode != 0:
        raise AssertionError(f"git {args} failed: {proc.stderr}")
    return proc


@pytest.fixture()
def driver_repo(tmp_path, monkeypatch):
    repo = tmp_path / "repo"
    repo.mkdir()
    monkeypatch.chdir(repo)
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    git(["init", "-q", "-b", "main"], repo)
    git(["config", "user.email", "d@e"], repo)
    git(["config", "user.name", "d"], repo)
    # Register the driver exactly as documented, %P included.
    git(["config", "merge.semmerge.driver",
         f"{sys.executable} {DRIVER} %O %A %B %P"], repo)
    (repo / ".gitattributes").write_text("*.ts merge=semmerge\n")
    # host backend: the driver's CLI subprocess must not dial an
    # accelerator. structured_apply: added decls carry their text so
    # the applier can materialize them (plain parity mode keeps the
    # reference's add-is-metadata-only behavior).
    (repo / ".semmerge.toml").write_text(
        '[engine]\nbackend = "host"\nstructured_apply = true\n')
    (repo / "a.ts").write_text(BASE_TS)
    git(["add", "-A"], repo)
    git(["commit", "-qm", "base"], repo)
    return repo, env


def make_branches(repo):
    # branch-a renames greet -> salute (same file); branch-b edits the
    # same file by adding a declaration, so the merge driver must fire.
    git(["checkout", "-qb", "branch-a"], repo)
    (repo / "a.ts").write_text(BASE_TS.replace("greet", "salute"))
    git(["commit", "-qam", "rename"], repo)
    git(["checkout", "-q", "main"], repo)
    git(["checkout", "-qb", "branch-b"], repo)
    (repo / "a.ts").write_text(
        BASE_TS + "export function added(flag: boolean): boolean {\n"
                  "  return !flag;\n}\n")
    git(["commit", "-qam", "add-decl"], repo)
    git(["checkout", "-q", "main"], repo)
    git(["merge", "-q", "--no-ff", "branch-a", "-m", "take-a"], repo)


def test_real_git_merge_through_driver(driver_repo):
    repo, env = driver_repo
    make_branches(repo)
    proc = git(["merge", "--no-ff", "branch-b", "-m", "semantic"], repo,
               check=False, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    merged = (repo / "a.ts").read_text()
    assert "salute" in merged, "side A's rename must survive"
    assert "added" in merged, "side B's added decl must survive"
    assert "greet" not in merged
    # Driver-specific artifacts: the repo-level latch and semmerge notes.
    assert (repo / ".git" / ".semmerge.lock").exists()
    notes = git(["notes", "--ref", "semmerge", "list"], repo, check=False)
    assert notes.returncode == 0 and notes.stdout.strip(), \
        "semmerge notes must be recorded for the merged heads"
    # The stored op log round-trips as JSON.
    first = notes.stdout.splitlines()[0].split()[1]
    blob = git(["notes", "--ref", "semmerge", "show", first], repo, env=env)
    ops = json.loads(blob.stdout)
    assert any(op["type"] in ("renameSymbol", "addDecl") for op in ops)


def test_stale_lock_recovery(driver_repo):
    repo, env = driver_repo
    make_branches(repo)
    # Forge a latch that matches this exact merge's head pair but is
    # old: without stale handling the driver would skip the engine and
    # publish "ours", losing branch-b's change.
    head = git(["rev-parse", "HEAD"], repo).stdout.strip()
    merge_head = git(["rev-parse", "branch-b"], repo).stdout.strip()
    lock = repo / ".git" / ".semmerge.lock"
    lock.write_text(f"{head} {merge_head}")
    old = time.time() - 7200
    os.utime(lock, (old, old))
    proc = git(["merge", "--no-ff", "branch-b", "-m", "semantic"], repo,
               check=False, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    merged = (repo / "a.ts").read_text()
    assert "salute" in merged and "added" in merged
    assert lock.stat().st_mtime > old + 3600, "latch must be refreshed"


def test_engine_failure_leaves_file_as_git_materialized_it(driver_repo):
    """CLI failure inside the driver: %A must be left exactly as git
    materialized it (ours — so git's own conflict handling wins), the
    file stays unmerged, and the latch is cleared so the NEXT driver
    invocation retries the full merge instead of copying back a stale
    resolution."""
    repo, env = driver_repo
    make_branches(repo)
    ours = (repo / "a.ts").read_bytes()  # HEAD content git hands as %A
    env = dict(env)
    env["SEMMERGE_FAULT"] = "apply:fault"
    env["SEMMERGE_STRICT"] = "1"
    proc = git(["merge", "--no-ff", "branch-b", "-m", "x"], repo,
               check=False, env=env)
    assert proc.returncode != 0, "an engine fault must not auto-merge"
    assert (repo / "a.ts").read_bytes() == ours, \
        "%A must be byte-identical to what git materialized"
    status = git(["status", "--porcelain"], repo).stdout
    assert any(line.startswith("UU") or line.startswith("AA")
               for line in status.splitlines()), \
        "the file must stay unmerged for the user to resolve"
    assert not (repo / ".git" / ".semmerge.lock").exists(), \
        "a failed run must clear the latch so the next invocation retries"
    # And the retry (fault removed) succeeds from the clean state.
    git(["merge", "--abort"], repo)
    env.pop("SEMMERGE_FAULT")
    env.pop("SEMMERGE_STRICT")
    proc = git(["merge", "--no-ff", "branch-b", "-m", "retry"], repo,
               check=False, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    merged = (repo / "a.ts").read_text()
    assert "salute" in merged and "added" in merged


def test_divergent_rename_surfaces_conflict(driver_repo):
    repo, env = driver_repo
    git(["checkout", "-qb", "conf-a"], repo)
    (repo / "a.ts").write_text(BASE_TS.replace("greet", "left"))
    git(["commit", "-qam", "ca"], repo)
    git(["checkout", "-q", "main"], repo)
    git(["checkout", "-qb", "conf-b"], repo)
    (repo / "a.ts").write_text(BASE_TS.replace("greet", "right"))
    git(["commit", "-qam", "cb"], repo)
    git(["checkout", "-q", "conf-a"], repo)
    proc = git(["merge", "--no-ff", "conf-b", "-m", "boom"], repo,
               check=False, env=env)
    assert proc.returncode != 0, "divergent rename must not auto-merge"
    report = json.loads((repo / ".semmerge-conflicts.json").read_text())
    assert any(c["category"] == "DivergentRename" for c in report)
    # A failed engine run must not leave a latch that would mask a retry.
    assert not (repo / ".git" / ".semmerge.lock").exists()
