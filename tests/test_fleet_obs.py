"""Fleet-wide observability plane (ISSUE 15): stitched cross-process
traces, federated telemetry, and OTLP export.

The bar:

- A fleet member ships its per-request span tree over the wire; the
  router grafts it (``SpanRecorder.absorb_dicts``) into one tree per
  trace id with ``seconds`` carried byte-for-byte and parent links
  preserved.
- One merge through a live 2-member fleet yields a single stitched
  artifact spanning three processes — router (``fleet`` layer), member
  daemon (``service`` layer), and the member's subprocess worker
  (``worker`` layer) — that ``validate_fleet_trace`` accepts and
  ``semmerge trace analyze --fleet`` attributes across router hops.
- A hedged request's loser leg is annotated ``outcome=lost`` in the
  stitched tree; a member SIGKILLed mid-request leaves ONE tree
  carrying both the failed attempt and the failover retry.
- Histogram exemplars are per-bucket (OpenMetrics): a p99 outlier's
  trace id survives later p50 traffic.
- ``spans_to_otlp`` / ``metrics_to_otlp`` payloads pass
  ``validate_export``; the background exporter delivers to a local
  collector and *drops* (never blocks) on a full queue.
"""
import importlib.util
import json
import os
import pathlib
import signal
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from semantic_merge_tpu.fleet import hashring
from semantic_merge_tpu.obs import export as obs_export
from semantic_merge_tpu.obs import metrics as obs_metrics
from semantic_merge_tpu.obs import spans as obs_spans
from semantic_merge_tpu.service import protocol

from test_fleet import _control, _spawn_router, _stop_router
from test_resilience import build_repo, raw_close, raw_conn, send_merge

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
_SCHEMA_SCRIPT = REPO_ROOT / "scripts" / "check_trace_schema.py"


@pytest.fixture(scope="module")
def schema():
    spec = importlib.util.spec_from_file_location("check_trace_schema",
                                                  _SCHEMA_SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# Per-bucket exemplars (OpenMetrics)
# ---------------------------------------------------------------------------

def test_histogram_exemplars_are_per_bucket():
    reg = obs_metrics.Registry()
    h = reg.histogram("x_seconds", "h", buckets=(0.01, 1.0))
    h.observe(5.0, exemplar="outlier1")       # +Inf bucket (idx 2)
    h.observe(0.001, exemplar="fast1")        # bucket 0
    for i in range(50):                       # p50 stream, same bucket
        h.observe(0.002, exemplar=f"fast{i + 2}")
    data = reg.to_dict()["histograms"]["x_seconds"]["series"][0]
    ex = data["exemplars"]
    # The outlier's id survived the fast-bucket stream — the property
    # last-write-wins per series could not provide.
    assert ex["2"] == {"trace_id": "outlier1", "value": 5.0}
    # Within a bucket the most recent observation wins.
    assert ex["0"] == {"trace_id": "fast51", "value": 0.002}
    assert set(ex) == {"0", "2"}
    # Series without exemplars don't grow the key (wire compat).
    h2 = reg.histogram("y_seconds", "h", buckets=(1.0,))
    h2.observe(0.5)
    assert "exemplars" not in \
        reg.to_dict()["histograms"]["y_seconds"]["series"][0]


def test_exemplar_schema_round_trip(schema):
    reg = obs_metrics.Registry()
    h = reg.histogram("z_seconds", "h", buckets=(0.1, 1.0))
    h.observe(0.05, exemplar="aabb", verb="semmerge")
    assert schema.validate_metrics(reg.to_dict()) == []
    # The pre-OpenMetrics per-series shape is rejected as drift.
    bad = reg.to_dict()
    series = bad["histograms"]["z_seconds"]["series"][0]
    series["exemplar"] = series.pop("exemplars")["0"]
    assert any("per-bucket" in e for e in schema.validate_metrics(bad))


# ---------------------------------------------------------------------------
# Cross-process graft: absorb_dicts
# ---------------------------------------------------------------------------

def _member_tree():
    rec = obs_spans.SpanRecorder(detailed=True)
    with obs_spans.request_scope("t1", rec):
        with obs_spans.span("service.execute", layer="service"):
            with obs_spans.span("worker.diff", layer="worker"):
                time.sleep(0.001)
    return rec


def test_absorb_dicts_preserves_seconds_byte_for_byte():
    shipped = _member_tree().span_dicts()
    router = obs_spans.SpanRecorder(detailed=False)
    anchor = router._new_id()
    obs_spans.record_into(router, "fleet.relay", 0.5, t_start=0.0,
                          layer="fleet", member="m0", attempt=1,
                          outcome="ok")
    router.absorb_dicts(shipped, t_base=0.25, member="m0", attempt=1)
    rows = router.span_dicts()
    grafted = {r["name"]: r for r in rows if r["layer"] != "fleet"}
    assert set(grafted) == {"service.execute", "worker.diff"}
    # The phase totals of the grafted subtree equal the shipped tree
    # byte-for-byte: seconds are carried untouched through the graft.
    assert [grafted[r["name"]]["seconds"] for r in shipped] \
        == [r["seconds"] for r in shipped]
    # Start times re-anchor at t_base; graft meta stamps every row.
    for row in shipped:
        g = grafted[row["name"]]
        assert g["t_start"] == round(row["t_start"] + 0.25, 6)
        assert g["meta"]["member"] == "m0" and g["meta"]["attempt"] == 1
    # Parent links survive the id remap: the worker span still hangs
    # off the execute span, and ids never collide with the router's.
    ex, wk = grafted["service.execute"], grafted["worker.diff"]
    assert wk["parent_id"] == ex["span_id"]
    assert ex["span_id"] > anchor and wk["span_id"] > anchor
    assert ex["depth"] == 0 and wk["depth"] == 1


def test_absorb_dicts_reparents_under_caller_span():
    shipped = _member_tree().span_dicts()
    router = obs_spans.SpanRecorder(detailed=False)
    router.absorb_dicts(shipped, parent_id=77, depth=2, member="m1",
                        attempt=3)
    rows = {r["name"]: r for r in router.span_dicts()}
    assert rows["service.execute"]["parent_id"] == 77
    assert rows["service.execute"]["depth"] == 2
    assert rows["worker.diff"]["depth"] == 3


# ---------------------------------------------------------------------------
# OTLP mapping + exporter
# ---------------------------------------------------------------------------

def test_spans_to_otlp_validates_and_anchors(schema):
    rec = _member_tree()
    payload = obs_export.spans_to_otlp("ab12cd34ab12cd34",
                                       rec.span_dicts(),
                                       epoch_unix_nano=1_000_000_000)
    assert schema.validate_export(payload) == []
    spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    by_name = {s["name"]: s for s in spans}
    # Our 16-hex ids left-pad to OTLP's 32 so they stay greppable.
    assert by_name["worker.diff"]["traceId"] \
        == "0000000000000000ab12cd34ab12cd34"
    assert by_name["worker.diff"]["parentSpanId"] \
        == by_name["service.execute"]["spanId"]
    for s in spans:
        assert int(s["endTimeUnixNano"]) >= int(s["startTimeUnixNano"]) \
            >= 1_000_000_000


def test_spans_to_otlp_error_status(schema):
    rows = [{"name": "fleet.route", "layer": "fleet", "t_start": 0.0,
             "seconds": 0.1, "depth": 0, "span_id": 1, "parent_id": -1,
             "thread": "t", "status": "error", "error": "boom",
             "meta": {"member": "m0"}}]
    payload = obs_export.spans_to_otlp("ff", rows)
    assert schema.validate_export(payload) == []
    span = payload["resourceSpans"][0]["scopeSpans"][0]["spans"][0]
    assert span["status"] == {"code": 2, "message": "boom"}


def test_metrics_to_otlp_validates(schema):
    reg = obs_metrics.Registry()
    reg.counter("fleet_requests_total", "h").inc(verb="semmerge")
    reg.gauge("fleet_members", "h").set(2)
    h = reg.histogram("service_request_seconds", "h", buckets=(0.1, 1.0))
    h.observe(0.05, exemplar="cafe")
    h.observe(9.0, exemplar="beef")
    payload = obs_export.metrics_to_otlp(reg.to_dict(),
                                         time_unix_nano=123)
    assert schema.validate_export(payload) == []
    metrics = {m["name"]: m for m in
               payload["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]}
    assert metrics["fleet_requests_total"]["sum"]["isMonotonic"] is True
    point = metrics["service_request_seconds"]["histogram"]["dataPoints"][0]
    assert point["bucketCounts"] == ["1", "0", "1"]
    assert point["explicitBounds"] == [0.1, 1.0]
    assert {e["traceId"][-4:] for e in point["exemplars"]} \
        == {"cafe", "beef"}


class _CollectorSink(ThreadingHTTPServer):
    """Minimal OTLP collector: records every POST body by path."""

    daemon_threads = True

    def __init__(self):
        self.received = []
        self.lock = threading.Lock()
        self.release = threading.Event()
        self.release.set()
        super().__init__(("127.0.0.1", 0), _SinkHandler)


class _SinkHandler(BaseHTTPRequestHandler):
    def do_POST(self):  # noqa: N802 (http.server contract)
        self.server.release.wait(timeout=30)
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        with self.server.lock:
            self.server.received.append((self.path, json.loads(body)))
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def log_message(self, fmt, *args):
        pass


@pytest.fixture
def collector():
    sink = _CollectorSink()
    t = threading.Thread(target=sink.serve_forever, daemon=True)
    t.start()
    yield sink
    sink.shutdown()
    sink.server_close()


def _wait(predicate, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.05)
    return False


def test_exporter_ships_both_kinds(schema, collector):
    endpoint = f"http://127.0.0.1:{collector.server_address[1]}"
    exporter = obs_export.Exporter(endpoint, queue_size=8)
    exporter.export_trace("dead", _member_tree().span_dicts())
    reg = obs_metrics.Registry()
    reg.counter("c_total", "h").inc()
    exporter.export_metrics(reg.to_dict())
    assert _wait(lambda: len(collector.received) >= 2), \
        "exporter never delivered"
    exporter.close()
    by_path = dict(collector.received)
    assert set(by_path) == {"/v1/traces", "/v1/metrics"}
    assert schema.validate_export(by_path["/v1/traces"]) == []
    assert schema.validate_export(by_path["/v1/metrics"]) == []


def test_exporter_drops_on_full_queue_without_blocking(collector):
    endpoint = f"http://127.0.0.1:{collector.server_address[1]}"
    dropped = obs_metrics.REGISTRY.counter(
        "otlp_dropped_total", "").value(kind="traces")
    collector.release.clear()  # wedge the collector
    exporter = obs_export.Exporter(endpoint, queue_size=1, timeout_s=0.3)
    rows = _member_tree().span_dicts()
    t0 = time.monotonic()
    for i in range(8):
        exporter.export_trace(f"{i:016x}", rows)
    enqueue_s = time.monotonic() - t0
    assert enqueue_s < 1.0, "a wedged collector must not backpressure"
    assert obs_metrics.REGISTRY.counter(
        "otlp_dropped_total", "").value(kind="traces") > dropped
    collector.release.set()
    exporter.close()


def test_maybe_exporter_off_by_default(monkeypatch):
    monkeypatch.delenv(obs_export.ENV_ENDPOINT, raising=False)
    assert obs_export.maybe_exporter() is None


# ---------------------------------------------------------------------------
# Member daemon ships its span tree (direct, no router)
# ---------------------------------------------------------------------------

def test_member_daemon_ships_span_tree(tmp_path, daemon_factory, schema):
    """A daemon in fleet-member posture returns its request span tree in
    the response meta; grafting those dicts reproduces the member's
    phase totals byte-for-byte. A plain daemon ships nothing."""
    repo = build_repo(tmp_path / "repo")
    sock = str(tmp_path / "member.sock")
    daemon_factory(sock, extra_env={"SEMMERGE_FLEET_MEMBER": "m9"},
                   timeout=120)
    conn = raw_conn(sock, timeout=300.0)
    try:
        send_merge(conn, str(repo), req_id=1, idem_key="ship-1",
                   argv=["basebr", "brA", "brB",
                         "--backend", "subprocess"])
        resp = protocol.read_message(conn[1])
    finally:
        raw_close(conn)
    assert resp.get("result", {}).get("exit_code") == 0, resp
    meta = resp["result"]["meta"]
    shipped = meta["spans"]
    names = {r["name"] for r in shipped}
    assert "service.execute" in names
    assert any(r.get("layer") == "worker" for r in shipped), \
        "subprocess-backend merge must carry worker-process spans"
    for row in shipped:
        assert not schema.validate_span(row, row["name"])
    # The graft reproduces the member tree byte-for-byte.
    rec = obs_spans.SpanRecorder(detailed=False)
    rec.absorb_dicts(shipped, t_base=1.0, member="m9", attempt=1)
    assert sorted((r["name"], r["seconds"]) for r in rec.span_dicts()) \
        == sorted((r["name"], r["seconds"]) for r in shipped)


def test_plain_daemon_ships_no_spans(tmp_path, service_daemon):
    repo = build_repo(tmp_path / "repo")
    conn = raw_conn(service_daemon, timeout=300.0)
    try:
        send_merge(conn, str(repo), req_id=1, idem_key="noship-1")
        resp = protocol.read_message(conn[1])
    finally:
        raw_close(conn)
    assert resp.get("result", {}).get("exit_code") == 0, resp
    assert "spans" not in resp["result"]["meta"]


# ---------------------------------------------------------------------------
# Live fleet: stitched traces, federation, failover, hedging
# ---------------------------------------------------------------------------

def _read_artifact(trace_dir, trace_id, timeout=30.0):
    path = pathlib.Path(trace_dir) / f"{trace_id}.json"
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if path.is_file():
            return json.loads(path.read_text(encoding="utf-8"))
        time.sleep(0.1)
    raise AssertionError(f"no stitched artifact at {path}")


def _cli(argv, env_extra, cwd, timeout=300):
    env = dict(os.environ)
    env.update({"PYTHONPATH": str(REPO_ROOT), "JAX_PLATFORMS": "cpu"})
    env.pop("SEMMERGE_FAULT", None)
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "semantic_merge_tpu", *argv],
        capture_output=True, text=True, env=env, cwd=cwd, timeout=timeout)


def test_fleet_stitched_trace_and_failover(tmp_path, schema):
    """The tentpole, end to end: one merge through a 2-member fleet
    leaves one stitched tree spanning router + member + subprocess
    worker; the fleet surfaces (analyze --fleet, stats --fleet,
    federated metrics) read it back; and a member SIGKILLed mid-request
    still yields ONE tree carrying the failed attempt and the failover
    retry."""
    repo = build_repo(tmp_path / "repo")
    trace_dir = tmp_path / "traces"
    sock = str(tmp_path / "fleet.sock")
    router = _spawn_router(
        sock, members=2,
        extra_env={"SEMMERGE_FLEET_HEDGE": "off",
                   "SEMMERGE_FLEET_TRACE_DIR": str(trace_dir)})
    try:
        conn = raw_conn(sock, timeout=600.0)
        try:
            send_merge(conn, str(repo), req_id=1, idem_key="stitch-1",
                       argv=["basebr", "brA", "brB",
                             "--backend", "subprocess"])
            resp = protocol.read_message(conn[1])
        finally:
            raw_close(conn)
        assert resp.get("result", {}).get("exit_code") == 0, resp
        trace_id = resp["result"]["meta"]["trace_id"]

        artifact = _read_artifact(trace_dir, trace_id)
        assert schema.validate_fleet_trace(artifact) == []
        rows = artifact["spans"]
        layers = {r.get("layer") for r in rows}
        # Three processes in one tree: router / member daemon /
        # subprocess worker.
        assert {"fleet", "service", "worker"} <= layers
        names = {r["name"] for r in rows}
        assert {"fleet.wal_fsync", "fleet.route", "fleet.relay",
                "service.execute"} <= names
        owner = hashring.owner(hashring.repo_key(str(repo)),
                               ["m0", "m1"])
        relays = [r for r in rows if r["name"] == "fleet.relay"]
        assert [r["meta"]["outcome"] for r in relays] == ["ok"]
        assert relays[0]["meta"]["member"] == owner
        for r in rows:
            if r.get("layer") != "fleet":
                assert r["meta"]["member"] == owner
                assert r["meta"]["attempt"] == 1
        assert len(list(trace_dir.glob("*.json"))) == 1

        # Router-hop attribution through the CLI.
        proc = _cli(["trace", "analyze", "--fleet", "--json",
                     str(trace_dir / f"{trace_id}.json")], {},
                    str(tmp_path))
        assert proc.returncode == 0, proc.stderr
        analysis = json.loads(proc.stdout)
        assert set(analysis["buckets"]) == {
            "route", "wal_fsync", "relay", "hedge_wait",
            "member_execute"}
        assert analysis["trace_id"] == trace_id
        assert analysis["buckets"]["member_execute"] > 0
        assert analysis["total_seconds"] >= \
            analysis["buckets"]["member_execute"]

        # Federated telemetry over the wire verb: every sample labeled
        # by origin, rollup gauges present.
        metrics = _control(sock, "metrics")
        assert metrics["federated"] is True
        text = metrics["prometheus"]
        for member in ("router", "m0", "m1"):
            assert f'member="{member}"' in text, member
        assert "fleet_member_up" in text

        # stats --fleet and serve --status --fleet aggregate through
        # the router in one round-trip.
        env = {"SEMMERGE_SERVICE_SOCKET": sock, "SEMMERGE_DAEMON": "off"}
        proc = _cli(["stats", "--daemon", "--fleet", "--json"], env,
                    str(tmp_path))
        assert proc.returncode == 0, proc.stderr
        agg = json.loads(proc.stdout)
        assert agg["router"]["fleet"] is True
        assert set(agg["members"]) == {"m0", "m1"}
        assert all(isinstance(m, dict) and m.get("fleet_member") == mid
                   for mid, m in agg["members"].items())
        proc = _cli(["serve", "--status", "--fleet",
                     "--socket", sock], env, str(tmp_path))
        assert proc.returncode == 0, proc.stderr
        assert set(json.loads(proc.stdout)["members"]) == {"m0", "m1"}

        # Mid-request member SIGKILL: the hang fault holds the request
        # inside the owner's execute window; killing the owner turns
        # that leg into a transport failure and the failover retry
        # lands on the peer — all inside ONE stitched tree.
        status = _control(sock, "status")
        pids = {m["id"]: m["pid"] for m in status["members"]}
        conn = raw_conn(sock, timeout=600.0)
        try:
            send_merge(conn, str(repo), req_id=2, idem_key="kill-1",
                       env={"SEMMERGE_FAULT": "service:execute:hang=2"})
            time.sleep(0.8)
            os.kill(pids[owner], signal.SIGKILL)
            resp = protocol.read_message(conn[1])
        finally:
            raw_close(conn)
        assert resp.get("result", {}).get("exit_code") == 0, resp
        kill_tid = resp["result"]["meta"]["trace_id"]
        assert kill_tid != trace_id
        artifact = _read_artifact(trace_dir, kill_tid)
        assert schema.validate_fleet_trace(artifact) == []
        rows = artifact["spans"]
        dead = [r for r in rows if r["name"] == "fleet.relay"
                and r["meta"]["outcome"] == "transport"]
        assert dead and dead[0]["meta"]["member"] == owner
        assert dead[0]["meta"]["attempt"] == 1
        assert any(r["name"] == "fleet.failover" and
                   r["meta"].get("reason") == "transport" for r in rows)
        other = "m1" if owner == "m0" else "m0"
        winners = [r for r in rows if r["name"] == "fleet.relay"
                   and r["meta"]["outcome"] == "ok"]
        assert winners and winners[0]["meta"]["member"] == other
        assert winners[0]["meta"]["attempt"] >= 2
        grafted = [r for r in rows if r.get("layer") != "fleet"]
        assert grafted
        assert all(r["meta"]["member"] == other and
                   r["meta"]["attempt"] >= 2 for r in grafted)
        route = [r for r in rows if r["name"] == "fleet.route"]
        assert route and route[0]["meta"]["attempt"] >= 2
    finally:
        _stop_router(router)


def test_fleet_hedged_loser_annotated_in_stitched_trace(tmp_path,
                                                        schema):
    """The hedge pair in the stitched tree: the winner's ``fleet.hedge``
    carries ``won=true/outcome=won``, the loser's ``won=false/
    outcome=lost``, and the ``fleet.hedge_wait`` window is attributed
    separately from the relay."""
    repo = build_repo(tmp_path / "repo")
    trace_dir = tmp_path / "traces"
    sock = str(tmp_path / "fleet.sock")
    router = _spawn_router(
        sock, members=2,
        extra_env={"SEMMERGE_FLEET_HEDGE_MS": "50",
                   "SEMMERGE_SERVICE_WORKERS": "1",
                   "SEMMERGE_SERVICE_DRAIN_TIMEOUT": "1",
                   "SEMMERGE_FLEET_TRACE_DIR": str(trace_dir)})
    wedge = None
    try:
        owner = hashring.owner(hashring.repo_key(str(repo)),
                               ["m0", "m1"])
        # Wedge the owner's single worker (--inplace never hedges).
        wedge = raw_conn(sock, timeout=600.0)
        send_merge(wedge, str(repo),
                   env={"SEMMERGE_FAULT": "service:execute:hang=20"},
                   argv=["basebr", "brA", "brB", "--inplace",
                         "--backend", "host"],
                   req_id=1, idem_key="wedge")
        time.sleep(0.8)
        conn = raw_conn(sock, timeout=600.0)
        try:
            send_merge(conn, str(repo), req_id=2, idem_key="hedged")
            resp = protocol.read_message(conn[1])
        finally:
            raw_close(conn)
        assert resp.get("result", {}).get("exit_code") == 0, resp
        trace_id = resp["result"]["meta"]["trace_id"]
        artifact = _read_artifact(trace_dir, trace_id)
        assert schema.validate_fleet_trace(artifact) == []
        rows = artifact["spans"]
        hedges = {r["meta"]["member"]: r["meta"] for r in rows
                  if r["name"] == "fleet.hedge"}
        other = "m1" if owner == "m0" else "m0"
        assert hedges[owner] == dict(hedges[owner], won=False,
                                     outcome="lost")
        assert hedges[other] == dict(hedges[other], won=True,
                                     outcome="won")
        assert any(r["name"] == "fleet.hedge_wait" for r in rows)
        winners = [r for r in rows if r["name"] == "fleet.relay"
                   and r["meta"]["outcome"] == "ok"]
        assert winners and winners[0]["meta"]["member"] == other
        grafted = [r for r in rows if r.get("layer") != "fleet"]
        assert grafted
        assert all(r["meta"]["member"] == other for r in grafted)
    finally:
        if wedge is not None:
            raw_close(wedge)
        _stop_router(router)
