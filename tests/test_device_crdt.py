"""Device RGA materialization parity vs the host CRDT."""
import random

from semantic_merge_tpu.core.crdt import RGA, Key
from semantic_merge_tpu.ops.crdt import materialize_batch


def test_empty_batch():
    assert materialize_batch([]) == []


def test_single_list_matches_host():
    r = RGA()
    r.insert(Key("a", 2, "u1", "op2"), "second")
    r.insert(Key("a", 1, "u1", "op1"), "first")
    r.delete("second")
    assert materialize_batch([r]) == [r.materialize()]


def test_fuzz_batch_matches_host():
    rng = random.Random(3)
    rgas = []
    for _ in range(25):
        r = RGA()
        for _ in range(rng.randint(0, 9)):
            k = Key(rng.choice("abc"), rng.randint(0, 3), rng.choice("uv"),
                    f"op{rng.randint(0, 20)}")
            v = f"val{rng.randint(0, 5)}"
            action = rng.random()
            if action < 0.6:
                r.insert(k, v)
            elif action < 0.8:
                r.move(v, k)
            else:
                r.insert(k, v)
                r.delete(f"val{rng.randint(0, 5)}")
        rgas.append(r)
    assert materialize_batch(rgas) == [r.materialize() for r in rgas]
