"""Java / C# frontend + backend tests.

The reference ships these backends as NotImplementedError stubs
(reference ``semmerge/lang/java/bridge.py``, ``semmerge/lang/cs/bridge.py``);
here they are real. Coverage mirrors the TS scanner tests: indexing of
every declared kind, rename/move/add/delete detection through the shared
diff pipeline, changeSignature refinement, and full 3-way composition.
"""
import textwrap

from semantic_merge_tpu.backends.base import get_backend
from semantic_merge_tpu.frontend.cfamily import (CSHARP, JAVA,
                                                 scan_file_cfamily)
from semantic_merge_tpu.frontend.snapshot import Snapshot


JAVA_SRC = textwrap.dedent("""\
    package com.example;

    import java.util.List;

    public class Greeter {
        private int count;
        private String prefix = "hi", suffix = "!";

        public Greeter(int count) {
            this.count = count;
        }

        public String greet(String name, List<String> extras) {
            if (name == null) { return ""; }
            return prefix + name;
        }

        static int helper() { return 42; }

        enum Mood { HAPPY, SAD, NEUTRAL }
    }

    interface Speaker {
        String speak(int volume);
    }

    record Point(int x, int y) {}
    """)


def test_java_scan_kinds_and_signatures():
    nodes = scan_file_cfamily("src/Greeter.java", JAVA_SRC, JAVA)
    by_name = {}
    for n in nodes:  # first wins: the class lists before its constructor
        by_name.setdefault(n.name, n)
    assert by_name["Greeter"].kind == "ClassDeclaration"
    # Direct members: count, prefix-field, ctor, greet, helper, Mood = 6
    assert by_name["Greeter"].signature == "class{6}"
    assert by_name["count"].signature == "vars{1}"
    assert by_name["prefix"].signature == "vars{2}"
    assert by_name["Greeter"].addressId.startswith("src/Greeter.java::Greeter::")
    ctor = [n for n in nodes if n.kind == "ConstructorDeclaration"]
    assert len(ctor) == 1 and ctor[0].signature == "ctor(int)"
    assert by_name["greet"].signature == "fn(String,List<String>)->String"
    assert by_name["helper"].signature == "fn()->int"
    assert by_name["Mood"].signature == "enum{3}"
    assert by_name["Speaker"].kind == "InterfaceDeclaration"
    assert by_name["Speaker"].signature == "iface{1}"
    assert by_name["speak"].signature == "fn(int)->String"
    assert by_name["Point"].signature == "record{2}"
    # Pre-order: the class lists before its members.
    names = [n.name for n in nodes]
    assert names.index("Greeter") < names.index("count") < names.index("greet")


CS_SRC = textwrap.dedent("""\
    using System;

    namespace Example.App
    {
        public class Counter
        {
            private int _count;
            public int Count { get; set; } = 0;

            public Counter(int start) { _count = start; }

            public int Increment(int by) => _count += by;

            public static string Describe(Counter c, string label)
            {
                return $"{label}: {c.Count}";
            }
        }

        public struct Pair { public int A; public int B; }

        public interface IShape
        {
            double Area(double scale);
        }

        public enum Color { Red, Green = 5, Blue }
    }
    """)


def test_csharp_scan_kinds_and_signatures():
    nodes = scan_file_cfamily("src/Counter.cs", CS_SRC, CSHARP)
    by_name = {}
    for n in nodes:
        by_name.setdefault(n.name, n)
    assert by_name["Counter"].kind == "ClassDeclaration"
    # _count, Count (property), ctor, Increment, Describe = 5
    assert by_name["Counter"].signature == "class{5}"
    assert by_name["Count"].kind == "PropertyDeclaration"
    assert by_name["Count"].signature == "prop:int"
    ctor = [n for n in nodes if n.kind == "ConstructorDeclaration"]
    assert len(ctor) == 1 and ctor[0].signature == "ctor(int)"
    assert by_name["Increment"].signature == "fn(int)->int"
    assert by_name["Describe"].signature == "fn(Counter,string)->string"
    assert by_name["Pair"].kind == "StructDeclaration"
    assert by_name["Pair"].signature == "struct{2}"
    assert by_name["IShape"].signature == "iface{1}"
    assert by_name["Area"].signature == "fn(double)->double"
    assert by_name["Color"].signature == "enum{3}"


def test_java_backend_rename_and_move():
    base = Snapshot(files=[{"path": "src/A.java", "content":
                            "class A { int f(int x) { return x; } }\n"}])
    left = Snapshot(files=[{"path": "src/A.java", "content":
                            "class A { int g(int x) { return x; } }\n"}])  # rename f→g
    right = Snapshot(files=[{"path": "lib/A.java", "content":
                             "class A { int f(int x) { return x; } }\n"}])  # move file
    backend = get_backend("java")
    result = backend.build_and_diff(base, left, right, base_rev="b", seed="s",
                                    timestamp="2026-01-01T00:00:00Z")
    kinds_l = [op.type for op in result.op_log_left]
    assert "renameSymbol" in kinds_l
    rename = next(op for op in result.op_log_left if op.type == "renameSymbol")
    assert rename.params["oldName"] == "f" and rename.params["newName"] == "g"
    kinds_r = [op.type for op in result.op_log_right]
    assert "moveDecl" in kinds_r
    composed, conflicts = backend.compose(result.op_log_left, result.op_log_right)
    assert conflicts == []
    # The move chain retargets the rename into the moved file.
    rename_c = next(op for op in composed if op.type == "renameSymbol"
                    and op.params.get("oldName") == "f")
    assert rename_c.params["file"] == "lib/A.java"


def test_java_backend_change_signature():
    base = Snapshot(files=[{"path": "A.java", "content":
                            "class A { int f(int x) { return x; } }\n"}])
    right = Snapshot(files=[{"path": "A.java", "content":
                             "class A { int f(long x) { return 1; } }\n"}])
    backend = get_backend("java")
    plain = backend.diff(base, right, change_signature=False)
    assert {op.type for op in plain} >= {"addDecl", "deleteDecl"}
    refined = backend.diff(base, right, change_signature=True)
    sigs = [op for op in refined if op.type == "changeSignature"]
    assert len(sigs) == 1
    assert sigs[0].params["oldSignature"] == "fn(int)->int"
    assert sigs[0].params["newSignature"] == "fn(long)->int"


def test_csharp_backend_divergent_rename_conflict():
    base = Snapshot(files=[{"path": "A.cs", "content":
                            "class A { int F(int x) => x; }\n"}])
    left = Snapshot(files=[{"path": "A.cs", "content":
                            "class A { int G(int x) => x; }\n"}])
    right = Snapshot(files=[{"path": "A.cs", "content":
                             "class A { int H(int x) => x; }\n"}])
    backend = get_backend("cs")
    result = backend.build_and_diff(base, left, right, base_rev="b", seed="s",
                                    timestamp="2026-01-01T00:00:00Z")
    composed, conflicts = backend.compose(result.op_log_left, result.op_log_right)
    assert len(conflicts) == 1
    assert conflicts[0].category == "DivergentRename"


def test_backends_ignore_foreign_extensions():
    base = Snapshot(files=[{"path": "a.ts", "content": "export function f(): void {}"},
                           {"path": "A.java", "content": "class A { }"}])
    backend = get_backend("java")
    ops = backend.diff(base, Snapshot(files=[]))
    # Only the Java class produces a delete; the .ts file is invisible.
    assert len(ops) == 1 and ops[0].params["file"] == "A.java"


def test_nested_types_and_annotations():
    src = textwrap.dedent("""\
        @Deprecated
        @SuppressWarnings("all")
        public final class Outer {
            static class Inner {
                void run() {}
            }
            @interface Marker { }
        }
        """)
    nodes = scan_file_cfamily("Outer.java", src, JAVA)
    by_name = {n.name: n for n in nodes}
    assert by_name["Outer"].signature == "class{2}"
    assert by_name["Inner"].signature == "class{1}"
    assert by_name["run"].signature == "fn()->void"
    assert by_name["Marker"].kind == "InterfaceDeclaration"
    # Full start includes the annotations (pos 0 for the first decl).
    assert by_name["Outer"].pos == 0


def test_java_non_sealed_class_is_indexed():
    src = ("sealed class A permits B {}\n"
           "non-sealed class B extends A { int f(int x) { return x; } }\n")
    nodes = scan_file_cfamily("S.java", src, JAVA)
    names = {n.name for n in nodes}
    assert {"A", "B", "f"} <= names


def test_csharp_expression_bodied_property():
    src = "class C { public int X => 42; public int Y { get; set; } }\n"
    nodes = scan_file_cfamily("C.cs", src, CSHARP)
    by_name = {n.name: n for n in nodes}
    assert by_name["X"].kind == "PropertyDeclaration"
    assert by_name["X"].signature == "prop:int"
    assert by_name["Y"].signature == "prop:int"
    assert by_name["C"].signature == "class{2}"


def test_csharp_record_struct_name():
    src = "record struct P(int A, int B);\nrecord class Q(int C);\n"
    nodes = scan_file_cfamily("R.cs", src, CSHARP)
    by_name = {n.name: n for n in nodes}
    assert by_name["P"].kind == "RecordDeclaration"
    assert by_name["P"].signature == "record{2}"
    assert by_name["Q"].signature == "record{1}"


def test_field_declarator_count_ignores_generic_commas():
    src = ("class C {\n"
           "  Map<String,Integer> m = new HashMap<String,Integer>();\n"
           "  int a = f(1, 2), b;\n"
           "}\n")
    nodes = scan_file_cfamily("C.java", src, JAVA)
    by_name = {n.name: n for n in nodes}
    assert by_name["m"].signature == "vars{1}"
    assert by_name["a"].signature == "vars{2}"


def test_java_legacy_array_field_and_truncated_annotation():
    nodes = scan_file_cfamily("A.java", "class A { int a[]; int b; }", JAVA)
    by_name = {n.name: n for n in nodes}
    assert by_name["a"].signature == "vars{1}"
    assert by_name["A"].signature == "class{2}"
    # Truncated file must not raise.
    nodes = scan_file_cfamily("X.java", "class A {}\n@interface", JAVA)
    assert [n.name for n in nodes] == ["A"]


def test_indexed_assignment_is_not_a_field():
    src = "enum E { A; }\nclass C { void f() {} }\n"
    # Statement-shaped tokens in a member region: arr[idx] = val;
    src2 = "class D { int a[]; }\nclass X { { arr[idx] = val; } }\n"
    nodes = scan_file_cfamily("A.java", src2, JAVA)
    names = [n.name for n in nodes]
    assert "idx" not in names and "val" not in names
    assert "a" in names


def test_java_body_motion_extract():
    """Body-motion markers ride the shared lift_statements tail, so the
    C-family backends get extract detection for free: a new Java method
    whose body left an edited method emits extractMethod."""
    base = Snapshot(files=[{"path": "src/A.java", "content":
                            "class A { int work(int x) "
                            "{ return x * 2 + 1; } }\n"}])
    side = Snapshot(files=[
        {"path": "src/A.java", "content":
         "class A { int work(int x) { return help(x, 0); } }\n"},
        {"path": "src/B.java", "content":
         "class B { int help(int x, int pad) { return x * 2 + 1; } }\n"}])
    backend = get_backend("java")
    ops = backend.diff(base, side, base_rev="b", seed="s",
                       timestamp="2026-01-01T00:00:00Z", statement_ops=True)
    ext = [o for o in ops if o.type == "extractMethod"]
    assert len(ext) == 1
    assert ext[0].params["newName"] == "help"
    edited = [o for o in ops if o.type == "editStmtBlock"]
    assert edited and ext[0].target.symbolId == edited[0].target.symbolId
