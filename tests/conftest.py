"""Test configuration.

Tests run on a virtual 8-device CPU mesh so that every sharding and
collective path compiles and executes without TPU hardware; the bench
harness runs the same code on the real chip. The env vars must be set
before the first ``import jax`` anywhere in the process.
"""
import os
import sys
import pathlib

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
