"""Test configuration.

Tests run on a virtual 8-device CPU mesh so that every sharding and
collective path compiles and executes without TPU hardware; the bench
harness runs the same code on the real chip. The platform pinning +
relay-plugin factory surgery lives in
``semantic_merge_tpu.utils.jaxenv.force_cpu`` (shared with the driver
entry points ``__graft_entry__.dryrun_multichip`` and ``bench.py``) and
must run before the first jax backend initialisation.
"""
import os
import sys
import pathlib

# Persistent XLA compilation cache: device-kernel tests compile a handful
# of padded shapes; caching makes repeat suite runs take seconds.
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from semantic_merge_tpu.utils.jaxenv import enable_compile_cache, force_cpu  # noqa: E402

enable_compile_cache()

force_cpu(8)
