"""Test configuration.

Tests run on a virtual 8-device CPU mesh so that every sharding and
collective path compiles and executes without TPU hardware; the bench
harness runs the same code on the real chip. The env vars must be set
before the first ``import jax`` anywhere in the process.
"""
import os
import sys
import pathlib

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
# Persistent XLA compilation cache: device-kernel tests compile a handful
# of padded shapes; caching makes repeat suite runs take seconds.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/semmerge_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

# If a TPU plugin (e.g. an 'axon' loopback relay) was registered by a
# sitecustomize hook, drop its factory so CPU-only tests never dial the
# accelerator — backend discovery would otherwise block on the relay.
try:
    import jax
    # chex (via optax) imports jax.experimental.checkify, whose import-time
    # MLIR lowering registration inspects the live platform registry —
    # import it BEFORE the factory surgery below or it raises on the
    # half-removed 'tpu' plugin platform. Failure must not skip the
    # surgery: without it CPU-only tests dial the accelerator relay.
    try:
        import optax  # noqa: F401
    except ImportError:
        pass
    # Pallas registers a 'tpu' MLIR lowering at import time and raises
    # once the platform registry has been stripped — import it first too
    # (the kernels themselves run in interpret mode on CPU).
    try:
        import jax.experimental.pallas  # noqa: F401
        import jax.experimental.pallas.tpu  # noqa: F401
    except Exception:
        pass
    import jax._src.xla_bridge as _xb

    # jax may already be imported (a sitecustomize hook importing the
    # plugin pulls jax in before conftest runs), so the env vars above
    # were read too late — update the live config as well.
    jax.config.update("jax_platforms", "cpu")
    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name not in ("cpu", "interpreter"):
            _xb._backend_factories.pop(_name, None)
except Exception:
    pass

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
