"""Test configuration.

Tests run on a virtual 8-device CPU mesh so that every sharding and
collective path compiles and executes without TPU hardware; the bench
harness runs the same code on the real chip. The platform pinning +
relay-plugin factory surgery lives in
``semantic_merge_tpu.utils.jaxenv.force_cpu`` (shared with the driver
entry points ``__graft_entry__.dryrun_multichip`` and ``bench.py``) and
must run before the first jax backend initialisation.
"""
import os
import subprocess
import sys
import pathlib
import time

import pytest

# Hermeticity: no test (or subprocess a test spawns) silently delegates
# to a merge service daemon unless it opts in explicitly — auto mode in
# e.g. the driver tests would leak spawned daemons across the suite.
os.environ.setdefault("SEMMERGE_DAEMON", "off")

# Persistent XLA compilation cache: device-kernel tests compile a handful
# of padded shapes; caching makes repeat suite runs take seconds.
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from semantic_merge_tpu.utils.jaxenv import enable_compile_cache, force_cpu  # noqa: E402

enable_compile_cache()

force_cpu(8)

_REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


def spawn_service_daemon(socket_path: str, extra_env=None,
                         timeout: float = 60.0) -> subprocess.Popen:
    """Start a merge service daemon on ``socket_path`` and wait for its
    handshake. Shared by the service tests and the fault matrix."""
    from semantic_merge_tpu.service import client as service_client
    env = dict(os.environ)
    env["PYTHONPATH"] = str(_REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    env["SEMMERGE_DAEMON"] = "off"
    env.pop("SEMMERGE_FAULT", None)
    env.pop("SEMMERGE_METRICS", None)
    if extra_env:
        env.update(extra_env)
    log = open(socket_path + ".log", "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "semantic_merge_tpu", "serve",
         "--socket", socket_path],
        stdin=subprocess.DEVNULL, stdout=log, stderr=log,
        cwd="/", env=env, start_new_session=True)
    log.close()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        conn = service_client._try_connect(socket_path, timeout=2.0)
        if conn is not None:
            service_client._close(*conn)
            return proc
        if proc.poll() is not None:
            raise RuntimeError(
                f"daemon exited rc={proc.returncode} during startup "
                f"(log: {socket_path}.log)")
        time.sleep(0.1)
    proc.kill()
    raise RuntimeError(f"daemon did not come up within {timeout:g}s")


@pytest.fixture
def daemon_factory():
    """Spawn dedicated daemons a test may kill or wedge without
    poisoning the shared session daemon. Leftovers are killed."""
    procs = []

    def _spawn(socket_path: str, **kwargs) -> subprocess.Popen:
        proc = spawn_service_daemon(socket_path, **kwargs)
        procs.append(proc)
        return proc

    yield _spawn
    for proc in procs:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


@pytest.fixture(scope="session")
def service_daemon(tmp_path_factory):
    """One warm daemon for the whole session (jax import + compile are
    paid once). Tests that kill or wedge a daemon spawn their own."""
    sock = str(tmp_path_factory.mktemp("svc") / "daemon.sock")
    proc = spawn_service_daemon(sock)
    yield sock
    from semantic_merge_tpu.service import client as service_client
    try:
        service_client.call_control("shutdown", path=sock)
        proc.wait(timeout=15)
    except Exception:
        proc.kill()
