"""Touched-scope formatting (`[engine] formatter_scope = "touched"`).

The reference formats the WHOLE merged tree (prettier --write .,
reference ``semmerge/emitter.py:14-25``) — every merge reformats files
it never visited. Touched scope formats only what the merge wrote, so
untouched files stay byte-identical; "tree" remains the parity default.
"""
import json
import subprocess
import sys

from semantic_merge_tpu.runtime.emitter import emit_files

RECORDER = """\
import json, sys
with open({log!r}, "a") as fh:
    fh.write(json.dumps(sys.argv[1:]) + "\\n")
"""


def _recorder_cmd(tmp_path):
    log = tmp_path / "fmt.log"
    script = tmp_path / "rec.py"
    script.write_text(RECORDER.format(log=str(log)))
    return [sys.executable, str(script)], log


def test_emit_files_paths_formats_only_listed(tmp_path):
    tree = tmp_path / "tree"
    tree.mkdir()
    (tree / "a.ts").write_text("x\n")
    (tree / "b.ts").write_text("y\n")
    cmd, log = _recorder_cmd(tmp_path)
    emit_files(tree, cmd, paths=["b.ts", "missing.ts"])
    (args,) = [json.loads(line) for line in log.read_text().splitlines()]
    # Touched mode appends the touched list instead of tree mode's ".";
    # missing files are dropped rather than passed to the tool.
    assert args == ["b.ts"]


def test_emit_files_tree_mode_appends_dot(tmp_path):
    tree = tmp_path / "tree"
    tree.mkdir()
    cmd, log = _recorder_cmd(tmp_path)
    emit_files(tree, cmd)
    (args,) = [json.loads(line) for line in log.read_text().splitlines()]
    assert args == ["."]


def test_emit_files_empty_touched_skips_formatter(tmp_path):
    tree = tmp_path / "tree"
    tree.mkdir()
    cmd, log = _recorder_cmd(tmp_path)
    emit_files(tree, cmd, paths=[])
    assert not log.exists()


def test_emit_files_glob_metachars_escaped_in_place(tmp_path):
    # prettier reads explicit args as fast-glob patterns: a touched
    # pages/[id].ts would match pages/i.ts instead of itself. The path
    # is backslash-escaped (fast-glob's literal-path escape), so the
    # route file formats in place — no whole-tree fallback, untouched
    # files keep their bytes.
    tree = tmp_path / "tree"
    (tree / "pages").mkdir(parents=True)
    (tree / "pages" / "[id].ts").write_text("x\n")
    (tree / "pages" / "(group)").mkdir()
    (tree / "pages" / "(group)" / "p!.tsx").write_text("y\n")
    cmd, log = _recorder_cmd(tmp_path)
    emit_files(tree, cmd, paths=["pages/[id].ts", "pages/(group)/p!.tsx"])
    (args,) = [json.loads(line) for line in log.read_text().splitlines()]
    assert args == [r"pages/\(group\)/p\!.tsx", r"pages/\[id\].ts"]


def test_cli_touched_scope_end_to_end(tmp_path, monkeypatch):
    def git(*args):
        subprocess.run(["git", *args], cwd=tmp_path, check=True,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    cmd, log = _recorder_cmd(tmp_path)
    (tmp_path / ".semmerge.toml").write_text(
        "[engine]\nformatter_scope = \"touched\"\n"
        "[languages.typescript]\nenabled = true\n"
        f"formatter_cmd = {json.dumps(cmd)}\n")
    (tmp_path / "touched.ts").write_text(
        "export function foo(n: number): number { return n; }\n")
    (tmp_path / "untouched.ts").write_text(
        "export function other(s: string): string { return s; }\n")
    git("init", "-q", "-b", "main")
    git("config", "user.email", "t@e")
    git("config", "user.name", "t")
    git("add", "-A")
    git("commit", "-qm", "base")
    git("branch", "basebr")
    git("checkout", "-qb", "ba")
    (tmp_path / "touched.ts").write_text(
        "export function bar(n: number): number { return n; }\n")
    git("commit", "-qam", "rename")
    git("checkout", "-q", "main")
    git("checkout", "-qb", "bb")
    (tmp_path / "notes.txt").write_text("text file both sides keep\nplus\n")
    git("add", "-A")
    git("commit", "-qm", "side")
    git("checkout", "-q", "main")

    monkeypatch.chdir(tmp_path)
    from semantic_merge_tpu.cli import main
    rc = main(["semmerge", "basebr", "ba", "bb", "--backend", "host"])
    assert rc == 0
    (args,) = [json.loads(line) for line in log.read_text().splitlines()]
    assert "touched.ts" in args
    assert "untouched.ts" not in args
    # Text-fallback writes outside the backend's indexed extensions
    # (notes.txt) must not reach the formatter as explicit args.
    assert "notes.txt" not in args
