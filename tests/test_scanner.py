"""Frontend scanner tests: declaration indexing semantics."""
from semantic_merge_tpu.frontend.scanner import scan_file, scan_snapshot


def kinds(nodes):
    return [(n.kind, n.name) for n in nodes]


def test_function_declaration_signature_and_address():
    nodes = scan_file("src/a.ts", "export function add(a: number, b: number): number {\n  return a + b;\n}\n")
    assert len(nodes) == 1
    n = nodes[0]
    assert n.kind == "FunctionDeclaration"
    assert n.name == "add"
    assert n.signature == "fn(number,number)->number"
    # First declaration in a file has fullstart 0 (TS node.pos semantics).
    assert n.addressId == "src/a.ts::add::0"


def test_untyped_params_display_as_any():
    nodes = scan_file("a.ts", "function f(x, y) { return x; }\n")
    assert nodes[0].signature == "fn(any,any)->any"


def test_nested_declarations_are_indexed_preorder():
    src = "function outer() {\n  function inner(s: string): void {}\n}\n"
    nodes = scan_file("a.ts", src)
    assert [n.name for n in nodes] == ["outer", "inner"]
    assert nodes[1].signature == "fn(string)->void"


def test_class_interface_enum_member_counts():
    src = (
        "class Point { x = 0; y = 0; dist(): number { return 0; } }\n"
        "interface Shape { area(): number; name: string; }\n"
        "enum Color { Red, Green, Blue }\n"
    )
    nodes = scan_file("a.ts", src)
    sigs = {n.name: n.signature for n in nodes}
    assert sigs == {"Point": "class{3}", "Shape": "iface{2}", "Color": "enum{3}"}


def test_variable_statements_anon_and_declarator_counts():
    nodes = scan_file("a.ts", "const a = 1, b = 2;\nlet msg = 'hi';\n")
    assert [(n.kind, n.name, n.signature) for n in nodes] == [
        ("VariableStatement", None, "vars{2}"),
        ("VariableStatement", None, "vars{1}"),
    ]
    assert nodes[0].addressId.endswith("::anon::0")


def test_expressions_and_for_heads_not_indexed():
    src = (
        "const f = function named() { return 1; };\n"
        "const C = class Named {};\n"
        "const g = () => 1;\n"
        "for (const i of [1, 2]) { }\n"
    )
    nodes = scan_file("a.ts", src)
    # Only the three VariableStatements; no function/class declarations,
    # no for-head const.
    assert [n.kind for n in nodes] == ["VariableStatement"] * 3


def test_rename_preserves_symbol_id():
    base = scan_file("a.ts", "export function foo(a: number): number { return a; }\n")
    side = scan_file("a.ts", "export function bar(a: number): number { return a; }\n")
    assert base[0].symbolId == side[0].symbolId
    assert base[0].name != side[0].name


def test_position_shift_changes_address_spurious_move_quirk():
    # Any upstream edit shifts n.pos → addressId differs (the reference's
    # documented spurious-move quirk, workers/ts/src/sast.ts:65-67).
    base = scan_file("a.ts", "function f(): void {}\nfunction g(x: string): string { return x; }\n")
    side = scan_file("a.ts", "// comment\nfunction f(): void {}\nfunction g(x: string): string { return x; }\n")
    assert base[1].symbolId == side[1].symbolId
    assert base[1].addressId != side[1].addressId


def test_snapshot_type_resolution_cross_file():
    files = [
        {"path": "types.ts", "content": "export interface Vec { x: number; }\n"},
        {"path": "main.ts", "content": "export function len(v: Vec): number { return v.x; }\n"},
    ]
    nodes = scan_snapshot(files)
    by_name = {n.name: n for n in nodes if n.name}
    # Vec is declared in the snapshot → keeps its name in the signature.
    assert by_name["len"].signature == "fn(Vec)->number"


def test_unresolved_type_reference_displays_any():
    # No default lib is loaded (reference host returns "" for lib files),
    # so Array<T> and unknown names collapse to any.
    nodes = scan_file("a.ts", "function f(xs: Array<number>, p: Promise<void>): Missing { return xs; }\n")
    assert nodes[0].signature == "fn(any,any)->any"


def test_array_and_union_rendering():
    nodes = scan_file("a.ts", "function f(xs: number[], u: string | number): void {}\n")
    assert nodes[0].signature == "fn(number[],string | number)->void"


def test_template_and_regex_do_not_confuse_scanner():
    src = (
        "const s = `hello ${name} {brace}`;\n"
        "const re = /function notreal\\//g;\n"
        "function real(): void {}\n"
    )
    nodes = scan_file("a.ts", src)
    assert ("FunctionDeclaration", "real") in kinds(nodes)
    assert len([n for n in nodes if n.kind == "FunctionDeclaration"]) == 1


def test_same_shape_decls_collide_last_wins_in_diff():
    # Two classes with the same member count share a symbolId — the
    # reference's coarse-signature collision (implementation.md:1309).
    nodes = scan_file("a.ts", "class A { x = 1; }\nclass B { y = 2; }\n")
    assert nodes[0].symbolId == nodes[1].symbolId


def test_trailing_comma_tuple_type_renders():
    """Regression: `[A, B,]` (legal TS) must not crash the renderer."""
    from semantic_merge_tpu.frontend.scanner import scan_snapshot_py
    nodes = scan_snapshot_py([{
        "path": "a.ts",
        "content": "export function t(p: [string, number,]): void {}\n"}])
    assert nodes[0].signature == "fn([string, number])->void"
