"""Device-side op-log rendering parity (``ops/render.py``).

The device renderer assembles the serialized op-log JSON as fixed-width
byte tensors on the accelerator; the host does one d2h copy and a
concat. These tests pin the contract that makes the posture safe to
flip: the payload bytes are IDENTICAL to the PR-2 host tail pipeline —
per-side op logs and the composed stream, across conflicts, rename
chains, CRDT/statement ops, both fetch modes, co-batched dispatch, and
adversarial string content — and every render failure under ``auto``
falls back to the host pipeline silently, while ``require`` surfaces a
typed ``RenderFault`` (exit 20).
"""
from __future__ import annotations

import pytest

import bench
from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
from semantic_merge_tpu.core.ops import OpLog
from semantic_merge_tpu.errors import RenderFault
from semantic_merge_tpu.frontend.snapshot import Snapshot

TS = "2026-01-01T00:00:00Z"


def snap(files):
    return Snapshot(files=[{"path": p, "content": c} for p, c in files])


def merge_payloads(base, left, right, **kw):
    """Byte-comparable form of everything the render path can touch:
    both op-log payloads, the composed payload, the composed dicts
    (materialization parity, not just serialization), conflicts."""
    backend = TpuTSBackend(mesh=False)
    res, composed, conflicts = backend.merge(
        base, left, right, base_rev="bench", seed="bench", timestamp=TS,
        **kw)
    composed_bytes = composed.to_json_bytes() \
        if hasattr(composed, "to_json_bytes") else None
    return (
        OpLog(res.op_log_left).to_json_bytes(),
        OpLog(res.op_log_right).to_json_bytes(),
        composed_bytes,
        [op.to_dict() for op in composed],
        [c.to_dict() for c in conflicts],
    )


def render_on(monkeypatch, posture="require"):
    monkeypatch.setenv("SEMMERGE_DEVICE_RENDER", posture)
    monkeypatch.setenv("SEMMERGE_RENDER_MIN_ROWS", "0")
    monkeypatch.setenv("SEMMERGE_MESH", "off")


def render_off(monkeypatch):
    monkeypatch.setenv("SEMMERGE_DEVICE_RENDER", "off")
    monkeypatch.setenv("SEMMERGE_MESH", "off")


def _nasty_workload():
    """Every JSON-escaping hazard the renderer's escaped string table
    must reproduce: quotes, backslashes, control chars, non-ASCII
    (multi-byte UTF-8), and long names straddling segment widths."""
    base, left, right = [], [], []
    specials = ['q"uote', "back\\slash", "tab\there", "nl\nline",
                "bell\x07", "emojié€", "del\x7f",
                "x" * 300]
    for i, s in enumerate(specials):
        path = f"src/ü{i}.ts"
        safe = f"fn{i}"
        content = f"export function {safe}(x: number): number " \
                  f"{{ return {i}; }}\n"
        base.append((path, content))
        # Rename into an adversarial name on the left; move on the
        # right — both sides' string tables carry the hazards.
        left.append((path, content.replace(f"{safe}(", f"n{i}_{s}(")))
        right.append((f"lib/é{i}.ts", content))
    return snap(base), snap(left), snap(right)


@pytest.mark.parametrize("split", ["0", "1"], ids=["onebuf", "split"])
@pytest.mark.parametrize("workload", ["clean", "divergent", "nasty"])
def test_render_byte_parity(monkeypatch, workload, split):
    monkeypatch.setenv("SEMMERGE_SPLIT_FETCH", split)
    if workload == "nasty":
        snaps = _nasty_workload()
    else:
        snaps = bench.synth_repo(60, 4, divergent=workload == "divergent")
    render_off(monkeypatch)
    want = merge_payloads(*snaps)
    if workload == "divergent":
        assert want[4], "divergent workload must produce conflicts"
    render_on(monkeypatch, "require")
    got = merge_payloads(*snaps)
    assert got == want


def test_render_statement_ops_parity(monkeypatch):
    # Statement-level ops ride the CRDT materialization path; their
    # reordered/composed streams must serialize identically whether the
    # per-side payloads came from the device renderer or the host.
    snaps = bench.synth_repo(40, 4, divergent=True)
    render_off(monkeypatch)
    want = merge_payloads(*snaps, statement_ops=True)
    render_on(monkeypatch, "require")
    got = merge_payloads(*snaps, statement_ops=True)
    assert got == want


def test_render_sides_swapped_parity(monkeypatch):
    # Convergence probe: swapping the sides reorders every composed
    # decision; the rendered payloads must track the host pipeline in
    # both orientations independently.
    base, left, right = bench.synth_repo(40, 4, divergent=True)
    for sides in ((left, right), (right, left)):
        render_off(monkeypatch)
        want = merge_payloads(base, *sides)
        render_on(monkeypatch, "require")
        assert merge_payloads(base, *sides) == want


def test_render_empty_stream(monkeypatch):
    base, _, _ = bench.synth_repo(6, 2)
    render_on(monkeypatch, "require")
    left_json, right_json, composed_bytes, composed, conflicts = \
        merge_payloads(base, base, base)
    assert left_json == b"[]" and right_json == b"[]"
    assert composed == [] and conflicts == []
    if composed_bytes is not None:
        assert composed_bytes == b"[]"


def test_render_auto_falls_back_on_width_guard(monkeypatch):
    # A 1-byte width cap makes every render ineligible mid-dispatch;
    # auto posture must silently serve the host-pipeline bytes.
    snaps = bench.synth_repo(20, 3, divergent=True)
    render_off(monkeypatch)
    want = merge_payloads(*snaps)
    render_on(monkeypatch, "auto")
    monkeypatch.setenv("SEMMERGE_RENDER_MAX_WIDTH", "1")
    assert merge_payloads(*snaps) == want


def test_render_require_width_guard_raises(monkeypatch):
    snaps = bench.synth_repo(20, 3)
    render_on(monkeypatch, "require")
    monkeypatch.setenv("SEMMERGE_RENDER_MAX_WIDTH", "1")
    with pytest.raises(RenderFault) as err:
        merge_payloads(*snaps)
    assert err.value.exit_code == 20
    assert err.value.stage == "render"


def test_render_min_rows_gates_auto(monkeypatch):
    # Under auto, streams below the row floor skip the renderer — the
    # handle must be absent, the payloads still correct.
    snaps = bench.synth_repo(6, 2, divergent=True)
    render_off(monkeypatch)
    want = merge_payloads(*snaps)
    monkeypatch.setenv("SEMMERGE_DEVICE_RENDER", "auto")
    monkeypatch.setenv("SEMMERGE_RENDER_MIN_ROWS", "1000000")
    monkeypatch.setenv("SEMMERGE_MESH", "off")
    assert merge_payloads(*snaps) == want


def test_render_cobatched_dispatch_parity(monkeypatch):
    # Co-batched requests take the packed multi-merge program, which
    # does not attach render handles; posture must not perturb the
    # bytes (auto: fallback) nor fault spuriously.
    import contextlib
    import threading

    from semantic_merge_tpu import batch
    from semantic_merge_tpu.utils import reqenv

    monkeypatch.setenv("SEMMERGE_MESH", "off")
    snaps = bench.synth_repo(4, 2)
    render_off(monkeypatch)
    want = merge_payloads(*snaps)
    render_on(monkeypatch, "auto")
    batch.activate(window_ms=100.0)
    try:
        n = 3
        results, errors = [None] * n, [None] * n
        barrier = threading.Barrier(n)

        def work(i):
            try:
                be = TpuTSBackend(mesh=False)
                with reqenv.overlay({batch.ENV_POSTURE: "off"}):
                    be.merge(*snaps, base_rev="bench", seed="bench",
                             timestamp=TS)
                barrier.wait()
                res, composed, conflicts = be.merge(
                    *snaps, base_rev="bench", seed="bench", timestamp=TS)
                results[i] = (
                    OpLog(res.op_log_left).to_json_bytes(),
                    OpLog(res.op_log_right).to_json_bytes(),
                    composed.to_json_bytes()
                    if hasattr(composed, "to_json_bytes") else None,
                    [op.to_dict() for op in composed],
                    [c.to_dict() for c in conflicts],
                )
            except BaseException as exc:  # noqa: BLE001 - surfaced below
                errors[i] = exc
                with contextlib.suppress(threading.BrokenBarrierError):
                    barrier.abort()

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        for exc in errors:
            if exc is not None:
                raise exc
    finally:
        batch.deactivate()
    for i, got in enumerate(results):
        assert got == want, f"request {i} diverged under device render"


def test_render_handle_consumed_once(monkeypatch):
    # The fast path serves the rendered bytes; a second serialization
    # of the same view must still be byte-identical (the handle caches
    # its fetched buffer — or the host fallback reproduces it).
    snaps = bench.synth_repo(20, 3, divergent=True)
    render_on(monkeypatch, "require")
    backend = TpuTSBackend(mesh=False)
    res, composed, _ = backend.merge(
        *snaps, base_rev="bench", seed="bench", timestamp=TS)
    first = OpLog(res.op_log_left).to_json_bytes()
    second = OpLog(res.op_log_left).to_json_bytes()
    assert first == second
    if hasattr(composed, "to_json_bytes"):
        assert composed.to_json_bytes() == composed.to_json_bytes()
