"""Fault-containment matrix (ISSUE 4 tentpole).

For every injectable stage×kind pair (``SEMMERGE_FAULT``), the merge
must either:

- land on the documented degradation-ladder rung — ultimately the
  whole-tree textual 3-way merge, whose result must be **byte-exact**
  against an independently computed textual merge of the same three
  trees — or,
- under ``SEMMERGE_STRICT=1`` / ``--no-degrade``, exit with the fault's
  documented exit code with the work tree **bitwise untouched**.

Plus: crash-safe ``--inplace`` commit (journal/rollback/roll-forward,
including a real SIGKILL mid-commit resolved by ``semmerge --resume``),
and schema validation of the ``degradation`` spans / fault metric
series via ``scripts/check_trace_schema.py``.
"""
import contextlib
import hashlib
import importlib.util
import json
import os
import pathlib
import signal
import socket
import subprocess
import sys
import threading

import pytest

from semantic_merge_tpu.cli import main
from semantic_merge_tpu.errors import (ApplyFault, EXIT_CODES, FormatFault,
                                       ParseFault, WorkerFault)
from semantic_merge_tpu.obs import metrics as obs_metrics
from semantic_merge_tpu.runtime import inplace
from semantic_merge_tpu.utils import faults

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent

#: Artifacts the engine itself writes next to the work tree — excluded
#: from tree-state comparisons.
ARTIFACTS = {".semmerge-conflicts.json", ".semmerge-trace.json",
             ".semmerge-events.jsonl", ".semmerge-journal.json",
             ".semmerge-postmortem"}


def git(args, cwd):
    subprocess.run(["git", *args], cwd=cwd, check=True,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def commit_all(root, msg):
    git(["add", "-A"], root)
    env = {"GIT_AUTHOR_DATE": "2024-01-01T00:00:00Z",
           "GIT_COMMITTER_DATE": "2024-01-01T00:00:00Z"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        git(["commit", "-q", "-m", msg], root)
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.update({k: v})


@pytest.fixture
def repo(tmp_path, monkeypatch):
    """A repo whose SEMANTIC merge result equals its TEXTUAL merge
    result (A's edits and B's adds touch disjoint files), so every
    ladder rung must converge on the same bytes."""
    root = tmp_path / "repo"
    root.mkdir()
    git(["init", "-q", "-b", "main"], root)
    git(["config", "user.email", "t@example.com"], root)
    git(["config", "user.name", "t"], root)
    monkeypatch.chdir(root)
    (root / "src").mkdir()
    (root / "src/util.ts").write_text(
        "export function foo(n: number): number {\n  return n;\n}\n")
    (root / "notes.txt").write_text("hello\n")
    commit_all(root, "base")
    git(["branch", "basebr"], root)
    git(["checkout", "-qb", "brA"], root)
    (root / "src/util.ts").write_text(
        "export function bar(n: number): number {\n  return n;\n}\n")
    commit_all(root, "rename foo->bar")
    git(["checkout", "-q", "main"], root)
    git(["checkout", "-qb", "brB"], root)
    (root / "extra.ts").write_text(
        "export function extra(s: string): string { return s; }\n")
    (root / "notes.txt").write_text("hello\nworld\n")
    commit_all(root, "add extra + edit notes")
    git(["checkout", "-q", "main"], root)
    faults.reset()
    yield root
    faults.reset()


def tree_state(root: pathlib.Path) -> dict:
    """``{relpath: sha256}`` of every tracked-tree file (skips .git and
    engine artifacts)."""
    out = {}
    for p in sorted(root.rglob("*")):
        if not p.is_file():
            continue
        rel = p.relative_to(root).as_posix()
        if rel.startswith(".git/") or rel.split("/")[0] in ARTIFACTS \
                or rel.startswith(inplace.STAGE_DIR + "/"):
            continue
        out[rel] = hashlib.sha256(p.read_bytes()).hexdigest()
    return out


def expected_textual_tree(root: pathlib.Path) -> dict:
    """Independent oracle: the whole-tree 3-way textual merge of
    basebr/brA/brB, computed straight from the tars."""
    from semantic_merge_tpu.runtime.git import archive_bytes, temp_tree
    from semantic_merge_tpu.runtime.textmerge import apply_text_fallback
    base = archive_bytes("basebr", cwd=root)
    left = archive_bytes("brA", cwd=root)
    right = archive_bytes("brB", cwd=root)
    with temp_tree(base) as tree:
        conflicts, deleted, _ = apply_text_fallback(
            tree, base, left, right, indexed_extensions=frozenset())
        assert not conflicts and not deleted
        return {p.relative_to(tree).as_posix():
                hashlib.sha256(p.read_bytes()).hexdigest()
                for p in sorted(tree.rglob("*")) if p.is_file()}


def counter_total(name: str) -> float:
    data = obs_metrics.REGISTRY.to_dict()
    metric = data.get("counters", {}).get(name, {})
    return sum(s["value"] for s in metric.get("series", []))


def run_merge_cli(*extra, backend="host"):
    return main(["semmerge", "basebr", "brA", "brB",
                 "--inplace", "--backend", backend, *extra])


# ---------------------------------------------------------------------------
# Degradation-ladder matrix (default posture)
# ---------------------------------------------------------------------------

LADDER_MATRIX = [
    # (stage, kind, backend) — every case must exit 0 with the
    # byte-exact textual-equivalent tree and record >=1 degradation.
    ("scan", "fault", "host"),
    ("scan", "raise", "host"),
    ("apply", "fault", "host"),
    ("apply", "raise", "host"),
    ("emit", "fault", "host"),
    ("worker", "fault", "subprocess"),
]


@pytest.mark.parametrize("stage,kind,backend", LADDER_MATRIX)
def test_fault_degrades_to_byte_exact_textual_merge(repo, monkeypatch,
                                                    stage, kind, backend):
    expected = expected_textual_tree(repo)
    monkeypatch.setenv("SEMMERGE_FAULT", f"{stage}:{kind}")
    degr0 = counter_total("merge_degradations_total")
    rc = run_merge_cli(backend=backend)
    assert rc == 0, f"{stage}:{kind} must land on a working rung"
    assert tree_state(repo) == expected, \
        f"{stage}:{kind} result must be byte-exact vs the textual merge"
    assert counter_total("merge_degradations_total") > degr0, \
        "the ladder transition must be recorded"
    assert not (repo / inplace.JOURNAL).exists()
    assert not (repo / inplace.STAGE_DIR).exists()


@pytest.mark.parametrize("stage,kind", [("kernel", "fault"),
                                        ("chain", "fault")])
def test_device_stage_faults_degrade(repo, monkeypatch, stage, kind):
    pytest.importorskip("jax")
    try:
        from semantic_merge_tpu.backends.base import get_backend
        get_backend("tpu").close()
    except Exception:
        pytest.skip("tpu backend unavailable in this environment")
    expected = expected_textual_tree(repo)
    monkeypatch.setenv("SEMMERGE_FAULT", f"{stage}:{kind}")
    degr0 = counter_total("merge_degradations_total")
    rc = run_merge_cli(backend="tpu")
    assert rc == 0
    assert tree_state(repo) == expected
    assert counter_total("merge_degradations_total") > degr0


# ---------------------------------------------------------------------------
# Strict mode: documented exit code, work tree bitwise untouched
# ---------------------------------------------------------------------------

STRICT_MATRIX = [
    ("scan", "fault", "host", ParseFault.exit_code),
    ("apply", "fault", "host", ApplyFault.exit_code),
    ("apply", "raise", "host", ApplyFault.exit_code),  # boundary classifies
    ("emit", "fault", "host", FormatFault.exit_code),
    ("worker", "fault", "subprocess", WorkerFault.exit_code),
]


@pytest.mark.parametrize("stage,kind,backend,code", STRICT_MATRIX)
def test_strict_mode_exits_with_documented_code(repo, monkeypatch,
                                                stage, kind, backend, code):
    before = tree_state(repo)
    monkeypatch.setenv("SEMMERGE_FAULT", f"{stage}:{kind}")
    monkeypatch.setenv("SEMMERGE_STRICT", "1")
    rc = run_merge_cli(backend=backend)
    assert rc == code, f"{stage}:{kind} must exit {code} in strict mode"
    assert tree_state(repo) == before, \
        "a strict failure exit must leave the work tree bitwise untouched"


def test_no_degrade_flag_equals_strict_env(repo, monkeypatch):
    before = tree_state(repo)
    monkeypatch.setenv("SEMMERGE_FAULT", "apply:fault")
    rc = run_merge_cli("--no-degrade")
    assert rc == ApplyFault.exit_code
    assert tree_state(repo) == before


def test_exit_codes_documented_and_distinct():
    assert EXIT_CODES == {"ParseFault": 10, "KernelFault": 11,
                          "WorkerFault": 12, "ApplyFault": 13,
                          "FormatFault": 14, "DeadlineFault": 15,
                          "BatchFault": 16, "ResolveFault": 17,
                          "MeshFault": 18, "FleetFault": 19,
                          "RenderFault": 20, "TransportFault": 21}
    assert len(set(EXIT_CODES.values())) == len(EXIT_CODES)
    # Reserved result codes stay distinct from fault codes.
    assert not {0, 1, 2, 3} & set(EXIT_CODES.values())


def test_nth_hit_selector(monkeypatch):
    faults.reset()
    monkeypatch.setenv("SEMMERGE_FAULT", "apply:raise:2")
    assert faults.check("apply") is None  # first hit passes
    with pytest.raises(RuntimeError):
        faults.check("apply")  # second hit fires
    assert faults.check("apply") is None  # third passes again


# ---------------------------------------------------------------------------
# Service stages: injected daemon faults land as typed wire errors
# ---------------------------------------------------------------------------

def _daemon_client_env(sock: str, **extra) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    env["SEMMERGE_DAEMON"] = "require"
    env["SEMMERGE_SERVICE_SOCKET"] = sock
    env.pop("SEMMERGE_FAULT", None)
    env.update(extra)
    return env


SERVICE_FAULT_MATRIX = [
    # Every daemon request stage classifies as WorkerFault (the daemon
    # is an out-of-process worker from the client's point of view) and
    # must come back over the wire with its exit code preserved.
    ("service:accept", WorkerFault.exit_code),
    ("service:dispatch", WorkerFault.exit_code),
    ("service:execute", WorkerFault.exit_code),
]


@pytest.mark.parametrize("stage,code", SERVICE_FAULT_MATRIX)
def test_service_stage_fault_is_typed_wire_error(repo, service_daemon,
                                                 stage, code):
    """``SEMMERGE_FAULT`` rides the request env overlay: the injected
    stage fault fails THIS request with the documented exit code, the
    work tree stays untouched, and the daemon serves the next request
    — faults degrade or return typed errors, never kill the daemon."""
    before = tree_state(repo)
    proc = subprocess.run(
        [sys.executable, "-m", "semantic_merge_tpu", "semmerge",
         "basebr", "brA", "brB", "--inplace", "--backend", "host"],
        cwd=repo, capture_output=True, text=True,
        env=_daemon_client_env(service_daemon,
                               SEMMERGE_FAULT=f"{stage}:fault"))
    assert proc.returncode == code, \
        f"{stage}:fault must exit {code} over the wire: {proc.stderr}"
    assert "WorkerFault" in proc.stderr
    assert tree_state(repo) == before, \
        "a service-stage fault must leave the work tree bitwise untouched"
    # The daemon survived and completes the identical request cleanly.
    proc2 = subprocess.run(
        [sys.executable, "-m", "semantic_merge_tpu", "semmerge",
         "basebr", "brA", "brB", "--inplace", "--backend", "host"],
        cwd=repo, capture_output=True, text=True,
        env=_daemon_client_env(service_daemon))
    assert proc2.returncode == 0, proc2.stderr
    assert "bar" in (repo / "src/util.ts").read_text()


def test_service_stages_registered_as_worker_faults():
    from semantic_merge_tpu.errors import STAGE_FAULTS
    for stage in ("service:accept", "service:dispatch", "service:execute"):
        assert STAGE_FAULTS[stage] is WorkerFault
    # The compound stage survives SEMMERGE_FAULT's colon syntax.
    faults.reset()
    try:
        os.environ["SEMMERGE_FAULT"] = "service:dispatch:raise:2"
        assert faults.check("service:dispatch") is None
        with pytest.raises(RuntimeError):
            faults.check("service:dispatch")
    finally:
        os.environ.pop("SEMMERGE_FAULT", None)
        faults.reset()


# ---------------------------------------------------------------------------
# Net stages: the fleet transport seam (typed TransportFault, exit 21)
# ---------------------------------------------------------------------------

NET_FAULT_STAGES = ("net:connect", "net:read", "net:partition", "net:slow")


def test_net_stages_registered_as_transport_faults():
    from semantic_merge_tpu.errors import STAGE_FAULTS, TransportFault
    assert TransportFault.exit_code == 21
    for stage in ("transport",) + NET_FAULT_STAGES:
        assert STAGE_FAULTS[stage] is TransportFault
    # The compound stage survives SEMMERGE_FAULT's colon syntax.
    faults.reset()
    try:
        os.environ["SEMMERGE_FAULT"] = "net:connect:fault"
        with pytest.raises(TransportFault) as exc_info:
            faults.check("net:connect")
        assert exc_info.value.stage == "net:connect"
        assert exc_info.value.cause == "injected"
        assert exc_info.value.exit_code == 21
    finally:
        os.environ.pop("SEMMERGE_FAULT", None)
        faults.reset()


def _fleet_client_env(posture: str, sock: str, **extra) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    env["SEMMERGE_FLEET"] = posture
    env["SEMMERGE_SERVICE_SOCKET"] = sock
    env.pop("SEMMERGE_DAEMON", None)
    env.pop("SEMMERGE_FAULT", None)
    env.update(extra)
    return env


@pytest.fixture
def sink_socket(tmp_path):
    """A listener that accepts connections and never answers. The
    ``net:read`` seam fires after a successful dial, so it needs
    something on the other end of the socket — but never a reply."""
    path = str(tmp_path / "sink.sock")
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(path)
    srv.listen(8)
    held = []

    def _accept():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            held.append(conn)

    threading.Thread(target=_accept, daemon=True).start()
    yield path
    srv.close()
    for conn in held:
        with contextlib.suppress(OSError):
            conn.close()


def _run_fleet_merge(repo, env):
    # delegate() runs in __main__ before the CLI imports, so the fleet
    # transport seam is only reachable through a real subprocess.
    return subprocess.run(
        [sys.executable, "-m", "semantic_merge_tpu", "semmerge",
         "basebr", "brA", "brB", "--inplace", "--backend", "host"],
        cwd=repo, capture_output=True, text=True, env=env)


@pytest.mark.parametrize("stage", NET_FAULT_STAGES)
def test_net_fault_require_fleet_exits_21_tree_untouched(repo, sink_socket,
                                                         stage):
    """Under ``SEMMERGE_FLEET=require`` every injected transport fault
    is exit 21 with the work tree bitwise untouched: the fault fires in
    the dial/read seam, before any merge work starts."""
    before = tree_state(repo)
    proc = _run_fleet_merge(repo, _fleet_client_env(
        "require", sink_socket, SEMMERGE_FAULT=f"{stage}:fault"))
    assert proc.returncode == 21, \
        f"{stage}:fault must exit 21 under require: {proc.stderr}"
    assert "fleet transport failed" in proc.stderr
    assert tree_state(repo) == before, \
        "a transport fault under require must leave the tree untouched"


@pytest.mark.parametrize("stage", NET_FAULT_STAGES)
def test_net_fault_auto_fleet_falls_back_byte_exact(repo, sink_socket,
                                                    stage):
    """Under ``SEMMERGE_FLEET=auto`` the same faults degrade through
    the ladder: the client falls back in-process and the settled tree
    is byte-exact against the independent textual oracle."""
    expected = expected_textual_tree(repo)
    proc = _run_fleet_merge(repo, _fleet_client_env(
        "auto", sink_socket, SEMMERGE_FAULT=f"{stage}:fault"))
    assert proc.returncode == 0, proc.stderr
    assert tree_state(repo) == expected, \
        "the auto-posture fallback must settle byte-exact"


# ---------------------------------------------------------------------------
# Batch stages: typed BatchFault registration + compound-stage parsing
# ---------------------------------------------------------------------------

def test_batch_stages_registered_as_batch_faults():
    from semantic_merge_tpu.errors import STAGE_FAULTS, BatchFault, MeshFault
    assert BatchFault.exit_code == 16
    for stage in ("batch", "batch:pack", "batch:dispatch", "batch:scatter",
                  "batch:mesh"):
        assert STAGE_FAULTS[stage] is BatchFault
    # The leader-side mesh contract has its own typed fault: exit 18,
    # only ever surfaced under SEMMERGE_MESH=require.
    assert STAGE_FAULTS["mesh"] is MeshFault
    assert MeshFault.exit_code == 18
    # The compound stage survives SEMMERGE_FAULT's colon syntax.
    faults.reset()
    try:
        os.environ["SEMMERGE_FAULT"] = "batch:pack:fault"
        with pytest.raises(BatchFault) as exc_info:
            faults.check("batch:pack")
        assert exc_info.value.stage == "batch:pack"
        assert exc_info.value.cause == "injected"
    finally:
        os.environ.pop("SEMMERGE_FAULT", None)
        faults.reset()


BATCH_FAULT_STAGES = ["batch:pack", "batch:mesh", "batch:dispatch",
                      "batch:scatter"]


@pytest.mark.parametrize("stage", BATCH_FAULT_STAGES)
def test_batch_stage_fault_degrades_request_to_unbatched(repo, monkeypatch,
                                                         stage):
    """In the default (auto) posture an injected batch-stage fault
    lands THIS request on the inline unbatched path: the merge still
    succeeds with the exact result — never worse than one-shot."""
    from semantic_merge_tpu import batch
    expected = expected_textual_tree(repo)  # == semantic result by design
    monkeypatch.setenv("SEMMERGE_MESH", "off")  # single-device: eligible
    monkeypatch.setenv("SEMMERGE_FAULT", f"{stage}:fault")
    batch.activate(window_ms=20.0)
    try:
        rc = run_merge_cli(backend="tpu")
    finally:
        batch.deactivate()
    assert rc == 0, f"{stage}:fault must degrade to the inline dispatch"
    assert tree_state(repo) == expected


@pytest.mark.parametrize("stage", BATCH_FAULT_STAGES)
def test_batch_stage_fault_strict_require_exits_16(repo, monkeypatch, stage):
    """``SEMMERGE_BATCH=require`` + strict: the injected batch fault is
    fatal with its documented exit code and an untouched work tree."""
    from semantic_merge_tpu import batch
    from semantic_merge_tpu.errors import BatchFault
    before = tree_state(repo)
    monkeypatch.setenv("SEMMERGE_MESH", "off")  # single-device: eligible
    monkeypatch.setenv("SEMMERGE_FAULT", f"{stage}:fault")
    monkeypatch.setenv("SEMMERGE_BATCH", "require")
    monkeypatch.setenv("SEMMERGE_STRICT", "1")
    batch.activate(window_ms=20.0)
    try:
        rc = run_merge_cli(backend="tpu")
    finally:
        batch.deactivate()
    assert rc == BatchFault.exit_code
    assert tree_state(repo) == before


def test_batch_mesh_fault_counts_fallback_and_degrades(repo, monkeypatch):
    """The ``batch:mesh`` stage is the mesh seam of the batched path:
    an injected fault there degrades THIS request to the inline
    dispatch (merge still exact) AND increments the
    ``batch_mesh_fallbacks_total{reason="fault"}`` counter the mesh
    runbook keys its fallback alerting on."""
    from semantic_merge_tpu import batch
    from semantic_merge_tpu.obs import metrics as obs_metrics
    expected = expected_textual_tree(repo)
    monkeypatch.setenv("SEMMERGE_MESH", "off")  # single-device: eligible
    monkeypatch.setenv("SEMMERGE_FAULT", "batch:mesh:fault")
    counter = obs_metrics.REGISTRY.counter("batch_mesh_fallbacks_total")
    before = counter.value(reason="fault")
    batch.activate(window_ms=20.0)
    try:
        rc = run_merge_cli(backend="tpu")
    finally:
        batch.deactivate()
    assert rc == 0, "batch:mesh fault must degrade to the inline dispatch"
    assert tree_state(repo) == expected
    assert counter.value(reason="fault") >= before + 1


# ---------------------------------------------------------------------------
# Resolver stages: injected faults degrade to conflict-as-result
# ---------------------------------------------------------------------------

@pytest.fixture
def conflict_repo(tmp_path, monkeypatch):
    """A repo whose semantic merge raises a DivergentRename conflict
    (both branches rename ``foo``, to different names), with asymmetric
    reference evidence so the search resolver has a unique winner."""
    root = tmp_path / "crepo"
    root.mkdir()
    git(["init", "-q", "-b", "main"], root)
    git(["config", "user.email", "t@example.com"], root)
    git(["config", "user.name", "t"], root)
    monkeypatch.chdir(root)
    (root / "src").mkdir()
    (root / "src/util.ts").write_text(
        "export function foo(n: number): number {\n  return n;\n}\n"
        "export function use(s: string): number {\n"
        "  return foo(s.length);\n}\n")
    commit_all(root, "base")
    git(["branch", "basebr"], root)
    git(["checkout", "-qb", "brA"], root)
    (root / "src/util.ts").write_text(
        "export function bar(n: number): number {\n  return n;\n}\n"
        "export function use(s: string): number {\n"
        "  return bar(s.length);\n}\n")
    commit_all(root, "rename foo->bar + rewrite caller")
    git(["checkout", "-q", "main"], root)
    git(["checkout", "-qb", "brB"], root)
    (root / "src/util.ts").write_text(
        "export function baz(n: number): number {\n  return n;\n}\n"
        "export function use(s: string): number {\n"
        "  return foo(s.length);\n}\n")
    commit_all(root, "rename foo->baz, decl only")
    git(["checkout", "-q", "main"], root)
    faults.reset()
    yield root
    faults.reset()


RESOLVER_FAULT_STAGES = ["resolver:propose", "resolver:verify"]


@pytest.mark.parametrize("stage", RESOLVER_FAULT_STAGES)
def test_resolver_stage_fault_falls_back_byte_exact(conflict_repo,
                                                    monkeypatch, stage):
    """Posture ``auto`` + injected resolver fault: the merge degrades
    to conflict-as-result — exit 1, work tree and conflicts artifact
    byte-exact against a resolver-OFF run — and leaves a postmortem
    bundle with reason ``resolver-fault``. Never a crash."""
    artifact = conflict_repo / ".semmerge-conflicts.json"
    monkeypatch.setenv("SEMMERGE_RESOLVE", "off")
    rc = run_merge_cli()
    assert rc == 1, "the fixture must raise a real conflict"
    baseline_tree = tree_state(conflict_repo)
    baseline_artifact = artifact.read_bytes()
    assert isinstance(json.loads(baseline_artifact), list), \
        "resolver-off artifact keeps the legacy bare-array shape"
    faults.reset()
    monkeypatch.setenv("SEMMERGE_RESOLVE", "auto")
    monkeypatch.setenv("SEMMERGE_FAULT", f"{stage}:fault")
    rc = run_merge_cli()
    assert rc == 1, f"{stage}:fault under auto must fall back to exit 1"
    assert tree_state(conflict_repo) == baseline_tree, \
        "the fallback work tree must be byte-exact vs resolver-off"
    assert artifact.read_bytes() == baseline_artifact, \
        "the fallback artifact must be byte-exact vs resolver-off"
    bundles = list((conflict_repo / ".semmerge-postmortem").glob("*.json"))
    assert any(json.loads(b.read_text()).get("reason") == "resolver-fault"
               for b in bundles), \
        "the absorbed resolver fault must leave a postmortem bundle"


@pytest.mark.parametrize("stage", RESOLVER_FAULT_STAGES)
def test_resolver_stage_fault_require_exits_17(conflict_repo, monkeypatch,
                                               stage):
    """``--resolve require``: the injected resolver fault is fatal with
    the documented exit code and an untouched work tree."""
    from semantic_merge_tpu.errors import ResolveFault
    before = tree_state(conflict_repo)
    monkeypatch.setenv("SEMMERGE_FAULT", f"{stage}:fault")
    rc = run_merge_cli("--resolve", "require")
    assert rc == ResolveFault.exit_code
    assert tree_state(conflict_repo) == before


def test_resolver_stages_registered_as_resolve_faults():
    from semantic_merge_tpu.errors import STAGE_FAULTS, ResolveFault
    assert ResolveFault.exit_code == 17
    for stage in ("resolve", "resolver:propose", "resolver:verify"):
        assert STAGE_FAULTS[stage] is ResolveFault
    # The compound stage survives SEMMERGE_FAULT's colon syntax.
    faults.reset()
    try:
        os.environ["SEMMERGE_FAULT"] = "resolver:propose:fault"
        with pytest.raises(ResolveFault) as exc_info:
            faults.check("resolver:propose")
        assert exc_info.value.stage == "resolver:propose"
        assert exc_info.value.cause == "injected"
    finally:
        os.environ.pop("SEMMERGE_FAULT", None)
        faults.reset()


# ---------------------------------------------------------------------------
# No fault injected: clean merge, no degradations recorded
# ---------------------------------------------------------------------------

def test_clean_merge_records_no_degradation(repo, monkeypatch):
    monkeypatch.delenv("SEMMERGE_FAULT", raising=False)
    degr0 = counter_total("merge_degradations_total")
    rc = run_merge_cli()
    assert rc == 0
    assert counter_total("merge_degradations_total") == degr0
    assert "bar" in (repo / "src/util.ts").read_text()
    assert (repo / "extra.ts").exists()


# ---------------------------------------------------------------------------
# Trace artifact: degradation spans + fault metric series validate
# ---------------------------------------------------------------------------

def _schema_module():
    script = REPO_ROOT / "scripts" / "check_trace_schema.py"
    spec = importlib.util.spec_from_file_location("cts_faults", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_degraded_trace_validates_against_schema(repo, monkeypatch):
    monkeypatch.setenv("SEMMERGE_FAULT", "apply:fault")
    rc = run_merge_cli("--trace")
    assert rc == 0
    trace = json.loads((repo / ".semmerge-trace.json").read_text())
    degr = [s for s in trace["spans"] if s["name"] == "degradation"]
    assert degr, "a degraded --trace run must record degradation spans"
    assert degr[0]["meta"]["to"] == "text"
    assert degr[0]["meta"]["fault"] == "ApplyFault"
    schema = _schema_module()
    assert schema.validate_trace(trace) == []
    assert schema.validate_degradations(trace) == []


def test_schema_rejects_malformed_degradation_records():
    schema = _schema_module()
    bad_span = {"schema": 1, "phases": [], "counters": {},
                "total_seconds": 0.0, "device": None,
                "spans": [{"name": "degradation", "t_start": 0.0,
                           "seconds": 0.0, "depth": 0, "span_id": 1,
                           "parent_id": -1, "thread": "t", "status": "ok",
                           "error": None, "meta": {"from": "tpu"}}]}
    assert any("degradation" in e for e in
               schema.validate_degradations(bad_span))
    bad_labels = {"metrics": {"counters": {"merge_degradations_total": {
        "series": [{"labels": {"oops": "x"}, "value": 1}]}}}}
    assert any("merge_degradations_total" in e for e in
               schema.validate_degradations(bad_labels))


# ---------------------------------------------------------------------------
# verify.typecheck_ts: toolchain-vs-type-failure distinction + deadline
# ---------------------------------------------------------------------------

def _fake_npx(tmp_path, monkeypatch, body: str):
    """Install a fake ``npx`` at the front of PATH."""
    bindir = tmp_path / "bin"
    bindir.mkdir(exist_ok=True)
    npx = bindir / "npx"
    npx.write_text("#!/bin/sh\n" + body)
    npx.chmod(0o755)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    return tmp_path


def test_typecheck_npx_without_tsc_passes_vacuously(tmp_path, monkeypatch):
    """npx present but tsc uninstalled: npx prints its own error and
    exits nonzero — the documented vacuous pass, NOT exit-2."""
    from semantic_merge_tpu.runtime.verify import typecheck_ts
    _fake_npx(tmp_path, monkeypatch,
              'echo "npm error could not determine executable to run"\n'
              "exit 1\n")
    ok, diags = typecheck_ts(tmp_path)
    assert ok is True and diags == []


def test_typecheck_real_type_error_still_fails(tmp_path, monkeypatch):
    from semantic_merge_tpu.runtime.verify import typecheck_ts
    _fake_npx(tmp_path, monkeypatch,
              "echo \"a.ts(1,1): error TS2304: Cannot find name 'x'.\"\n"
              "exit 2\n")
    ok, diags = typecheck_ts(tmp_path)
    assert ok is False
    assert any("error TS2304" in line for line in diags)


def test_typecheck_clean_pass(tmp_path, monkeypatch):
    from semantic_merge_tpu.runtime.verify import typecheck_ts
    _fake_npx(tmp_path, monkeypatch, "exit 0\n")
    assert typecheck_ts(tmp_path) == (True, [])


def test_typecheck_deadline_raises_deadline_fault(tmp_path, monkeypatch):
    from semantic_merge_tpu.errors import DeadlineFault
    from semantic_merge_tpu.runtime.verify import typecheck_ts
    _fake_npx(tmp_path, monkeypatch, "sleep 30\n")
    with pytest.raises(DeadlineFault) as exc_info:
        typecheck_ts(tmp_path, deadline=0.5)
    assert exc_info.value.stage == "verify"
    assert exc_info.value.exit_code == 15


# ---------------------------------------------------------------------------
# Crash-safe --inplace commit
# ---------------------------------------------------------------------------

def test_sigkill_during_commit_resumes_consistently(repo):
    """A real SIGKILL between the journal write and the renames: the
    work tree is recoverable, and ``semmerge --resume`` rolls the
    commit forward to the exact merge result."""
    expected = expected_textual_tree(repo)  # == semantic result by design
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    env["SEMMERGE_FAULT"] = "commit:kill"
    proc = subprocess.run(
        [sys.executable, "-m", "semantic_merge_tpu", "semmerge",
         "basebr", "brA", "brB", "--inplace", "--backend", "host"],
        cwd=repo, env=env, capture_output=True)
    assert proc.returncode == -signal.SIGKILL
    assert (repo / inplace.JOURNAL).exists(), \
        "the intent journal must survive the kill"
    rc = main(["semmerge", "--resume"])
    assert rc == 0
    assert tree_state(repo) == expected
    assert not (repo / inplace.JOURNAL).exists()
    assert not (repo / inplace.STAGE_DIR).exists()


def test_partial_commit_rolls_forward(tmp_path):
    """A commit interrupted halfway through its renames (journal
    present, some staged files already moved) completes idempotently."""
    root = tmp_path / "wt"
    stage = root / inplace.STAGE_DIR
    (stage / "dir").mkdir(parents=True)
    (stage / "dir/b.txt").write_text("new-b")
    (root / "a.txt").write_text("new-a")  # 'a' already committed
    (root / "gone.txt").write_text("stale")
    journal = {"schema": 1, "state": "committing",
               "writes": ["a.txt", "dir/b.txt"], "deletes": ["gone.txt"]}
    (root / inplace.JOURNAL).write_text(json.dumps(journal))
    action, n = inplace.recover(root)
    assert action == "rolled-forward" and n == 2
    assert (root / "a.txt").read_text() == "new-a"
    assert (root / "dir/b.txt").read_text() == "new-b"
    assert not (root / "gone.txt").exists()
    assert not (root / inplace.JOURNAL).exists()
    assert not stage.exists()


def test_pre_journal_stage_rolls_back(tmp_path):
    root = tmp_path / "wt"
    stage = root / inplace.STAGE_DIR
    stage.mkdir(parents=True)
    (stage / "x.txt").write_text("staged-but-never-journaled")
    (root / "keep.txt").write_text("old")
    action, _ = inplace.recover(root)
    assert action == "rolled-back"
    assert (root / "keep.txt").read_text() == "old"
    assert not stage.exists()


def test_tampered_journal_cannot_escape_work_tree(tmp_path):
    root = tmp_path / "wt"
    root.mkdir()
    outside = tmp_path / "victim.txt"
    outside.write_text("precious")
    (root / inplace.JOURNAL).write_text(json.dumps(
        {"schema": 1, "state": "committing", "writes": [],
         "deletes": ["../victim.txt"]}))
    with pytest.raises(ApplyFault):
        inplace.recover(root)
    assert outside.read_text() == "precious"
    assert (root / inplace.JOURNAL).exists(), "refused journal is kept"


def test_next_inplace_merge_auto_recovers(repo):
    """An interrupted commit's journal is resolved automatically at the
    start of the next --inplace merge — no manual --resume needed."""
    stage = repo / inplace.STAGE_DIR
    stage.mkdir()
    (stage / "leftover.txt").write_text("from an interrupted run")
    (repo / inplace.JOURNAL).write_text(json.dumps(
        {"schema": 1, "state": "committing",
         "writes": ["leftover.txt"], "deletes": []}))
    rc = run_merge_cli()
    assert rc == 0
    assert (repo / "leftover.txt").read_text() == "from an interrupted run"
    assert not (repo / inplace.JOURNAL).exists()
    assert "bar" in (repo / "src/util.ts").read_text()
