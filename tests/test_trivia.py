"""Comment/whitespace (trivia) behavior through structured apply.

The reference leaves CST trivia reattachment as a 3-line stub
(reference ``workers/ts/src/emit.ts:1-3``; design at
``implementation.md:1173-1185``). This framework's answer is the
*full-start span* contract: a decl's span starts at the end of the
previous token (the TS parser's ``node.pos``), so the comments and
whitespace leading a declaration travel WITH it — deletes remove their
decl's leading comment, adds carry theirs, and untouched regions stay
byte-identical. These tests pin that contract end to end.
"""
from semantic_merge_tpu.backends.base import get_backend, run_merge
from semantic_merge_tpu.frontend.snapshot import Snapshot
from semantic_merge_tpu.runtime.applier import apply_ops

BASE = (
    "// greets the caller\n"
    "export function greet(name: string): string {\n"
    "  return name;\n"
    "}\n"
    "// counts things (keep me!)\n"
    "export function count(xs: number[]): number {\n"
    "  return xs.length;\n"
    "}\n"
)


def snap(content, path="a.ts"):
    return Snapshot(files=[{"path": path, "content": content}])


def merge_to_tree(tmp_path, base_c, left_c, right_c):
    host = get_backend("host")
    _, composed, conflicts = run_merge(
        host, snap(base_c), snap(left_c), snap(right_c),
        base_rev="r", seed="s", structured_apply=True)
    assert conflicts == []
    base_tree = tmp_path / "base"
    base_tree.mkdir()
    (base_tree / "a.ts").write_text(base_c)
    return apply_ops(base_tree, composed)


def test_deleted_decl_takes_its_leading_comment(tmp_path):
    left = BASE.replace(
        "// greets the caller\n"
        "export function greet(name: string): string {\n"
        "  return name;\n"
        "}\n", "")
    out = merge_to_tree(tmp_path, BASE, left, BASE)
    text = (out / "a.ts").read_text()
    assert "greet" not in text
    assert "// greets the caller" not in text, \
        "the deleted decl's leading comment must go with it (full start)"
    assert "// counts things (keep me!)" in text
    assert "count" in text


def test_added_decl_carries_its_leading_comment(tmp_path):
    right = BASE + (
        "// freshly added helper\n"
        "export function added(flag: boolean): boolean {\n"
        "  return !flag;\n"
        "}\n")
    out = merge_to_tree(tmp_path, BASE, BASE, right)
    text = (out / "a.ts").read_text()
    assert "// freshly added helper" in text, \
        "an added decl's span starts at full start: its comment travels too"
    assert text.index("// freshly added helper") < text.index("function added")


def test_untouched_regions_stay_byte_identical(tmp_path):
    # A pure rename must leave every comment and blank line untouched;
    # the rename rewrites word-boundary identifier occurrences only
    # ("greets" in the comment is not the identifier "greet").
    import re
    left = re.sub(r"\bgreet\b", "salute", BASE)
    out = merge_to_tree(tmp_path, BASE, left, BASE)
    text = (out / "a.ts").read_text()
    assert text == left
    assert "// greets the caller" in text
    assert "// counts things (keep me!)" in text


def test_changesignature_replacement_carries_comment(tmp_path):
    # changeSignature splices the side's full-start span over the
    # base's: the replacement text includes the side's comment.
    base = BASE
    left = BASE.replace(
        "// greets the caller\n"
        "export function greet(name: string): string {",
        "// now louder\n"
        "export function greet(name: number): string {")
    host = get_backend("host")
    _, composed, conflicts = run_merge(
        host, snap(base), snap(left), snap(base),
        base_rev="r", seed="s", change_signature=True, structured_apply=True)
    assert conflicts == []
    assert any(op.type == "changeSignature" for op in composed)
    base_tree = tmp_path / "b"
    base_tree.mkdir()
    (base_tree / "a.ts").write_text(base)
    out = apply_ops(base_tree, composed)
    text = (out / "a.ts").read_text()
    assert "// now louder" in text
    assert "// greets the caller" not in text
    assert "name: number" in text
