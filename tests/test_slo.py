"""SLO engine + on-demand daemon profiling (ISSUE 11 tentpole).

The contracts under test:

- **Grammar** — ``merge:p99<800ms,err<1%`` parses into labelled
  clauses; verb aliases map to wire verbs; ``*`` expands per verb;
  malformed specs raise :class:`SloParseError` (loudly at startup).
- **Windows** — slot-ring accounting under a fake clock: observations
  age out of the fast window before the slow one; burn rates follow.
- **Trip edges** — only ``evaluate(consume_edges=True)`` (the daemon's
  monitor thread) latches an edge; status polls never swallow one.
- **Daemon integration** — a daemon started with a tight objective and
  tiny windows goes unhealthy after one slow merge: ``status`` carries
  the slo block, ``/healthz`` flips to 503 degraded, and the flight
  recorder dumps an ``slo-burn`` postmortem bundle.
- **Profiling** — the ``profile`` wire verb captures a non-empty
  bundle, twice in a row (the profiler session must not poison the
  process-global state), and concurrent captures are rejected busy.
"""
import json
import os
import pathlib
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from semantic_merge_tpu.obs import metrics as obs_metrics
from semantic_merge_tpu.obs import slo as obs_slo
from semantic_merge_tpu.service import client as svc_client

from test_service_tracing import build_repo, client_env, run_client

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# Grammar


def test_parse_objectives_latency_and_error_clauses():
    clauses = obs_slo.parse_objectives("merge:p99<800ms,err<1%")
    assert [c.kind for c in clauses] == ["latency", "error"]
    lat, err = clauses
    assert lat.target == "semmerge"  # alias resolved to the wire verb
    assert lat.quantile == pytest.approx(0.99)
    assert lat.threshold_s == pytest.approx(0.8)
    assert lat.budget == pytest.approx(0.01)
    assert lat.text == "merge:p99<800ms"
    assert err.budget == pytest.approx(0.01)
    assert err.text == "merge:err<1%"


def test_parse_objectives_star_expands_per_verb_and_units():
    clauses = obs_slo.parse_objectives("*:p50<2s")
    assert sorted(c.target for c in clauses) == \
        sorted(obs_slo._KNOWN_VERBS)
    assert all(c.threshold_s == pytest.approx(2.0) for c in clauses)
    # The per-verb expansion labels each clause with its own verb.
    assert sorted(c.text for c in clauses) == \
        sorted(f"{v}:p50<2s" for v in obs_slo._KNOWN_VERBS)


def test_parse_objectives_multiple_targets():
    clauses = obs_slo.parse_objectives("merge:p99<1s;diff:err<5%")
    assert [(c.target, c.kind) for c in clauses] == \
        [("semmerge", "latency"), ("semdiff", "error")]


@pytest.mark.parametrize("spec", [
    "merge:p99>800ms",      # wrong comparator
    "merge:p99<800",        # no unit
    "merge:q50<1ms",        # unknown clause head
    "merge:err<1",          # error bound without %
    "merge:err<200%",       # budget out of range
    "merge:p0<1ms",         # quantile out of (0, 100)
    "merge:p100<1ms",
    "merge:",               # no clauses
    "p99<800ms",            # no target separator... parsed as target
    "",                     # empty spec
])
def test_parse_objectives_rejects_malformed(spec):
    with pytest.raises(obs_slo.SloParseError):
        obs_slo.parse_objectives(spec)


# ---------------------------------------------------------------------------
# Windows + burn under a fake clock


def _engine(spec, **kwargs):
    t = [1000.0]
    kwargs.setdefault("fast_window", 10.0)
    kwargs.setdefault("slow_window", 60.0)
    kwargs.setdefault("slot_seconds", 1.0)
    eng = obs_slo.SloEngine(obs_slo.parse_objectives(spec),
                            clock=lambda: t[0], **kwargs)
    return eng, t


def _burns(verdict, text):
    row = next(r for r in verdict["objectives"] if r["objective"] == text)
    return row["burn_fast"], row["burn_slow"]


def test_latency_burn_and_fast_window_aging():
    eng, t = _engine("merge:p99<100ms")
    for _ in range(10):
        eng.observe("semmerge", 0.5)  # all 10 violate the 100ms bound
    fast, slow = _burns(eng.evaluate(), "merge:p99<100ms")
    # violation fraction 1.0 over budget 0.01 -> burn 100 in both windows
    assert fast == pytest.approx(100.0, rel=0.05)
    assert slow == pytest.approx(100.0, rel=0.05)
    # Age past the fast window but stay inside the slow one.
    t[0] += 30.0
    for _ in range(90):
        eng.observe("semmerge", 0.001)  # healthy traffic now
    fast, slow = _burns(eng.evaluate(), "merge:p99<100ms")
    assert fast == pytest.approx(0.0, abs=1.0)
    # Slow window still remembers the 10 bad samples out of 100.
    assert slow > 1.0


def test_error_burn_counts_failures():
    eng, t = _engine("merge:err<10%")
    for i in range(10):
        eng.observe("semmerge", 0.01, error=(i < 5))
    fast, slow = _burns(eng.evaluate(), "merge:err<10%")
    assert fast == pytest.approx(5.0)  # 50% errors / 10% budget
    assert slow == pytest.approx(5.0)


def test_no_samples_means_zero_burn_and_healthy():
    eng, _ = _engine("merge:p99<1ms")
    verdict = eng.evaluate()
    assert verdict["healthy"] is True
    assert _burns(verdict, "merge:p99<1ms") == (0.0, 0.0)


def test_eviction_drops_slots_past_slow_window():
    eng, t = _engine("merge:err<1%")
    eng.observe("semmerge", 0.01, error=True)
    t[0] += 120.0  # well past the 60s slow window
    eng.observe("semmerge", 0.01)  # triggers eviction
    verdict = eng.evaluate()
    assert verdict["healthy"] is True
    fast, slow = _burns(verdict, "merge:err<1%")
    assert fast == 0.0 and slow == 0.0


def test_trip_edges_latch_only_when_consumed():
    eng, t = _engine("merge:p99<1ms")
    for _ in range(5):
        eng.observe("semmerge", 1.0)
    # A status-style poll sees the trip but must not consume the edge.
    polled = eng.evaluate()
    assert polled["healthy"] is False
    assert polled["newly_tripped"] == []
    # The monitor's consuming evaluate gets the edge exactly once.
    first = eng.evaluate(consume_edges=True)
    assert [r["objective"] for r in first["newly_tripped"]] == \
        ["merge:p99<1ms"]
    second = eng.evaluate(consume_edges=True)
    assert second["newly_tripped"] == []
    # Trip counter incremented once, with the objective label.
    counter = obs_metrics.REGISTRY.counter(obs_slo.TRIP_COUNTER)
    assert counter.value(objective="merge:p99<1ms") >= 1


def test_burn_gauges_published_with_documented_labels():
    eng, _ = _engine("merge:p99<1ms")
    eng.observe("semmerge", 1.0)
    eng.evaluate()
    dump = obs_metrics.REGISTRY.to_dict()
    series = dump["gauges"][obs_slo.BURN_GAUGE]["series"]
    windows = {s["labels"]["window"] for s in series
               if s["labels"].get("objective") == "merge:p99<1ms"}
    assert {"fast", "slow"} <= windows
    for s in series:
        assert sorted(s["labels"].keys()) == ["objective", "window"]
        assert s["value"] >= 0


def test_status_carries_window_quantiles():
    eng, _ = _engine("merge:p99<10s")
    for v in (0.01, 0.02, 0.03, 0.5):
        eng.observe("semmerge", v)
    eng.observe("semmerge", 0.5, error=True)
    status = eng.status()
    assert "newly_tripped" not in status
    wq = status["window_quantiles"]["semmerge"]
    assert wq["count"] == 5 and wq["errors"] == 1
    assert 0 < wq["p50_ms"] <= wq["p99_ms"]


def test_from_env_precedence_and_absence(monkeypatch):
    monkeypatch.delenv(obs_slo.ENV_OBJECTIVES, raising=False)
    assert obs_slo.from_env() is None
    eng = obs_slo.from_env("merge:p99<1s", config_fast_window=7.0)
    assert eng is not None and eng.fast_window == pytest.approx(7.0)
    monkeypatch.setenv(obs_slo.ENV_OBJECTIVES, "diff:err<2%")
    monkeypatch.setenv(obs_slo.ENV_FAST_WINDOW, "11")
    eng = obs_slo.from_env("merge:p99<1s")  # env spec wins over config
    assert [c.target for c in eng.clauses] == ["semdiff"]
    assert eng.fast_window == pytest.approx(11.0)
    monkeypatch.setenv(obs_slo.ENV_OBJECTIVES, "merge:bogus<1")
    with pytest.raises(obs_slo.SloParseError):
        obs_slo.from_env()


# ---------------------------------------------------------------------------
# Daemon integration: burn -> status/healthz/postmortem
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_daemon_burn_degrades_healthz_and_dumps_postmortem(
        tmp_path, daemon_factory):
    """One deliberately-slow merge against a 1ms p99 objective with
    second-scale windows: the monitor thread trips the objective, the
    status verb and /healthz report degraded, and an ``slo-burn``
    bundle lands in SEMMERGE_POSTMORTEM_DIR."""
    pm_dir = tmp_path / "postmortem"
    sock = str(tmp_path / "daemon.sock")
    daemon_factory(sock, extra_env={
        "SEMMERGE_SLO": "merge:p99<1ms",
        "SEMMERGE_SLO_FAST_WINDOW": "20",
        "SEMMERGE_SLO_SLOW_WINDOW": "40",
        "SEMMERGE_SLO_SLOT": "1",
        "SEMMERGE_SLO_EVAL_INTERVAL": "0.2",
        "SEMMERGE_METRICS_PORT": "0",
        "SEMMERGE_POSTMORTEM_DIR": str(pm_dir),
    })
    repo = build_repo(tmp_path / "repo")
    proc = run_client(repo, client_env(sock))
    assert proc.returncode == 0, proc.stderr

    deadline = time.monotonic() + 30
    status = None
    while time.monotonic() < deadline:
        status = svc_client.call_control("status", path=sock)
        slo = status.get("slo")
        if slo and not slo.get("healthy", True):
            break
        time.sleep(0.2)
    assert status is not None
    slo = status.get("slo")
    assert slo and slo["healthy"] is False, f"slo never went unhealthy: {slo}"
    row = next(r for r in slo["objectives"]
               if r["objective"] == "merge:p99<1ms")
    assert row["tripped"] is True
    assert row["burn_fast"] >= 1.0 and row["burn_slow"] >= 1.0
    assert slo["window_quantiles"]["semmerge"]["count"] >= 1

    # /healthz flips to 503 with the degraded flag set.
    port = status.get("metrics_port")
    assert port, "daemon must report its bound telemetry port"
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10)
    assert exc_info.value.code == 503
    body = json.loads(exc_info.value.read())
    assert body["degraded"] is True
    assert body["slo"]["healthy"] is False

    # The monitor's consuming evaluate dumped exactly one slo-burn
    # bundle for the excursion (edge-latched, not one per tick).
    deadline = time.monotonic() + 15
    bundles = []
    while time.monotonic() < deadline:
        bundles = sorted(pm_dir.glob("*.json")) if pm_dir.is_dir() else []
        if bundles:
            break
        time.sleep(0.2)
    assert bundles, "slo-burn trip must dump a postmortem bundle"
    data = json.loads(bundles[0].read_text())
    assert data["reason"] == "slo-burn"
    assert data["slo"]["healthy"] is False
    # The bundle passes the schema validator, including the new reason.
    script = REPO_ROOT / "scripts" / "check_trace_schema.py"
    ok = subprocess.run(
        [sys.executable, str(script), "validate_postmortem",
         str(bundles[0])], capture_output=True, text=True, timeout=60)
    assert ok.returncode == 0, ok.stderr
    # And the status payload satisfies the slo-block validator.
    status_path = tmp_path / "status.json"
    status_path.write_text(json.dumps(status))
    ok = subprocess.run(
        [sys.executable, str(script), "validate_slo", str(status_path)],
        capture_output=True, text=True, timeout=60)
    assert ok.returncode == 0, ok.stderr


# ---------------------------------------------------------------------------
# On-demand profiling
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_profile_verb_captures_nonempty_bundle_twice(tmp_path,
                                                     service_daemon):
    """Two back-to-back captures: each bundle directory is non-empty
    and self-describing; the second must not fail because the first
    left the process-global profiler session poisoned."""
    for i in range(2):
        out = svc_client.capture_profile(
            0.3, out_dir=tmp_path / f"cap{i}", path=service_daemon)
        assert out.get("ok") is True, out
        bundle_dir = pathlib.Path(out["dir"])
        assert bundle_dir.is_dir()
        assert out["files"], f"capture {i} produced an empty bundle"
        manifest = json.loads((bundle_dir / "bundle.json").read_text())
        assert manifest["schema"] == 1 and manifest["ok"] is True
        assert manifest["seconds"] == pytest.approx(0.3)
        assert "metrics_before" in manifest and "metrics_after" in manifest


@pytest.mark.slow
def test_profile_cli_command(tmp_path, service_daemon):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    env["SEMMERGE_SERVICE_SOCKET"] = service_daemon
    proc = subprocess.run(
        [sys.executable, "-m", "semantic_merge_tpu", "profile", "--daemon",
         "--seconds", "0.3", "--out", str(tmp_path / "cli-cap"), "--json"],
        capture_output=True, text=True, env=env, timeout=120)
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    assert out["ok"] is True and out["files"]


def test_profile_cli_without_daemon_fails_cleanly(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    env["SEMMERGE_SERVICE_SOCKET"] = str(tmp_path / "absent.sock")
    proc = subprocess.run(
        [sys.executable, "-m", "semantic_merge_tpu", "profile", "--daemon",
         "--seconds", "0.2"],
        capture_output=True, text=True, env=env, timeout=120)
    assert proc.returncode == 1
    assert "daemon" in proc.stderr.lower()


# ---------------------------------------------------------------------------
# Profiler-session recovery (satellite: runtime/trace.py fix)
# ---------------------------------------------------------------------------


def test_start_profiler_session_recovers_from_poisoned_state(tmp_path,
                                                             monkeypatch):
    """A crashed --profile run leaves jax's module-global profiler state
    wedged; the next start must stop the stale session and retry instead
    of failing every capture until daemon restart."""
    from semantic_merge_tpu.runtime import trace as rt_trace

    calls = {"start": 0, "stop": 0}

    class FakeProfiler:
        @staticmethod
        def start_trace(path):
            calls["start"] += 1
            if calls["start"] == 1:
                raise RuntimeError("profiler session already active")

        @staticmethod
        def stop_trace():
            calls["stop"] += 1

    import jax
    monkeypatch.setattr(jax, "profiler", FakeProfiler)
    assert rt_trace.start_profiler_session(str(tmp_path)) is True
    assert calls == {"start": 2, "stop": 1}
    failures = obs_metrics.REGISTRY.counter(rt_trace.PROFILER_FAILURES)
    assert failures.value(reason="start") >= 1
