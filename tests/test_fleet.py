"""Fault-tolerant daemon fleet (ISSUE 14): rendezvous-hash routing,
the durable dispatch WAL, the client's fleet posture and spawn-race
reconnect, and a live router fronting supervised members.

The bar:

- Rendezvous hashing is deterministic, roughly balanced, and — the
  property the fleet exists for — removing a member moves *only* that
  member's keys.
- The WAL journals a request durably before dispatch, carries unacked
  entries across restarts (replay set), archives history for the
  chaos audit, and tolerates a torn tail from a SIGKILL mid-append.
- ``SEMMERGE_FLEET=require`` with no router is the documented exit 19;
  ``auto`` falls back through the daemon posture. A plain daemon on
  the socket never satisfies a fleet connect (``fleet: true`` is
  required in the hello).
- A client that loses the daemon spawn race keeps reconnecting for a
  bounded window instead of treating the winner's slow handshake as a
  hard transport failure.
- A live router announces itself, pins a repo to its rendezvous owner,
  drains members on request, and hedges a slow member's read to a
  second member (first response wins).
"""
import json
import os
import pathlib
import socket
import subprocess
import sys
import threading
import time

import pytest

from semantic_merge_tpu.fleet import FLEET_EXIT, hashring, mode
from semantic_merge_tpu.fleet import wal as fleet_wal
from semantic_merge_tpu.service import protocol

from test_resilience import build_repo, raw_close, raw_conn, send_merge

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# Rendezvous hashing
# ---------------------------------------------------------------------------

MEMBERS = ["m0", "m1", "m2"]
KEYS = [f"/repos/project-{i}" for i in range(300)]


def test_rendezvous_owner_deterministic_and_balanced():
    owners = {k: hashring.owner(k, MEMBERS) for k in KEYS}
    assert owners == {k: hashring.owner(k, MEMBERS) for k in KEYS}
    assert owners == {k: hashring.owner(k, list(reversed(MEMBERS)))
                      for k in KEYS}, "owner must not depend on order"
    counts = {m: 0 for m in MEMBERS}
    for m in owners.values():
        counts[m] += 1
    # Rough balance: no member below a third of its fair share.
    assert all(c >= len(KEYS) / len(MEMBERS) / 3 for c in counts.values()), \
        counts


def test_rendezvous_removal_moves_only_failed_members_keys():
    owners = {k: hashring.owner(k, MEMBERS) for k in KEYS}
    survivors = ["m0", "m2"]
    moved = hashring.moved_keys(KEYS, MEMBERS, survivors)
    assert set(moved) == {k for k, o in owners.items() if o == "m1"}
    # Survivors keep every key they already owned.
    for k, o in owners.items():
        if o != "m1":
            assert hashring.owner(k, survivors) == o
    # And adding the member back restores the original assignment.
    assert {k: hashring.owner(k, MEMBERS) for k in KEYS} == owners


def test_rendezvous_rank_is_total_failover_order():
    for k in KEYS[:20]:
        rank = hashring.rank(k, MEMBERS)
        assert sorted(rank) == sorted(MEMBERS)
        assert rank[0] == hashring.owner(k, MEMBERS)
        # Rank with the owner removed == the tail of the full rank:
        # failover lands exactly where the rehash says it should.
        assert hashring.rank(k, [m for m in MEMBERS if m != rank[0]]) \
            == rank[1:]
    with pytest.raises(ValueError):
        hashring.owner("/k", [])


def test_repo_key_is_realpath(tmp_path):
    repo = tmp_path / "repo"
    repo.mkdir()
    link = tmp_path / "link"
    link.symlink_to(repo)
    assert hashring.repo_key(str(link)) == hashring.repo_key(str(repo))


# ---------------------------------------------------------------------------
# Write-ahead log
# ---------------------------------------------------------------------------

def test_wal_journal_ack_and_replay_cycle(tmp_path):
    d = str(tmp_path / "wal")
    w = fleet_wal.WriteAheadLog(d)
    assert w.open() == []
    w.record_request("k1", "semmerge", {"argv": ["a"]}, "t1")
    w.record_request("k2", "semmerge", {"argv": ["b"]}, "t2")
    w.record_dispatch("k1", "m0")
    w.ack("k1")
    assert w.open_count() == 1
    # Re-journaling an open key is a no-op (replay keeps the original).
    w.record_request("k2", "semmerge", {"argv": ["DIFFERENT"]}, "t2")
    w.close()
    # The next incarnation replays exactly the unacked entries.
    w2 = fleet_wal.WriteAheadLog(d)
    pending = w2.open()
    assert [(r["key"], r["params"]) for r in pending] \
        == [("k2", {"argv": ["b"]})]
    w2.ack("k2")
    w2.close()
    w3 = fleet_wal.WriteAheadLog(d)
    assert w3.open() == []
    w3.close()


def test_wal_tolerates_torn_tail_and_archives_history(tmp_path):
    d = str(tmp_path / "wal")
    w = fleet_wal.WriteAheadLog(d)
    w.open()
    w.record_request("k1", "semmerge", {"argv": []}, None)
    w.close()
    # SIGKILL mid-append: a torn half-record at the tail.
    with open(os.path.join(d, fleet_wal.WAL_FILE), "a",
              encoding="utf-8") as fh:
        fh.write('{"kind": "ack", "key"')
    w2 = fleet_wal.WriteAheadLog(d)
    assert [r["key"] for r in w2.open()] == ["k1"], \
        "torn ack must not settle the entry"
    w2.ack("k1")
    w2.close()
    # The full history (including archived segments) remains readable
    # for the chaos audit: the request and its eventual ack are there.
    records = fleet_wal.read_records(d)
    kinds = {r["kind"] for r in records}
    assert kinds <= set(fleet_wal.RECORD_KINDS)
    assert any(r["kind"] == "request" and r["key"] == "k1"
               for r in records)
    assert any(r["kind"] == "ack" and r["key"] == "k1" for r in records)
    assert any(name.startswith("wal.") and name != fleet_wal.WAL_FILE
               for name in os.listdir(d)), "expected archived segments"


def test_wal_request_is_durable_before_dispatch(tmp_path):
    """The fsync contract: after record_request returns, a fresh reader
    of the *file* (not the in-memory state) sees the entry."""
    d = str(tmp_path / "wal")
    w = fleet_wal.WriteAheadLog(d)
    w.open()
    w.record_request("k-durable", "semmerge", {"argv": ["x"]}, "t")
    path = os.path.join(d, fleet_wal.WAL_FILE)
    rows = [json.loads(line) for line in
            open(path, encoding="utf-8").read().splitlines() if line]
    assert any(r["kind"] == "request" and r["key"] == "k-durable"
               for r in rows)
    w.close()


# ---------------------------------------------------------------------------
# Posture + client behavior
# ---------------------------------------------------------------------------

def test_fleet_posture_parsing(monkeypatch):
    monkeypatch.delenv("SEMMERGE_FLEET", raising=False)
    assert mode() == "off"
    for raw, want in [("auto", "auto"), ("require", "require"),
                      ("off", "off"), ("1", "auto"), ("on", "auto"),
                      ("0", "off"), ("bogus", "off"),
                      ("REQUIRE", "require")]:
        monkeypatch.setenv("SEMMERGE_FLEET", raw)
        assert mode() == want, raw
    assert FLEET_EXIT == 19


def test_fleet_require_without_router_exits_19(tmp_path):
    env = dict(os.environ)
    env.update({"PYTHONPATH": str(REPO_ROOT),
                "SEMMERGE_FLEET": "require",
                "SEMMERGE_SERVICE_SOCKET": str(tmp_path / "none.sock")})
    proc = subprocess.run(
        [sys.executable, "-m", "semantic_merge_tpu", "semmerge",
         "a", "b", "c"],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
        timeout=120)
    assert proc.returncode == 19, proc.stderr
    assert "fleet required" in proc.stderr


def test_plain_daemon_does_not_satisfy_fleet_connect(service_daemon,
                                                     monkeypatch):
    """A fleet-postured connect demands ``fleet: true`` in the hello —
    a plain daemon on the socket is unusable for the fleet branch (it
    still serves the daemon posture)."""
    from semantic_merge_tpu.service import client as service_client
    monkeypatch.setenv("SEMMERGE_SERVICE_SOCKET", service_daemon)
    assert service_client._try_connect(service_daemon) is not None \
        and service_client._try_connect(
            service_daemon, require_fleet=True) is None


def test_client_reconnects_when_spawn_loses_bind_race(tmp_path,
                                                      monkeypatch):
    """The spawn-race fix: the spawned process exits (lost the bind
    race) while the race winner is connectable but slow to answer —
    the client must keep reconnecting for the bounded window instead
    of failing hard on the first dead probe."""
    from semantic_merge_tpu.service import client as service_client
    sock_path = str(tmp_path / "race.sock")
    monkeypatch.setenv("SEMMERGE_SERVICE_SOCKET", sock_path)
    monkeypatch.setenv("SEMMERGE_SERVICE_RECONNECT", "5.0")

    # The "race loser": a process that exits immediately.
    loser = subprocess.Popen([sys.executable, "-c", "pass"])
    loser.wait(timeout=30)
    monkeypatch.setattr(service_client, "_spawn_daemon",
                        lambda path: loser)

    # The "race winner": binds late and then answers the handshake —
    # the single-probe behavior this test pins against would give up
    # before it comes up.
    def winner():
        time.sleep(1.0)
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(sock_path)
        srv.listen(4)
        srv.settimeout(10.0)
        try:
            conn, _ = srv.accept()
            rfile = conn.makefile("r", encoding="utf-8")
            wfile = conn.makefile("w", encoding="utf-8")
            req = protocol.read_message(rfile)
            protocol.write_message(wfile, {
                "id": req["id"],
                "result": {"ok": True, "pid": os.getpid(),
                           "version": protocol.PROTOCOL_VERSION}})
            time.sleep(0.5)  # hold until the client returns
            conn.close()
        finally:
            srv.close()

    t = threading.Thread(target=winner, daemon=True)
    t.start()
    conn = service_client._connect_or_spawn()
    service_client._close(*conn)
    t.join(timeout=15)


# ---------------------------------------------------------------------------
# Live router
# ---------------------------------------------------------------------------

def _spawn_router(sock_path, *, members=2, extra_env=None,
                  timeout=120.0):
    env = dict(os.environ)
    env.update({"PYTHONPATH": str(REPO_ROOT), "JAX_PLATFORMS": "cpu",
                "SEMMERGE_DAEMON": "off",
                "SEMMERGE_FLEET_HEALTH_INTERVAL": "0.2",
                "SEMMERGE_SUPERVISE_BACKOFF": "0.1",
                "SEMMERGE_SERVICE_DRAIN_TIMEOUT": "2"})
    for key in ("SEMMERGE_FAULT", "SEMMERGE_METRICS",
                "SEMMERGE_SERVICE_SOCKET"):
        env.pop(key, None)
    env.update(extra_env or {})
    log = open(sock_path + ".log", "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "semantic_merge_tpu", "fleet",
         "--socket", sock_path, "--members", str(members)],
        stdin=subprocess.DEVNULL, stdout=log, stderr=log,
        cwd="/", env=env, start_new_session=True)
    log.close()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"router exited rc={proc.returncode} "
                               f"(log: {sock_path}.log)")
        status = _control(sock_path, "status")
        if status and status.get("fleet") \
                and status.get("members_up", 0) >= members:
            return proc
        time.sleep(0.2)
    proc.kill()
    raise RuntimeError(f"fleet not up within {timeout:g}s "
                       f"(log: {sock_path}.log)")


def _control(sock_path, method, params=None):
    try:
        conn = raw_conn(sock_path, timeout=30.0)
    except OSError:
        return None
    try:
        protocol.write_message(conn[2], {"id": 1, "method": method,
                                         "params": params or {}})
        resp = protocol.read_message(conn[1])
        return (resp or {}).get("result")
    except (OSError, protocol.ProtocolError):
        return None
    finally:
        raw_close(conn)


def _stop_router(proc):
    import signal as signal_mod
    if proc.poll() is None:
        proc.send_signal(signal_mod.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


def _counter_total(status, name, **labels):
    metric = (status.get("metrics") or {}).get("counters", {}) \
        .get(name, {})
    total = 0.0
    for s in metric.get("series", []):
        got = s.get("labels") or {}
        if all(got.get(k) == v for k, v in labels.items()):
            total += s["value"]
    return total


def test_fleet_router_affinity_drain_and_posture(tmp_path):
    """One live 2-member router: the hello announces the fleet, a
    repo's requests pin to its rendezvous owner, a drained member
    leaves the ring while its peer keeps serving, and the real client
    in ``SEMMERGE_FLEET=require`` routes through the router."""
    repo = build_repo(tmp_path / "repo")
    sock = str(tmp_path / "fleet.sock")
    router = _spawn_router(sock, members=2,
                           extra_env={"SEMMERGE_FLEET_HEDGE": "off"})
    try:
        # Hello announce.
        conn = raw_conn(sock)
        try:
            protocol.write_message(conn[2], {"id": 0, "method": "hello",
                                             "params": {}})
            hello = protocol.read_message(conn[1])["result"]
        finally:
            raw_close(conn)
        assert hello["ok"] and hello["fleet"] is True
        assert hello["members_up"] == 2

        # Affinity: every request for one repo lands on its
        # rendezvous owner (hedging disabled for this router).
        owner = hashring.owner(hashring.repo_key(str(repo)),
                               ["m0", "m1"])
        for i in range(3):
            conn = raw_conn(sock, timeout=300.0)
            try:
                send_merge(conn, str(repo), req_id=i,
                           idem_key=f"aff-{i}")
                resp = protocol.read_message(conn[1])
            finally:
                raw_close(conn)
            assert resp.get("result", {}).get("exit_code") == 0, resp
        status = _control(sock, "status")
        by_id = {m["id"]: m for m in status["members"]}
        assert by_id[owner]["dispatches"] == 3
        other = "m1" if owner == "m0" else "m0"
        assert by_id[other]["dispatches"] == 0

        # Drain the owner: it leaves the ring (failover counted with
        # reason=drain), acknowledges admission-closed, and the peer
        # takes over its keyspace.
        ack = _control(sock, "drain", {"member": owner})
        assert ack["ok"] and ack["member_ack"]["draining"] is True
        status = _control(sock, "status")
        assert status["members_up"] == 1
        assert _counter_total(status, "fleet_failovers_total",
                              reason="drain") >= 1
        conn = raw_conn(sock, timeout=300.0)
        try:
            send_merge(conn, str(repo), req_id=9, idem_key="aff-post")
            resp = protocol.read_message(conn[1])
        finally:
            raw_close(conn)
        assert resp.get("result", {}).get("exit_code") == 0, resp
        status = _control(sock, "status")
        assert {m["id"]: m for m in status["members"]}[other][
            "dispatches"] == 1

        # The real client, fleet-required, routes through the router.
        env = dict(os.environ)
        env.update({"PYTHONPATH": str(REPO_ROOT),
                    "SEMMERGE_FLEET": "require",
                    "SEMMERGE_SERVICE_SOCKET": sock})
        env.pop("SEMMERGE_FAULT", None)
        proc = subprocess.run(
            [sys.executable, "-m", "semantic_merge_tpu", "semmerge",
             "basebr", "brA", "brB", "--backend", "host"],
            capture_output=True, text=True, env=env, cwd=str(repo),
            timeout=300)
        assert proc.returncode == 0, proc.stderr
        # `semmerge fleet --status` sees the same router.
        proc = subprocess.run(
            [sys.executable, "-m", "semantic_merge_tpu", "fleet",
             "--socket", sock, "--status"],
            capture_output=True, text=True, env=env, timeout=300)
        assert proc.returncode == 0, proc.stderr
        assert json.loads(proc.stdout)["fleet"] is True
    finally:
        _stop_router(router)


def test_fleet_router_hedges_slow_member(tmp_path):
    """Hedged reads: wedge the owner member's single worker, then send
    a non-inplace merge — after the hedge delay the router launches a
    second leg on the other member, whose response wins."""
    repo = build_repo(tmp_path / "repo")
    sock = str(tmp_path / "fleet.sock")
    router = _spawn_router(
        sock, members=2,
        extra_env={"SEMMERGE_FLEET_HEDGE_MS": "50",
                   "SEMMERGE_SERVICE_WORKERS": "1"})
    wedge = None
    try:
        owner = hashring.owner(hashring.repo_key(str(repo)),
                               ["m0", "m1"])
        # Wedge the owner: --inplace traffic never hedges, so this
        # hang occupies exactly the owner's single worker.
        wedge = raw_conn(sock, timeout=300.0)
        send_merge(wedge, str(repo),
                   env={"SEMMERGE_FAULT": "service:execute:hang=20"},
                   argv=["basebr", "brA", "brB", "--inplace",
                         "--backend", "host"],
                   req_id=1, idem_key="wedge")
        time.sleep(1.0)
        # Non-inplace read for the same repo: the primary leg queues
        # behind the wedge; the hedge leg answers first.
        conn = raw_conn(sock, timeout=300.0)
        try:
            send_merge(conn, str(repo), req_id=2, idem_key="hedged")
            resp = protocol.read_message(conn[1])
        finally:
            raw_close(conn)
        assert resp.get("result", {}).get("exit_code") == 0, resp
        status = _control(sock, "status")
        assert _counter_total(status, "fleet_hedges_total") >= 1
        assert _counter_total(status, "fleet_hedge_wins_total") >= 1
        other = "m1" if owner == "m0" else "m0"
        by_id = {m["id"]: m for m in status["members"]}
        assert by_id[other]["dispatches"] >= 1
    finally:
        if wedge is not None:
            raw_close(wedge)
        _stop_router(router)
