"""Golden-corpus fixtures for the typeToString emulation (VERDICT r3 #5).

``tests/golden/*.json`` pins the full decl records (symbolId,
addressId, kind, name, spans, signature) the scanner must produce for
~20 tricky snapshots: generics, unions, inferred returns,
object-literal types, tuples, qualified names, ``.tsx``, nested decls,
expression positions, ``for``-head exclusions, modifiers.

The expected values encode the reference worker's *documented*
no-default-lib semantics (reference ``workers/ts/src/sast.ts:19-96``:
unresolved identifiers display ``any``, primitives as written, member
counts for class/iface/enum/vars) — captured from a reviewed scanner
run, since the real Node worker cannot execute in this image. Any
drift in the emulation fails these tests; when a Node toolchain is
available, the same JSON shape accepts op logs captured from the real
worker verbatim.

Every fixture is also replayed through the native C++ scanner when it
builds, pinning Python↔C++ bit-parity on exactly the tricky rendering
paths.
"""
import json
import pathlib

import pytest

from semantic_merge_tpu.frontend.scanner import scan_snapshot_py

GOLDEN_DIR = pathlib.Path(__file__).parent / "golden"
FIXTURES = sorted(GOLDEN_DIR.glob("*.json"))


def node_dict(n):
    return {"symbolId": n.symbolId, "addressId": n.addressId, "kind": n.kind,
            "name": n.name, "file": n.file, "pos": n.pos, "end": n.end,
            "signature": n.signature}


@pytest.mark.parametrize("path", FIXTURES, ids=lambda p: p.stem)
def test_golden_python_scanner(path):
    fixture = json.loads(path.read_text())
    nodes = scan_snapshot_py(fixture["files"])
    assert [node_dict(n) for n in nodes] == fixture["expected"]


@pytest.mark.parametrize("path", FIXTURES, ids=lambda p: p.stem)
def test_golden_native_scanner(path):
    from semantic_merge_tpu.frontend import native
    fixture = json.loads(path.read_text())
    nodes = native.try_scan_snapshot(fixture["files"])
    if nodes is None:
        pytest.skip("native scanner unavailable")
    assert [node_dict(n) for n in nodes] == fixture["expected"]


def test_fixture_inventory():
    # The corpus must keep covering the tricky categories.
    names = {p.stem for p in FIXTURES}
    required = {
        "overloads", "default_type_params", "decorators",
        "declare_module", "triple_slash",
        "generics_function", "union_intersection", "inferred_return",
        "object_literal_types", "array_types", "unresolved_identifiers",
        "resolved_in_snapshot", "tsx_component", "nested_decls",
        "class_member_count", "interface_enum", "var_statements",
        "expressions_not_indexed", "for_heads_not_vars",
        "optional_default_rest", "modifiers", "qualified_and_parenthesized",
        "duplicate_signatures_collide", "no_annotations",
        "multifile_moves_identity",
    }
    assert required <= names, f"missing fixtures: {required - names}"
