"""Mesh-sharded merge kernels: bit-parity vs single-device vs host.

The VERDICT round-1 gap: the merge pipeline itself never touched the
mesh. These tests run the ``dp``-sharded diff sort-join and compose
(:mod:`semantic_merge_tpu.ops.sharded`) on the virtual 8-device CPU
mesh and assert exact agreement with the single-device kernels and the
pure-Python host oracle on fuzzed ~1k-decl/op streams — the sharded
DivergentRename join and symbol-table all-gather of the BASELINE north
star.
"""
import random

import numpy as np
import pytest

import jax

from semantic_merge_tpu.backends.ts_host import HostTSBackend
from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
from semantic_merge_tpu.core.compose import compose_oplogs
from semantic_merge_tpu.core.encode import DeclTensor
from semantic_merge_tpu.core.ops import Op, Target
from semantic_merge_tpu.frontend.snapshot import Snapshot
from semantic_merge_tpu.ops.compose import compose_oplogs_device
from semantic_merge_tpu.ops.diff import diff_lift_device, diff_lift_device_pair
from semantic_merge_tpu.ops.sharded import (compose_oplogs_device_sharded,
                                            diff_lift_device_pair_sharded,
                                            diff_lift_device_sharded)
from semantic_merge_tpu.parallel.mesh import build_mesh, parse_mesh_shape

DIFF_FIELDS = ("kind", "sym", "a_addr", "a_name", "a_file",
               "b_addr", "b_name", "b_file")


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(jax.devices(), dp=8, pp=1, sp=1, tp=1, ep=1).mesh


def rand_decls(rng: np.random.Generator, n: int, n_syms: int) -> DeclTensor:
    sym = rng.integers(0, n_syms, n).astype(np.int32)
    addr = rng.integers(100, 100 + 3 * max(n, 1), n).astype(np.int32)
    name = rng.integers(0, max(n_syms // 2, 2), n).astype(np.int32)
    name[rng.random(n) < 0.1] = -1  # anonymous decls (VariableStatement)
    file = rng.integers(500, 530, n).astype(np.int32)
    return DeclTensor(sym=sym, addr=addr, name=name, file=file, n=n)


def assert_diff_equal(a, b):
    assert a.n_ops == b.n_ops
    for f in DIFF_FIELDS:
        np.testing.assert_array_equal(
            getattr(a, f)[: a.n_ops], getattr(b, f)[: a.n_ops], err_msg=f)


class TestShardedDiff:
    def test_fuzz_1k_decls(self, mesh):
        rng = np.random.default_rng(42)
        for trial in range(6):
            nb = int(rng.integers(1, 1100))
            ns = int(rng.integers(1, 1100))
            base = rand_decls(rng, nb, n_syms=max(nb // 2, 4))
            side = rand_decls(rng, ns, n_syms=max(nb // 2, 4))
            single = diff_lift_device(base, side)
            sharded = diff_lift_device_sharded(base, side, mesh)
            assert_diff_equal(single, sharded)

    def test_duplicate_symbols_collide_last_wins(self, mesh):
        # Heavy symbol collisions: first-occurrence emission with
        # last-occurrence data must survive the shard boundaries.
        rng = np.random.default_rng(7)
        base = rand_decls(rng, 700, n_syms=5)
        side = rand_decls(rng, 650, n_syms=5)
        assert_diff_equal(diff_lift_device(base, side),
                          diff_lift_device_sharded(base, side, mesh))

    def test_pair_kernel(self, mesh):
        rng = np.random.default_rng(3)
        base = rand_decls(rng, 900, n_syms=400)
        left = rand_decls(rng, 930, n_syms=400)
        right = rand_decls(rng, 880, n_syms=400)
        sl, sr = diff_lift_device_pair(base, left, right)
        hl, hr = diff_lift_device_pair_sharded(base, left, right, mesh)
        assert_diff_equal(sl, hl)
        assert_diff_equal(sr, hr)

    def test_empty_and_tiny(self, mesh):
        rng = np.random.default_rng(5)
        empty = DeclTensor.empty()
        one = rand_decls(rng, 1, n_syms=1)
        for b, s in [(empty, one), (one, empty), (empty, empty), (one, one)]:
            assert_diff_equal(diff_lift_device(b, s),
                              diff_lift_device_sharded(b, s, mesh))


def mk(op_type, sym, params=None, ts="2024-01-01T00:00:00Z", op_id=None,
       addr=None):
    return Op.new(op_type, Target(symbolId=sym, addressId=addr),
                  params=params or {}, provenance={"timestamp": ts},
                  op_id=op_id)


def rand_ops(rng: random.Random, n: int, side: str, n_syms: int = 40):
    types = ["renameSymbol", "moveDecl", "addDecl", "deleteDecl",
             "editStmtBlock", "modifyImport"]
    out = []
    for i in range(n):
        t = rng.choice(types)
        params = {}
        if t == "renameSymbol":
            params = {"oldName": "o", "newName": rng.choice(["p", "q", "r"]),
                      "file": f"f{rng.randint(0, 3)}.ts"}
        elif t == "moveDecl":
            if rng.random() < 0.8:
                params["newAddress"] = f"addr-{rng.randint(0, 9)}"
            if rng.random() < 0.5:
                params["newFile"] = f"g{rng.randint(0, 3)}.ts"
            elif rng.random() < 0.5:
                params["file"] = f"h{rng.randint(0, 3)}.ts"
        ts = rng.choice(["2024-01-01T00:00:00Z", "2024-06-01T00:00:00Z"])
        out.append(mk(t, f"sym-{rng.randint(0, n_syms)}", params, ts=ts,
                      op_id=f"{side}{i:04d}" + "0" * 27, addr=f"ba-{i}"))
    return out


def dicts(ops):
    return [o.to_dict() for o in ops]


class TestShardedCompose:
    def test_fuzz_1k_ops_three_way(self, mesh):
        rng = random.Random(11)
        for trial in range(5):
            A = rand_ops(rng, rng.randint(0, 1000), "a")
            B = rand_ops(rng, rng.randint(0, 1000), "b")
            h_ops, h_conf = compose_oplogs(A, B)
            d_ops, d_conf = compose_oplogs_device(A, B)
            s_ops, s_conf = compose_oplogs_device_sharded(A, B, mesh)
            assert dicts(h_ops) == dicts(d_ops) == dicts(s_ops), f"trial {trial}"
            assert ([c.to_dict() for c in h_conf]
                    == [c.to_dict() for c in d_conf]
                    == [c.to_dict() for c in s_conf]), f"trial {trial}"

    def test_divergent_rename_across_shards(self, mesh):
        # Conflicting renames far apart in the stream: the sharded
        # candidate join must still surface them to the cursor walk.
        ra = mk("renameSymbol", "s", {"newName": "x"}, op_id="1" * 32)
        rb = mk("renameSymbol", "s", {"newName": "y"}, op_id="2" * 32)
        filler_a = rand_ops(random.Random(1), 500, "a", n_syms=500)
        filler_b = rand_ops(random.Random(2), 500, "b", n_syms=500)
        A = [ra] + filler_a
        B = [rb] + filler_b
        h_ops, h_conf = compose_oplogs(A, B)
        s_ops, s_conf = compose_oplogs_device_sharded(A, B, mesh)
        assert dicts(h_ops) == dicts(s_ops)
        assert [c.to_dict() for c in h_conf] == [c.to_dict() for c in s_conf]

    def test_chain_spans_shard_boundary(self, mesh):
        # One symbol's move chain feeding ops that land on later shards.
        ops_a = [mk("moveDecl", "sym-x",
                    {"newAddress": f"A{i}", "newFile": f"m{i}.ts"},
                    ts=f"2024-01-0{i + 1}T00:00:00Z",
                    op_id=f"a{i:03d}" + "0" * 28, addr="ba")
                 for i in range(4)]
        ops_b = [mk("editStmtBlock", "sym-x", {},
                    ts="2024-06-01T00:00:00Z",
                    op_id=f"b{i:03d}" + "0" * 28, addr="ba")
                 for i in range(600)]
        h_ops, h_conf = compose_oplogs(ops_a, ops_b)
        s_ops, s_conf = compose_oplogs_device_sharded(ops_a, ops_b, mesh)
        assert dicts(h_ops) == dicts(s_ops)
        assert not h_conf and not s_conf

    def test_empty(self, mesh):
        assert compose_oplogs_device_sharded([], [], mesh) == ([], [])


class TestNonPowerOfTwoMesh:
    """A dp size that is not a power of two (e.g. a 6-device slice) must
    still split the padded buckets evenly (core.encode.shard_bucket)."""

    @pytest.fixture(scope="class")
    def mesh6(self):
        return build_mesh(jax.devices()[:6], dp=6, pp=1, sp=1, tp=1,
                          ep=1).mesh

    def test_diff_parity_dp6(self, mesh6):
        rng = np.random.default_rng(17)
        base = rand_decls(rng, 333, n_syms=100)
        side = rand_decls(rng, 200, n_syms=100)
        assert_diff_equal(diff_lift_device(base, side),
                          diff_lift_device_sharded(base, side, mesh6))

    def test_compose_parity_dp6(self, mesh6):
        rng = random.Random(23)
        A = rand_ops(rng, 250, "a")
        B = rand_ops(rng, 190, "b")
        h_ops, h_conf = compose_oplogs(A, B)
        s_ops, s_conf = compose_oplogs_device_sharded(A, B, mesh6)
        assert dicts(h_ops) == dicts(s_ops)
        assert [c.to_dict() for c in h_conf] == [c.to_dict() for c in s_conf]


class TestShardedBackend:
    def test_auto_mesh_on_multichip(self):
        backend = TpuTSBackend()
        assert backend._mesh is not None, (
            "8 visible devices must auto-shard the merge kernels")

    def test_backend_end_to_end_parity(self):
        host = HostTSBackend()
        tpu = TpuTSBackend()  # auto dp=8 mesh on the virtual CPU mesh
        files = {}
        rng = random.Random(9)
        for i in range(40):
            decls = [f"export function fn{i}_{j}(x: number): number "
                     f"{{ return {j}; }}" for j in range(rng.randint(1, 4))]
            files[f"src/m{i}.ts"] = "\n".join(decls) + "\n"
        base = Snapshot(files=[{"path": p, "content": c}
                               for p, c in files.items()])
        left_files = dict(files)
        left_files["src/m0.ts"] = files["src/m0.ts"].replace("fn0_0", "renamed0")
        right_files = dict(files)
        right_files["lib/m1.ts"] = right_files.pop("src/m1.ts")
        left = Snapshot(files=[{"path": p, "content": c}
                               for p, c in left_files.items()])
        right = Snapshot(files=[{"path": p, "content": c}
                                for p, c in right_files.items()])
        h = host.build_and_diff(base, left, right, base_rev="r", seed="s",
                                timestamp="T")
        t = tpu.build_and_diff(base, left, right, base_rev="r", seed="s",
                               timestamp="T")
        assert dicts(h.op_log_left) == dicts(t.op_log_left)
        assert dicts(h.op_log_right) == dicts(t.op_log_right)
        hc, hf = host.compose(h.op_log_left, h.op_log_right)
        tc, tf = tpu.compose(t.op_log_left, t.op_log_right)
        assert dicts(hc) == dicts(tc)
        assert [c.to_dict() for c in hf] == [c.to_dict() for c in tf]

    def test_parse_mesh_shape(self):
        assert parse_mesh_shape("auto") == {}
        assert parse_mesh_shape("") == {}
        assert parse_mesh_shape("dp=4,tp=2") == {"dp": 4, "tp": 2}
        with pytest.raises(ValueError):
            parse_mesh_shape("bogus=2")
        with pytest.raises(ValueError):
            parse_mesh_shape("dp=x")
