"""Multi-host scaffolding: config resolution and hybrid-mesh layout.

True multi-process bring-up cannot run in one test process; these tests
cover the environment contract and — on the virtual 8-device CPU mesh —
that the hybrid (DCN x ICI) mesh puts slice crossings only on the
designated DCN axis and still executes sharded collectives.
"""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from semantic_merge_tpu.parallel.distributed import (  # noqa: E402
    build_hybrid_mesh, resolve_distributed_config)
from semantic_merge_tpu.parallel.mesh import MESH_AXES  # noqa: E402


def test_resolve_config_single_host_default():
    cfg = resolve_distributed_config(env={})
    assert not cfg.multi_host
    assert cfg.num_processes == 1 and cfg.process_id == 0


def test_resolve_config_multi_host():
    cfg = resolve_distributed_config(env={
        "SEMMERGE_COORDINATOR": "10.0.0.1:1234",
        "SEMMERGE_NUM_PROCESSES": "4",
        "SEMMERGE_PROCESS_ID": "2",
    })
    assert cfg.multi_host
    assert cfg.coordinator_address == "10.0.0.1:1234"
    assert cfg.process_id == 2


def test_resolve_config_jax_fallback_and_missing_coordinator():
    cfg = resolve_distributed_config(env={
        "JAX_COORDINATOR_ADDRESS": "h:1", "JAX_NUM_PROCESSES": "2",
        "JAX_PROCESS_ID": "1"})
    assert cfg.multi_host and cfg.coordinator_address == "h:1"
    with pytest.raises(ValueError):
        resolve_distributed_config(env={"SEMMERGE_NUM_PROCESSES": "2"})


def test_hybrid_mesh_single_slice_degrades_to_plain():
    mesh = build_hybrid_mesh(jax.devices())
    assert np.prod(list(mesh.axis_sizes.values())) == len(jax.devices())


def _fake_two_slices():
    devices = jax.devices()
    assert len(devices) == 8
    return devices, [0] * 4 + [1] * 4


def test_hybrid_mesh_slice_crossings_only_on_dcn_axis():
    devices, slice_ids = _fake_two_slices()
    mesh = build_hybrid_mesh(devices, slice_ids=slice_ids, dcn_axis="dp",
                             sp=2, tp=1, pp=1, ep=1)
    sizes = mesh.axis_sizes
    assert sizes["dp"] % 2 == 0
    sid = {d: s for d, s in zip(devices, slice_ids)}
    arr = mesh.mesh.devices
    # Moving along any non-dcn axis never changes slice.
    for axis, name in enumerate(MESH_AXES):
        if name == "dp" or arr.shape[axis] == 1:
            continue
        first = np.take(arr, 0, axis=axis)
        for k in range(1, arr.shape[axis]):
            other = np.take(arr, k, axis=axis)
            assert all(sid[a] == sid[b] for a, b in
                       zip(first.ravel(), other.ravel())), name


def test_hybrid_mesh_executes_collectives():
    devices, slice_ids = _fake_two_slices()
    mesh = build_hybrid_mesh(devices, slice_ids=slice_ids, dcn_axis="dp",
                             sp=2, tp=1, pp=1, ep=1)
    x = jnp.arange(16.0).reshape(8, 2)

    def body(x):
        return jax.lax.psum(x, "dp")

    from semantic_merge_tpu.utils.jaxenv import shard_map_compat
    out = shard_map_compat(body, mesh=mesh.mesh,
                           in_specs=P("dp", "sp"), out_specs=P(None, "sp"))(x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(x).reshape(4, 2, 2).sum(axis=0))


def test_hybrid_mesh_rejects_bad_factor():
    devices, slice_ids = _fake_two_slices()
    with pytest.raises(ValueError):
        build_hybrid_mesh(devices, slice_ids=slice_ids, dcn_axis="dp",
                          dp=3, sp=1, tp=1, pp=1, ep=1)


def test_hybrid_mesh_shape_drives_product_backend():
    """[engine] mesh_shape = 'hybrid:...' builds the DCN-aware mesh in
    the real backend path and the sharded merge keeps oracle parity."""
    import types
    from semantic_merge_tpu.backends.base import get_backend
    from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
    from semantic_merge_tpu.frontend.snapshot import Snapshot
    from semantic_merge_tpu.parallel.mesh import parse_mesh_spec

    kind, dcn, sizes = parse_mesh_spec("hybrid:dcn=dp,dp=8")
    assert (kind, dcn, sizes) == ("hybrid", "dp", {"dp": 8})
    assert parse_mesh_spec("dp=4,tp=2") == ("flat", None, {"dp": 4, "tp": 2})

    backend = TpuTSBackend(mesh=False)
    config = types.SimpleNamespace(engine=types.SimpleNamespace(
        mesh_shape="hybrid:dcn=dp,dp=8"))
    backend.configure(config)
    assert backend._mesh is not None
    assert backend._mesh.shape["dp"] == 8

    files = [{"path": f"m{i}.ts",
              "content": f"export function fn{i}(x: number): number "
                         f"{{ return x + {i}; }}\n"} for i in range(12)]
    base = Snapshot(files=files)
    left = Snapshot(files=[dict(f, content=f["content"].replace("fn0", "renamed0"))
                           for f in files])
    right = Snapshot(files=[dict(f, path=("lib/" + f["path"]
                                          if f["path"] == "m1.ts" else f["path"]))
                            for f in files])
    rt = backend.build_and_diff(base, left, right, base_rev="r", seed="s",
                                timestamp="T")
    host = get_backend("host")
    rh = host.build_and_diff(base, left, right, base_rev="r", seed="s",
                             timestamp="T")
    ops_t, _ = backend.compose(rt.op_log_left, rt.op_log_right)
    ops_h, _ = host.compose(rh.op_log_left, rh.op_log_right)
    assert [o.to_dict() for o in ops_t] == [o.to_dict() for o in ops_h]
