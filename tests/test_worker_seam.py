"""Out-of-process worker seam (VERDICT r3 Missing #2).

The subprocess backend speaks newline JSON-RPC to a child worker —
reference ``semmerge/lang/ts/bridge.py:80-118`` / ``workers/ts/src/
index.ts:9-51``. Tests cover: full-merge parity through the seam,
crash isolation (a killed worker raises cleanly and a fresh worker
serves the next call), per-request error isolation, and that an
EXTERNAL program implementing the protocol can be a backend.
"""
import json
import os
import pathlib
import signal
import sys
import textwrap

import pytest

from semantic_merge_tpu.backends.base import run_merge, get_backend
from semantic_merge_tpu.backends.subproc import SubprocessBackend, WorkerError
from semantic_merge_tpu.frontend.snapshot import Snapshot


def snap(files):
    return Snapshot(files=[{"path": p, "content": c} for p, c in files])


BASE = snap([("a.ts", "export function f(x: number): number { return x; }\n")])
LEFT = snap([("a.ts", "export function g(x: number): number { return x; }\n")])
RIGHT = snap([("lib/a.ts", "export function f(x: number): number { return x; }\n")])


@pytest.fixture()
def backend():
    b = SubprocessBackend()
    yield b
    b.close()


def test_full_merge_parity_through_worker(backend):
    host = get_backend("host")
    res_w, comp_w, conf_w = run_merge(backend, BASE, LEFT, RIGHT,
                                      base_rev="r", seed="s")
    res_h, comp_h, conf_h = run_merge(host, BASE, LEFT, RIGHT,
                                      base_rev="r", seed="s")
    assert [o.to_dict() for o in res_w.op_log_left] == \
        [o.to_dict() for o in res_h.op_log_left]
    assert [o.to_dict() for o in comp_w] == [o.to_dict() for o in comp_h]
    assert [c.to_dict() for c in conf_w] == [c.to_dict() for c in conf_h]


def test_worker_crash_recovers_transparently(backend):
    ops = backend.diff(BASE, LEFT, base_rev="r", seed="s")
    assert ops
    # Kill the live worker out from under the backend: calls are
    # stateless, so the next call spawns a fresh worker and succeeds.
    proc = backend._proc
    assert proc is not None
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()
    ops2 = backend.diff(BASE, LEFT, base_rev="r", seed="s")
    assert [o.to_dict() for o in ops2] == [o.to_dict() for o in ops]


def test_midcall_death_raises_cleanly(tmp_path):
    # A worker that reads one request and exits without answering: the
    # caller gets a WorkerError, not a hang or a corrupted merge.
    script = tmp_path / "dying_worker.py"
    script.write_text("import sys\nsys.stdin.readline()\n")
    backend = SubprocessBackend(worker_cmd=[sys.executable, str(script)])
    try:
        with pytest.raises(WorkerError):
            backend.diff(BASE, LEFT, base_rev="r", seed="s")
    finally:
        backend.close()


def test_request_error_does_not_kill_worker():
    import subprocess
    proc = subprocess.Popen(
        [sys.executable, "-m", "semantic_merge_tpu.runtime.worker"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True, bufsize=1)
    try:
        proc.stdin.write(json.dumps({"id": 1, "method": "nope"}) + "\n")
        proc.stdin.flush()
        reply = json.loads(proc.stdout.readline())
        assert reply["id"] == 1 and "error" in reply
        proc.stdin.write(json.dumps({"id": 2, "method": "ping"}) + "\n")
        proc.stdin.flush()
        reply2 = json.loads(proc.stdout.readline())
        assert reply2["result"]["pong"] is True, \
            "worker must survive a failed request"
    finally:
        proc.kill()


def test_external_program_can_implement_the_protocol(tmp_path):
    # A minimal non-semmerge worker: answers every buildAndDiff with one
    # canned addDecl op — proof the seam admits external tools.
    script = tmp_path / "toy_worker.py"
    script.write_text(textwrap.dedent("""
        import json, sys
        OP = {"id": "x"*8, "schemaVersion": 1, "type": "addDecl",
              "target": {"symbolId": "toy", "addressId": "toy::a::0"},
              "params": {"file": "toy.ts"}, "guards": {},
              "effects": {"summary": "add decl"}, "provenance": {}}
        for line in sys.stdin:
            req = json.loads(line)
            if req["method"] == "shutdown":
                print(json.dumps({"id": req["id"], "result": {}})); break
            print(json.dumps({"id": req["id"], "result": {
                "opLogLeft": [OP], "opLogRight": [], "symbolMaps": {}}}))
            sys.stdout.flush()
    """))
    backend = SubprocessBackend(worker_cmd=[sys.executable, str(script)])
    try:
        result = backend.build_and_diff(BASE, LEFT, RIGHT)
        assert len(result.op_log_left) == 1
        assert result.op_log_left[0].target.symbolId == "toy"
    finally:
        backend.close()


def test_config_selects_worker_cmd(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / ".semmerge.toml").write_text(
        '[engine]\nbackend = "subprocess"\n'
        f'worker_cmd = ["{sys.executable}", "-m", '
        '"semantic_merge_tpu.runtime.worker", "--backend", "host"]\n')
    from semantic_merge_tpu.config import load_config
    config = load_config()
    assert config.engine.worker_cmd is not None
    b = get_backend("subprocess")
    b.configure(config)
    try:
        ops = b.diff(BASE, LEFT, base_rev="r", seed="s")
        assert ops
    finally:
        b.close()
