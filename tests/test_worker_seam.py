"""Out-of-process worker seam (VERDICT r3 Missing #2).

The subprocess backend speaks newline JSON-RPC to a child worker —
reference ``semmerge/lang/ts/bridge.py:80-118`` / ``workers/ts/src/
index.ts:9-51``. Tests cover: full-merge parity through the seam,
crash isolation (a killed worker raises cleanly and a fresh worker
serves the next call), per-request error isolation, and that an
EXTERNAL program implementing the protocol can be a backend.
"""
import json
import os
import pathlib
import signal
import sys
import textwrap

import pytest

from semantic_merge_tpu.backends.base import run_merge, get_backend
from semantic_merge_tpu.backends.subproc import SubprocessBackend, WorkerError
from semantic_merge_tpu.frontend.snapshot import Snapshot


def snap(files):
    return Snapshot(files=[{"path": p, "content": c} for p, c in files])


BASE = snap([("a.ts", "export function f(x: number): number { return x; }\n")])
LEFT = snap([("a.ts", "export function g(x: number): number { return x; }\n")])
RIGHT = snap([("lib/a.ts", "export function f(x: number): number { return x; }\n")])


@pytest.fixture()
def backend():
    b = SubprocessBackend()
    yield b
    b.close()


def test_full_merge_parity_through_worker(backend):
    host = get_backend("host")
    res_w, comp_w, conf_w = run_merge(backend, BASE, LEFT, RIGHT,
                                      base_rev="r", seed="s")
    res_h, comp_h, conf_h = run_merge(host, BASE, LEFT, RIGHT,
                                      base_rev="r", seed="s")
    assert [o.to_dict() for o in res_w.op_log_left] == \
        [o.to_dict() for o in res_h.op_log_left]
    assert [o.to_dict() for o in comp_w] == [o.to_dict() for o in comp_h]
    assert [c.to_dict() for c in conf_w] == [c.to_dict() for c in conf_h]


def test_worker_crash_recovers_transparently(backend):
    ops = backend.diff(BASE, LEFT, base_rev="r", seed="s")
    assert ops
    # Kill the live worker out from under the backend: calls are
    # stateless, so the next call spawns a fresh worker and succeeds.
    proc = backend._proc
    assert proc is not None
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()
    ops2 = backend.diff(BASE, LEFT, base_rev="r", seed="s")
    assert [o.to_dict() for o in ops2] == [o.to_dict() for o in ops]


def test_midcall_death_raises_cleanly(tmp_path):
    # A worker that reads one request and exits without answering: the
    # caller gets a WorkerError, not a hang or a corrupted merge.
    script = tmp_path / "dying_worker.py"
    script.write_text("import sys\nsys.stdin.readline()\n")
    backend = SubprocessBackend(worker_cmd=[sys.executable, str(script)])
    try:
        with pytest.raises(WorkerError):
            backend.diff(BASE, LEFT, base_rev="r", seed="s")
    finally:
        backend.close()


def test_request_error_does_not_kill_worker():
    import subprocess
    proc = subprocess.Popen(
        [sys.executable, "-m", "semantic_merge_tpu.runtime.worker"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True, bufsize=1)
    try:
        proc.stdin.write(json.dumps({"id": 1, "method": "nope"}) + "\n")
        proc.stdin.flush()
        reply = json.loads(proc.stdout.readline())
        assert reply["id"] == 1 and "error" in reply
        proc.stdin.write(json.dumps({"id": 2, "method": "ping"}) + "\n")
        proc.stdin.flush()
        reply2 = json.loads(proc.stdout.readline())
        assert reply2["result"]["pong"] is True, \
            "worker must survive a failed request"
    finally:
        proc.kill()


def test_external_program_can_implement_the_protocol(tmp_path):
    # A minimal non-semmerge worker: answers every buildAndDiff with one
    # canned addDecl op — proof the seam admits external tools.
    script = tmp_path / "toy_worker.py"
    script.write_text(textwrap.dedent("""
        import json, sys
        OP = {"id": "x"*8, "schemaVersion": 1, "type": "addDecl",
              "target": {"symbolId": "toy", "addressId": "toy::a::0"},
              "params": {"file": "toy.ts"}, "guards": {},
              "effects": {"summary": "add decl"}, "provenance": {}}
        for line in sys.stdin:
            req = json.loads(line)
            if req["method"] == "shutdown":
                print(json.dumps({"id": req["id"], "result": {}})); break
            print(json.dumps({"id": req["id"], "result": {
                "opLogLeft": [OP], "opLogRight": [], "symbolMaps": {}}}))
            sys.stdout.flush()
    """))
    backend = SubprocessBackend(worker_cmd=[sys.executable, str(script)])
    try:
        result = backend.build_and_diff(BASE, LEFT, RIGHT)
        assert len(result.op_log_left) == 1
        assert result.op_log_left[0].target.symbolId == "toy"
    finally:
        backend.close()


def counter_total(name):
    from semantic_merge_tpu.obs import metrics as obs_metrics
    metric = obs_metrics.REGISTRY.to_dict().get("counters", {}).get(name, {})
    return sum(s["value"] for s in metric.get("series", []))


def test_wedged_worker_hits_deadline_not_a_hang(monkeypatch):
    """A worker that sleeps past the request deadline: the call must
    return in bounded time as a WorkerFault (process-group killed),
    with the deadline kill and the bounded respawn retry observable in
    metrics — never a hang on readline()."""
    import time as _time
    from semantic_merge_tpu.errors import WorkerFault
    from semantic_merge_tpu.utils import faults
    monkeypatch.setenv("SEMMERGE_FAULT", "worker-serve:hang=60")
    faults.reset()
    kills0 = counter_total("subprocess_deadline_kills_total")
    retries0 = counter_total("subprocess_retries_total")
    b = SubprocessBackend(deadline=0.75, max_retries=1)
    t0 = _time.monotonic()
    try:
        with pytest.raises(WorkerError) as exc_info:
            b.diff(BASE, LEFT, base_rev="r", seed="s")
    finally:
        b.close()
    elapsed = _time.monotonic() - t0
    assert elapsed < 30, f"wedged worker must be bounded, took {elapsed:.1f}s"
    assert isinstance(exc_info.value, WorkerFault)
    assert exc_info.value.cause == "deadline"
    assert counter_total("subprocess_deadline_kills_total") >= kills0 + 2, \
        "both the first attempt and the respawned resend must be killed"
    assert counter_total("subprocess_retries_total") == retries0 + 1, \
        "exactly one bounded respawn-and-resend"


def test_garbage_speaking_worker_faults_cleanly(monkeypatch):
    from semantic_merge_tpu.errors import WorkerFault
    from semantic_merge_tpu.utils import faults
    monkeypatch.setenv("SEMMERGE_FAULT", "worker-serve:garbage")
    faults.reset()
    b = SubprocessBackend(max_retries=1)
    try:
        with pytest.raises(WorkerError) as exc_info:
            b.diff(BASE, LEFT, base_rev="r", seed="s")
    finally:
        b.close()
    assert isinstance(exc_info.value, WorkerFault)
    assert exc_info.value.cause == "protocol"


def test_respawn_and_resend_recovers_transparently(tmp_path):
    """A worker that dies before answering its first request, once: the
    supervised call respawns, resends, and succeeds — the caller never
    sees the failure."""
    flag = tmp_path / "died-once"
    wrapper = tmp_path / "flaky_worker.py"
    wrapper.write_text(textwrap.dedent(f"""
        import os, runpy, sys
        flag = {str(flag)!r}
        if not os.path.exists(flag):
            open(flag, "w").close()
            sys.stdin.readline()  # swallow the request, die unanswered
            sys.exit(9)
        sys.argv = ["worker", "--backend", "host"]
        runpy.run_module("semantic_merge_tpu.runtime.worker",
                         run_name="__main__")
    """))
    retries0 = counter_total("subprocess_retries_total")
    b = SubprocessBackend(worker_cmd=[sys.executable, str(wrapper)],
                          max_retries=1)
    host = get_backend("host")
    try:
        ops = b.diff(BASE, LEFT, base_rev="r", seed="s")
        expected = host.diff(BASE, LEFT, base_rev="r", seed="s")
    finally:
        b.close()
        host.close()
    assert [o.to_dict() for o in ops] == [o.to_dict() for o in expected]
    assert flag.exists(), "the first worker must really have died"
    assert counter_total("subprocess_retries_total") == retries0 + 1


def test_worker_error_is_a_merge_fault():
    # The ladder catches MergeFault; WorkerError must be inside that
    # taxonomy or a dead worker would escape as a raw traceback.
    from semantic_merge_tpu.errors import MergeFault, WorkerFault
    assert issubclass(WorkerError, WorkerFault)
    assert issubclass(WorkerError, MergeFault)
    assert WorkerError("x").exit_code == 12


def test_config_selects_worker_cmd(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / ".semmerge.toml").write_text(
        '[engine]\nbackend = "subprocess"\n'
        f'worker_cmd = ["{sys.executable}", "-m", '
        '"semantic_merge_tpu.runtime.worker", "--backend", "host"]\n')
    from semantic_merge_tpu.config import load_config
    config = load_config()
    assert config.engine.worker_cmd is not None
    b = get_backend("subprocess")
    b.configure(config)
    try:
        ops = b.diff(BASE, LEFT, base_rev="r", seed="s")
        assert ops
    finally:
        b.close()
