"""Bit-parity tests: device pipeline vs host oracle.

The TPU backend must produce byte-identical op logs, composed streams,
and conflict records to the host implementations — the framework's
equivalent of the reference BASELINE's "bit-identical op logs vs the
Node worker" north star.
"""
import random

import pytest

from semantic_merge_tpu.backends.ts_host import HostTSBackend
from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
from semantic_merge_tpu.core.compose import compose_oplogs
from semantic_merge_tpu.core.ops import Op, Target
from semantic_merge_tpu.frontend.snapshot import Snapshot
from semantic_merge_tpu.ops.compose import compose_oplogs_device


def dicts(ops):
    return [o.to_dict() for o in ops]


def mk(op_type, sym, params=None, ts="2024-01-01T00:00:00Z", op_id=None, addr=None):
    return Op.new(op_type, Target(symbolId=sym, addressId=addr),
                  params=params or {}, provenance={"timestamp": ts}, op_id=op_id)


@pytest.fixture(scope="module")
def backends():
    return HostTSBackend(), TpuTSBackend()


def snap(files):
    return Snapshot(files=[{"path": p, "content": c} for p, c in files.items()])


class TestDiffLiftParity:
    def test_rename_move_add_delete(self, backends):
        host, tpu = backends
        base = snap({
            "src/util.ts": "export function foo(n: number): number { return n; }\n"
                           "export function keep(s: string): string { return s; }\n",
            "src/other.ts": "class P { x = 1; }\nconst a = 1;\n",
        })
        left = snap({
            "src/util.ts": "export function bar(n: number): number { return n; }\n"
                           "export function keep(s: string): string { return s; }\n",
            "src/other.ts": "class P { x = 1; }\nconst a = 1;\n",
        })
        right = snap({
            "lib/util.ts": "export function foo(n: number): number { return n; }\n"
                           "export function keep(s: string): string { return s; }\n",
            "src/other.ts": "class P { x = 1; }\nconst a = 1;\nenum E { A, B }\n",
        })
        h = host.build_and_diff(base, left, right, base_rev="rev", seed="s", timestamp="T")
        t = tpu.build_and_diff(base, left, right, base_rev="rev", seed="s", timestamp="T")
        assert dicts(h.op_log_left) == dicts(t.op_log_left)
        assert dicts(h.op_log_right) == dicts(t.op_log_right)
        assert h.symbol_maps == t.symbol_maps

    def test_duplicate_symbol_collisions(self, backends):
        host, tpu = backends
        # Same-shape decls collide (class{1} == class{1}); Map last-wins
        # must hold on device too.
        base = snap({"a.ts": "class A { x = 1; }\nclass B { y = 2; }\n"})
        side = snap({"a.ts": "class A { x = 1; }\nclass C { z = 9; }\nclass D { w = 0; }\n"})
        h = host.diff(base, side, base_rev="r", seed="s", timestamp="T")
        t = tpu.diff(base, side, base_rev="r", seed="s", timestamp="T")
        assert dicts(h) == dicts(t)

    def test_empty_and_identical_snapshots(self, backends):
        host, tpu = backends
        empty = snap({})
        same = snap({"a.ts": "export function f(): void {}\n"})
        for b, s in [(empty, same), (same, empty), (same, same), (empty, empty)]:
            h = host.diff(b, s, base_rev="r", seed="s", timestamp="T")
            t = tpu.diff(b, s, base_rev="r", seed="s", timestamp="T")
            assert dicts(h) == dicts(t)

    def test_many_files_fuzz(self, backends):
        host, tpu = backends
        rng = random.Random(13)
        names = ["alpha", "beta", "gamma", "delta", "eps"]
        def gen(n_files, shift):
            files = {}
            for i in range(n_files):
                decls = []
                for j in range(rng.randint(0, 4)):
                    nm = rng.choice(names) + str(j + shift)
                    ty = rng.choice(["number", "string", "boolean"])
                    decls.append(f"export function {nm}(x: {ty}): {ty} {{ return x; }}")
                files[f"f{i}.ts"] = "\n".join(decls) + "\n"
            return snap(files)
        for trial in range(5):
            base = gen(rng.randint(1, 6), 0)
            side = gen(rng.randint(1, 6), rng.randint(0, 1))
            h = host.diff(base, side, base_rev="r", seed="s", timestamp="T")
            t = tpu.diff(base, side, base_rev="r", seed="s", timestamp="T")
            assert dicts(h) == dicts(t), f"trial {trial}"


class TestComposeParity:
    def test_rename_vs_move_chain(self):
        rename = mk("renameSymbol", "sym-1",
                    {"oldName": "foo", "newName": "bar", "file": "src/util.ts"},
                    op_id="a" * 32)
        move = mk("moveDecl", "sym-1",
                  {"oldFile": "src/util.ts", "newFile": "lib/util.ts",
                   "oldAddress": "src/util.ts::foo::0",
                   "newAddress": "lib/util.ts::foo::0"}, op_id="b" * 32)
        h = compose_oplogs([rename], [move])
        d = compose_oplogs_device([rename], [move])
        assert dicts(h[0]) == dicts(d[0])
        assert [c.to_dict() for c in h[1]] == [c.to_dict() for c in d[1]]

    def test_divergent_rename_conflict(self):
        ra = mk("renameSymbol", "s", {"newName": "x"}, op_id="1" * 32)
        rb = mk("renameSymbol", "s", {"newName": "y"}, op_id="2" * 32)
        h = compose_oplogs([ra], [rb])
        d = compose_oplogs_device([ra], [rb])
        assert dicts(h[0]) == dicts(d[0])
        assert [c.to_dict() for c in h[1]] == [c.to_dict() for c in d[1]]

    def test_masked_conflict_quirk(self):
        ra = mk("renameSymbol", "s", {"newName": "x"}, op_id="1" * 32)
        ob = mk("renameSymbol", "unrelated", {"newName": "n"}, op_id="2" * 32)
        rb = mk("renameSymbol", "s", {"newName": "y"}, op_id="3" * 32)
        h = compose_oplogs([ra], [ob, rb])
        d = compose_oplogs_device([ra], [ob, rb])
        assert dicts(h[0]) == dicts(d[0])
        assert len(h[1]) == len(d[1]) == 0

    def test_newname_type_sensitivity(self):
        # The host conflict check compares raw values: 1 != "1" conflicts,
        # 1 == 1.0 does not. The device equality_key encoding must agree.
        ra = mk("renameSymbol", "s", {"newName": 1}, op_id="1" * 32)
        rb = mk("renameSymbol", "s", {"newName": "1"}, op_id="2" * 32)
        assert len(compose_oplogs([ra], [rb])[1]) == len(compose_oplogs_device([ra], [rb])[1]) == 1
        rc = mk("renameSymbol", "s", {"newName": 1.0}, op_id="3" * 32)
        assert len(compose_oplogs([ra], [rc])[1]) == len(compose_oplogs_device([ra], [rc])[1]) == 0

    def test_empty_newfile_falls_back_to_file(self):
        # Host move-chain uses truthiness: newFile="" falls back to file.
        m = mk("moveDecl", "s", {"newAddress": "A2", "newFile": "", "file": "x.ts"},
               op_id="3" * 32)
        later = mk("editStmtBlock", "s", {}, op_id="4" * 32)
        h = compose_oplogs([m, later], [])
        d = compose_oplogs_device([m, later], [])
        assert dicts(h[0]) == dicts(d[0])
        assert h[0][0].params["newFile"] == "x.ts"

    def test_fuzz_parity(self):
        rng = random.Random(7)
        types = ["renameSymbol", "moveDecl", "addDecl", "deleteDecl",
                 "editStmtBlock", "modifyImport"]

        def rand_op(i, side):
            t = rng.choice(types)
            sym = f"sym-{rng.randint(0, 5)}"
            params = {}
            if t == "renameSymbol":
                params = {"oldName": "o", "newName": rng.choice(["p", "q", "r"]),
                          "file": f"f{rng.randint(0, 3)}.ts"}
            elif t == "moveDecl":
                if rng.random() < 0.8:
                    params["newAddress"] = f"addr-{rng.randint(0, 9)}"
                if rng.random() < 0.5:
                    params["newFile"] = f"g{rng.randint(0, 3)}.ts"
                elif rng.random() < 0.5:
                    params["file"] = f"h{rng.randint(0, 3)}.ts"
            ts = rng.choice(["2024-01-01T00:00:00Z", "2024-06-01T00:00:00Z"])
            return mk(t, sym, params, ts=ts, op_id=f"{side}{i:03d}" + "0" * 28,
                      addr=f"base-addr-{i}")

        for trial in range(20):
            A = [rand_op(i, "a") for i in range(rng.randint(0, 12))]
            B = [rand_op(i, "b") for i in range(rng.randint(0, 12))]
            h = compose_oplogs(A, B)
            d = compose_oplogs_device(A, B)
            assert dicts(h[0]) == dicts(d[0]), f"trial {trial}"
            assert [c.to_dict() for c in h[1]] == [c.to_dict() for c in d[1]], f"trial {trial}"
