"""Unit tests for the unified observability layer (semantic_merge_tpu.obs):
span nesting/exception paths, histogram bucket edges, Prometheus text
rendering round-trip, device telemetry shape, the Tracer adapter's
--profile fix, the SEMMERGE_LOG fallback, and the `semmerge stats`
subcommand."""
import json
import os
import re
import subprocess
import sys

import pytest

from semantic_merge_tpu.obs import device as obs_device
from semantic_merge_tpu.obs import metrics as obs_metrics
from semantic_merge_tpu.obs import spans as obs_spans


# ---------------------------------------------------------------------------
# spans


def test_span_nesting_depth_and_parent_links():
    rec = obs_spans.SpanRecorder()
    with obs_spans.activated(rec):
        with obs_spans.span("outer", layer="cli"):
            with obs_spans.span("inner", layer="ops", k=1):
                pass
            with obs_spans.span("inner2", layer="ops"):
                pass
    by_name = {s.name: s for s in rec.spans}
    assert by_name["outer"].depth == 0
    assert by_name["outer"].parent_id == -1
    assert by_name["inner"].depth == 1
    assert by_name["inner"].parent_id == by_name["outer"].span_id
    assert by_name["inner2"].parent_id == by_name["outer"].span_id
    assert by_name["inner"].meta == {"k": 1}
    # Children complete (and record) before their parent.
    assert rec.spans.index(by_name["inner"]) < rec.spans.index(by_name["outer"])


def test_span_exception_path_marks_error_and_propagates():
    rec = obs_spans.SpanRecorder()
    with obs_spans.activated(rec):
        with pytest.raises(ValueError):
            with obs_spans.span("boom", layer="ops"):
                raise ValueError("nope")
    (span,) = rec.spans
    assert span.status == "error"
    assert span.error == "ValueError"
    assert span.seconds >= 0


def test_span_records_metrics_even_without_recorder():
    before = obs_metrics.phase_totals().get("dark_phase_xyz", 0.0)
    with obs_spans.span("dark_phase_xyz"):
        pass
    after = obs_metrics.phase_totals()["dark_phase_xyz"]
    assert after >= before
    # But no span record was built anywhere.
    assert obs_spans.current() is None


def test_stale_deactivate_is_noop_for_other_recorder():
    a, b = obs_spans.SpanRecorder(), obs_spans.SpanRecorder()
    obs_spans.activate(a)
    obs_spans.deactivate(b)  # stale handle: must not clobber a
    assert obs_spans.current() is a
    obs_spans.deactivate(a)
    assert obs_spans.current() is None


def test_phase_totals_since_scopes_one_run():
    before = obs_metrics.phase_totals()
    with obs_spans.span("scoped_phase_abc"):
        pass
    delta = obs_metrics.phase_totals_since(before)
    assert "scoped_phase_abc" in delta
    assert delta["scoped_phase_abc"] >= 0


def test_events_jsonl_round_trip(tmp_path):
    rec = obs_spans.SpanRecorder()
    with obs_spans.activated(rec):
        with obs_spans.span("alpha", layer="frontend"):
            obs_spans.event("marker", detail="x")
    path = tmp_path / "events.jsonl"
    rec.write_jsonl(path)
    rows = [json.loads(line) for line in path.read_text().splitlines()]
    kinds = {r["type"] for r in rows}
    assert kinds == {"span", "event"}
    span_row = next(r for r in rows if r["type"] == "span")
    assert span_row["name"] == "alpha" and span_row["layer"] == "frontend"


# ---------------------------------------------------------------------------
# metrics


def test_histogram_bucket_edges_are_inclusive_upper_bounds():
    h = obs_metrics.Histogram("t_hist", buckets=(1.0, 2.0, 4.0))
    h.observe(1.0)   # exactly on a bound -> that bucket
    h.observe(1.5)
    h.observe(2.0)
    h.observe(4.0001)  # past the last finite bound -> +Inf
    series = h._series[()]
    assert series["counts"] == [1, 2, 0, 1]
    assert series["count"] == 4
    assert series["sum"] == pytest.approx(8.5001)


def test_counter_gauge_labels_and_kind_mismatch():
    reg = obs_metrics.Registry()
    c = reg.counter("hits", "help text")
    c.inc(2, kind="a")
    c.inc(3, kind="b")
    assert c.value(kind="a") == 2 and c.value(kind="b") == 3
    g = reg.gauge("hwm")
    g.max(5)
    g.max(3)  # smaller -> ignored
    assert g.value() == 5
    with pytest.raises(TypeError):
        reg.gauge("hits")  # registered as a counter


def _parse_prometheus(text):
    """Minimal exposition parser: {(name, frozenset(labels)): value}."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = re.match(r"^([A-Za-z0-9_:]+)(\{(.*)\})? (.+)$", line)
        assert m, f"unparseable exposition line: {line!r}"
        name, _, labels_raw, value = m.groups()
        labels = frozenset(
            tuple(p.split("=", 1)) for p in
            re.findall(r'[A-Za-z0-9_]+="[^"]*"', labels_raw or ""))
        out[(name, labels)] = float(value)
    return out


def test_prometheus_rendering_round_trip():
    reg = obs_metrics.Registry()
    reg.counter("rt_total", "a counter").inc(3, phase="x")
    reg.counter("rt_total").inc(1.5, phase="y")
    reg.gauge("rt_gauge").set(7)
    h = reg.histogram("rt_seconds", buckets=(0.1, 1.0))
    h.observe(0.05, phase="x")
    h.observe(0.5, phase="x")
    h.observe(2.0, phase="x")

    text = reg.render_prometheus()
    parsed = _parse_prometheus(text)
    assert parsed[("rt_total", frozenset({("phase", '"x"')}))] == 3
    assert parsed[("rt_total", frozenset({("phase", '"y"')}))] == 1.5
    assert parsed[("rt_gauge", frozenset())] == 7
    # Histogram: cumulative buckets, _sum, _count survive the round trip.
    assert parsed[("rt_seconds_bucket",
                   frozenset({("phase", '"x"'), ("le", '"0.1"')}))] == 1
    assert parsed[("rt_seconds_bucket",
                   frozenset({("phase", '"x"'), ("le", '"1"')}))] == 2
    assert parsed[("rt_seconds_bucket",
                   frozenset({("phase", '"x"'), ("le", '"+Inf"')}))] == 3
    assert parsed[("rt_seconds_count", frozenset({("phase", '"x"')}))] == 3
    assert parsed[("rt_seconds_sum",
                   frozenset({("phase", '"x"')}))] == pytest.approx(2.55)
    # The JSON form renders identically through the artifact-side path.
    assert obs_metrics.render_prometheus_from_dict(reg.to_dict()) == text


def test_metrics_dump_json_and_prom(tmp_path):
    obs_metrics.REGISTRY.counter("dump_probe_total").inc(1)
    jpath = tmp_path / "m.json"
    obs_metrics.dump(str(jpath))
    data = json.loads(jpath.read_text())
    assert "dump_probe_total" in data["counters"]
    ppath = tmp_path / "m.prom"
    obs_metrics.dump(str(ppath))
    assert "dump_probe_total" in ppath.read_text()


# ---------------------------------------------------------------------------
# streaming quantiles (histogram_quantile + Histogram.quantile)


def test_histogram_quantile_interpolates_within_bucket():
    # 10 samples in (1, 2]: the interpolated p50 sits mid-bucket.
    counts = [0, 10, 0, 0]
    q = obs_metrics.histogram_quantile((1.0, 2.0, 4.0), counts, 0.5)
    assert 1.0 < q <= 2.0
    assert q == pytest.approx(1.5)
    # p100 is the bucket's upper bound; p0+epsilon its lower edge side.
    assert obs_metrics.histogram_quantile(
        (1.0, 2.0, 4.0), counts, 1.0) == pytest.approx(2.0)


def test_histogram_quantile_empty_and_overflow_clamp():
    assert obs_metrics.histogram_quantile((1.0, 2.0), [0, 0, 0], 0.99) == 0.0
    # All mass in +Inf: clamp to the highest finite bound, never inf.
    q = obs_metrics.histogram_quantile((1.0, 2.0), [0, 0, 7], 0.5)
    assert q == pytest.approx(2.0)


def test_histogram_quantile_matches_exact_percentiles_of_samples():
    """Property check: the interpolated quantile of bucketed samples
    must land within one bucket width of the exact percentile."""
    import random

    rng = random.Random(1234)
    bounds = tuple(obs_metrics.PHASE_BUCKETS)
    samples = [rng.uniform(0.001, 30.0) for _ in range(500)]
    counts = [0] * (len(bounds) + 1)
    for s in samples:
        import bisect
        counts[bisect.bisect_left(bounds, s)] += 1
    samples.sort()
    for q in (0.1, 0.5, 0.9, 0.99):
        exact = samples[min(len(samples) - 1, int(q * len(samples)))]
        est = obs_metrics.histogram_quantile(bounds, counts, q)
        # The estimate must land in the same bucket as the exact value
        # (bucket resolution is the error bound of the method).
        import bisect
        assert bisect.bisect_left(bounds, est) in (
            bisect.bisect_left(bounds, exact) - 1,
            bisect.bisect_left(bounds, exact),
            bisect.bisect_left(bounds, exact) + 1)


def test_histogram_quantile_is_monotone_in_q():
    import random

    rng = random.Random(99)
    bounds = (0.01, 0.1, 1.0, 10.0)
    counts = [rng.randint(0, 20) for _ in range(len(bounds) + 1)]
    if sum(counts) == 0:
        counts[1] = 3
    qs = [obs_metrics.histogram_quantile(bounds, counts, q / 20)
          for q in range(21)]
    assert qs == sorted(qs)


def test_histogram_quantile_rejects_mismatched_counts():
    with pytest.raises(ValueError):
        obs_metrics.histogram_quantile((1.0, 2.0), [1, 2], 0.5)


def test_histogram_quantile_method_and_snapshot():
    h = obs_metrics.Histogram("q_hist", buckets=(0.1, 1.0, 10.0))
    assert h.quantile(0.99) == 0.0  # no samples yet
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v, verb="semmerge")
    snap = h.snapshot(verb="semmerge")
    assert snap["count"] == 4 and sum(snap["counts"]) == 4
    q99 = h.quantile(0.99, verb="semmerge")
    assert 1.0 < q99 <= 10.0
    # Unlabeled series is independent and empty.
    assert h.quantile(0.5) == 0.0


# ---------------------------------------------------------------------------
# device telemetry


def test_device_snapshot_shape_is_stable():
    snap = obs_device.snapshot()
    for key in ("jax_imported", "platform", "device_count", "transfer_bytes",
                "transfer_count", "live_buffer_bytes_hwm",
                "compile_cache_events"):
        assert key in snap
    obs_device.record_transfer("h2d", 128)
    snap2 = obs_device.snapshot()
    assert snap2["transfer_bytes"].get("h2d", 0) >= 128


# ---------------------------------------------------------------------------
# Tracer adapter


def test_tracer_profile_dir_writes_phase_json_without_trace(tmp_path,
                                                            monkeypatch):
    """--profile DIR without --trace must still persist phase timings
    into DIR (they were silently discarded before)."""
    import semantic_merge_tpu.runtime.trace as trace_mod

    # Keep the unit test off the real JAX profiler.
    monkeypatch.setattr(
        trace_mod.Tracer, "__post_init__",
        lambda self: (self.enabled or self.profile_dir) and obs_spans.activate(
            self.__dict__.setdefault("_recorder", obs_spans.SpanRecorder())))
    prof = tmp_path / "profdir"
    tracer = trace_mod.Tracer(enabled=False, profile_dir=str(prof))
    with tracer.phase("snapshot"):
        pass
    tracer.write(tmp_path / "unused-trace.json")
    written = json.loads((prof / "semmerge-trace.json").read_text())
    assert [p["name"] for p in written["phases"]] == ["snapshot"]
    # Not --trace: the cwd artifact must NOT appear.
    assert not (tmp_path / "unused-trace.json").exists()
    assert obs_spans.current() is None


def test_tracer_enabled_writes_trace_events_and_spans(tmp_path):
    import semantic_merge_tpu.runtime.trace as trace_mod
    tracer = trace_mod.Tracer(enabled=True)
    with tracer.phase("merge", backend="host"):
        with obs_spans.span("scan", layer="frontend"):
            pass
    tracer.count("conflicts", 0)
    out = tmp_path / ".semmerge-trace.json"
    tracer.write(out)
    data = json.loads(out.read_text())
    assert data["schema"] == 1
    assert data["counters"] == {"conflicts": 0}
    names = {s["name"] for s in data["spans"]}
    assert {"merge", "scan"} <= names
    assert "device" in data and "metrics" in data
    events = tmp_path / ".semmerge-events.jsonl"
    assert events.exists()
    assert obs_spans.current() is None


# ---------------------------------------------------------------------------
# SEMMERGE_LOG fallback (satellite fix: invalid level must not kill
# every entry point at import time)


def _logger_level(env_value):
    env = dict(os.environ, SEMMERGE_LOG=env_value)
    proc = subprocess.run(
        [sys.executable, "-c",
         "from semantic_merge_tpu.utils.loggingx import logger; "
         "print(logger.level)"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=60)
    return proc


def test_invalid_semmerge_log_falls_back_to_info():
    proc = _logger_level("verbose")
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == "20"  # INFO
    assert "invalid SEMMERGE_LOG" in proc.stderr


def test_lowercase_and_numeric_semmerge_log_accepted():
    proc = _logger_level("debug")
    assert proc.returncode == 0 and proc.stdout.strip() == "10"
    proc = _logger_level("30")
    assert proc.returncode == 0 and proc.stdout.strip() == "30"


# ---------------------------------------------------------------------------
# stats subcommand


def test_stats_renders_trace_metrics_and_events(tmp_path, monkeypatch, capsys):
    import semantic_merge_tpu.runtime.trace as trace_mod
    from semantic_merge_tpu.cli import main

    monkeypatch.chdir(tmp_path)
    tracer = trace_mod.Tracer(enabled=True)
    with tracer.phase("merge"):
        with obs_spans.span("scan", layer="frontend"):
            pass
    tracer.write(".semmerge-trace.json")

    assert main(["stats"]) == 0
    out = capsys.readouterr().out
    assert "merge" in out and "frontend" in out

    assert main(["stats", ".semmerge-events.jsonl"]) == 0
    assert "spans" in capsys.readouterr().out

    assert main(["stats", "--prometheus"]) == 0
    assert "semmerge_phase_seconds_bucket" in capsys.readouterr().out

    obs_metrics.dump(str(tmp_path / "metrics.json"))
    assert main(["stats", "metrics.json"]) == 0
    assert "semmerge_phase_seconds" in capsys.readouterr().out

    assert main(["stats", "missing.json"]) == 1
