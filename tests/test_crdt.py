"""RGA CRDT host-implementation tests."""
from semantic_merge_tpu.core.crdt import RGA, Key


def test_insert_orders_by_key_tuple():
    rga = RGA()
    rga.insert(Key("a", 2, "u1", "op2"), "second")
    rga.insert(Key("a", 1, "u1", "op1"), "first")
    rga.insert(Key("b", 1, "u1", "op3"), "third")
    assert rga.materialize() == ["first", "second", "third"]


def test_equal_keys_keep_insertion_order():
    rga = RGA()
    k = Key("a", 1, "u", "same")
    rga.insert(k, "x")
    rga.insert(k, "y")
    assert rga.materialize() == ["x", "y"]


def test_delete_tombstones_all_matches():
    rga = RGA()
    rga.insert(Key("a", 1, "u", "1"), "v")
    rga.insert(Key("a", 2, "u", "2"), "v")
    rga.delete("v")
    assert rga.materialize() == []


def test_move_relocates_first_live_element():
    rga = RGA()
    rga.insert(Key("a", 1, "u", "1"), "x")
    rga.insert(Key("a", 2, "u", "2"), "y")
    rga.move("x", Key("a", 3, "u", "3"))
    assert rga.materialize() == ["y", "x"]


def test_convergence_any_op_order():
    ops = [
        (Key("a", 1, "u1", "1"), "alpha"),
        (Key("a", 1, "u2", "2"), "beta"),
        (Key("b", 0, "u1", "3"), "gamma"),
    ]
    r1, r2 = RGA(), RGA()
    for k, v in ops:
        r1.insert(k, v)
    for k, v in reversed(ops):
        r2.insert(k, v)
    assert r1.materialize() == r2.materialize()
