"""Tests for the mesh/model stack: ring attention parity, sharded
training, mesh factorization, graft entry points."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from semantic_merge_tpu.models.encoder import EncoderConfig  # noqa: E402
from semantic_merge_tpu.models.features import encode_batch  # noqa: E402
from semantic_merge_tpu.models.matcher import (MatcherConfig,  # noqa: E402
                                               init_matcher, make_scorer,
                                               make_sharded_train_step)
from semantic_merge_tpu.parallel.mesh import build_mesh  # noqa: E402
from semantic_merge_tpu.parallel.ring import ring_attention  # noqa: E402

CFG = MatcherConfig(encoder=EncoderConfig(
    vocab=512, d_model=64, n_heads=4, d_head=16,
    n_layers=2, d_ff=128, n_experts=2))


def _batch(n=8, seq=32):
    srcs_a = [f"export function f{i}(x: number): number {{ return x * {i}; }}"
              for i in range(n)]
    srcs_b = [f"export function g{i}(x: number): number {{ return x * {i}; }}"
              for i in range(n)]
    ta, ma = encode_batch(srcs_a, 512, seq)
    tb, mb = encode_batch(srcs_b, 512, seq)
    return {"tokens_a": ta, "mask_a": ma, "tokens_b": tb, "mask_b": mb}


def _dense_attention(q, k, v, kmask):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    s = jnp.where(kmask[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


def test_ring_attention_matches_dense():
    rng = np.random.RandomState(0)
    b, l, h, dh = 4, 16, 4, 8
    q = rng.randn(b, l, h, dh).astype(np.float32)
    k = rng.randn(b, l, h, dh).astype(np.float32)
    v = rng.randn(b, l, h, dh).astype(np.float32)
    mask = rng.rand(b, l) > 0.2
    mask[:, 0] = True  # at least one live key per row
    mesh = build_mesh(dp=2, pp=1, sp=2, tp=2, ep=1)
    out_ring = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              jnp.asarray(mask), mesh.mesh)
    out_dense = _dense_attention(jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v), jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_dense),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("sizes", [
    {"dp": 2, "pp": 1, "sp": 2, "tp": 2, "ep": 1},
    {"dp": 2, "pp": 2, "sp": 1, "tp": 1, "ep": 2},
    {"dp": 8, "pp": 1, "sp": 1, "tp": 1, "ep": 1},
])
def test_sharded_train_step_decreases_loss(sizes):
    mesh = build_mesh(**sizes)
    params, opt_state = init_matcher(jax.random.PRNGKey(0), CFG)
    batch = _batch()
    step = make_sharded_train_step(CFG, mesh)
    losses = []
    for _ in range(4):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_scorer_prefers_true_pairs():
    mesh = build_mesh(dp=2, pp=1, sp=2, tp=2, ep=1)
    params, opt_state = init_matcher(jax.random.PRNGKey(1), CFG)
    batch = _batch()
    step = make_sharded_train_step(CFG, mesh)
    for _ in range(30):
        params, opt_state, _ = step(params, opt_state, batch)
    scorer = make_scorer(CFG, mesh)
    true_scores = np.asarray(scorer(params, batch["tokens_a"], batch["mask_a"],
                                    batch["tokens_b"], batch["mask_b"]))
    shuffled = np.roll(np.arange(len(true_scores)), 1)
    cross_scores = np.asarray(scorer(params, batch["tokens_a"], batch["mask_a"],
                                     batch["tokens_b"][shuffled],
                                     batch["mask_b"][shuffled]))
    assert true_scores.mean() > cross_scores.mean()


def test_mesh_factorization():
    mesh = build_mesh()
    sizes = mesh.axis_sizes
    assert np.prod(list(sizes.values())) == len(jax.devices())
    with pytest.raises(ValueError):
        build_mesh(dp=3, pp=1, sp=1, tp=1, ep=1)


def test_graft_entry_points():
    import importlib.util
    import pathlib
    spec = importlib.util.spec_from_file_location(
        "graft_entry", pathlib.Path(__file__).parent.parent / "__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 32, 64)
    mod.dryrun_multichip(8)


def test_routed_topk_moe_forward_and_sharding():
    """moe_mode='topk' is real routed EP: top-k capacity-bounded
    dispatch/combine, running sharded over the ep axis (VERDICT r3 #10)."""
    import dataclasses
    import jax
    import jax.numpy as jnp
    import numpy as np
    from semantic_merge_tpu.models.encoder import (EncoderConfig,
                                                   encoder_forward,
                                                   init_encoder)
    from semantic_merge_tpu.parallel.mesh import build_mesh

    mesh = build_mesh(jax.devices(), dp=2, ep=2, pp=1, sp=2, tp=1)
    cfg_soft = EncoderConfig(vocab=128, d_model=32, n_heads=4, d_head=8,
                             n_layers=2, d_ff=64, n_experts=4)
    cfg_topk = dataclasses.replace(cfg_soft, moe_mode="topk", moe_top_k=2)
    params = init_encoder(jax.random.PRNGKey(0), cfg_soft)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 128)
    mask = jnp.ones((4, 16), bool)

    outs = {}
    for name, cfg in (("soft", cfg_soft), ("topk", cfg_topk)):
        fn = jax.jit(lambda p, t, m, c=cfg: encoder_forward(p, t, m, c, mesh))
        outs[name] = np.asarray(fn(params, tokens, mask))
        assert np.isfinite(outs[name]).all()
    # Routing genuinely changes compute (not a renamed soft blend).
    assert not np.allclose(outs["soft"], outs["topk"])


def test_routed_moe_capacity_drop_is_graceful():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from semantic_merge_tpu.models.encoder import EncoderConfig, _routed_moe

    cfg = EncoderConfig(vocab=64, d_model=16, n_heads=2, d_head=8,
                        n_layers=1, d_ff=32, n_experts=2,
                        moe_mode="topk", moe_top_k=1,
                        moe_capacity_factor=0.25)  # force overflow drops
    rng = jax.random.PRNGKey(0)
    h = jax.random.normal(rng, (2, 8, 16), jnp.bfloat16)
    # All tokens prefer expert 0 -> most exceed capacity and drop.
    logits = jnp.stack([jnp.full((2, 8), 5.0), jnp.full((2, 8), -5.0)], -1)
    w1 = jax.random.normal(rng, (2, 16, 32), jnp.bfloat16)
    w2 = jax.random.normal(rng, (2, 32, 16), jnp.bfloat16)
    out = np.asarray(_routed_moe(h, logits, w1, w2, cfg))
    assert np.isfinite(out).all()
    # Dropped tokens contribute no FFN delta: their rows are exactly 0.
    flat = out.reshape(-1, 16)
    zero_rows = int((np.abs(flat).max(axis=1) == 0).sum())
    assert zero_rows >= 8, f"expected >=8 dropped tokens, got {zero_rows}"
