"""Tests for the mesh/model stack: ring attention parity, sharded
training, mesh factorization, graft entry points."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from semantic_merge_tpu.models.encoder import EncoderConfig  # noqa: E402
from semantic_merge_tpu.models.features import encode_batch  # noqa: E402
from semantic_merge_tpu.models.matcher import (MatcherConfig,  # noqa: E402
                                               init_matcher, make_scorer,
                                               make_sharded_train_step)
from semantic_merge_tpu.parallel.mesh import build_mesh  # noqa: E402
from semantic_merge_tpu.parallel.ring import ring_attention  # noqa: E402

CFG = MatcherConfig(encoder=EncoderConfig(
    vocab=512, d_model=64, n_heads=4, d_head=16,
    n_layers=2, d_ff=128, n_experts=2))


def _batch(n=8, seq=32):
    srcs_a = [f"export function f{i}(x: number): number {{ return x * {i}; }}"
              for i in range(n)]
    srcs_b = [f"export function g{i}(x: number): number {{ return x * {i}; }}"
              for i in range(n)]
    ta, ma = encode_batch(srcs_a, 512, seq)
    tb, mb = encode_batch(srcs_b, 512, seq)
    return {"tokens_a": ta, "mask_a": ma, "tokens_b": tb, "mask_b": mb}


def _dense_attention(q, k, v, kmask):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    s = jnp.where(kmask[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


def test_ring_attention_matches_dense():
    rng = np.random.RandomState(0)
    b, l, h, dh = 4, 16, 4, 8
    q = rng.randn(b, l, h, dh).astype(np.float32)
    k = rng.randn(b, l, h, dh).astype(np.float32)
    v = rng.randn(b, l, h, dh).astype(np.float32)
    mask = rng.rand(b, l) > 0.2
    mask[:, 0] = True  # at least one live key per row
    mesh = build_mesh(dp=2, pp=1, sp=2, tp=2, ep=1)
    out_ring = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              jnp.asarray(mask), mesh.mesh)
    out_dense = _dense_attention(jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v), jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_dense),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("sizes", [
    {"dp": 2, "pp": 1, "sp": 2, "tp": 2, "ep": 1},
    {"dp": 2, "pp": 2, "sp": 1, "tp": 1, "ep": 2},
    {"dp": 8, "pp": 1, "sp": 1, "tp": 1, "ep": 1},
])
def test_sharded_train_step_decreases_loss(sizes):
    mesh = build_mesh(**sizes)
    params, opt_state = init_matcher(jax.random.PRNGKey(0), CFG)
    batch = _batch()
    step = make_sharded_train_step(CFG, mesh)
    losses = []
    for _ in range(4):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_scorer_prefers_true_pairs():
    mesh = build_mesh(dp=2, pp=1, sp=2, tp=2, ep=1)
    params, opt_state = init_matcher(jax.random.PRNGKey(1), CFG)
    batch = _batch()
    step = make_sharded_train_step(CFG, mesh)
    for _ in range(30):
        params, opt_state, _ = step(params, opt_state, batch)
    scorer = make_scorer(CFG, mesh)
    true_scores = np.asarray(scorer(params, batch["tokens_a"], batch["mask_a"],
                                    batch["tokens_b"], batch["mask_b"]))
    shuffled = np.roll(np.arange(len(true_scores)), 1)
    cross_scores = np.asarray(scorer(params, batch["tokens_a"], batch["mask_a"],
                                     batch["tokens_b"][shuffled],
                                     batch["mask_b"][shuffled]))
    assert true_scores.mean() > cross_scores.mean()


def test_mesh_factorization():
    mesh = build_mesh()
    sizes = mesh.axis_sizes
    assert np.prod(list(sizes.values())) == len(jax.devices())
    with pytest.raises(ValueError):
        build_mesh(dp=3, pp=1, sp=1, tp=1, ep=1)


def test_graft_entry_points():
    import importlib.util
    import pathlib
    spec = importlib.util.spec_from_file_location(
        "graft_entry", pathlib.Path(__file__).parent.parent / "__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 32, 64)
    mod.dryrun_multichip(8)
