"""Cross-host fleet transport (ISSUE 19 tentpole): address parsing,
deadlines/backoff, TLS/mTLS loopback, half-open detection, and elastic
membership over real TCP.

The bar:

- ``tcp://host:port`` (bracketed IPv6 included) selects the TCP
  transport; anything malformed is a loud ``ValueError``, never a
  silent unix-path fallback. ``:0`` listeners resolve to a dialable
  advertised address.
- Backoff is jittered and capped — both the transport's full-jitter
  resend backoff and the client's decorrelated reconnect backoff.
- With ``SEMMERGE_FLEET_TLS_*`` configured, the loopback round trip is
  mTLS end to end, and a client without a certificate is refused by a
  CA-pinned server.
- ``heartbeat`` distinguishes a dead member (``connect``) from a
  half-open/partitioned one (``read-timeout``): the shape TCP keepalive
  cannot see.
- A standalone daemon joins a live router over TCP (``serve --join``),
  shows up in ``member_status`` as a remote ready member, drains as
  ``draining`` (not ``dead``), and leaves cleanly — by verb or by
  SIGTERM (the teardown announces the departure).
"""
import contextlib
import json
import os
import pathlib
import signal
import socket
import ssl
import subprocess
import sys
import threading
import time

import pytest

from semantic_merge_tpu.errors import TransportFault
from semantic_merge_tpu.fleet import transport
from semantic_merge_tpu.service import protocol

from test_fleet import _control, _counter_total, _spawn_router, _stop_router

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# Addresses
# ---------------------------------------------------------------------------

def test_tcp_address_parsing():
    assert transport.is_tcp("tcp://10.0.0.7:7633")
    assert not transport.is_tcp("/run/semmerge.sock")
    assert transport.tcp_endpoint("tcp://10.0.0.7:7633") == ("10.0.0.7",
                                                             7633)
    assert transport.tcp_endpoint("tcp://[::1]:7633") == ("::1", 7633)
    assert transport.tcp_endpoint("tcp://localhost:0") == ("localhost", 0)
    for bad in ("/run/semmerge.sock", "tcp://", "tcp://host",
                "tcp://host:port", "tcp://:7633", "tcp://[]:7633"):
        with pytest.raises(ValueError):
            transport.tcp_endpoint(bad)


def test_bound_address_resolves_ephemeral_port():
    srv = transport.listen("tcp://127.0.0.1:0")
    try:
        addr = transport.bound_address(srv, "tcp://127.0.0.1:0")
        host, port = transport.tcp_endpoint(addr)
        assert host == "127.0.0.1" and port > 0
        assert port == srv.getsockname()[1]
    finally:
        srv.close()
    # Pass-throughs: fixed ports and unix paths come back untouched.
    assert transport.bound_address(None, "/run/x.sock") == "/run/x.sock"


# ---------------------------------------------------------------------------
# Backoff
# ---------------------------------------------------------------------------

def test_resend_backoff_is_jittered_and_capped():
    for attempt in range(12):
        ceiling = min(2.0, 0.05 * (2.0 ** attempt))
        samples = [transport.backoff_s(attempt) for _ in range(50)]
        assert all(0.0 <= s <= ceiling for s in samples), (attempt, samples)
    assert len({round(transport.backoff_s(6), 9)
                for _ in range(50)}) > 1, "backoff must be jittered"


def test_client_reconnect_backoff_decorrelated():
    """The client's reconnect loop uses decorrelated jitter: each delay
    is drawn from ``[base, prev * 3]`` capped at 2s — delays grow from
    the previous *sample* (not a fixed ladder), so colliding clients
    spread out instead of re-arriving in lockstep."""
    from semantic_merge_tpu.service.client import _reconnect_backoff_s
    assert _reconnect_backoff_s(0.0) == pytest.approx(0.05)
    for prev in (0.05, 0.2, 1.0, 50.0):
        samples = [_reconnect_backoff_s(prev) for _ in range(100)]
        hi = min(2.0, max(prev * 3.0, 0.05))
        assert all(0.05 <= s <= hi for s in samples), (prev, samples)
    assert all(_reconnect_backoff_s(100.0) <= 2.0 for _ in range(100))
    assert len({round(_reconnect_backoff_s(1.0), 9)
                for _ in range(50)}) > 1, "reconnect backoff must jitter"
    # A full chain stays within the cap from any start.
    delay = 0.0
    for _ in range(20):
        delay = _reconnect_backoff_s(delay)
        assert 0.05 <= delay <= 2.0


# ---------------------------------------------------------------------------
# Loopback round trips (plaintext + TLS)
# ---------------------------------------------------------------------------

class _HelloServer:
    """A minimal in-process member: answers ``hello`` on a transport
    listener. ``mute=True`` accepts and never replies (the half-open
    shape); ``slam=True`` closes immediately after accept."""

    def __init__(self, *, mute=False, slam=False):
        self.sock = transport.listen("tcp://127.0.0.1:0")
        self.address = transport.bound_address(self.sock,
                                               "tcp://127.0.0.1:0")
        self._mute, self._slam = mute, slam
        self._held = []
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except ssl.SSLError:  # a refused client handshake must not
                continue          # kill the accept loop (OSError subclass!)
            except (OSError, ValueError):
                return
            if self._slam:
                conn.close()
                continue
            if self._mute:
                self._held.append(conn)
                continue
            try:
                rfile = conn.makefile("r", encoding="utf-8")
                wfile = conn.makefile("w", encoding="utf-8")
                req = protocol.read_message(rfile)
                protocol.write_message(wfile, {
                    "id": req["id"],
                    "result": {"ok": True, "pid": os.getpid(),
                               "version": protocol.PROTOCOL_VERSION,
                               "fleet": False, "draining": False}})
            except Exception:  # noqa: BLE001
                pass
            finally:
                with contextlib.suppress(OSError):
                    conn.close()

    def close(self):
        with contextlib.suppress(OSError):
            self.sock.close()
        for conn in self._held:
            with contextlib.suppress(OSError):
                conn.close()


def test_plaintext_tcp_roundtrip_and_heartbeat():
    srv = _HelloServer()
    try:
        hello = transport.heartbeat(srv.address, timeout=10.0)
        assert hello["ok"] and hello["draining"] is False
        result = transport.call(srv.address, "hello", {}, timeout=10.0)
        assert result and result["ok"]
    finally:
        srv.close()


def test_heartbeat_distinguishes_dead_from_half_open():
    # Dead: nothing listening — the dial itself fails.
    srv = _HelloServer()
    dead_addr = srv.address
    srv.close()
    time.sleep(0.05)
    with pytest.raises(TransportFault) as exc_info:
        transport.heartbeat(dead_addr, timeout=2.0)
    assert exc_info.value.cause == "connect"
    # Half-open: the dial succeeds, the reply never comes.
    mute = _HelloServer(mute=True)
    try:
        with pytest.raises(TransportFault) as exc_info:
            transport.heartbeat(mute.address, timeout=0.5)
        assert exc_info.value.cause == "read-timeout"
        assert exc_info.value.exit_code == 21
    finally:
        mute.close()


def test_roundtrip_peer_close_is_typed():
    srv = _HelloServer(slam=True)
    try:
        with pytest.raises(TransportFault) as exc_info:
            transport.roundtrip(srv.address, {"id": 0, "method": "hello",
                                              "params": {}},
                                read_deadline=5.0)
        # Slammed mid-request: either a clean EOF or the broken pipe /
        # reset surfaces — all typed, never a bare OSError.
        assert exc_info.value.cause in ("eof", "ProtocolError",
                                        "ConnectionResetError",
                                        "BrokenPipeError")
    finally:
        srv.close()


def test_call_returns_none_after_resend_budget(tmp_path):
    addr = "tcp://127.0.0.1:1"  # reserved port: always refused
    t0 = time.monotonic()
    assert transport.call(addr, "hello", {}, timeout=0.5, retries=1) is None
    assert time.monotonic() - t0 < 30.0


# ---------------------------------------------------------------------------
# TLS / mTLS
# ---------------------------------------------------------------------------

def _make_certs(tmp_path):
    """A private CA plus one endpoint cert signed by it (both fleet
    sides share the same material in these loopback tests)."""
    ca_key, ca_pem = str(tmp_path / "ca.key"), str(tmp_path / "ca.pem")
    ep_key, ep_csr, ep_pem = (str(tmp_path / "ep.key"),
                              str(tmp_path / "ep.csr"),
                              str(tmp_path / "ep.pem"))
    run = lambda *argv: subprocess.run(  # noqa: E731
        argv, check=True, capture_output=True)
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", ca_key, "-out", ca_pem, "-days", "2",
        "-subj", "/CN=semmerge-test-ca")
    run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
        "-keyout", ep_key, "-out", ep_csr, "-subj", "/CN=127.0.0.1")
    run("openssl", "x509", "-req", "-in", ep_csr, "-CA", ca_pem,
        "-CAkey", ca_key, "-CAcreateserial", "-out", ep_pem, "-days", "2")
    return ca_pem, ep_pem, ep_key


def test_mtls_roundtrip_and_unauthenticated_client_refused(tmp_path,
                                                           monkeypatch):
    if not os.path.exists("/usr/bin/openssl"):
        pytest.skip("openssl unavailable")
    ca_pem, ep_pem, ep_key = _make_certs(tmp_path)
    monkeypatch.setenv(transport.ENV_TLS_CERT, ep_pem)
    monkeypatch.setenv(transport.ENV_TLS_KEY, ep_key)
    monkeypatch.setenv(transport.ENV_TLS_CA, ca_pem)
    assert transport.tls_enabled()
    srv = _HelloServer()  # listener wraps itself from the same env
    try:
        hello = transport.heartbeat(srv.address, timeout=10.0)
        assert hello["ok"], "mTLS loopback hello must succeed"
        # A client with no certificate must be refused by the
        # CA-pinned server. TLS 1.3 delivers the certificate_required
        # alert on first I/O, not at the handshake — either way the
        # failure is a typed TransportFault, and the server's accept
        # loop survives to serve the next authenticated client.
        monkeypatch.delenv(transport.ENV_TLS_CERT)
        monkeypatch.delenv(transport.ENV_TLS_KEY)
        with pytest.raises(TransportFault):
            transport.heartbeat(srv.address, timeout=5.0)
        monkeypatch.setenv(transport.ENV_TLS_CERT, ep_pem)
        monkeypatch.setenv(transport.ENV_TLS_KEY, ep_key)
        assert transport.heartbeat(srv.address, timeout=10.0)["ok"]
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# Elastic membership: serve --join over real TCP
# ---------------------------------------------------------------------------

def _spawn_member(router_sock, tmp_path, *, member_id="blue",
                  join_interval="0.5", capacity=2, extra_env=None):
    env = dict(os.environ)
    env.update({"PYTHONPATH": str(REPO_ROOT), "JAX_PLATFORMS": "cpu",
                "SEMMERGE_DAEMON": "off",
                "SEMMERGE_FLEET_JOIN_INTERVAL": join_interval,
                "SEMMERGE_SERVICE_DRAIN_TIMEOUT": "2"})
    for key in ("SEMMERGE_FAULT", "SEMMERGE_METRICS",
                "SEMMERGE_SERVICE_SOCKET"):
        env.pop(key, None)
    env.update(extra_env or {})
    log = open(str(tmp_path / f"member-{member_id}.log"), "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "semantic_merge_tpu", "serve",
         "--socket", "tcp://127.0.0.1:0", "--join", router_sock,
         "--member-id", member_id, "--capacity", str(capacity)],
        stdin=subprocess.DEVNULL, stdout=log, stderr=log,
        cwd="/", env=env, start_new_session=True)
    log.close()
    return proc


def _wait_members(router_sock, want_ids, *, timeout=120.0):
    deadline = time.monotonic() + timeout
    status = None
    while time.monotonic() < deadline:
        status = _control(router_sock, "status")
        got = {m["id"] for m in (status or {}).get("members", [])}
        if got == set(want_ids):
            return status
        time.sleep(0.2)
    raise AssertionError(f"members never settled to {want_ids}: "
                         f"{status and status.get('members')}")


def _stop_member(proc):
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


def test_tcp_member_joins_drains_and_leaves(tmp_path):
    """The full elastic lifecycle against a pure-remote router
    (``--members 0``): a standalone TCP daemon announces itself, shows
    up as a remote ready member with a dialable advertised address,
    drains as ``draining`` (distinguished from ``dead``), and a
    deliberate ``leave`` removes it from the ring."""
    sock = str(tmp_path / "fleet.sock")
    router = _spawn_router(sock, members=0)
    member = None
    try:
        # Join: a long announce interval means exactly one announce —
        # the leave below must stick, not race a re-join.
        member = _spawn_member(sock, tmp_path, member_id="blue",
                               join_interval="3600")
        status = _wait_members(sock, {"blue"})
        blue = {m["id"]: m for m in status["members"]}["blue"]
        assert blue["remote"] is True
        assert blue["state"] == "ready"
        assert blue["capacity"] == 2
        assert transport.is_tcp(blue["socket"])
        host, port = transport.tcp_endpoint(blue["socket"])
        assert port > 0, "the :0 listener must advertise a real port"
        assert status["members_up"] == 1
        assert _counter_total(status, "fleet_joins_total") >= 1

        # member_status merges the member's own status with the
        # router-side state.
        ms = _control(sock, "member_status")
        block = ms["members"]["blue"]
        assert block["state"] == "ready"
        assert block["router_view"]["remote"] is True
        assert block.get("transport") == "tcp"

        # Drain: deliberately out of the ring, NOT dead.
        ack = _control(sock, "drain", {"member": "blue"})
        assert ack["ok"] and ack["member_ack"]["draining"] is True
        status = _control(sock, "status")
        blue = {m["id"]: m for m in status["members"]}["blue"]
        assert blue["state"] == "draining"
        assert status["members_draining"] == 1
        assert status["members_dead"] == 0
        assert status["members_up"] == 0

        # Leave: gone from the member table entirely.
        ack = _control(sock, "leave", {"member": "blue"})
        assert ack["ok"] and ack["member"] == "blue"
        status = _control(sock, "status")
        assert all(m["id"] != "blue" for m in status["members"])
    finally:
        if member is not None:
            _stop_member(member)
        _stop_router(router)


def test_tcp_member_sigterm_announces_leave(tmp_path):
    """SIGTERM to a joined member is a *deliberate* departure: its
    teardown sends ``leave``, so the router records a leave (never a
    crash eject) and the ring shrinks immediately."""
    sock = str(tmp_path / "fleet.sock")
    router = _spawn_router(
        sock, members=0,
        extra_env={"SEMMERGE_FLEET_HEALTH_INTERVAL": "0.3"})
    member = None
    try:
        member = _spawn_member(sock, tmp_path, member_id="ephem",
                               join_interval="0.4")
        _wait_members(sock, {"ephem"})
        _stop_member(member)
        member = None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            status = _control(sock, "status")
            if status and not any(m["id"] == "ephem"
                                  for m in status["members"]):
                break
            time.sleep(0.2)
        status = _control(sock, "status")
        assert all(m["id"] != "ephem" for m in status["members"])
        assert _counter_total(status, "fleet_failovers_total",
                              reason="leave") >= 1
    finally:
        if member is not None:
            _stop_member(member)
        _stop_router(router)


def test_router_status_reports_transport_block(tmp_path):
    sock = str(tmp_path / "fleet.sock")
    router = _spawn_router(sock, members=0)
    try:
        status = _control(sock, "status")
        t = status["transport"]
        assert t["tls"] is False
        assert t["connect_timeout_s"] > 0
        assert t["heartbeat_timeout_s"] > 0
        assert t["resends"] >= 0
        assert t["handoff_max"] >= 1
        assert isinstance(status["affinity_epoch"], int)
    finally:
        _stop_router(router)
