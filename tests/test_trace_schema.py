"""Wires scripts/check_trace_schema.py into tier-1: trace/events
artifacts produced by the real Tracer must validate, and schema drift
(malformed spans, histogram count mismatches) must be rejected."""
import importlib.util
import json
import pathlib
import subprocess
import sys

import pytest

from semantic_merge_tpu.obs import spans as obs_spans

_SCRIPT = (pathlib.Path(__file__).resolve().parent.parent
           / "scripts" / "check_trace_schema.py")


@pytest.fixture(scope="module")
def schema():
    spec = importlib.util.spec_from_file_location("check_trace_schema",
                                                  _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def artifacts(tmp_path):
    """Real artifacts from the real Tracer — what the CLI writes."""
    import semantic_merge_tpu.runtime.trace as trace_mod
    tracer = trace_mod.Tracer(enabled=True)
    with tracer.phase("snapshot"):
        pass
    with tracer.phase("merge", backend="host"):
        with obs_spans.span("scan", layer="frontend", files=2):
            pass
        obs_spans.event("cache", hits=1)
    tracer.count("conflicts", 0)
    trace = tmp_path / ".semmerge-trace.json"
    tracer.write(trace)
    return trace, tmp_path / ".semmerge-events.jsonl"


def test_real_artifacts_validate(schema, artifacts):
    trace, events = artifacts
    assert schema.validate_trace(json.loads(trace.read_text())) == []
    assert schema.validate_events(events.read_text().splitlines()) == []


def test_script_cli_exit_codes(artifacts):
    trace, events = artifacts
    ok = subprocess.run([sys.executable, str(_SCRIPT), str(trace),
                         str(events)], capture_output=True, text=True,
                        timeout=60)
    assert ok.returncode == 0, ok.stderr
    bad = trace.with_name("bad.json")
    bad.write_text("{}")
    fail = subprocess.run([sys.executable, str(_SCRIPT), str(bad)],
                          capture_output=True, text=True, timeout=60)
    assert fail.returncode == 1
    assert "missing key" in fail.stderr


def test_drifted_trace_is_rejected(schema, artifacts):
    trace, _ = artifacts
    data = json.loads(trace.read_text())

    broken = dict(data)
    broken.pop("phases")
    assert any("phases" in e for e in schema.validate_trace(broken))

    broken = json.loads(trace.read_text())
    broken["phases"][0]["seconds"] = "fast"
    assert any("seconds" in e for e in schema.validate_trace(broken))

    broken = json.loads(trace.read_text())
    broken["spans"][0]["status"] = "meh"
    assert any("status" in e for e in schema.validate_trace(broken))

    broken = json.loads(trace.read_text())
    hists = broken["metrics"]["histograms"]
    name = next(iter(hists))
    hists[name]["series"][0]["count"] += 1  # counts no longer sum up
    assert any("sum to count" in e for e in schema.validate_trace(broken))


def test_drifted_events_are_rejected(schema, artifacts):
    _, events = artifacts
    lines = events.read_text().splitlines()
    assert schema.validate_events(lines + ['{"type": "mystery"}'])
    assert schema.validate_events(["not json"])
    row = json.loads(next(line for line in lines
                          if '"type": "span"' in line or
                          '"type":"span"' in line))
    row.pop("thread")
    assert any("thread" in e
               for e in schema.validate_events([json.dumps(row)]))
