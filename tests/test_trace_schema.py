"""Wires scripts/check_trace_schema.py into tier-1: trace/events
artifacts produced by the real Tracer must validate, and schema drift
(malformed spans, histogram count mismatches) must be rejected."""
import importlib.util
import json
import pathlib
import subprocess
import sys

import pytest

from semantic_merge_tpu.obs import spans as obs_spans

_SCRIPT = (pathlib.Path(__file__).resolve().parent.parent
           / "scripts" / "check_trace_schema.py")


@pytest.fixture(scope="module")
def schema():
    spec = importlib.util.spec_from_file_location("check_trace_schema",
                                                  _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def artifacts(tmp_path):
    """Real artifacts from the real Tracer — what the CLI writes."""
    import semantic_merge_tpu.runtime.trace as trace_mod
    tracer = trace_mod.Tracer(enabled=True)
    with tracer.phase("snapshot"):
        pass
    with tracer.phase("merge", backend="host"):
        with obs_spans.span("scan", layer="frontend", files=2):
            pass
        obs_spans.event("cache", hits=1)
    tracer.count("conflicts", 0)
    trace = tmp_path / ".semmerge-trace.json"
    tracer.write(trace)
    return trace, tmp_path / ".semmerge-events.jsonl"


def test_real_artifacts_validate(schema, artifacts):
    trace, events = artifacts
    assert schema.validate_trace(json.loads(trace.read_text())) == []
    assert schema.validate_events(events.read_text().splitlines()) == []


def test_degradation_records_validate(schema, tmp_path):
    """A trace carrying the fault-containment layer's records — a
    ``degradation`` span (cli.py ladder) and the fault metric series —
    must validate; the span/labels are part of the documented schema."""
    from semantic_merge_tpu.obs import metrics as obs_metrics
    import semantic_merge_tpu.runtime.trace as trace_mod
    tracer = trace_mod.Tracer(enabled=True)
    with tracer.phase("merge", backend="tpu"):
        obs_spans.record("degradation", 0.0, layer="cli",
                         **{"from": "tpu", "to": "host",
                            "fault": "KernelFault", "stage": "kernel"})
    obs_metrics.REGISTRY.counter(
        "merge_degradations_total", "t").inc(
        1, **{"from": "tpu", "to": "host", "fault": "KernelFault"})
    obs_metrics.REGISTRY.counter(
        "subprocess_retries_total", "t").inc(1, method="diff")
    trace = tmp_path / ".semmerge-trace.json"
    tracer.write(trace)
    data = json.loads(trace.read_text())
    assert schema.validate_trace(data) == []
    assert schema.validate_degradations(data) == []
    names = {s["name"] for s in data["spans"]}
    assert "degradation" in names


def test_service_records_validate(schema, tmp_path):
    """A trace carrying the merge-service layer's records — the three
    ``service.*`` request spans and the service metric series — must
    validate; drifted shapes (renamed span, missing verb meta, labeled
    queue-depth gauge) are rejected."""
    from semantic_merge_tpu.obs import metrics as obs_metrics
    import semantic_merge_tpu.runtime.trace as trace_mod
    tracer = trace_mod.Tracer(enabled=True)
    with tracer.phase("merge", backend="host"):
        obs_spans.record("service.accept", 0.001, layer="service",
                         verb="semmerge")
        obs_spans.record("service.queue_wait", 0.0, layer="service",
                         verb="semmerge")
        obs_spans.record("service.execute", 0.25, layer="service",
                         verb="semmerge")
    obs_metrics.REGISTRY.counter("service_requests_total", "t").inc(
        1, verb="semmerge", outcome="ok")
    obs_metrics.REGISTRY.gauge("service_queue_depth", "t").set(0)
    obs_metrics.REGISTRY.counter("declcache_hits_total", "t").inc(3)
    trace = tmp_path / ".semmerge-trace.json"
    tracer.write(trace)
    data = json.loads(trace.read_text())
    assert schema.validate_trace(data) == []
    assert schema.validate_service(data) == []

    broken = json.loads(trace.read_text())
    for s in broken["spans"]:
        if s["name"] == "service.execute":
            s["name"] = "service.exec2"
    assert any("unknown service span" in e
               for e in schema.validate_service(broken))

    broken = json.loads(trace.read_text())
    for s in broken["spans"]:
        if s["name"].startswith("service."):
            s.get("meta", {}).pop("verb", None)
    assert any("verb" in e for e in schema.validate_service(broken))

    broken = json.loads(trace.read_text())
    gauge = broken["metrics"]["gauges"]["service_queue_depth"]
    gauge["series"][0]["labels"] = {"socket": "x"}
    assert any("no labels" in e for e in schema.validate_service(broken))


def test_batch_records_validate(schema, tmp_path, monkeypatch):
    """A REAL co-batched merge under the Tracer records the four
    ``batch.*`` spans and the batching metric series; the artifact
    passes ``validate_batch`` and drifted shapes (renamed span, missing
    ``requests`` meta, mislabeled outcome counter, labeled histogram)
    are rejected."""
    import threading

    import bench
    import semantic_merge_tpu.runtime.trace as trace_mod
    from semantic_merge_tpu import batch
    from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
    from semantic_merge_tpu.obs import metrics as obs_metrics

    monkeypatch.setenv("SEMMERGE_MESH", "off")
    snaps = bench.synth_repo(4, 2)
    backends = [TpuTSBackend(mesh=False) for _ in range(2)]
    for be in backends:
        be.merge(*snaps)  # warm before the scheduler exists: no batching
    tracer = trace_mod.Tracer(enabled=True)
    batch.activate(window_ms=100.0)
    try:
        with tracer.phase("merge", backend="tpu"):
            barrier = threading.Barrier(2)

            def work(be):
                barrier.wait()
                be.merge(*snaps)

            threads = [threading.Thread(target=work, args=(be,))
                       for be in backends]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
    finally:
        batch.deactivate()
    trace = tmp_path / ".semmerge-trace.json"
    tracer.write(trace)
    data = json.loads(trace.read_text())
    data["metrics"] = obs_metrics.REGISTRY.to_dict()
    assert schema.validate_trace(data) == []
    assert schema.validate_batch(data) == []
    names = {s["name"] for s in data["spans"]}
    # mesh-off here, so only the core four are guaranteed —
    # batch.mesh_build fires when a dispatch mesh forms (test_batch.py
    # covers the meshed artifact).
    assert set(schema.BATCH_CORE_SPANS) <= names, \
        f"a co-batched merge must record all core batch spans, got {names}"

    broken = json.loads(json.dumps(data))
    for s in broken["spans"]:
        if s["name"] == "batch.dispatch":
            s["name"] = "batch.dispatch2"
    assert any("unknown batch span" in e
               for e in schema.validate_batch(broken))

    broken = json.loads(json.dumps(data))
    for s in broken["spans"]:
        if s["name"].startswith("batch."):
            s.get("meta", {}).pop("requests", None)
    assert any("requests" in e for e in schema.validate_batch(broken))

    broken = json.loads(json.dumps(data))
    counter = broken["metrics"]["counters"]["batch_requests_total"]
    counter["series"][0]["labels"] = {"verb": "semmerge"}
    assert any("batch_requests_total" in e
               for e in schema.validate_batch(broken))

    broken = json.loads(json.dumps(data))
    hist = broken["metrics"]["histograms"]["batch_size"]
    hist["series"][0]["labels"] = {"bucket": "x"}
    assert any("batch_size" in e for e in schema.validate_batch(broken))


def test_resilience_records_validate(schema, tmp_path, monkeypatch):
    """REAL resilience primitives — a circuit breaker tripping open and
    recovering, a load-shed counter, the supervisor restart span —
    produce an artifact that passes ``validate_resilience``; drifted
    shapes (mislabeled shed counter, undocumented shed reason, breaker
    gauge without its ``rung`` label or with an out-of-range state,
    restart span without meta) are rejected."""
    import semantic_merge_tpu.runtime.trace as trace_mod
    from semantic_merge_tpu.obs import metrics as obs_metrics
    from semantic_merge_tpu.service import resilience

    monkeypatch.setenv("SEMMERGE_BREAKER", "on")
    monkeypatch.setenv("SEMMERGE_BREAKER_THRESHOLD", "2")
    # Cooldown long enough that a loaded box can't age the breaker into
    # half-open between record_failure and the open assert (0.01 flaked).
    monkeypatch.setenv("SEMMERGE_BREAKER_COOLDOWN", "0.25")
    board = resilience.BreakerBoard()
    tracer = trace_mod.Tracer(enabled=True)
    with tracer.phase("merge", backend="host"):
        assert board.allow("fused")
        board.record_failure("fused")
        board.record_failure("fused")   # trips open
        assert not board.allow("fused")
        import time
        time.sleep(0.3)
        assert board.allow("fused")     # half-open probe
        board.record_success("fused")   # closes
        obs_spans.record("supervisor.restart", 0.2, layer="service",
                         reason="crash", attempt=1, rc=12)
    obs_metrics.REGISTRY.counter("service_shed_total", "t").inc(
        1, reason="rss-soft")
    obs_metrics.REGISTRY.counter("service_idempotent_replays_total",
                                 "t").inc(1)
    obs_metrics.REGISTRY.gauge("service_rss_mb", "t").set(123.0)
    trace = tmp_path / ".semmerge-trace.json"
    tracer.write(trace)
    data = json.loads(trace.read_text())
    data["metrics"] = obs_metrics.REGISTRY.to_dict()
    assert schema.validate_trace(data) == []
    assert schema.validate_resilience(data) == []
    counters = data["metrics"]["counters"]
    tos = {s["labels"]["to"]
           for s in counters["breaker_transitions_total"]["series"]}
    assert {"open", "half-open", "closed"} <= tos

    broken = json.loads(json.dumps(data))
    shed = broken["metrics"]["counters"]["service_shed_total"]
    shed["series"][0]["labels"] = {"cause": "rss-soft"}
    assert any("service_shed_total" in e
               for e in schema.validate_resilience(broken))

    broken = json.loads(json.dumps(data))
    shed = broken["metrics"]["counters"]["service_shed_total"]
    shed["series"][0]["labels"] = {"reason": "because"}
    assert any("'because'" in e for e in schema.validate_resilience(broken))

    broken = json.loads(json.dumps(data))
    gauge = broken["metrics"]["gauges"]["breaker_state"]
    gauge["series"][0]["labels"] = {}
    assert any("breaker_state" in e
               for e in schema.validate_resilience(broken))

    broken = json.loads(json.dumps(data))
    gauge = broken["metrics"]["gauges"]["breaker_state"]
    gauge["series"][0]["value"] = 7
    assert any("not in (0, 1, 2)" in e
               for e in schema.validate_resilience(broken))

    broken = json.loads(json.dumps(data))
    for s in broken["spans"]:
        if s["name"] == "supervisor.restart":
            s["meta"] = {}
    assert any("supervisor.restart" in e
               for e in schema.validate_resilience(broken))


def test_script_cli_exit_codes(artifacts):
    trace, events = artifacts
    ok = subprocess.run([sys.executable, str(_SCRIPT), str(trace),
                         str(events)], capture_output=True, text=True,
                        timeout=60)
    assert ok.returncode == 0, ok.stderr
    bad = trace.with_name("bad.json")
    bad.write_text("{}")
    fail = subprocess.run([sys.executable, str(_SCRIPT), str(bad)],
                          capture_output=True, text=True, timeout=60)
    assert fail.returncode == 1
    assert "missing key" in fail.stderr


def test_drifted_trace_is_rejected(schema, artifacts):
    trace, _ = artifacts
    data = json.loads(trace.read_text())

    broken = dict(data)
    broken.pop("phases")
    assert any("phases" in e for e in schema.validate_trace(broken))

    broken = json.loads(trace.read_text())
    broken["phases"][0]["seconds"] = "fast"
    assert any("seconds" in e for e in schema.validate_trace(broken))

    broken = json.loads(trace.read_text())
    broken["spans"][0]["status"] = "meh"
    assert any("status" in e for e in schema.validate_trace(broken))

    broken = json.loads(trace.read_text())
    hists = broken["metrics"]["histograms"]
    name = next(iter(hists))
    hists[name]["series"][0]["count"] += 1  # counts no longer sum up
    assert any("sum to count" in e for e in schema.validate_trace(broken))


def test_apply_phase_spans_in_real_trace(schema, tmp_path):
    """A real columnar-apply merge under ``--trace`` must record the
    apply-layer span names BENCH and the runbook reference
    (``apply_ops`` + ``apply_columnar``) — renaming them is schema
    drift. The artifact must also still validate structurally."""
    import pathlib
    import tempfile

    import bench
    import semantic_merge_tpu.runtime.trace as trace_mod
    from semantic_merge_tpu.backends.base import run_merge
    from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
    from semantic_merge_tpu.runtime.applier import apply_ops

    base, left, right = bench.synth_repo(6, 2)
    tracer = trace_mod.Tracer(enabled=True)
    with tracer.phase("merge", backend="tpu"):
        _, composed, _ = run_merge(TpuTSBackend(mesh=False), base, left,
                                   right, base_rev="r", seed="s",
                                   timestamp="2026-01-01T00:00:00Z")
    with tracer.phase("materialize"):
        tree = pathlib.Path(tempfile.mkdtemp())
        for f in base.files:
            p = tree / f["path"]
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(f["content"])
        apply_ops(tree, composed)
    trace = tmp_path / ".semmerge-trace.json"
    tracer.write(trace)
    data = json.loads(trace.read_text())
    assert schema.validate_trace(data) == []
    assert schema.validate_phase_coverage(
        data, ("apply_ops", "apply_columnar")) == []
    # Drift detection: a renamed span surfaces as a coverage error.
    assert schema.validate_phase_coverage(data, ("apply_ops_v2",))


def test_postmortem_bundle_validates(schema, tmp_path, monkeypatch):
    """A REAL flight-recorder bundle — ring rows from actual spans, a
    typed fault, breaker states — passes ``validate_postmortem``;
    drifted shapes (undocumented reason, bad breaker state, ring row
    missing a key, wrong schema version) are rejected."""
    from semantic_merge_tpu.errors import ParseFault
    from semantic_merge_tpu.obs import flight as obs_flight
    monkeypatch.delenv(obs_flight.ENV_DIR, raising=False)
    monkeypatch.setenv(obs_flight.ENV_RING, "64")
    obs_flight.reset()
    try:
        with obs_spans.request_scope("req-abc123"):
            obs_spans.record("scan", 0.01, layer="frontend", files=2)
            fault = None
            try:
                with obs_spans.span("apply", layer="cli"):
                    raise ParseFault("injected", stage="scan",
                                     cause="injected")
            except ParseFault as exc:
                fault = exc
            path = obs_flight.dump("req-abc123", "fault-escape",
                                   fault=fault,
                                   breakers={"fused": "open"},
                                   root=tmp_path)
    finally:
        obs_flight.reset()
    assert path is not None and path.parent.name == ".semmerge-postmortem"
    data = json.loads(path.read_text())
    assert schema.validate_postmortem(data) == []
    assert data["trace_id"] == "req-abc123"
    assert data["fault"]["type"] == "ParseFault"
    rows = {r["name"]: r for r in data["spans"]}
    assert rows["scan"]["trace_id"] == "req-abc123"
    assert rows["apply"]["status"] == "error"

    broken = json.loads(json.dumps(data))
    broken["reason"] = "bad-day"
    assert any("reason" in e for e in schema.validate_postmortem(broken))

    broken = json.loads(json.dumps(data))
    broken["breakers"] = {"fused": "exploded"}
    assert any("breakers" in e for e in schema.validate_postmortem(broken))

    broken = json.loads(json.dumps(data))
    broken["spans"][0].pop("thread")
    assert any("thread" in e for e in schema.validate_postmortem(broken))

    broken = json.loads(json.dumps(data))
    broken["schema"] = 2
    assert any("schema" in e for e in schema.validate_postmortem(broken))

    assert any("trace_id" in e for e in schema.validate_postmortem(
        {**data, "trace_id": ""}))


def test_postmortem_cli_subcommand(schema, tmp_path, monkeypatch):
    from semantic_merge_tpu.obs import flight as obs_flight
    monkeypatch.delenv(obs_flight.ENV_DIR, raising=False)
    path = obs_flight.dump("cli-check", "degradation", root=tmp_path)
    ok = subprocess.run([sys.executable, str(_SCRIPT),
                         "validate_postmortem", str(path)],
                        capture_output=True, text=True, timeout=60)
    assert ok.returncode == 0, ok.stderr
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    fail = subprocess.run([sys.executable, str(_SCRIPT),
                           "validate_postmortem", str(bad)],
                          capture_output=True, text=True, timeout=60)
    assert fail.returncode == 1
    assert "missing key" in fail.stderr


def test_request_traces_validator(schema, tmp_path):
    """Two traces written under distinct request scopes validate as an
    isolated set; shared ids, missing ids, and foreign-id-stamped spans
    are rejected — the concurrent-daemon contract."""
    import semantic_merge_tpu.runtime.trace as trace_mod

    def one_trace(tid):
        with obs_spans.request_scope(tid):
            tracer = trace_mod.Tracer(enabled=True)
            with tracer.phase("merge", backend="host"):
                obs_spans.record("service.queue_wait", 0.001,
                                 layer="service", verb="semmerge")
            path = tmp_path / f"{tid}.json"
            tracer.write(path)
        return json.loads(path.read_text())

    traces = [one_trace("req-a"), one_trace("req-b")]
    assert schema.validate_request_traces(traces) == []
    assert [t["trace_id"] for t in traces] == ["req-a", "req-b"]

    assert any("non-empty" in e for e in schema.validate_request_traces([]))

    broken = json.loads(json.dumps(traces))
    broken[1]["trace_id"] = "req-a"
    assert any("duplicates" in e
               for e in schema.validate_request_traces(broken))

    broken = json.loads(json.dumps(traces))
    broken[0]["trace_id"] = None
    assert any("trace_id" in e
               for e in schema.validate_request_traces(broken))

    broken = json.loads(json.dumps(traces))
    broken[0]["spans"][0]["meta"]["trace_id"] = "req-b"
    assert any("interleaved" in e
               for e in schema.validate_request_traces(broken))


def test_slo_records_validate(schema, tmp_path):
    """REAL SLO engine output — burn gauges + trip counter published by
    an evaluating engine, plus a daemon-status-shaped slo block — passes
    ``validate_slo``; drifted shapes (mislabeled gauge, undocumented
    window, negative burn, malformed status block) are rejected."""
    from semantic_merge_tpu.obs import metrics as obs_metrics
    from semantic_merge_tpu.obs import slo as obs_slo

    engine = obs_slo.SloEngine(
        obs_slo.parse_objectives("merge:p99<1ms,err<1%"))
    for _ in range(3):
        engine.observe("semmerge", 0.5)
    status = engine.evaluate(consume_edges=True)
    payload = {"metrics": obs_metrics.REGISTRY.to_dict(),
               "slo": engine.status()}
    assert schema.validate_slo(payload) == []
    assert status["newly_tripped"], "engine must have tripped"

    broken = json.loads(json.dumps(payload))
    gauge = broken["metrics"]["gauges"]["slo_burn_rate"]
    gauge["series"][0]["labels"] = {"objective": "x"}
    assert any("slo_burn_rate" in e for e in schema.validate_slo(broken))

    broken = json.loads(json.dumps(payload))
    gauge = broken["metrics"]["gauges"]["slo_burn_rate"]
    gauge["series"][0]["labels"]["window"] = "medium"
    assert any("'medium'" in e for e in schema.validate_slo(broken))

    broken = json.loads(json.dumps(payload))
    gauge = broken["metrics"]["gauges"]["slo_burn_rate"]
    gauge["series"][0]["value"] = -1.0
    assert any(">= 0" in e for e in schema.validate_slo(broken))

    broken = json.loads(json.dumps(payload))
    trips = broken["metrics"]["counters"]["slo_burn_trips_total"]
    trips["series"][0]["labels"] = {"objective": "x", "verb": "y"}
    assert any("slo_burn_trips_total" in e
               for e in schema.validate_slo(broken))

    broken = json.loads(json.dumps(payload))
    broken["slo"]["healthy"] = "yes"
    assert any("healthy" in e for e in schema.validate_slo(broken))

    broken = json.loads(json.dumps(payload))
    broken["slo"]["objectives"][0]["burn_fast"] = -2.0
    assert any("burn_fast" in e for e in schema.validate_slo(broken))

    # The CLI subcommand wires the same validator.
    good = tmp_path / "status.json"
    good.write_text(json.dumps(payload))
    ok = subprocess.run([sys.executable, str(_SCRIPT), "validate_slo",
                         str(good)], capture_output=True, text=True,
                        timeout=60)
    assert ok.returncode == 0, ok.stderr
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(broken))
    fail = subprocess.run([sys.executable, str(_SCRIPT), "validate_slo",
                           str(bad)], capture_output=True, text=True,
                          timeout=60)
    assert fail.returncode == 1
    assert "burn_fast" in fail.stderr


def test_slo_burn_reason_is_documented(schema):
    from semantic_merge_tpu.obs import flight as obs_flight
    assert "slo-burn" in schema.POSTMORTEM_REASONS
    assert tuple(schema.POSTMORTEM_REASONS) == tuple(obs_flight.REASONS)


def test_bench_record_validates(schema):
    """A representative BENCH record — with the additive host-tail,
    apply-phase, and strict-preset fields — validates; broken shapes
    are rejected field by field."""
    record = {
        "metric": "files merged/sec/chip (synthetic)", "value": 123.4,
        "unit": "files/sec", "vs_baseline": 5.1, "parity": True,
        "phases_ms": {"scan_encode": 20.0, "kernel": 190.0,
                      "serialize": 50.0, "compose_materialize": 12.0,
                      "apply_plan": 11.0},
        "host_phases_ms": {"build_and_diff": 600.0},
        "host_tail_ms": 90.0, "device_roundtrip_ms": 0.1,
        "overlap": {"host_workers": 8, "worker_ms": 40.0,
                    "hidden_ms": 30.0},
        "strict_ms": 900.0, "nonstrict_ms": 500.0,
        "strict_conflicts": 0, "strict_motion_ops": 2,
        "slo_overhead_pct": 0.4, "slo_dark_ms": 100.0, "slo_on_ms": 100.4,
    }
    assert schema.validate_bench(record) == []
    assert schema.validate_bench({**record, "slo_overhead_pct": "low"})
    for name in schema.APPLY_PHASE_SPANS:
        assert schema.validate_bench(
            {**record, "phases_ms": {name: -1.0}})
    assert schema.validate_bench({**record, "parity": "yes"})
    assert schema.validate_bench({**record, "overlap": {"worker_ms": 1.0}})
    assert schema.validate_bench({**record, "strict_ms": "fast"})
    missing = dict(record)
    missing.pop("vs_baseline")
    assert any("vs_baseline" in e for e in schema.validate_bench(missing))


def test_bench_cli_flag(schema, artifacts, tmp_path):
    bench_json = tmp_path / "bench.json"
    bench_json.write_text(json.dumps({
        "metric": "m", "value": 1.0, "unit": "files/sec",
        "vs_baseline": 1.0}))
    trace, events = artifacts
    ok = subprocess.run([sys.executable, str(_SCRIPT), str(trace),
                         str(events), "--bench", str(bench_json)],
                        capture_output=True, text=True, timeout=60)
    assert ok.returncode == 0, ok.stderr
    bench_json.write_text(json.dumps({"metric": "m"}))
    fail = subprocess.run([sys.executable, str(_SCRIPT), str(trace),
                           "--bench", str(bench_json)],
                          capture_output=True, text=True, timeout=60)
    assert fail.returncode == 1
    assert "bench:" in fail.stderr


def _conflict_row():
    return {"id": "c1", "category": "DivergentRename", "symbolId": "sym",
            "addressIds": ["src/util.ts::foo::0"], "opA": {}, "opB": {},
            "minimalSlice": {}, "suggestions": []}


def _v2_conflicts_payload():
    return {
        "schema_version": 2,
        "conflicts": [_conflict_row()],
        "resolutions": [{
            "conflict_id": "c1", "category": "DivergentRename",
            "resolver": "search", "status": "accepted", "cause": None,
            "candidate": {"id": "keepA", "label": "Rename to bar",
                          "rationale": "2 references", "drop": ["op-b"],
                          "replace": []},
            "candidates": 2, "scores": {"keepA": 2, "keepB": 1},
            "gates": [
                {"gate": "recompose", "ok": True, "ms": 1.2},
                {"gate": "parity", "ok": True, "ms": 0.4},
                {"gate": "typecheck", "ok": True, "ms": 3.0},
                {"gate": "format", "ok": True, "ms": 0.2},
            ]}],
    }


def test_conflicts_artifact_validates(schema, tmp_path):
    """Both legal artifact shapes pass ``validate_conflicts`` — the
    legacy bare array (resolution tier not run, byte-identical to the
    reference) and the v2 object with the ``resolutions`` audit block —
    and drift is rejected field by field; the CLI subcommand wires the
    same validator."""
    assert schema.validate_conflicts([_conflict_row()]) == []
    v2 = _v2_conflicts_payload()
    assert schema.validate_conflicts(v2) == []

    assert any("schema_version" in e for e in schema.validate_conflicts(
        {**v2, "schema_version": 3}))
    assert any("missing key" in e for e in schema.validate_conflicts(
        {**v2, "conflicts": [{}]}))

    broken = json.loads(json.dumps(v2))
    broken["resolutions"][0]["status"] = "maybe"
    assert any("status" in e for e in schema.validate_conflicts(broken))

    broken = json.loads(json.dumps(v2))
    broken["resolutions"][0]["cause"] = "tie"  # accepted + cause: illegal
    assert any("null" in e for e in schema.validate_conflicts(broken))

    broken = json.loads(json.dumps(v2))
    broken["resolutions"][0]["status"] = "rejected"
    broken["resolutions"][0]["cause"] = None
    assert any("non-empty" in e for e in schema.validate_conflicts(broken))

    broken = json.loads(json.dumps(v2))
    gates = broken["resolutions"][0]["gates"]
    gates[0], gates[1] = gates[1], gates[0]
    assert any("documented order" in e
               for e in schema.validate_conflicts(broken))

    broken = json.loads(json.dumps(v2))
    broken["resolutions"][0]["gates"][0]["gate"] = "vibes"
    assert any("'vibes'" in e for e in schema.validate_conflicts(broken))

    broken = json.loads(json.dumps(v2))
    broken["resolutions"][0]["gates"][0]["ms"] = -1
    assert any("ms" in e for e in schema.validate_conflicts(broken))

    good = tmp_path / "conflicts.json"
    good.write_text(json.dumps(v2))
    ok = subprocess.run([sys.executable, str(_SCRIPT),
                         "validate_conflicts", str(good)],
                        capture_output=True, text=True, timeout=60)
    assert ok.returncode == 0, ok.stderr
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"schema_version": 2}))
    fail = subprocess.run([sys.executable, str(_SCRIPT),
                           "validate_conflicts", str(bad)],
                          capture_output=True, text=True, timeout=60)
    assert fail.returncode == 1
    assert "conflicts" in fail.stderr


def test_resolver_fault_reason_and_metric_documented(schema):
    from semantic_merge_tpu.obs import flight as obs_flight
    assert "resolver-fault" in schema.POSTMORTEM_REASONS
    assert tuple(schema.POSTMORTEM_REASONS) == tuple(obs_flight.REASONS)
    assert schema.FAULT_METRIC_LABELS["resolutions_total"] == \
        ("category", "outcome")


def test_drifted_events_are_rejected(schema, artifacts):
    _, events = artifacts
    lines = events.read_text().splitlines()
    assert schema.validate_events(lines + ['{"type": "mystery"}'])
    assert schema.validate_events(["not json"])
    row = json.loads(next(line for line in lines
                          if '"type": "span"' in line or
                          '"type":"span"' in line))
    row.pop("thread")
    assert any("thread" in e
               for e in schema.validate_events([json.dumps(row)]))


def test_fleet_records_validate(schema, tmp_path):
    """A trace carrying the fleet-router layer's records — the three
    ``fleet.*`` spans, the fleet metric series, and a WAL history —
    must validate; drifted shapes (undocumented span/reason, labeled
    gauge, malformed WAL record) are rejected field by field. The CLI
    subcommand wires the same validator."""
    from semantic_merge_tpu.fleet import wal as fleet_wal
    from semantic_merge_tpu.obs import metrics as obs_metrics
    import semantic_merge_tpu.runtime.trace as trace_mod
    tracer = trace_mod.Tracer(enabled=True)
    with tracer.phase("route"):
        obs_spans.record("fleet.route", 0.01, layer="fleet",
                         verb="semmerge", member="m0")
        obs_spans.record("fleet.failover", 0.0, layer="fleet",
                         reason="transport", member="m1")
        obs_spans.record("fleet.hedge", 0.0, layer="fleet",
                         member="m2", won=True)
    obs_metrics.REGISTRY.counter("fleet_failovers_total", "t").inc(
        1, reason="crash")
    obs_metrics.REGISTRY.counter("fleet_rehash_moves_total", "t").inc(2)
    obs_metrics.REGISTRY.counter("fleet_hedges_total", "t").inc(1)
    obs_metrics.REGISTRY.counter("fleet_hedge_wins_total", "t").inc(1)
    obs_metrics.REGISTRY.counter("fleet_wal_replayed_total", "t").inc(1)
    obs_metrics.REGISTRY.gauge("fleet_members", "t").set(3)
    trace = tmp_path / ".semmerge-trace.json"
    tracer.write(trace)
    data = json.loads(trace.read_text())
    # A REAL WAL history rides along (router status/chaos audit shape).
    wal_dir = str(tmp_path / "wal")
    w = fleet_wal.WriteAheadLog(wal_dir)
    w.open()
    w.record_request("k1", "semmerge", {"argv": ["a", "b", "c"]}, "t1")
    w.record_dispatch("k1", "m0")
    w.ack("k1")
    w.close()
    data["wal"] = fleet_wal.read_records(wal_dir)
    assert data["wal"], "expected journal records"
    assert schema.validate_trace(data) == []
    assert schema.validate_fleet(data) == []

    broken = json.loads(json.dumps(data))
    for s in broken["spans"]:
        if s["name"] == "fleet.route":
            s["name"] = "fleet.rout3"
    assert any("unknown fleet span" in e
               for e in schema.validate_fleet(broken))

    broken = json.loads(json.dumps(data))
    for s in broken["spans"]:
        if s["name"] == "fleet.failover":
            s["meta"]["reason"] = "mystery"
    assert any("mystery" in e for e in schema.validate_fleet(broken))

    broken = json.loads(json.dumps(data))
    for s in broken["spans"]:
        if s["name"] == "fleet.hedge":
            s["meta"]["won"] = "yes"
    assert any("boolean" in e for e in schema.validate_fleet(broken))

    broken = json.loads(json.dumps(data))
    fo = broken["metrics"]["counters"]["fleet_failovers_total"]
    fo["series"][0]["labels"] = {"reason": "crash", "member": "m0"}
    assert any("fleet_failovers_total" in e
               for e in schema.validate_fleet(broken))

    broken = json.loads(json.dumps(data))
    gauge = broken["metrics"]["gauges"]["fleet_members"]
    gauge["series"][0]["labels"] = {"socket": "x"}
    assert any("no labels" in e for e in schema.validate_fleet(broken))

    broken = json.loads(json.dumps(data))
    broken["wal"].append({"kind": "mystery", "key": "k2", "t": 1.0})
    assert any("mystery" in e for e in schema.validate_fleet(broken))

    broken = json.loads(json.dumps(data))
    broken["wal"] = [{"kind": "request", "key": "k1", "t": 1.0}]
    assert any("missing" in e for e in schema.validate_fleet(broken))

    # The CLI subcommand wires the same validator.
    good = tmp_path / "fleet.json"
    good.write_text(json.dumps(data))
    ok = subprocess.run([sys.executable, str(_SCRIPT), "validate_fleet",
                         str(good)], capture_output=True, text=True,
                        timeout=60)
    assert ok.returncode == 0, ok.stderr
    bad = tmp_path / "fleet-bad.json"
    bad.write_text(json.dumps(broken))
    fail = subprocess.run([sys.executable, str(_SCRIPT),
                           "validate_fleet", str(bad)],
                          capture_output=True, text=True, timeout=60)
    assert fail.returncode == 1
    assert "missing" in fail.stderr


def test_fleet_reasons_and_shed_draining_documented(schema):
    """The fleet-era additions to the shared taxonomies: postmortem
    reason ``fleet-failover`` (mirrored from obs/flight REASONS),
    shed reason ``draining`` (a drained member's admission close),
    and the documented failover-reason set."""
    from semantic_merge_tpu.obs import flight as obs_flight
    assert "fleet-failover" in schema.POSTMORTEM_REASONS
    assert tuple(schema.POSTMORTEM_REASONS) == tuple(obs_flight.REASONS)
    assert "draining" in schema.SHED_REASONS
    assert set(schema.FLEET_SPAN_META) == set(schema.FLEET_SPANS)
    assert schema.FLEET_METRIC_LABELS["fleet_failovers_total"] == \
        ("reason",)
    assert tuple(schema.FLEET_WAL_KINDS) == \
        tuple(schema.FLEET_WAL_REQUIRED)
    assert tuple(schema.FLEET_RELAY_OUTCOMES) == ("ok", "late",
                                                  "transport")


def _stitched_artifact():
    """A real stitched tree: member-side recorder shipped as dicts and
    grafted into a router-side recorder, the router.py code path."""
    member = obs_spans.SpanRecorder(detailed=True)
    with obs_spans.request_scope("ab12cd34ab12cd34", member):
        with obs_spans.span("service.execute", layer="service"):
            with obs_spans.span("worker.diff", layer="worker"):
                pass
    router = obs_spans.SpanRecorder(detailed=False)
    obs_spans.record_into(router, "fleet.wal_fsync", 0.001, t_start=0.0,
                          layer="fleet")
    obs_spans.record_into(router, "fleet.relay", 0.4, t_start=0.001,
                          layer="fleet", member="m0", attempt=1,
                          outcome="ok")
    obs_spans.record_into(router, "fleet.route", 0.5, t_start=0.001,
                          layer="fleet", verb="semmerge", member="m0",
                          attempt=1)
    router.absorb_dicts(member.span_dicts(), t_base=0.05, member="m0",
                        attempt=1)
    return {"schema": 1, "kind": "fleet-trace",
            "trace_id": "ab12cd34ab12cd34", "router_pid": 1234,
            "socket": "/tmp/fleet.sock",
            "spans": router.span_dicts()}


def test_fleet_trace_validator(schema, tmp_path):
    """The stitched-artifact tier: a grafted tree validates; trees
    missing the graft meta (member/attempt on grafted spans), the
    router spans, or the member spans are rejected; hedged-loser and
    relay outcomes stay in the documented sets. The CLI subcommand
    wires the same validator."""
    data = _stitched_artifact()
    assert schema.validate_fleet_trace(data) == []

    broken = json.loads(json.dumps(data))
    for s in broken["spans"]:
        if s["name"] == "service.execute":
            del s["meta"]["member"]
    assert any("graft meta 'member'" in e
               for e in schema.validate_fleet_trace(broken))

    broken = json.loads(json.dumps(data))
    for s in broken["spans"]:
        if s["layer"] == "worker":
            s["meta"]["attempt"] = 0
    assert any("attempt" in e
               for e in schema.validate_fleet_trace(broken))

    broken = json.loads(json.dumps(data))
    broken["spans"] = [s for s in broken["spans"]
                       if s["layer"] == "fleet"]
    assert any("no grafted member span" in e
               for e in schema.validate_fleet_trace(broken))

    broken = json.loads(json.dumps(data))
    broken["spans"] = [s for s in broken["spans"]
                       if s["layer"] != "fleet"]
    assert any("no fleet." in e
               for e in schema.validate_fleet_trace(broken))

    broken = json.loads(json.dumps(data))
    for s in broken["spans"]:
        if s["name"] == "fleet.relay":
            s["meta"]["outcome"] = "mystery"
    assert any("mystery" in e for e in schema.validate_fleet_trace(broken))

    # A hedged loser whose outcome contradicts ``won`` is drift.
    broken = json.loads(json.dumps(data))
    broken["spans"].append(dict(broken["spans"][0],
                                name="fleet.hedge", layer="fleet",
                                meta={"member": "m1", "won": False,
                                      "outcome": "won"}))
    assert any("contradicts" in e
               for e in schema.validate_fleet_trace(broken))

    assert schema.validate_fleet_trace([]) \
        == ["fleet-trace: top level must be a JSON object"]
    assert any("schema" in e
               for e in schema.validate_fleet_trace({"schema": 7}))

    good = tmp_path / "stitched.json"
    good.write_text(json.dumps(data))
    ok = subprocess.run([sys.executable, str(_SCRIPT),
                         "validate_fleet_trace", str(good)],
                        capture_output=True, text=True, timeout=60)
    assert ok.returncode == 0, ok.stderr
    bad = tmp_path / "stitched-bad.json"
    bad.write_text(json.dumps(broken))
    fail = subprocess.run([sys.executable, str(_SCRIPT),
                           "validate_fleet_trace", str(bad)],
                          capture_output=True, text=True, timeout=60)
    assert fail.returncode == 1
    usage = subprocess.run([sys.executable, str(_SCRIPT),
                            "validate_fleet_trace"],
                           capture_output=True, text=True, timeout=60)
    assert usage.returncode == 2


def test_transport_records_validate(schema, tmp_path):
    """The cross-host transport plane (ISSUE 19): the membership spans
    (``fleet.join`` / ``fleet.handoff`` / ``fleet.heartbeat``), the
    ``fleet_transport_*`` counters, and the ``fleet_member_draining``
    gauge validate; drifted shapes (undocumented op/outcome/reason,
    capacity < 1, labeled resend counter, non-binary draining gauge)
    are rejected field by field. The CLI subcommand wires the same
    validator next to ``validate_fleet``."""
    from semantic_merge_tpu.obs import metrics as obs_metrics
    import semantic_merge_tpu.runtime.trace as trace_mod
    tracer = trace_mod.Tracer(enabled=True)
    with tracer.phase("route"):
        obs_spans.record("fleet.join", 0.01, layer="fleet",
                         member="blue", address="tcp://10.0.0.7:7633",
                         capacity=2)
        obs_spans.record("fleet.handoff", 0.005, layer="fleet",
                         member="blue", reason="join", ok=True)
        obs_spans.record("fleet.heartbeat", 0.002, layer="fleet",
                         member="blue", outcome="timeout")
    obs_metrics.REGISTRY.counter("fleet_transport_errors_total",
                                 "t").inc(1, op="dial")
    obs_metrics.REGISTRY.counter("fleet_transport_resends_total",
                                 "t").inc(1)
    obs_metrics.REGISTRY.counter("fleet_heartbeats_total",
                                 "t").inc(1, outcome="ok")
    obs_metrics.REGISTRY.counter("fleet_handoffs_total",
                                 "t").inc(1, reason="leave")
    obs_metrics.REGISTRY.counter("fleet_affinity_misses_total",
                                 "t").inc(1)
    obs_metrics.REGISTRY.counter("fleet_joins_total", "t").inc(1)
    obs_metrics.REGISTRY.gauge("fleet_member_draining",
                               "t").set(1.0, member="blue")
    trace = tmp_path / ".semmerge-trace.json"
    tracer.write(trace)
    data = json.loads(trace.read_text())
    assert schema.validate_trace(data) == []
    assert schema.validate_transport(data) == []
    # The membership spans are fleet spans too — the fleet validator
    # must accept the same artifact.
    assert schema.validate_fleet(data) == []

    def spans_named(doc, name):
        return [r for r in doc["spans"] if r.get("name") == name]

    broken = json.loads(json.dumps(data))
    del spans_named(broken, "fleet.join")[0]["meta"]["member"]
    assert any("missing 'member'" in e
               for e in schema.validate_transport(broken))

    broken = json.loads(json.dumps(data))
    spans_named(broken, "fleet.join")[0]["meta"]["capacity"] = 0
    assert any("capacity" in e
               for e in schema.validate_transport(broken))

    broken = json.loads(json.dumps(data))
    spans_named(broken, "fleet.handoff")[0]["meta"]["reason"] = "mystery"
    assert any("mystery" in e for e in schema.validate_transport(broken))

    broken = json.loads(json.dumps(data))
    spans_named(broken, "fleet.handoff")[0]["meta"]["ok"] = "yes"
    assert any("boolean" in e for e in schema.validate_transport(broken))

    broken = json.loads(json.dumps(data))
    spans_named(broken, "fleet.heartbeat")[0]["meta"]["outcome"] = "slowish"
    assert any("slowish" in e for e in schema.validate_transport(broken))

    broken = json.loads(json.dumps(data))
    series = broken["metrics"]["counters"][
        "fleet_transport_errors_total"]["series"]
    series[0]["labels"] = {"op": "telepathy"}
    assert any("telepathy" in e
               for e in schema.validate_transport(broken))

    broken = json.loads(json.dumps(data))
    series = broken["metrics"]["counters"][
        "fleet_transport_resends_total"]["series"]
    series[0]["labels"] = {"member": "blue"}
    assert any("fleet_transport_resends_total" in e
               for e in schema.validate_transport(broken))

    broken = json.loads(json.dumps(data))
    series = broken["metrics"]["counters"][
        "fleet_heartbeats_total"]["series"]
    series[0]["labels"] = {"outcome": "shrug"}
    assert any("shrug" in e for e in schema.validate_transport(broken))

    broken = json.loads(json.dumps(data))
    gauge = broken["metrics"]["gauges"]["fleet_member_draining"]
    gauge["series"][0]["labels"] = {"socket": "x"}
    assert any("fleet_member_draining" in e
               for e in schema.validate_transport(broken))

    broken = json.loads(json.dumps(data))
    gauge = broken["metrics"]["gauges"]["fleet_member_draining"]
    gauge["series"][0]["value"] = 0.5
    assert any("fleet_member_draining" in e
               for e in schema.validate_transport(broken))

    assert schema.validate_transport([]) \
        == ["transport: top level must be a JSON object"]

    # The CLI subcommand wires the same validator.
    good = tmp_path / "transport.json"
    good.write_text(json.dumps(data))
    ok = subprocess.run([sys.executable, str(_SCRIPT),
                         "validate_transport", str(good)],
                        capture_output=True, text=True, timeout=60)
    assert ok.returncode == 0, ok.stderr
    bad = tmp_path / "transport-bad.json"
    bad.write_text(json.dumps(broken))
    fail = subprocess.run([sys.executable, str(_SCRIPT),
                           "validate_transport", str(bad)],
                          capture_output=True, text=True, timeout=60)
    assert fail.returncode == 1
    assert "fleet_member_draining" in fail.stderr


def test_transport_taxonomies_documented(schema):
    """The transport validator's documented sets mirror the living
    code: span meta keys, op labels, heartbeat outcomes, and the
    handoff-reason superset of the failover reasons."""
    from semantic_merge_tpu.fleet import transport
    assert {"fleet.join", "fleet.handoff", "fleet.heartbeat"} \
        <= set(schema.FLEET_SPANS)
    assert schema.FLEET_SPAN_META["fleet.join"] == ("member", "address",
                                                    "capacity")
    assert tuple(schema.TRANSPORT_OPS) == tuple(transport.OPS)
    assert tuple(schema.TRANSPORT_HEARTBEAT_OUTCOMES) \
        == tuple(transport.HEARTBEAT_OUTCOMES)
    assert set(schema.FLEET_FAILOVER_REASONS) \
        <= set(schema.TRANSPORT_HANDOFF_REASONS) | {"crash", "health"}
    assert "partition" in schema.FLEET_FAILOVER_REASONS
    assert "leave" in schema.FLEET_FAILOVER_REASONS


def test_export_validator(schema, tmp_path):
    """The OTLP tier: real ``obs.export`` payloads (traces and metrics)
    validate; malformed ids, reversed timestamps, and kind-less metrics
    are rejected. The CLI subcommand wires the same validator."""
    from semantic_merge_tpu.obs import export as obs_export
    from semantic_merge_tpu.obs import metrics as obs_metrics
    data = _stitched_artifact()
    traces = obs_export.spans_to_otlp(data["trace_id"], data["spans"])
    assert schema.validate_export(traces) == []

    reg = obs_metrics.Registry()
    reg.counter("otlp_exported_total", "t").inc(kind="traces")
    reg.histogram("service_request_seconds", "t",
                  buckets=(0.1, 1.0)).observe(0.5, exemplar="abcd")
    metrics = obs_export.metrics_to_otlp(reg.to_dict())
    assert schema.validate_export(metrics) == []

    broken = json.loads(json.dumps(traces))
    broken["resourceSpans"][0]["scopeSpans"][0]["spans"][0]["traceId"] \
        = "xyz"
    assert any("traceId" in e for e in schema.validate_export(broken))

    broken = json.loads(json.dumps(traces))
    span = broken["resourceSpans"][0]["scopeSpans"][0]["spans"][0]
    span["endTimeUnixNano"] = str(int(span["startTimeUnixNano"]) - 1)
    assert any("endTimeUnixNano" in e
               for e in schema.validate_export(broken))

    broken = json.loads(json.dumps(metrics))
    m = broken["resourceMetrics"][0]["scopeMetrics"][0]["metrics"][0]
    for kind in ("sum", "gauge", "histogram"):
        m.pop(kind, None)
    assert any("exactly one of" in e
               for e in schema.validate_export(broken))

    broken = json.loads(json.dumps(metrics))
    for m in broken["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]:
        hist = m.get("histogram")
        if hist:
            hist["dataPoints"][0]["bucketCounts"].append("0")
    assert any("bucketCounts" in e
               for e in schema.validate_export(broken))

    assert schema.validate_export({}) \
        == ["export: need resourceSpans or resourceMetrics"]

    good = tmp_path / "otlp.json"
    good.write_text(json.dumps(traces))
    ok = subprocess.run([sys.executable, str(_SCRIPT),
                         "validate_export", str(good)],
                        capture_output=True, text=True, timeout=60)
    assert ok.returncode == 0, ok.stderr
    bad = tmp_path / "otlp-bad.json"
    bad.write_text("[]")
    fail = subprocess.run([sys.executable, str(_SCRIPT),
                           "validate_export", str(bad)],
                          capture_output=True, text=True, timeout=60)
    assert fail.returncode == 1


def test_device_render_records_validate(schema, tmp_path, monkeypatch):
    """A trace from a REAL device-rendered merge with a residency hit —
    the ``render.d2h`` d2h-copy span, the ``residency.hit`` /
    ``residency.encode_delta`` spans, and the residency metric series —
    must pass ``validate_device_render``; drifted shapes (wrong layer,
    missing transfer meta, undocumented outcome/reason, labeled bytes
    gauge) are rejected field by field. The CLI subcommand wires the
    same validator."""
    import bench
    import semantic_merge_tpu.runtime.trace as trace_mod
    from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
    from semantic_merge_tpu.core.ops import OpLog
    from semantic_merge_tpu.frontend.snapshot import annotate_residency
    from semantic_merge_tpu.obs import metrics as obs_metrics
    from semantic_merge_tpu.service import residency

    monkeypatch.setenv("SEMMERGE_MESH", "off")
    monkeypatch.setenv("SEMMERGE_DEVICE_RENDER", "require")
    monkeypatch.setenv("SEMMERGE_RENDER_MIN_ROWS", "0")
    monkeypatch.setenv("SEMMERGE_RESIDENCY_CACHE", "on")
    residency.cache().reset()
    tracer = trace_mod.Tracer(enabled=True)
    backend = TpuTSBackend(mesh=False)
    with tracer.phase("merge", backend="tpu"):
        for _ in range(2):  # first populates residency, second hits
            base, left, right = bench.synth_repo(20, 3, divergent=True)
            annotate_residency(base, "", "cafe" * 10)
            res, composed, _ = backend.merge(
                base, left, right, base_rev="bench", seed="bench",
                timestamp="2026-01-01T00:00:00Z")
            OpLog(res.op_log_left).to_json_bytes()   # forces render.d2h
            OpLog(res.op_log_right).to_json_bytes()
    residency.cache().clear(reason="rss-hard")
    residency.cache().reset()
    trace = tmp_path / ".semmerge-trace.json"
    tracer.write(trace)
    data = json.loads(trace.read_text())
    data["metrics"] = obs_metrics.REGISTRY.to_dict()
    names = {row.get("name") for row in data["spans"]}
    assert {"render.d2h", "residency.hit",
            "residency.encode_delta"} <= names, names
    assert schema.validate_trace(data) == []
    assert schema.validate_device_render(data) == []

    def spans_named(doc, name):
        return [r for r in doc["spans"] if r.get("name") == name]

    broken = json.loads(json.dumps(data))
    spans_named(broken, "render.d2h")[0]["layer"] = "backend"
    assert any("render.d2h span layer" in e
               for e in schema.validate_device_render(broken))

    broken = json.loads(json.dumps(data))
    del spans_named(broken, "render.d2h")[0]["meta"]["rows"]
    assert any("'rows'" in e
               for e in schema.validate_device_render(broken))

    broken = json.loads(json.dumps(data))
    spans_named(broken, "residency.hit")[0]["meta"].pop("repo")
    assert any("'repo'" in e
               for e in schema.validate_device_render(broken))

    broken = json.loads(json.dumps(data))
    series = broken["metrics"]["counters"][
        "snapshot_residency_hits_total"]["series"]
    series[0]["labels"] = {"outcome": "warmish"}
    assert any("warmish" in e
               for e in schema.validate_device_render(broken))

    broken = json.loads(json.dumps(data))
    series = broken["metrics"]["counters"][
        "snapshot_residency_evictions_total"]["series"]
    series[0]["labels"] = {"why": "rss-hard"}
    assert any("snapshot_residency_evictions_total" in e
               for e in schema.validate_device_render(broken))

    broken = json.loads(json.dumps(data))
    gauge = broken["metrics"]["gauges"]["snapshot_residency_bytes"]
    gauge["series"][0]["labels"] = {"pool": "a"}
    assert any("no labels" in e
               for e in schema.validate_device_render(broken))

    good = tmp_path / "render-trace.json"
    good.write_text(json.dumps(data))
    ok = subprocess.run([sys.executable, str(_SCRIPT),
                         "validate_device_render", str(good)],
                        capture_output=True, text=True, timeout=60)
    assert ok.returncode == 0, ok.stderr
    bad = tmp_path / "render-bad.json"
    bad.write_text(json.dumps(broken))
    fail = subprocess.run([sys.executable, str(_SCRIPT),
                           "validate_device_render", str(bad)],
                          capture_output=True, text=True, timeout=60)
    assert fail.returncode == 1
