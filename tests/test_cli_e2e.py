"""End-to-end CLI tests on real git repositories.

Covers the reference's two e2e scenarios (tests/e2e_basic.sh and
tests/e2e_rename_move_decl.sh) plus the exit-code and artifact
contracts. Unlike the reference's basic e2e — which registered the git
driver under a misspelled key and therefore silently exercised git's
built-in merge — these tests invoke the engine directly and assert on
engine-specific artifacts (op logs in git notes, conflict JSON).
"""
import json
import os
import pathlib
import subprocess

import pytest

from semantic_merge_tpu.cli import main


def git(args, cwd):
    subprocess.run(["git", *args], cwd=cwd, check=True,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


@pytest.fixture
def repo(tmp_path, monkeypatch):
    root = tmp_path / "repo"
    root.mkdir()
    git(["init", "-q", "-b", "main"], root)
    git(["config", "user.email", "t@example.com"], root)
    git(["config", "user.name", "t"], root)
    monkeypatch.chdir(root)
    return root


def commit_all(root, msg):
    git(["add", "-A"], root)
    env_keys = {"GIT_AUTHOR_DATE": "2024-01-01T00:00:00Z",
                "GIT_COMMITTER_DATE": "2024-01-01T00:00:00Z"}
    old = {k: os.environ.get(k) for k in env_keys}
    os.environ.update(env_keys)
    try:
        git(["commit", "-q", "-m", msg], root)
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_semmerge_rename_vs_move(repo):
    # Base: src/util.ts with foo. A renames foo→bar; B moves the file.
    (repo / "src").mkdir()
    (repo / "src/util.ts").write_text("export function foo(n: number): number {\n  return n;\n}\n")
    commit_all(repo, "base")
    git(["branch", "basebr"], repo)

    git(["checkout", "-q", "-b", "branch-a"], repo)
    (repo / "src/util.ts").write_text("export function bar(n: number): number {\n  return n;\n}\n")
    commit_all(repo, "rename foo->bar")

    git(["checkout", "-q", "main"], repo)
    git(["checkout", "-q", "-b", "branch-b"], repo)
    (repo / "lib").mkdir()
    (repo / "src/util.ts").rename(repo / "lib/util.ts")
    commit_all(repo, "move util.ts")

    git(["checkout", "-q", "main"], repo)
    rc = main(["semmerge", "basebr", "branch-a", "branch-b",
               "--inplace", "--backend", "host"])
    assert rc == 0
    merged = repo / "lib/util.ts"
    assert merged.exists()
    assert "function bar" in merged.read_text()
    # Engine-specific artifact: op logs stored as git notes on both heads.
    notes = subprocess.run(
        ["git", "notes", "--ref", "semmerge", "show", "branch-a"],
        cwd=repo, stdout=subprocess.PIPE, text=True, check=True).stdout
    ops = json.loads(notes)
    assert any(o["type"] == "renameSymbol" for o in ops)


def test_semmerge_divergent_rename_conflict_exit_1(repo):
    (repo / "a.ts").write_text("export function foo(n: number): number { return n; }\n")
    commit_all(repo, "base")
    git(["branch", "basebr"], repo)

    git(["checkout", "-q", "-b", "branch-a"], repo)
    (repo / "a.ts").write_text("export function left(n: number): number { return n; }\n")
    commit_all(repo, "rename to left")

    git(["checkout", "-q", "main"], repo)
    git(["checkout", "-q", "-b", "branch-b"], repo)
    (repo / "a.ts").write_text("export function right(n: number): number { return n; }\n")
    commit_all(repo, "rename to right")

    git(["checkout", "-q", "main"], repo)
    rc = main(["semmerge", "basebr", "branch-a", "branch-b", "--backend", "host"])
    assert rc == 1
    artifact = repo / ".semmerge-conflicts.json"
    assert artifact.exists()
    conflicts = json.loads(artifact.read_text())
    assert conflicts and conflicts[0]["category"] == "DivergentRename"
    labels = [s["label"] for s in conflicts[0]["suggestions"]]
    assert "Rename to left" in labels and "Rename to right" in labels


def test_semdiff_outputs(repo, capsys):
    (repo / "a.ts").write_text("export function foo(n: number): number { return n; }\n")
    commit_all(repo, "base")
    git(["branch", "r1"], repo)
    (repo / "a.ts").write_text("export function bar(n: number): number { return n; }\n")
    commit_all(repo, "rename")
    git(["branch", "r2"], repo)

    rc = main(["semdiff", "r1", "r2", "--backend", "host"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "renameSymbol" in out

    rc = main(["semdiff", "r1", "r2", "--json-out", "--backend", "host"])
    out = capsys.readouterr().out
    ops = json.loads(out)
    types = {o["type"] for o in ops}
    assert "renameSymbol" in types


def test_semmerge_deterministic_op_logs(repo):
    (repo / "a.ts").write_text("export function foo(n: number): number { return n; }\n")
    commit_all(repo, "base")
    git(["branch", "basebr"], repo)
    git(["checkout", "-q", "-b", "branch-a"], repo)
    (repo / "a.ts").write_text("export function bar(n: number): number { return n; }\n")
    commit_all(repo, "rename")
    git(["checkout", "-q", "main"], repo)
    git(["checkout", "-q", "-b", "branch-b"], repo)
    (repo / "b.ts").write_text("export function extra(s: string): string { return s; }\n")
    commit_all(repo, "add file")
    git(["checkout", "-q", "main"], repo)

    def run_and_read():
        rc = main(["semmerge", "basebr", "branch-a", "branch-b", "--backend", "host"])
        assert rc == 0
        return subprocess.run(
            ["git", "notes", "--ref", "semmerge", "show", "branch-a"],
            cwd=repo, stdout=subprocess.PIPE, text=True, check=True).stdout

    first = run_and_read()
    second = run_and_read()
    # Byte-identical op logs across runs — [NFR-DET-001], which the
    # reference itself violates via uuid4/wall-clock provenance.
    assert first == second
    for op in json.loads(first):
        assert op["provenance"]["timestamp"] == "2024-01-01T00:00:00Z"


def test_trace_artifact(repo):
    (repo / "a.ts").write_text("export function foo(): void {}\n")
    commit_all(repo, "base")
    git(["branch", "basebr"], repo)
    git(["branch", "brA"], repo)
    git(["branch", "brB"], repo)
    rc = main(["semmerge", "basebr", "brA", "brB", "--backend", "host", "--trace"])
    assert rc == 0
    trace = json.loads((repo / ".semmerge-trace.json").read_text())
    phase_names = [p["name"] for p in trace["phases"]]
    # The non-strict CLI path runs diff+compose as one fused merge phase.
    assert "merge" in phase_names and "snapshot" in phase_names
    assert trace["counters"]["conflicts"] == 0


def test_trace_emits_spans_for_every_pipeline_phase(repo):
    """The unified observability layer: `semmerge merge --trace` on a
    multi-kind workload must produce a trace artifact whose span tree
    covers the frontend, ops, backend, and runtime layers (>= 8
    distinct instrumented phases), carries device telemetry, and
    validates against the documented schema; `semmerge stats` must
    render it. Runs the host backend: under the 8-device test mesh the
    tpu backend routes into the sharded path, which needs a newer
    jax.shard_map than this environment ships (same pre-existing skip
    reason as test_sharded_merge); the fused path's span coverage is
    asserted by tests/test_fused.py-adjacent unit runs and the bench
    harness on real hardware."""
    (repo / "src").mkdir()
    (repo / "src/a.ts").write_text(
        "export function foo(n: number): number {\n  return n;\n}\n")
    (repo / "src/b.ts").write_text(
        "export function other(s: string): string { return s; }\n")
    commit_all(repo, "base")
    git(["branch", "basebr"], repo)
    git(["checkout", "-qb", "brA"], repo)
    (repo / "src/a.ts").write_text(
        "export function bar(n: number): number {\n  return n;\n}\n")
    commit_all(repo, "rename")
    git(["checkout", "-q", "main"], repo)
    git(["checkout", "-qb", "brB"], repo)
    (repo / "lib").mkdir()
    (repo / "src/b.ts").rename(repo / "lib/b.ts")
    commit_all(repo, "move")
    git(["checkout", "-q", "main"], repo)

    rc = main(["semmerge", "basebr", "brA", "brB", "--backend", "host",
               "--trace"])
    assert rc == 0
    trace = json.loads((repo / ".semmerge-trace.json").read_text())

    span_names = {s["name"] for s in trace["spans"]}
    assert len(span_names) >= 8, sorted(span_names)
    layers = {s["layer"] for s in trace["spans"] if s.get("layer")}
    assert {"frontend", "ops", "backend", "runtime"} <= layers, layers
    # The CLI's own phases are intact (back-compat shape).
    phase_names = [p["name"] for p in trace["phases"]]
    for phase in ("snapshot", "merge", "materialize", "notes"):
        assert phase in phase_names
    # Device telemetry attached (host-path merge: platform captured
    # because the test process has JAX up; transfer ledger present).
    device = trace["device"]
    assert device["jax_imported"] and device["platform"]
    assert isinstance(device["transfer_bytes"], dict)
    assert isinstance(device["live_buffer_bytes_hwm"], (int, float))
    # Events stream written and both artifacts conform to the schema.
    events = repo / ".semmerge-events.jsonl"
    assert events.exists()
    import importlib.util
    script = (pathlib.Path(__file__).resolve().parent.parent
              / "scripts" / "check_trace_schema.py")
    spec = importlib.util.spec_from_file_location("cts", script)
    schema = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(schema)
    assert schema.validate_trace(trace) == []
    assert schema.validate_events(events.read_text().splitlines()) == []
    # stats renders all artifact shapes without error.
    assert main(["stats"]) == 0
    assert main(["stats", str(events)]) == 0
    assert main(["stats", "--prometheus"]) == 0


def test_config_selects_backend_and_seed(repo):
    (repo / ".semmerge.toml").write_text(
        "[core]\ndeterministic_seed = \"fixed-seed\"\n"
        "[engine]\nbackend = \"host\"\n"
    )
    (repo / "a.ts").write_text("export function foo(): void {}\n")
    commit_all(repo, "base")
    git(["branch", "basebr"], repo)
    git(["checkout", "-q", "-b", "brA"], repo)
    (repo / "a.ts").write_text("export function bar(): void {}\n")
    commit_all(repo, "rename")
    git(["checkout", "-q", "main"], repo)
    rc = main(["semmerge", "basebr", "brA", "main"])
    assert rc == 0


def test_semrebase_replays_stored_oplog(repo):
    """semrebase: the op log a merge stored in git notes replays onto a
    different revision — the [SPEC] flow the readable notes store makes
    real (reference requirements.md:119-124)."""
    (repo / "a.ts").write_text(
        "export function foo(n: number): number {\n  return n;\n}\n")
    commit_all(repo, "base")
    git(["branch", "basebr"], repo)
    git(["checkout", "-qb", "brA"], repo)
    (repo / "a.ts").write_text(
        "export function bar(n: number): number {\n  return n;\n}\n")
    commit_all(repo, "rename")
    git(["checkout", "-q", "main"], repo)
    git(["checkout", "-qb", "brB"], repo)
    (repo / "b.ts").write_text("export function other(): void {}\n")
    commit_all(repo, "side")
    git(["checkout", "-q", "main"], repo)
    # The merge stores brA's op log in semmerge notes.
    rc = main(["semmerge", "basebr", "brA", "brB", "--backend", "host"])
    assert rc == 0
    # Replay brA's note (the rename) onto brB, which still has foo.
    rc = main(["semrebase", "brA", "brB", "--inplace"])
    assert rc == 0
    text = (repo / "a.ts").read_text()
    assert "bar" in text and "foo" not in text
    assert (repo / "b.ts").exists(), "brB's own file must survive the replay"


def test_semrebase_replays_statement_ops_with_motion_markers(repo):
    """A statement-ops merge stores editStmtBlock ops AND motion
    markers (extractMethod) in notes; semrebase must replay the body
    edits and skip the markers harmlessly (applier unknown-op
    posture)."""
    (repo / "big.ts").write_text(
        "export function big(s: string): string { return s.trim() + '!'; }\n")
    commit_all(repo, "base")
    git(["branch", "basebr"], repo)
    git(["checkout", "-qb", "brA"], repo)
    (repo / "big.ts").write_text(
        "export function big(s: string): string { return helper(s, 0); }\n")
    (repo / "helper.ts").write_text(
        "export function helper(s: string, pad: number): string"
        " { return s.trim() + '!'; }\n")
    commit_all(repo, "extract")
    git(["checkout", "-q", "main"], repo)
    git(["checkout", "-qb", "brB"], repo)
    (repo / "other.ts").write_text("export function other(): void {}\n")
    commit_all(repo, "side")
    git(["checkout", "-q", "main"], repo)
    # structured-apply attaches decl text payloads, so the replayed
    # addDecl can create helper.ts (a payload-less addDecl degrades to
    # a logged skip — the applier's documented posture).
    rc = main(["semmerge", "basebr", "brA", "brB", "--backend", "host",
               "--statement-ops", "--structured-apply"])
    assert rc == 0
    note = json.loads(subprocess.run(
        ["git", "notes", "--ref", "semmerge", "show", "brA"], cwd=repo,
        check=True, capture_output=True, text=True).stdout)
    assert any(op["type"] == "extractMethod" for op in note)
    # Replay brA's note (body edit + addDecl + marker) onto brB.
    rc = main(["semrebase", "brA", "brB", "--inplace"])
    assert rc == 0
    assert "helper(s, 0)" in (repo / "big.ts").read_text()
    assert (repo / "helper.ts").exists()


def test_semrebase_without_note_fails_cleanly(repo):
    (repo / "a.ts").write_text("export function foo(): void {}\n")
    commit_all(repo, "base")
    rc = main(["semrebase", "HEAD", "HEAD"])
    assert rc == 1


def test_semmerge_incremental_matches_full_scan(repo):
    """Incremental scoping (engine.incremental, the default) must
    produce the same op logs and merged tree as a full-tree scan —
    unchanged files can contribute no diff rows and restriction
    preserves emission order, so op ids are identical
    (runtime/git.py merge_scope)."""
    (repo / "src").mkdir()

    def decl(i, name):
        # Unique param count per decl: symbolId hashes the structural
        # signature only, so same-shape decls would collide (the
        # reference's JS-Map quirk this test must avoid).
        params = ", ".join(f"p{k}: number" for k in range(i + 1))
        return f"export function {name}({params}): number {{\n  return {i};\n}}\n"

    for i in range(12):
        (repo / f"src/m{i}.ts").write_text(decl(i, f"fn{i}"))
    commit_all(repo, "base")
    git(["branch", "basebr"], repo)

    git(["checkout", "-qb", "brA"], repo)
    (repo / "src/m0.ts").write_text(decl(0, "renamed0"))
    commit_all(repo, "rename in m0")

    git(["checkout", "-q", "main"], repo)
    git(["checkout", "-qb", "brB"], repo)
    (repo / "lib").mkdir()
    (repo / "src/m3.ts").rename(repo / "lib/m3.ts")
    commit_all(repo, "move m3")
    git(["checkout", "-q", "main"], repo)

    from semantic_merge_tpu.runtime.git import merge_scope
    scope = merge_scope("basebr", "brA", "brB", cwd=repo)
    assert scope == {"src/m0.ts", "src/m3.ts", "lib/m3.ts"}

    def notes(rev):
        return subprocess.run(
            ["git", "notes", "--ref", "semmerge", "show", rev],
            cwd=repo, stdout=subprocess.PIPE, text=True, check=True).stdout

    rc = main(["semmerge", "basebr", "brA", "brB",
               "--inplace", "--backend", "host"])
    assert rc == 0
    inc_notes = (notes("brA"), notes("brB"))
    inc_tree = {p.relative_to(repo).as_posix(): p.read_text()
                for p in sorted(repo.rglob("*.ts"))}

    git(["checkout", "-q", "--", "."], repo)
    git(["clean", "-qfd", "--", "src", "lib"], repo)
    (repo / ".semmerge.toml").write_text(
        "[engine]\nincremental = false\n")
    rc = main(["semmerge", "basebr", "brA", "brB",
               "--inplace", "--backend", "host", "--trace"])
    assert rc == 0
    # The config switch must actually disable scoping: a full-tree run
    # records no scope_files counter.
    trace = json.loads((repo / ".semmerge-trace.json").read_text())
    assert "scope_files" not in trace.get("counters", {})
    assert (notes("brA"), notes("brB")) == inc_notes
    full_tree = {p.relative_to(repo).as_posix(): p.read_text()
                 for p in sorted(repo.rglob("*.ts"))}
    assert full_tree == inc_tree
    # The merge itself behaved: rename landed, move landed.
    assert "renamed0" in (repo / "src/m0.ts").read_text()
    assert (repo / "lib/m3.ts").exists()
