"""Parity of the one-round-trip fused merge path vs the host oracle.

The fused program (ops/fused.py) re-derives everything the two-program
device path computed — diff rows, deterministic SHA-256 op ids, compose
sort ranks, chain scans — inside one jit. Every test here compares its
observable output (op-log dicts, composed dicts, conflict dicts)
against the pure-Python oracle backend on the same snapshots.
"""
import hashlib
import random

import pytest

from semantic_merge_tpu.backends.base import get_backend, run_merge
from semantic_merge_tpu.frontend.snapshot import Snapshot


def _dicts(ops):
    return [o.to_dict() for o in ops]


def fused_backend():
    from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
    return TpuTSBackend(mesh=False)  # force the single-device fused path


def assert_parity(base, left, right, *, seed="s", base_rev="r",
                  timestamp="2026-01-02T03:04:05Z"):
    tpu = fused_backend()
    host = get_backend("host")
    res_t, comp_t, conf_t = run_merge(tpu, base, left, right, seed=seed,
                                      base_rev=base_rev, timestamp=timestamp)
    res_h, comp_h, conf_h = run_merge(host, base, left, right, seed=seed,
                                      base_rev=base_rev, timestamp=timestamp)
    assert _dicts(res_t.op_log_left) == _dicts(res_h.op_log_left)
    assert _dicts(res_t.op_log_right) == _dicts(res_h.op_log_right)
    assert _dicts(comp_t) == _dicts(comp_h)
    assert [c.to_dict() for c in conf_t] == [c.to_dict() for c in conf_h]
    # symbolMaps are built on host overlapping the device dispatch —
    # must still be complete and identical.
    assert res_t.symbol_maps == res_h.symbol_maps
    return comp_t, conf_t


def snap(files):
    return Snapshot(files=[{"path": p, "content": c} for p, c in files])


def test_sha256_device_matches_hashlib():
    from semantic_merge_tpu.ops.sha256 import sha256_host_check
    rng = random.Random(7)
    for _ in range(24):
        n = rng.randrange(0, 183)
        data = bytes(rng.randrange(256) for _ in range(n))
        blocks = max(1, (n + 9 + 63) // 64)
        assert sha256_host_check(data, blocks) == hashlib.sha256(data).hexdigest()


def test_rename_move_add_delete_parity():
    base = snap([
        ("a.ts", "export function f(x: number): number { return x; }\n"
                 "export function g(y: string): string { return y; }\n"),
        ("b.ts", "export class C { m(): void {} }\n"),
        ("c.ts", "export function gone(): void {}\n"),
    ])
    left = snap([
        ("a.ts", "export function renamed(x: number): number { return x; }\n"
                 "export function g(y: string): string { return y; }\n"),
        ("b.ts", "export class C { m(): void {} }\n"),
        ("c.ts", "export function gone(): void {}\n"),
        ("d.ts", "export function fresh(z: boolean): boolean { return z; }\n"),
    ])
    right = snap([
        ("a.ts", "export function f(x: number): number { return x; }\n"
                 "export function g(y: string): string { return y; }\n"),
        ("lib/b.ts", "export class C { m(): void {} }\n"),
    ])
    composed, conflicts = assert_parity(base, left, right)
    assert conflicts == []
    assert any(o.type == "moveDecl" for o in composed)
    assert any(o.type == "renameSymbol" for o in composed)
    assert any(o.type == "addDecl" for o in composed)
    assert any(o.type == "deleteDecl" for o in composed)


def test_divergent_rename_conflict_parity():
    base = snap([("a.ts", "export function f(x: number): number { return x; }\n")])
    left = snap([("a.ts", "export function lname(x: number): number { return x; }\n")])
    right = snap([("a.ts", "export function rname(x: number): number { return x; }\n")])
    _, conflicts = assert_parity(base, left, right)
    assert len(conflicts) == 1
    assert conflicts[0].to_dict()["category"] == "DivergentRename"


def test_rename_chain_context_parity():
    # A renames f; B moves the same symbol's file — the move must carry
    # renameContext and the chained address, identically on both paths.
    base = snap([("a.ts", "export function f(x: number): number { return x; }\n")])
    left = snap([("a.ts", "export function newf(x: number): number { return x; }\n")])
    right = snap([("lib/a.ts", "export function f(x: number): number { return x; }\n")])
    composed, _ = assert_parity(base, left, right)
    types = sorted(o.type for o in composed)
    # The rename changes the addressId too (addresses embed the name),
    # so side A emits move+rename; side B's file move adds another move.
    assert "renameSymbol" in types and "moveDecl" in types


def test_bench_workload_parity_with_conflicts():
    import bench
    base, left, right = bench.synth_repo(97, 3, divergent=True)
    _, conflicts = assert_parity(base, left, right, seed="bench",
                                 base_rev="bench",
                                 timestamp="2026-01-01T00:00:00Z")
    assert conflicts, "divergent preset must produce conflicts"


def test_bench_workload_parity_clean():
    import bench
    base, left, right = bench.synth_repo(60, 4)
    assert_parity(base, left, right, seed="bench", base_rev="bench",
                  timestamp="2026-01-01T00:00:00Z")


def test_fused_warm_repeat_and_capacity_growth():
    # Same backend across merges: device decl cache + string table must
    # not corrupt results; a larger second workload forces capacity
    # retry inside one engine.
    import bench
    tpu = fused_backend()
    host = get_backend("host")
    for files in (24, 24, 130):
        base, left, right = bench.synth_repo(files, 3)
        res_t, comp_t, conf_t = run_merge(tpu, base, left, right,
                                          seed="b", base_rev="b")
        res_h, comp_h, conf_h = run_merge(host, base, left, right,
                                          seed="b", base_rev="b")
        assert _dicts(comp_t) == _dicts(comp_h)
        assert _dicts(res_t.op_log_left) == _dicts(res_h.op_log_left)
        assert _dicts(res_t.op_log_right) == _dicts(res_h.op_log_right)


def test_fused_empty_and_identical_snapshots():
    empty = snap([])
    same = snap([("a.ts", "export function f(): void {}\n")])
    assert_parity(empty, empty, empty)
    assert_parity(same, same, same)


def test_fused_randomized_fuzz_parity():
    rng = random.Random(3)
    kinds = ["number", "string", "boolean"]
    for trial in range(6):
        n_files = rng.randrange(1, 14)
        files = {}
        for i in range(n_files):
            decls = []
            for d in range(rng.randrange(1, 4)):
                t = kinds[rng.randrange(3)]
                decls.append(f"export function fn{i}_{d}(p: {t}): {t} "
                             f"{{ return p; }}")
            files[f"m{i}.ts"] = "\n".join(decls) + "\n"

        def mutate(files, rng):
            out = {}
            for p, c in files.items():
                roll = rng.random()
                if roll < 0.2:
                    out["moved/" + p] = c
                elif roll < 0.4:
                    out[p] = c.replace("fn", f"rn{rng.randrange(9)}_", 1)
                elif roll < 0.5:
                    continue  # delete the file
                else:
                    out[p] = c
            if rng.random() < 0.4:
                out[f"new{rng.randrange(9)}.ts"] = (
                    "export function added(q: string): string { return q; }\n")
            return out

        base = snap(sorted(files.items()))
        left = snap(sorted(mutate(files, rng).items()))
        right = snap(sorted(mutate(files, rng).items()))
        assert_parity(base, left, right, seed=f"t{trial}")


def test_fused_sharded_parity_on_mesh():
    """The one-fetch fused merge also runs dp-sharded: distributed diff
    sort-join, row-sharded device SHA with digest all-gather, identical
    packed output. Parity vs the host oracle on the 8-device mesh,
    including a conflict workload and a warm repeat."""
    import jax
    from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
    from semantic_merge_tpu.parallel.mesh import build_mesh
    import bench

    mesh = build_mesh(jax.devices(), dp=8, pp=1, sp=1, tp=1, ep=1).mesh
    tpu = TpuTSBackend(mesh=mesh)
    host = get_backend("host")
    for files, divergent in ((60, False), (97, True), (60, False)):
        base, left, right = bench.synth_repo(files, 3, divergent=divergent)
        res_t, comp_t, conf_t = run_merge(tpu, base, left, right,
                                          seed="b", base_rev="b",
                                          timestamp="2026-01-01T00:00:00Z")
        res_h, comp_h, conf_h = run_merge(host, base, left, right,
                                          seed="b", base_rev="b",
                                          timestamp="2026-01-01T00:00:00Z")
        assert _dicts(res_t.op_log_left) == _dicts(res_h.op_log_left)
        assert _dicts(res_t.op_log_right) == _dicts(res_h.op_log_right)
        assert _dicts(comp_t) == _dicts(comp_h)
        assert [c.to_dict() for c in conf_t] == [c.to_dict() for c in conf_h]
        if divergent:
            assert conf_t


def test_fused_sharded_parity_non_pow2_mesh():
    import jax
    from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
    from semantic_merge_tpu.parallel.mesh import build_mesh
    import bench

    mesh = build_mesh(jax.devices()[:6], dp=6, pp=1, sp=1, tp=1, ep=1).mesh
    tpu = TpuTSBackend(mesh=mesh)
    host = get_backend("host")
    base, left, right = bench.synth_repo(40, 3)
    _, comp_t, _ = run_merge(tpu, base, left, right, seed="b", base_rev="b")
    _, comp_h, _ = run_merge(host, base, left, right, seed="b", base_rev="b")
    assert _dicts(comp_t) == _dicts(comp_h)


def test_fused_two_way_diff_parity():
    """semdiff's fused one-fetch path: device-hashed ids, same op log
    as the host oracle, warm repeat included."""
    import bench
    tpu = fused_backend()
    host = get_backend("host")
    for files in (30, 30, 90):
        base, left, _ = bench.synth_repo(files, 3)
        ops_t = tpu.diff(base, left, base_rev="r", seed="s",
                         timestamp="2026-01-01T00:00:00Z")
        ops_h = host.diff(base, left, base_rev="r", seed="s",
                          timestamp="2026-01-01T00:00:00Z")
        assert _dicts(ops_t) == _dicts(ops_h)


@pytest.mark.parametrize("split_env", ["1", "0"])
def test_fused_split_fetch_parity(monkeypatch, split_env):
    """Split-fetch (default) returns the packed result as
    (head, mid, chains) with pipelined device→host copies and the chain
    decode deferred into the composed view — content must be
    byte-identical to the one-buffer mode (SEMMERGE_SPLIT_FETCH=0) and
    to the host oracle, on both the single-device and dp-sharded
    kernels, including a conflict workload (whose rename-context patch
    rides the deferred decode)."""
    import jax
    import bench
    from semantic_merge_tpu.backends.ts_tpu import TpuTSBackend
    from semantic_merge_tpu.parallel.mesh import build_mesh

    monkeypatch.setenv("SEMMERGE_SPLIT_FETCH", split_env)
    host = get_backend("host")
    mesh = build_mesh(jax.devices(), dp=8, pp=1, sp=1, tp=1, ep=1).mesh
    for tpu in (fused_backend(), TpuTSBackend(mesh=mesh)):
        for files, divergent in ((60, False), (97, True)):
            base, left, right = bench.synth_repo(files, 3, divergent=divergent)
            res_t, comp_t, conf_t = run_merge(
                tpu, base, left, right, seed="b", base_rev="b",
                timestamp="2026-01-01T00:00:00Z")
            res_h, comp_h, conf_h = run_merge(
                host, base, left, right, seed="b", base_rev="b",
                timestamp="2026-01-01T00:00:00Z")
            assert _dicts(res_t.op_log_left) == _dicts(res_h.op_log_left)
            assert _dicts(res_t.op_log_right) == _dicts(res_h.op_log_right)
            assert _dicts(comp_t) == _dicts(comp_h)
            assert [c.to_dict() for c in conf_t] == [c.to_dict() for c in conf_h]
            if divergent:
                assert conf_t


def test_split_fetch_deferred_chains_survive_interner_growth(monkeypatch):
    """The deferred chain decode re-fetches the interner's object table
    at access time: materializing a split-fetch composed view AFTER a
    later merge has grown the interner must still decode the original
    merge's chain overrides correctly (indices are append-only stable).
    Serialization off the op streams must also work without forcing the
    chain fetch — the overlap the split mode exists for."""
    import bench
    from semantic_merge_tpu.core.ops import OpLog

    monkeypatch.setenv("SEMMERGE_SPLIT_FETCH", "1")
    tpu = fused_backend()
    host = get_backend("host")
    base, left, right = bench.synth_repo(40, 3, divergent=True)
    res_t, comp_t, _ = run_merge(tpu, base, left, right, seed="b",
                                 base_rev="b",
                                 timestamp="2026-01-01T00:00:00Z")
    # Serialize payloads BEFORE touching the composed view (bench/CLI
    # pipeline order); chains stay unfetched during this.
    assert comp_t.addr_s is None
    payload = OpLog(res_t.op_log_left).to_json_bytes()
    assert payload and comp_t.addr_s is None
    # A second, different merge grows the shared interner.
    base2, left2, right2 = bench.synth_repo(25, 4)
    run_merge(tpu, base2, left2, right2, seed="c", base_rev="c",
              timestamp="2026-01-01T00:00:00Z")
    # NOW materialize the first view — decode must be unaffected.
    res_h, comp_h, _ = run_merge(host, base, left, right, seed="b",
                                 base_rev="b",
                                 timestamp="2026-01-01T00:00:00Z")
    assert _dicts(comp_t) == _dicts(comp_h)


def test_snapshot_encode_cache_no_stale_hits():
    """The backend-level snapshot encode cache is keyed by content
    identity: mutating a file between merges on the SAME backend
    instance must change the result (no stale tensor reuse)."""
    tpu = fused_backend()
    host = get_backend("host")
    base = snap([("a.ts", "export function f(x: number): number { return x; }\n")])
    left1 = snap([("a.ts", "export function g(x: number): number { return x; }\n")])
    right = snap([("a.ts", "export function f(x: number): number { return x; }\n")])
    _, comp1, _ = run_merge(tpu, base, left1, right, seed="s", base_rev="r",
                            timestamp="2026-01-01T00:00:00Z")
    assert any(o.type == "renameSymbol" for o in comp1)
    # Second merge with a DIFFERENT rename on the same backend.
    left2 = snap([("a.ts", "export function h(x: number): number { return x; }\n")])
    _, comp2, _ = run_merge(tpu, base, left2, right, seed="s", base_rev="r",
                            timestamp="2026-01-01T00:00:00Z")
    _, comp2h, _ = run_merge(host, base, left2, right, seed="s", base_rev="r",
                             timestamp="2026-01-01T00:00:00Z")
    assert _dicts(comp2) == _dicts(comp2h)
    renames = [o for o in comp2 if o.type == "renameSymbol"]
    assert renames and renames[0].params["newName"] == "h"


def _inject_scope_collision(base, left, right):
    """Plant a colliding-signature pair across the scope boundary: a
    decl in an already-CHANGED file (renamed by side A) and a twin with
    the same name-free structural signature in an unchanged file that
    sorts LAST. Under Map-last-wins the full scan's survivor is the
    out-of-scope twin, so a scope-restricted merge without the guard
    changes which occurrence survives the symbol join."""
    dup = ("export function %s(a: string, b: string, c: string): "
           "string { return a; }\n")
    changed = base.files[0]["path"]
    for snap, name in ((base, "dupScoped"), (left, "dupRenamed"),
                       (right, "dupScoped")):
        f = next(f for f in snap.files if f["path"] == changed)
        f["content"] += dup % name
        snap.files.append({"path": "src/zzz_twin.ts",
                           "content": dup % "dupTwin"})


def test_incremental_scope_fuzz_parity():
    """The incremental invariant across varying repo sizes and the
    clean, DivergentRename, and COLLIDING-signature workloads:
    restricting all three snapshots to the changed-path union — with
    the collision guard's full-scan fallback, exactly as the CLI
    routes it — must produce identical op logs, composed ops, and
    conflicts to the full-tree merge. (The synthetic generator's edit
    mix is deterministic — rename/add/move/delete per its fixed
    modular pattern; trials vary the repo size, which shifts which
    files carry which edits, plus the conflict and collision flags.
    Collision trials drop the unique-signature restriction: a scoped
    symbolId gets an out-of-scope twin, the guard must fire, and the
    un-guarded restricted merge is asserted to actually diverge — the
    hole the guard closes.)"""
    import bench

    from semantic_merge_tpu.runtime.git import (scope_symbol_collisions,
                                                snapshot_symbol_index)

    host = get_backend("host")
    tpu = fused_backend()
    rng = random.Random(41)
    for trial in range(8):
        n = rng.randrange(20, 60)
        collide = trial >= 6
        if collide:
            base, left, right = bench.synth_repo_sparse(n, 3, 3)
            _inject_scope_collision(base, left, right)
        else:
            base, left, right = bench.synth_repo(n, 3,
                                                 divergent=bool(trial % 2))
        scope = bench.changed_paths(base, left, right)
        base_r, left_r, right_r = (base.restrict(scope),
                                   left.restrict(scope),
                                   right.restrict(scope))
        kw = dict(base_rev="r", seed="s", timestamp="2026-01-01T00:00:00Z")
        res_f, comp_f, conf_f = run_merge(host, base, left, right, **kw)
        # The CLI's guard: a scoped symbolId with an out-of-scope twin
        # forces the full-tree fallback.
        hazard = scope_symbol_collisions(scope, snapshot_symbol_index(base),
                                         (base_r, left_r, right_r))
        assert hazard == collide, trial
        if hazard:
            # The fallback is necessary: the un-guarded restricted
            # merge picks the wrong surviving occurrence.
            res_bad, comp_bad, _ = run_merge(host, base_r, left_r,
                                             right_r, **kw)
            assert (_dicts(res_bad.op_log_left)
                    != _dicts(res_f.op_log_left)
                    or _dicts(comp_bad) != _dicts(comp_f)), trial
            base_r, left_r, right_r = base, left, right
        res_i, comp_i, conf_i = run_merge(host, base_r, left_r, right_r,
                                          **kw)
        assert _dicts(res_i.op_log_left) == _dicts(res_f.op_log_left), trial
        assert _dicts(res_i.op_log_right) == _dicts(res_f.op_log_right), trial
        assert _dicts(comp_i) == _dicts(comp_f), trial
        assert [c.to_dict() for c in conf_i] == \
            [c.to_dict() for c in conf_f], trial
        # And the device path on the (guarded) restricted scope agrees.
        res_t, comp_t, conf_t = run_merge(tpu, base_r, left_r, right_r,
                                          **kw)
        assert _dicts(comp_t) == _dicts(comp_f)
        assert [c.to_dict() for c in conf_t] == [c.to_dict() for c in conf_f]


def test_snapshot_identity_cache_invalidates_on_mutation():
    """The snapshot-object identity cache must not serve stale results
    when a file's content string is replaced in place (the only way
    str content changes) — the fingerprint guard catches it."""
    tpu = fused_backend()
    base = snap([("a.ts", "export function f(x: number): number { return x; }\n")])
    left = snap([("a.ts", "export function g(x: number): number { return x; }\n")])
    right = snap([("a.ts", "export function f(x: number): number { return x; }\n")])
    _, comp1, _ = run_merge(tpu, base, left, right, seed="s", base_rev="r",
                            timestamp="2026-01-01T00:00:00Z")
    assert any(o.type == "renameSymbol" and o.params["newName"] == "g"
               for o in comp1)
    # Warm repeat on the SAME objects must be served by the identity
    # cache — pin that the per-file key recomputation did NOT run.
    import semantic_merge_tpu.backends.ts_tpu as ts_tpu_mod
    calls = []
    orig_scan = ts_tpu_mod.scan_snapshot_keyed
    ts_tpu_mod.scan_snapshot_keyed = \
        lambda files: (calls.append(1), orig_scan(files))[1]
    try:
        _, comp1b, _ = run_merge(tpu, base, left, right, seed="s",
                                 base_rev="r",
                                 timestamp="2026-01-01T00:00:00Z")
    finally:
        ts_tpu_mod.scan_snapshot_keyed = orig_scan
    assert calls == [], "warm repeat must hit the identity cache"
    assert _dicts(comp1b) == _dicts(comp1)
    # In-place mutation of the same Snapshot object must invalidate.
    left.files[0]["content"] = \
        "export function h(x: number): number { return x; }\n"
    _, comp2, _ = run_merge(tpu, base, left, right, seed="s", base_rev="r",
                            timestamp="2026-01-01T00:00:00Z")
    renames = [o for o in comp2 if o.type == "renameSymbol"]
    assert renames and renames[0].params["newName"] == "h"
