"""Diff/lift semantics tests against the reference's observable behavior."""
from semantic_merge_tpu.core.difflift import diff_nodes, lift
from semantic_merge_tpu.frontend.scanner import scan_file


def _scan(src_base, src_side, path="a.ts"):
    return scan_file(path, src_base), scan_file(path, src_side)


def test_rename_detected_via_stable_symbol_id():
    base, side = _scan(
        "export function foo(a: number): number { return a; }\n",
        "export function bar(a: number): number { return a; }\n",
    )
    diffs = diff_nodes(base, side)
    # addressId embeds the name (file::name::pos), so a rename also shifts
    # the address: the reference emits BOTH move and rename for the symbol
    # (workers/ts/src/diff.ts:16-21).
    assert [d.kind for d in diffs] == ["move", "rename"]
    ops = lift("base", diffs)
    op = [o for o in ops if o.type == "renameSymbol"][0]
    assert op.type == "renameSymbol"
    assert op.params["oldName"] == "foo"
    assert op.params["newName"] == "bar"
    assert op.params["file"] == "a.ts"
    assert op.guards == {"exists": True, "addressMatch": base[0].addressId}
    assert op.effects == {"summary": "rename foo→bar"}


def test_move_across_files():
    base = scan_file("a.ts", "export function f(x: string): string { return x; }\n")
    side = scan_file("lib/a.ts", "export function f(x: string): string { return x; }\n")
    diffs = diff_nodes(base, side)
    assert [d.kind for d in diffs] == ["move"]
    (op,) = lift("base", diffs)
    assert op.type == "moveDecl"
    assert op.params["oldFile"] == "a.ts"
    assert op.params["newFile"] == "lib/a.ts"
    assert op.params["oldAddress"] == base[0].addressId
    assert op.params["newAddress"] == side[0].addressId


def test_move_and_rename_both_emitted_for_one_symbol():
    base, side = _scan(
        "export function foo(n: number): void {}\n",
        "// moved down\n\nexport function renamed(n: number): void {}\n",
    )
    diffs = diff_nodes(base, side)
    assert sorted(d.kind for d in diffs) == ["move", "rename"]


def test_add_and_delete():
    base, side = _scan(
        "export function f(): void {}\n",
        "export function f(): void {}\nexport function g(s: string): string { return s; }\n",
    )
    diffs = diff_nodes(base, side)
    assert [d.kind for d in diffs] == ["add"]
    (op,) = lift("base", diffs)
    assert op.type == "addDecl" and op.params == {"file": "a.ts"}

    diffs_rev = diff_nodes(side, base)
    assert [d.kind for d in diffs_rev] == ["delete"]
    (op,) = lift("base", diffs_rev)
    assert op.type == "deleteDecl" and op.params == {"file": "a.ts"}


def test_signature_change_reports_delete_plus_add():
    # Changing a function's type changes symbolId → delete+add, not rename
    # (the reference quirk documented in SURVEY §3.4).
    base, side = _scan(
        "export function f(a: number): number { return a; }\n",
        "export function f(a: string): string { return a; }\n",
    )
    assert sorted(d.kind for d in diff_nodes(base, side)) == ["add", "delete"]


def test_duplicate_symbol_ids_last_wins_and_adds_repeat():
    # Base has one vars{1}; side has two vars{1} (same symbolId). The side
    # map keeps the last, and the add loop walks the raw list.
    base = scan_file("a.ts", "const a = 1;\n")
    side = scan_file("a.ts", "const a = 1;\nconst b = 2;\n")
    diffs = diff_nodes(base, side)
    # Same symbolId exists in both → no add; address compare is against the
    # LAST side occurrence (map last-wins), which moved → move op.
    assert [d.kind for d in diffs] == ["move"]
    assert diffs[0].b.addressId == side[1].addressId


def test_lift_is_deterministic():
    base, side = _scan(
        "export function foo(a: number): number { return a; }\n",
        "export function bar(a: number): number { return a; }\n",
    )
    ops1 = lift("base", diff_nodes(base, side), seed="s")
    ops2 = lift("base", diff_nodes(base, side), seed="s")
    assert [o.to_dict() for o in ops1] == [o.to_dict() for o in ops2]
    ops3 = lift("base", diff_nodes(base, side), seed="other")
    assert ops1[0].id != ops3[0].id


def test_statement_edits_extraction():
    """editStmtBlock ops for body-only changes; identity changes stay
    with their rename/move ops (core.difflift.statement_edits)."""
    from semantic_merge_tpu.core.difflift import statement_edits
    from semantic_merge_tpu.frontend.scanner import scan_snapshot
    base_files = [
        {"path": "a.ts", "content": "export function f(n: number): number { return 1; }\n"},
        {"path": "b.ts", "content": "export function g(s: string): string { return s; }\n"},
    ]
    side_files = [
        {"path": "a.ts", "content": "export function f(n: number): number { return 2; }\n"},
        {"path": "b.ts", "content": "export function g(s: string): string { return s; }\n"},
    ]
    base_nodes = scan_snapshot(base_files)
    side_nodes = scan_snapshot(side_files)
    base_map = {f["path"]: f["content"] for f in base_files}
    side_map = {f["path"]: f["content"] for f in side_files}
    ops = statement_edits(base_nodes, side_nodes, (base_map, side_map),
                          base_rev="r", seed="s", start_idx=0)
    assert [op.type for op in ops] == ["editStmtBlock"]
    op = ops[0]
    assert op.params["file"] == "a.ts"
    assert "return 1" in op.params["oldBody"]
    assert "return 2" in op.params["newBody"]
    assert op.params["oldBodyHash"] != op.params["newBodyHash"]
    # Deterministic ids: same inputs, same id.
    again = statement_edits(base_nodes, side_nodes, (base_map, side_map),
                            base_rev="r", seed="s", start_idx=0)
    assert again[0].id == op.id
    # A renamed decl's body change is NOT a statement edit (the rename
    # op records the change).
    renamed = [
        {"path": "a.ts", "content": "export function h(n: number): number { return 2; }\n"},
        side_files[1],
    ]
    ops2 = statement_edits(base_nodes, scan_snapshot(renamed),
                           (base_map, {f["path"]: f["content"] for f in renamed}),
                           base_rev="r", seed="s", start_idx=0)
    assert ops2 == []


def test_statement_edits_backend_parity():
    """Host and TPU backends emit identical op logs with statement_ops
    (the tpu path routes through the shared two-program lift)."""
    import pytest
    pytest.importorskip("jax")
    from semantic_merge_tpu.backends.base import get_backend
    from semantic_merge_tpu.frontend.snapshot import Snapshot
    base = Snapshot(files=[
        {"path": "a.ts", "content": "export function f(n: number): number { return 1; }\n"}])
    left = Snapshot(files=[
        {"path": "a.ts", "content": "export function f(n: number): number { return 10; }\n"}])
    right = Snapshot(files=[
        {"path": "a.ts", "content": "export function f(n: number): number { return 1; }\n"}])
    kw = dict(base_rev="r", seed="s", timestamp="2026-01-01T00:00:00Z",
              statement_ops=True)
    rh = get_backend("host").build_and_diff(base, left, right, **kw)
    rt = get_backend("tpu").build_and_diff(base, left, right, **kw)
    assert [o.to_dict() for o in rh.op_log_left] == [o.to_dict() for o in rt.op_log_left]
    assert [o.type for o in rh.op_log_left] == ["editStmtBlock"]
    assert rh.op_log_right == []
