"""Diff/lift semantics tests against the reference's observable behavior."""
from semantic_merge_tpu.core.difflift import diff_nodes, lift
from semantic_merge_tpu.frontend.scanner import scan_file


def _scan(src_base, src_side, path="a.ts"):
    return scan_file(path, src_base), scan_file(path, src_side)


def test_rename_detected_via_stable_symbol_id():
    base, side = _scan(
        "export function foo(a: number): number { return a; }\n",
        "export function bar(a: number): number { return a; }\n",
    )
    diffs = diff_nodes(base, side)
    # addressId embeds the name (file::name::pos), so a rename also shifts
    # the address: the reference emits BOTH move and rename for the symbol
    # (workers/ts/src/diff.ts:16-21).
    assert [d.kind for d in diffs] == ["move", "rename"]
    ops = lift("base", diffs)
    op = [o for o in ops if o.type == "renameSymbol"][0]
    assert op.type == "renameSymbol"
    assert op.params["oldName"] == "foo"
    assert op.params["newName"] == "bar"
    assert op.params["file"] == "a.ts"
    assert op.guards == {"exists": True, "addressMatch": base[0].addressId}
    assert op.effects == {"summary": "rename foo→bar"}


def test_move_across_files():
    base = scan_file("a.ts", "export function f(x: string): string { return x; }\n")
    side = scan_file("lib/a.ts", "export function f(x: string): string { return x; }\n")
    diffs = diff_nodes(base, side)
    assert [d.kind for d in diffs] == ["move"]
    (op,) = lift("base", diffs)
    assert op.type == "moveDecl"
    assert op.params["oldFile"] == "a.ts"
    assert op.params["newFile"] == "lib/a.ts"
    assert op.params["oldAddress"] == base[0].addressId
    assert op.params["newAddress"] == side[0].addressId


def test_move_and_rename_both_emitted_for_one_symbol():
    base, side = _scan(
        "export function foo(n: number): void {}\n",
        "// moved down\n\nexport function renamed(n: number): void {}\n",
    )
    diffs = diff_nodes(base, side)
    assert sorted(d.kind for d in diffs) == ["move", "rename"]


def test_add_and_delete():
    base, side = _scan(
        "export function f(): void {}\n",
        "export function f(): void {}\nexport function g(s: string): string { return s; }\n",
    )
    diffs = diff_nodes(base, side)
    assert [d.kind for d in diffs] == ["add"]
    (op,) = lift("base", diffs)
    assert op.type == "addDecl" and op.params == {"file": "a.ts"}

    diffs_rev = diff_nodes(side, base)
    assert [d.kind for d in diffs_rev] == ["delete"]
    (op,) = lift("base", diffs_rev)
    assert op.type == "deleteDecl" and op.params == {"file": "a.ts"}


def test_signature_change_reports_delete_plus_add():
    # Changing a function's type changes symbolId → delete+add, not rename
    # (the reference quirk documented in SURVEY §3.4).
    base, side = _scan(
        "export function f(a: number): number { return a; }\n",
        "export function f(a: string): string { return a; }\n",
    )
    assert sorted(d.kind for d in diff_nodes(base, side)) == ["add", "delete"]


def test_duplicate_symbol_ids_last_wins_and_adds_repeat():
    # Base has one vars{1}; side has two vars{1} (same symbolId). The side
    # map keeps the last, and the add loop walks the raw list.
    base = scan_file("a.ts", "const a = 1;\n")
    side = scan_file("a.ts", "const a = 1;\nconst b = 2;\n")
    diffs = diff_nodes(base, side)
    # Same symbolId exists in both → no add; address compare is against the
    # LAST side occurrence (map last-wins), which moved → move op.
    assert [d.kind for d in diffs] == ["move"]
    assert diffs[0].b.addressId == side[1].addressId


def test_lift_is_deterministic():
    base, side = _scan(
        "export function foo(a: number): number { return a; }\n",
        "export function bar(a: number): number { return a; }\n",
    )
    ops1 = lift("base", diff_nodes(base, side), seed="s")
    ops2 = lift("base", diff_nodes(base, side), seed="s")
    assert [o.to_dict() for o in ops1] == [o.to_dict() for o in ops2]
    ops3 = lift("base", diff_nodes(base, side), seed="other")
    assert ops1[0].id != ops3[0].id
