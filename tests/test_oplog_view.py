"""Columnar op-log view parity (ops/oplog_view.py).

The views must be observably identical to the eager Op-object path
they replaced: same ops from ``__getitem__``/iteration, byte-identical
``to_json()``, and a columnar DivergentRename cursor walk that agrees
with the host oracle walk (``core/compose.py:97``) on arbitrary
streams. End-to-end fused-vs-host parity (which now exercises these
views on every merge) lives in ``tests/test_fused.py``.
"""
from __future__ import annotations

import json
import random

import numpy as np
import pytest

from semantic_merge_tpu.core.ids import deterministic_op_id
from semantic_merge_tpu.core.ops import Op, OpLog, Target, dumps_canonical
from semantic_merge_tpu.frontend.scanner import DeclNode
from semantic_merge_tpu.ops.oplog_view import (
    KIND_ADD, KIND_DELETE, KIND_MOVE, KIND_RENAME,
    ComposedOpView, OpStreamView, cursor_walk_conflicts_columnar, _esc)


def test_kind_codes_match_device_diff():
    jax = pytest.importorskip("jax")  # noqa: F841
    from semantic_merge_tpu.ops import diff
    assert (KIND_RENAME, KIND_MOVE, KIND_ADD, KIND_DELETE) == (
        diff.KIND_RENAME, diff.KIND_MOVE, diff.KIND_ADD, diff.KIND_DELETE)


# Strings that stress the JSON fast path: quotes, backslashes, control
# chars, non-ASCII (must stay raw — ensure_ascii=False), emptiness.
_NASTY = ['plain', 'with "quotes"', 'back\\slash', 'tab\there',
          'new\nline', 'null\x00char', 'unicode→é漢', '', ' spaced ',
          'a/b.ts', "src/mod.ts::fn::12"]


def _node(i: int, rng: random.Random) -> DeclNode:
    name = rng.choice(_NASTY) + str(i)
    file = rng.choice(_NASTY) + f"{i}.ts"
    return DeclNode(symbolId=f"{i:016x}", addressId=f"{file}::{name}::{i}",
                    kind="function", name=name, file=file, pos=i, end=i + 1,
                    signature=f"sig{i}")


def _random_view(n: int, seed: int = 0):
    rng = random.Random(seed)
    base_nodes = [_node(i, rng) for i in range(n + 4)]
    side_nodes = [_node(1000 + i, rng) for i in range(n + 4)]
    kind = np.asarray([rng.choice([KIND_RENAME, KIND_MOVE, KIND_ADD,
                                   KIND_DELETE]) for _ in range(n)],
                      np.int32)
    a_slot = np.asarray([rng.randrange(len(base_nodes)) for _ in range(n)],
                        np.int32)
    b_slot = np.asarray([rng.randrange(len(side_nodes)) for _ in range(n)],
                        np.int32)
    words = np.asarray([[rng.getrandbits(31) for _ in range(4)]
                        for _ in range(n)], np.int32)
    prov = {"rev": "r", "timestamp": "2026-01-01T00:00:00Z"}
    return OpStreamView(kind, a_slot, b_slot, words, base_nodes,
                        side_nodes, prov)


def test_esc_matches_json_dumps():
    for s in _NASTY:
        assert _esc(s) == json.dumps(s, ensure_ascii=False)


def test_stream_view_getitem_iter_parity():
    view = _random_view(64, seed=1)
    # Single-item materialization must equal bulk materialization.
    spot = [view[i].to_dict() for i in (0, 5, 63, -1)]
    bulk = [op.to_dict() for op in view]
    assert len(bulk) == 64
    assert spot == [bulk[0], bulk[5], bulk[63], bulk[63]]
    # Cache coherence: repeated access returns the same object.
    assert view[5] is list(view)[5]


def test_stream_view_to_json_byte_parity():
    for seed in range(5):
        view = _random_view(48, seed=seed)
        expect = dumps_canonical([op.to_dict() for op in view])
        assert view.to_json() == expect
        # And through the OpLog seam the CLI/notes actually use.
        assert OpLog(view).to_json() == expect


def test_stream_view_to_json_empty():
    view = _random_view(0)
    assert view.to_json() == "[]"
    assert list(view) == []


def test_composed_view_applies_overrides():
    view = _random_view(8, seed=3)
    n = len(view)
    sides = [0] * n
    idxs = list(range(n))
    addr_s = [None, "A::1", None, None, "A::2", None, None, None]
    file_s = [None, "f.ts", "g.ts", None, None, None, None, None]
    name_s = [None, None, None, "nn", None, None, None, None]
    comp = ComposedOpView(sides, idxs, addr_s, file_s, name_s, view, view)
    from semantic_merge_tpu.ops.oplog_view import _materialize_decoded
    # Lazy single-row access before bulk materialization: rows without
    # overrides share the stream op (no clone).
    assert comp[5] is view[5]
    expect = [_materialize_decoded(view[i], addr_s[i], file_s[i], name_s[i])
              for i in range(n)]
    got = list(comp)  # bulk path (C factory when available)
    assert [o.to_dict() for o in got] == [o.to_dict() for o in expect]
    assert comp[1].to_dict() == expect[1].to_dict()


def _rand_sorted_streams(rng: random.Random, n: int):
    """Random canonically-sorted op streams plus aligned int columns —
    ops and columns describe the same stream, so both walks see the
    same data."""
    prec_pool = [10, 11, 11, 11, 30, 31]  # rename-heavy, with ties
    ops, prec, ren, sym, name = [], [], [], [], []
    rows = []
    for _ in range(n):
        p = rng.choice(prec_pool)
        is_ren = p == 11
        s = rng.randrange(6)
        nm = rng.randrange(4)
        rows.append((p, is_ren, s, nm))
    rows.sort(key=lambda r: r[0])
    for i, (p, is_ren, s, nm) in enumerate(rows):
        t = "renameSymbol" if is_ren else ("moveDecl" if p == 10 else
                                           ("addDecl" if p == 30 else
                                            "deleteDecl"))
        op = Op.new(t, Target(f"sym{s}", f"addr{i}"),
                    params={"newName": f"name{nm}"} if is_ren else {},
                    op_id=deterministic_op_id("s", "r", i, t),
                    provenance={"timestamp": "1970-01-01T00:00:00Z"})
        ops.append(op)
        prec.append(p)
        ren.append(is_ren)
        sym.append(s if is_ren else -1 - i)  # non-renames never match
        name.append(nm)
    return ops, prec, ren, sym, name


def test_columnar_walk_matches_oracle_walk():
    from semantic_merge_tpu.core.compose import cursor_walk_conflicts
    rng = random.Random(7)
    for trial in range(60):
        na, nb = rng.randrange(0, 14), rng.randrange(0, 14)
        ops_a, pa, ra, sa, nma = _rand_sorted_streams(rng, na)
        ops_b, pb, rb, sb, nmb = _rand_sorted_streams(rng, nb)
        keys_a = [(p, "1970-01-01T00:00:00Z") for p in pa]
        keys_b = [(p, "1970-01-01T00:00:00Z") for p in pb]
        want_conf, want_da, want_db = cursor_walk_conflicts(
            ops_a, ops_b, keys_a=keys_a, keys_b=keys_b)
        pairs, da, db = cursor_walk_conflicts_columnar(
            pa, ra, sa, nma, pb, rb, sb, nmb)
        assert (da, db) == (want_da, want_db), f"trial {trial}"
        assert len(pairs) == len(want_conf)
        for (ia, ib), conf in zip(pairs, want_conf):
            got = conf.to_dict()
            assert ops_a[ia].id in (got["opA"]["id"], got["opB"]["id"])


def test_native_serializer_byte_parity():
    """The C serializer (smn_oplog_json) must emit byte-identical JSON
    to the Python columnar serializer across nasty strings (quotes,
    backslashes, control chars incl. NUL, non-ASCII)."""
    from semantic_merge_tpu.frontend import native
    if not native.available():
        pytest.skip("native library unavailable")
    for seed in range(6):
        view = _random_view(64, seed=seed)
        expect = view._to_json_py()
        got = view._to_json_native_bytes()
        assert got is not None
        assert got.decode("utf-8") == expect
    empty = _random_view(0)
    assert empty.to_json() == "[]"


def test_c_op_factory_matches_python_materializers():
    """The C op factory (native/opfactory.c) must build value-identical
    Op objects: stream ops vs the Python per-kind builders, and
    composed ops vs the _materialize_decoded override path — across
    nasty strings and random override patterns."""
    from semantic_merge_tpu.frontend.native import load_opfactory
    if load_opfactory() is None:
        pytest.skip("op factory unavailable")
    from semantic_merge_tpu.ops.oplog_view import _materialize_decoded
    rng = random.Random(17)
    for seed in range(4):
        view = _random_view(56, seed=seed)
        expect = [view._build_one(i).to_dict() for i in range(len(view))]
        got = [op.to_dict() for op in _random_view(56, seed=seed).materialize()]
        assert got == expect
        # Composed: random refs + overrides over two distinct streams.
        left = _random_view(40, seed=seed)
        right = _random_view(40, seed=seed + 100)
        n = 64
        sides = [rng.randrange(2) for _ in range(n)]
        idxs = [rng.randrange(40) for _ in range(n)]
        def ov():
            return [rng.choice([None, None, 'x "q"', 'π→', '']) for _ in range(n)]
        addr_s, file_s, name_s = ov(), ov(), ov()
        comp = ComposedOpView(sides, idxs, addr_s, file_s, name_s, left, right)
        want = [_materialize_decoded(
                    (left if s == 0 else right)._build_one(i), a, f, nm).to_dict()
                for s, i, a, f, nm in zip(sides, idxs, addr_s, file_s, name_s)]
        assert [op.to_dict() for op in comp.materialize()] == want


def test_c_composed_ops_respect_per_side_provenance():
    """Composed rows must carry their own stream's provenance — the C
    path takes both prov dicts and selects by side."""
    from semantic_merge_tpu.frontend.native import load_opfactory
    left = _random_view(6, seed=1)
    right = _random_view(6, seed=2)
    right.prov = {"rev": "OTHER", "timestamp": "1999-01-01T00:00:00Z"}
    sides = [0, 1, 0, 1, 1, 0]
    idxs = [0, 1, 2, 3, 4, 5]
    none = [None] * 6
    comp = ComposedOpView(sides, idxs, none, none, none, left, right)
    ops = comp.materialize()
    for s, op in zip(sides, ops):
        assert op.provenance == (left.prov if s == 0 else right.prov)
    if load_opfactory() is None:
        pytest.skip("C factory unavailable (python path verified)")


def test_to_json_bytes_matches_str():
    """to_json_bytes must be exactly to_json().encode() on both the
    native and Python paths, and through the OpLog seam notes use."""
    for seed in (0, 3):
        view = _random_view(40, seed=seed)
        assert view.to_json_bytes() == view.to_json().encode("utf-8")
        assert OpLog(view).to_json_bytes() == \
            OpLog(view).to_json().encode("utf-8")
    # Plain-list OpLog path too.
    ops = list(_random_view(6, seed=1))
    assert OpLog(ops).to_json_bytes() == OpLog(ops).to_json().encode("utf-8")
