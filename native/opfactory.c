/* semmerge_opfactory — C op-object factory for the columnar op logs.
 *
 * The fused merge path keeps op logs as int32/digest columns
 * (semantic_merge_tpu/ops/oplog_view.py); consumers that need real Op
 * objects (the applier's handler dispatch, parity tests, the bench's
 * honest composed-stream consumption) previously materialized them in
 * Python at ~2 us/op — the largest host phase left after the native
 * JSON serializer. This extension builds the same objects with the
 * CPython C API: Op/Target instances via tp_new-free __new__ +
 * slot SetAttr, params/guards/effects as small dicts.
 *
 * v2 (host-tail pipelining): field strings come from per-snapshot
 * Python STRING LISTS (one list per node column: symbolId, addressId,
 * name, file — built once per snapshot and cached by the engine)
 * instead of being UTF-8-decoded out of a byte blob per op. A 46k-op
 * composed stream used to allocate ~230k fresh field strings per
 * materialize; now every field is a borrowed PyList_GET_ITEM + the
 * dict insert's incref. Only the op id (uuid) and the summary string
 * are created per op.
 *
 * Two entry points (STREAM = kind, a_slot, b_slot, words,
 * b_sym, b_addr, b_name, b_file, s_sym, s_addr, s_name, s_file):
 *   stream_ops(STREAM, prov, op_cls, target_cls) -> list[Op]
 *   composed_ops(STREAM_left, STREAM_right, sides, idxs,
 *                addr_ov, file_ov, name_ov,
 *                prov_left, prov_right, op_cls, target_cls) -> list[Op]
 * composed_ops applies the chain-override rules of
 * oplog_view._materialize_decoded row-by-row, building each final
 * composed op directly — the intermediate per-side stream objects are
 * never created. ``sides``/``idxs`` may be any row range (the tail
 * pipeline materializes shard slices independently and concatenates
 * in shard order). Byte-for-byte to_dict parity with the Python
 * materializers is fuzz-tested in tests/test_oplog_view.py.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>

/* Interned field/key names, created at module init. */
static PyObject *S_id, *S_schemaVersion, *S_type, *S_target, *S_params,
    *S_guards, *S_effects, *S_provenance, *S_symbolId, *S_addressId,
    *S_oldName, *S_newName, *S_file, *S_oldAddress, *S_newAddress,
    *S_oldFile, *S_newFile, *S_exists, *S_addressMatch, *S_summary,
    *S_renameContext;
static PyObject *T_renameSymbol, *T_moveDecl, *T_addDecl, *T_deleteDecl;
static PyObject *SUM_add, *SUM_del, *ARROW, *SUM_ren_prefix, *SUM_mov_prefix;
static PyObject *ONE;

typedef struct {
  const int32_t *kind, *a_slot, *b_slot;
  const int32_t *words; /* n*4 */
  /* Borrowed per-node field lists: [0..3] base sym/addr/name/file,
   * [4..7] side sym/addr/name/file. */
  PyObject *bf[4], *sf[4];
  Py_ssize_t nb, ns; /* node counts (list lengths) */
} Stream;

/* Slot descriptors fetched once per entry call: setting through
 * tp_descr_set skips the generic attribute machinery, and tp_alloc
 * skips the __new__ Python call — together ~3x on object build. */
typedef struct {
  PyTypeObject *op_t, *tgt_t;
  PyObject *op_d[8];  /* id, schemaVersion, type, target, params,
                         guards, effects, provenance */
  PyObject *tgt_d[2]; /* symbolId, addressId */
  int ok;
} Factory;

static int dset(PyObject *descr, PyObject *obj, PyObject *val) {
  /* factory_init guarantees tp_descr_set exists for every descriptor */
  return Py_TYPE(descr)->tp_descr_set(descr, obj, val);
}

static int factory_init(Factory *f, PyObject *op_cls, PyObject *target_cls) {
  memset(f, 0, sizeof(*f));
  if (!PyType_Check(op_cls) || !PyType_Check(target_cls)) {
    PyErr_SetString(PyExc_TypeError, "op_cls/target_cls must be types");
    return -1;
  }
  f->op_t = (PyTypeObject *)op_cls;
  f->tgt_t = (PyTypeObject *)target_cls;
  PyObject *names[8] = {S_id, S_schemaVersion, S_type, S_target, S_params,
                        S_guards, S_effects, S_provenance};
  for (int i = 0; i < 8; i++) {
    f->op_d[i] = PyObject_GetAttr(op_cls, names[i]);
    if (!f->op_d[i] || !Py_TYPE(f->op_d[i])->tp_descr_set) {
      PyErr_SetString(PyExc_TypeError, "op_cls lacks slot descriptors");
      return -1;
    }
  }
  PyObject *tnames[2] = {S_symbolId, S_addressId};
  for (int i = 0; i < 2; i++) {
    f->tgt_d[i] = PyObject_GetAttr(target_cls, tnames[i]);
    if (!f->tgt_d[i] || !Py_TYPE(f->tgt_d[i])->tp_descr_set) {
      PyErr_SetString(PyExc_TypeError, "target_cls lacks slot descriptors");
      return -1;
    }
  }
  f->ok = 1;
  return 0;
}

static void factory_clear(Factory *f) {
  for (int i = 0; i < 8; i++) Py_XDECREF(f->op_d[i]);
  for (int i = 0; i < 2; i++) Py_XDECREF(f->tgt_d[i]);
}

/* Borrowed field f (0 sym, 1 addr, 2 name, 3 file) of a node. */
static PyObject *fld(PyObject *const lists[4], Py_ssize_t n, Py_ssize_t node,
                     int f) {
  if (node < 0 || node >= n) {
    PyErr_SetString(PyExc_ValueError, "node index out of range");
    return NULL;
  }
  return PyList_GET_ITEM(lists[f], node);
}

static const char HEXD[] = "0123456789abcdef";

static PyObject *uuid_str(const int32_t *w4) {
  char buf[36];
  char hex[32];
  for (int k = 0; k < 4; k++) {
    uint32_t v = (uint32_t)w4[k];
    for (int j = 7; j >= 0; j--) {
      hex[k * 8 + j] = HEXD[v & 0xF];
      v >>= 4;
    }
  }
  int p = 0;
  for (int i = 0; i < 32; i++) {
    if (i == 8 || i == 12 || i == 16 || i == 20) buf[p++] = '-';
    buf[p++] = hex[i];
  }
  return PyUnicode_FromStringAndSize(buf, 36);
}

/* sym/addr borrowed; result owned by caller. */
static PyObject *make_target(const Factory *f, PyObject *sym,
                             PyObject *addr) {
  PyObject *t = f->tgt_t->tp_alloc(f->tgt_t, 0);
  if (!t) return NULL;
  if (dset(f->tgt_d[0], t, sym) < 0 || dset(f->tgt_d[1], t, addr) < 0) {
    Py_DECREF(t);
    return NULL;
  }
  return t;
}

/* Assemble one Op. op_id/type/prov borrowed;
 * target/params/guards/effects are owned refs STOLEN from the caller. */
static PyObject *make_op(const Factory *f, PyObject *op_id, PyObject *type,
                         PyObject *target /* stolen */,
                         PyObject *params /* stolen */,
                         PyObject *guards /* stolen */,
                         PyObject *effects /* stolen */, PyObject *prov) {
  PyObject *op = f->op_t->tp_alloc(f->op_t, 0);
  if (!op) goto fail;
  if (dset(f->op_d[0], op, op_id) < 0) goto fail_op;
  if (dset(f->op_d[1], op, ONE) < 0) goto fail_op;
  if (dset(f->op_d[2], op, type) < 0) goto fail_op;
  if (dset(f->op_d[3], op, target) < 0) goto fail_op;
  if (dset(f->op_d[4], op, params) < 0) goto fail_op;
  if (dset(f->op_d[5], op, guards) < 0) goto fail_op;
  if (dset(f->op_d[6], op, effects) < 0) goto fail_op;
  if (dset(f->op_d[7], op, prov) < 0) goto fail_op;
  Py_DECREF(target);
  Py_DECREF(params);
  Py_DECREF(guards);
  Py_DECREF(effects);
  return op;
fail_op:
  Py_DECREF(op);
fail:
  Py_XDECREF(target);
  Py_XDECREF(params);
  Py_XDECREF(guards);
  Py_XDECREF(effects);
  return NULL;
}

static PyObject *guards_for(PyObject *addr /* borrowed */) {
  PyObject *g = PyDict_New();
  if (!g) return NULL;
  if (PyDict_SetItem(g, S_exists, Py_True) < 0 ||
      PyDict_SetItem(g, S_addressMatch, addr) < 0) {
    Py_DECREF(g);
    return NULL;
  }
  return g;
}

static PyObject *summary3(PyObject *prefix, PyObject *a, PyObject *b) {
  /* prefix + a + ARROW + b */
  PyObject *s1 = PyUnicode_Concat(prefix, a);
  if (!s1) return NULL;
  PyObject *s2 = PyUnicode_Concat(s1, ARROW);
  Py_DECREF(s1);
  if (!s2) return NULL;
  PyObject *s3 = PyUnicode_Concat(s2, b);
  Py_DECREF(s2);
  return s3;
}

static PyObject *effects_summary(PyObject *summary /* stolen */) {
  if (!summary) return NULL;
  PyObject *e = PyDict_New();
  if (!e) {
    Py_DECREF(summary);
    return NULL;
  }
  if (PyDict_SetItem(e, S_summary, summary) < 0) {
    Py_DECREF(summary);
    Py_DECREF(e);
    return NULL;
  }
  Py_DECREF(summary);
  return e;
}

/* Build op i of a stream, applying composed-row overrides when
 * addr_ov/file_ov/name_ov are non-NULL (borrowed, may be Py_None).
 * Override semantics mirror oplog_view._materialize_decoded exactly,
 * except ops are always built fresh (value-identical). All field
 * strings are borrowed from the stream's node field lists. */
static PyObject *build_op(const Stream *s, Py_ssize_t i, PyObject *prov,
                          const Factory *f, PyObject *addr_ov,
                          PyObject *file_ov, PyObject *name_ov) {
  int k = s->kind[i];
  PyObject *op_id = uuid_str(s->words + 4 * i);
  if (!op_id) return NULL;
  PyObject *result = NULL;
  int has_addr = addr_ov && addr_ov != Py_None;
  int has_file = file_ov && file_ov != Py_None;
  int has_name = name_ov && name_ov != Py_None;

  if (k == 0 || k == 1) { /* renameSymbol / moveDecl */
    Py_ssize_t an = s->a_slot[i], bn = s->b_slot[i];
    PyObject *a_sym = fld(s->bf, s->nb, an, 0);
    PyObject *a_addr = fld(s->bf, s->nb, an, 1);
    if (!a_sym || !a_addr) goto done;
    PyObject *target = make_target(f, a_sym, has_addr ? addr_ov : a_addr);
    PyObject *guards = guards_for(a_addr);
    if (!target || !guards) {
      Py_XDECREF(target);
      Py_XDECREF(guards);
      goto done;
    }
    if (k == 0) { /* renameSymbol */
      PyObject *a_name = fld(s->bf, s->nb, an, 2);
      PyObject *b_name = fld(s->sf, s->ns, bn, 2);
      PyObject *b_file = fld(s->sf, s->ns, bn, 3);
      if (!a_name || !b_name || !b_file) {
        Py_DECREF(target);
        Py_DECREF(guards);
        goto done;
      }
      PyObject *params = PyDict_New();
      int ok = params && PyDict_SetItem(params, S_oldName, a_name) == 0 &&
               PyDict_SetItem(params, S_newName, b_name) == 0 &&
               PyDict_SetItem(params, S_file,
                              has_file ? file_ov : b_file) == 0;
      if (ok && has_file) /* rename + chained file: newFile then file */
        ok = PyDict_SetItem(params, S_newFile, file_ov) == 0;
      /* NOTE: _materialize_decoded sets newFile THEN overwrites file;
       * insertion order is oldName,newName,file,newFile — file was
       * already inserted above, so order matches. renameContext never
       * applies to renameSymbol. */
      PyObject *effects =
          ok ? effects_summary(summary3(SUM_ren_prefix, a_name, b_name))
             : NULL;
      if (!ok || !effects) {
        Py_XDECREF(params);
        Py_XDECREF(effects);
        Py_DECREF(target);
        Py_DECREF(guards);
        goto done;
      }
      result = make_op(f, op_id, T_renameSymbol, target, params, guards,
                       effects, prov);
    } else { /* moveDecl */
      PyObject *b_addr = fld(s->sf, s->ns, bn, 1);
      PyObject *a_file = fld(s->bf, s->nb, an, 3);
      PyObject *b_file = fld(s->sf, s->ns, bn, 3);
      if (!b_addr || !a_file || !b_file) {
        Py_DECREF(target);
        Py_DECREF(guards);
        goto done;
      }
      PyObject *params = PyDict_New();
      int ok = params && PyDict_SetItem(params, S_oldAddress, a_addr) == 0 &&
               PyDict_SetItem(params, S_newAddress,
                              has_addr ? addr_ov : b_addr) == 0 &&
               PyDict_SetItem(params, S_oldFile, a_file) == 0 &&
               PyDict_SetItem(params, S_newFile,
                              has_file ? file_ov : b_file) == 0;
      if (ok && has_name)
        ok = PyDict_SetItem(params, S_renameContext, name_ov) == 0;
      PyObject *effects =
          ok ? effects_summary(summary3(SUM_mov_prefix, a_addr, b_addr))
             : NULL;
      if (!ok || !effects) {
        Py_XDECREF(params);
        Py_XDECREF(effects);
        Py_DECREF(target);
        Py_DECREF(guards);
        goto done;
      }
      result = make_op(f, op_id, T_moveDecl, target, params, guards,
                       effects, prov);
    }
  } else { /* addDecl (2) / deleteDecl (3) */
    PyObject *const *lists = (k == 2) ? s->sf : s->bf;
    Py_ssize_t nn = (k == 2) ? s->ns : s->nb;
    Py_ssize_t node = (k == 2) ? s->b_slot[i] : s->a_slot[i];
    PyObject *sym = fld(lists, nn, node, 0);
    PyObject *addr = fld(lists, nn, node, 1);
    PyObject *fil = fld(lists, nn, node, 3);
    if (!sym || !addr || !fil) goto done;
    PyObject *target = make_target(f, sym, has_addr ? addr_ov : addr);
    PyObject *params = PyDict_New();
    int ok = target && params && PyDict_SetItem(params, S_file, fil) == 0;
    if (ok && has_name)
      ok = PyDict_SetItem(params, S_renameContext, name_ov) == 0;
    PyObject *guards = ok ? PyDict_New() : NULL;
    PyObject *effects = NULL;
    if (ok && guards) {
      PyObject *sum = (k == 2) ? SUM_add : SUM_del;
      Py_INCREF(sum);
      effects = effects_summary(sum);
    }
    if (!ok || !guards || !effects) {
      Py_XDECREF(target);
      Py_XDECREF(params);
      Py_XDECREF(guards);
      Py_XDECREF(effects);
      goto done;
    }
    result = make_op(f, op_id, (k == 2) ? T_addDecl : T_deleteDecl,
                     target, params, guards, effects, prov);
  }
done:
  Py_DECREF(op_id);
  return result;
}

/* ---- argument plumbing ---- */

typedef struct {
  Py_buffer kind, a_slot, b_slot, words;
  Stream s;
  Py_ssize_t n;
  int held;
} StreamArgs;

/* One stream is 12 consecutive args: 4 int32 column buffers followed
 * by 8 field lists (base sym/addr/name/file, side sym/addr/name/file). */
static int get_stream(PyObject *args, Py_ssize_t off, StreamArgs *sa) {
  PyObject *kind = PyTuple_GET_ITEM(args, off);
  PyObject *a_slot = PyTuple_GET_ITEM(args, off + 1);
  PyObject *b_slot = PyTuple_GET_ITEM(args, off + 2);
  PyObject *words = PyTuple_GET_ITEM(args, off + 3);
  memset(sa, 0, sizeof(*sa));
  for (int i = 0; i < 8; i++) {
    PyObject *lst = PyTuple_GET_ITEM(args, off + 4 + i);
    if (!PyList_Check(lst)) {
      PyErr_SetString(PyExc_TypeError, "node field columns must be lists");
      return -1;
    }
    if (i < 4)
      sa->s.bf[i] = lst;
    else
      sa->s.sf[i - 4] = lst;
  }
  sa->s.nb = PyList_GET_SIZE(sa->s.bf[0]);
  sa->s.ns = PyList_GET_SIZE(sa->s.sf[0]);
  for (int i = 1; i < 4; i++) {
    if (PyList_GET_SIZE(sa->s.bf[i]) != sa->s.nb ||
        PyList_GET_SIZE(sa->s.sf[i]) != sa->s.ns) {
      PyErr_SetString(PyExc_ValueError, "node field list length mismatch");
      return -1;
    }
  }
  if (PyObject_GetBuffer(kind, &sa->kind, PyBUF_C_CONTIGUOUS) < 0) return -1;
  if (PyObject_GetBuffer(a_slot, &sa->a_slot, PyBUF_C_CONTIGUOUS) < 0) goto f1;
  if (PyObject_GetBuffer(b_slot, &sa->b_slot, PyBUF_C_CONTIGUOUS) < 0) goto f2;
  if (PyObject_GetBuffer(words, &sa->words, PyBUF_C_CONTIGUOUS) < 0) goto f3;
  sa->n = sa->kind.len / 4;
  if (sa->a_slot.len != sa->kind.len || sa->b_slot.len != sa->kind.len ||
      sa->words.len != sa->kind.len * 4) {
    PyErr_SetString(PyExc_ValueError, "column length mismatch");
    goto f4;
  }
  sa->s.kind = (const int32_t *)sa->kind.buf;
  sa->s.a_slot = (const int32_t *)sa->a_slot.buf;
  sa->s.b_slot = (const int32_t *)sa->b_slot.buf;
  sa->s.words = (const int32_t *)sa->words.buf;
  sa->held = 1;
  return 0;
f4:
  PyBuffer_Release(&sa->words);
f3:
  PyBuffer_Release(&sa->b_slot);
f2:
  PyBuffer_Release(&sa->a_slot);
f1:
  PyBuffer_Release(&sa->kind);
  return -1;
}

static void release_stream(StreamArgs *sa) {
  if (!sa->held) return;
  PyBuffer_Release(&sa->kind);
  PyBuffer_Release(&sa->a_slot);
  PyBuffer_Release(&sa->b_slot);
  PyBuffer_Release(&sa->words);
  sa->held = 0;
}

static PyObject *py_stream_ops(PyObject *self, PyObject *args) {
  (void)self;
  if (PyTuple_GET_SIZE(args) != 15) {
    PyErr_SetString(PyExc_TypeError, "stream_ops expects 15 args");
    return NULL;
  }
  StreamArgs sa;
  if (get_stream(args, 0, &sa) < 0) return NULL;
  PyObject *prov = PyTuple_GET_ITEM(args, 12);
  Factory fac;
  if (factory_init(&fac, PyTuple_GET_ITEM(args, 13),
                   PyTuple_GET_ITEM(args, 14)) < 0) {
    factory_clear(&fac);
    release_stream(&sa);
    return NULL;
  }
  PyObject *out = PyList_New(sa.n);
  if (!out) {
    factory_clear(&fac);
    release_stream(&sa);
    return NULL;
  }
  for (Py_ssize_t i = 0; i < sa.n; i++) {
    PyObject *op = build_op(&sa.s, i, prov, &fac, NULL, NULL, NULL);
    if (!op) {
      Py_DECREF(out);
      factory_clear(&fac);
      release_stream(&sa);
      return NULL;
    }
    PyList_SET_ITEM(out, i, op);
  }
  factory_clear(&fac);
  release_stream(&sa);
  return out;
}

static PyObject *py_composed_ops(PyObject *self, PyObject *args) {
  (void)self;
  if (PyTuple_GET_SIZE(args) != 33) {
    PyErr_SetString(PyExc_TypeError, "composed_ops expects 33 args");
    return NULL;
  }
  StreamArgs left, right;
  if (get_stream(args, 0, &left) < 0) return NULL;
  if (get_stream(args, 12, &right) < 0) {
    release_stream(&left);
    return NULL;
  }
  PyObject *sides = PyTuple_GET_ITEM(args, 24);
  PyObject *idxs = PyTuple_GET_ITEM(args, 25);
  PyObject *addr_ov = PyTuple_GET_ITEM(args, 26);
  PyObject *file_ov = PyTuple_GET_ITEM(args, 27);
  PyObject *name_ov = PyTuple_GET_ITEM(args, 28);
  PyObject *prov_l = PyTuple_GET_ITEM(args, 29);
  PyObject *prov_r = PyTuple_GET_ITEM(args, 30);
  Factory fac;
  int fac_ok = factory_init(&fac, PyTuple_GET_ITEM(args, 31),
                            PyTuple_GET_ITEM(args, 32)) == 0;
  PyObject *out = NULL;
  if (!fac_ok) {
    factory_clear(&fac);
    release_stream(&left);
    release_stream(&right);
    return NULL;
  }
  Py_buffer sides_b = {0}, idxs_b = {0};
  if (PyObject_GetBuffer(sides, &sides_b, PyBUF_C_CONTIGUOUS) < 0) goto done0;
  if (PyObject_GetBuffer(idxs, &idxs_b, PyBUF_C_CONTIGUOUS) < 0) goto done1;
  {
    Py_ssize_t n = sides_b.len / 4;
    const int32_t *sd = (const int32_t *)sides_b.buf;
    const int32_t *ix = (const int32_t *)idxs_b.buf;
    if (idxs_b.len != sides_b.len ||
        !PyList_Check(addr_ov) || !PyList_Check(file_ov) ||
        !PyList_Check(name_ov) || PyList_GET_SIZE(addr_ov) != n ||
        PyList_GET_SIZE(file_ov) != n || PyList_GET_SIZE(name_ov) != n) {
      PyErr_SetString(PyExc_ValueError, "composed row arrays mismatch");
      goto done2;
    }
    out = PyList_New(n);
    if (!out) goto done2;
    for (Py_ssize_t i = 0; i < n; i++) {
      const Stream *s = (sd[i] == 0) ? &left.s : &right.s;
      Py_ssize_t row = ix[i];
      Py_ssize_t limit = (sd[i] == 0) ? left.n : right.n;
      if (row < 0 || row >= limit) {
        PyErr_SetString(PyExc_IndexError, "composed ref out of range");
        Py_CLEAR(out);
        goto done2;
      }
      PyObject *op = build_op(
          s, row, (sd[i] == 0) ? prov_l : prov_r, &fac,
          PyList_GET_ITEM(addr_ov, i), PyList_GET_ITEM(file_ov, i),
          PyList_GET_ITEM(name_ov, i));
      if (!op) {
        Py_CLEAR(out);
        goto done2;
      }
      PyList_SET_ITEM(out, i, op);
    }
  }
done2:
  PyBuffer_Release(&idxs_b);
done1:
  PyBuffer_Release(&sides_b);
done0:
  factory_clear(&fac);
  release_stream(&left);
  release_stream(&right);
  return out;
}

static PyMethodDef Methods[] = {
    {"stream_ops", py_stream_ops, METH_VARARGS,
     "Build one op stream's Op objects from its columns."},
    {"composed_ops", py_composed_ops, METH_VARARGS,
     "Build the composed Op sequence (any row range) from two streams' "
     "columns + per-row chain overrides."},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {PyModuleDef_HEAD_INIT,
                                       "semmerge_opfactory",
                                       NULL,
                                       -1,
                                       Methods,
                                       NULL,
                                       NULL,
                                       NULL,
                                       NULL};

static PyObject *intern(const char *s) { return PyUnicode_InternFromString(s); }

PyMODINIT_FUNC PyInit_semmerge_opfactory(void) {
  PyObject *m = PyModule_Create(&moduledef);
  if (!m) return NULL;
  S_id = intern("id");
  S_schemaVersion = intern("schemaVersion");
  S_type = intern("type");
  S_target = intern("target");
  S_params = intern("params");
  S_guards = intern("guards");
  S_effects = intern("effects");
  S_provenance = intern("provenance");
  S_symbolId = intern("symbolId");
  S_addressId = intern("addressId");
  S_oldName = intern("oldName");
  S_newName = intern("newName");
  S_file = intern("file");
  S_oldAddress = intern("oldAddress");
  S_newAddress = intern("newAddress");
  S_oldFile = intern("oldFile");
  S_newFile = intern("newFile");
  S_exists = intern("exists");
  S_addressMatch = intern("addressMatch");
  S_summary = intern("summary");
  S_renameContext = intern("renameContext");
  T_renameSymbol = intern("renameSymbol");
  T_moveDecl = intern("moveDecl");
  T_addDecl = intern("addDecl");
  T_deleteDecl = intern("deleteDecl");
  SUM_add = PyUnicode_FromString("add decl");
  SUM_del = PyUnicode_FromString("delete decl");
  SUM_ren_prefix = PyUnicode_FromString("rename ");
  SUM_mov_prefix = PyUnicode_FromString("move ");
  ARROW = PyUnicode_FromString("\xe2\x86\x92");
  ONE = PyLong_FromLong(1);
  return m;
}
