/* semmerge_opfactory — C op-object factory for the columnar op logs.
 *
 * The fused merge path keeps op logs as int32/digest columns
 * (semantic_merge_tpu/ops/oplog_view.py); consumers that need real Op
 * objects (the applier's handler dispatch, parity tests, the bench's
 * honest composed-stream consumption) previously materialized them in
 * Python at ~2 us/op — the largest host phase left after the native
 * JSON serializer. This extension builds the same objects with the
 * CPython C API: Op/Target instances via tp_new-free __new__ +
 * slot SetAttr, params/guards/effects as presized dicts, field
 * strings decoded from the cached node string tables
 * (oplog_view._node_table layout: 4 UTF-8 fields per node, int64
 * offsets).
 *
 * Two entry points:
 *   stream_ops(kind, a_slot, b_slot, words, base_blob, base_offs,
 *              side_blob, side_offs, prov, op_cls, target_cls) -> list[Op]
 *   composed_ops(<left stream args...>, <right stream args...>,
 *                sides, idxs, addr_ov, file_ov, name_ov,
 *                prov_left, prov_right, op_cls, target_cls) -> list[Op]
 * composed_ops applies the chain-override rules of
 * oplog_view._materialize_decoded row-by-row, building each final
 * composed op directly — the intermediate per-side stream objects are
 * never created. Byte-for-byte to_dict parity with the Python
 * materializers is fuzz-tested in tests/test_oplog_view.py.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>

/* Interned field/key names, created at module init. */
static PyObject *S_id, *S_schemaVersion, *S_type, *S_target, *S_params,
    *S_guards, *S_effects, *S_provenance, *S_symbolId, *S_addressId,
    *S_oldName, *S_newName, *S_file, *S_oldAddress, *S_newAddress,
    *S_oldFile, *S_newFile, *S_exists, *S_addressMatch, *S_summary,
    *S_renameContext;
static PyObject *T_renameSymbol, *T_moveDecl, *T_addDecl, *T_deleteDecl;
static PyObject *SUM_add, *SUM_del, *ARROW, *SUM_ren_prefix, *SUM_mov_prefix;
static PyObject *ONE;

typedef struct {
  const char *blob;
  Py_ssize_t blob_len;
  const int64_t *offs;
} NodeTab;

typedef struct {
  const int32_t *kind, *a_slot, *b_slot;
  const int32_t *words; /* n*4 */
  NodeTab bt, st;
} Stream;

/* Slot descriptors fetched once per entry call: setting through
 * tp_descr_set skips the generic attribute machinery, and tp_alloc
 * skips the __new__ Python call — together ~3x on object build. */
typedef struct {
  PyTypeObject *op_t, *tgt_t;
  PyObject *op_d[8];  /* id, schemaVersion, type, target, params,
                         guards, effects, provenance */
  PyObject *tgt_d[2]; /* symbolId, addressId */
  int ok;
} Factory;

static int dset(PyObject *descr, PyObject *obj, PyObject *val) {
  /* factory_init guarantees tp_descr_set exists for every descriptor */
  return Py_TYPE(descr)->tp_descr_set(descr, obj, val);
}

static int factory_init(Factory *f, PyObject *op_cls, PyObject *target_cls) {
  memset(f, 0, sizeof(*f));
  if (!PyType_Check(op_cls) || !PyType_Check(target_cls)) {
    PyErr_SetString(PyExc_TypeError, "op_cls/target_cls must be types");
    return -1;
  }
  f->op_t = (PyTypeObject *)op_cls;
  f->tgt_t = (PyTypeObject *)target_cls;
  PyObject *names[8] = {S_id, S_schemaVersion, S_type, S_target, S_params,
                        S_guards, S_effects, S_provenance};
  for (int i = 0; i < 8; i++) {
    f->op_d[i] = PyObject_GetAttr(op_cls, names[i]);
    if (!f->op_d[i] || !Py_TYPE(f->op_d[i])->tp_descr_set) {
      PyErr_SetString(PyExc_TypeError, "op_cls lacks slot descriptors");
      return -1;
    }
  }
  PyObject *tnames[2] = {S_symbolId, S_addressId};
  for (int i = 0; i < 2; i++) {
    f->tgt_d[i] = PyObject_GetAttr(target_cls, tnames[i]);
    if (!f->tgt_d[i] || !Py_TYPE(f->tgt_d[i])->tp_descr_set) {
      PyErr_SetString(PyExc_TypeError, "target_cls lacks slot descriptors");
      return -1;
    }
  }
  f->ok = 1;
  return 0;
}

static void factory_clear(Factory *f) {
  for (int i = 0; i < 8; i++) Py_XDECREF(f->op_d[i]);
  for (int i = 0; i < 2; i++) Py_XDECREF(f->tgt_d[i]);
}

/* Decode field f (0 sym, 1 addr, 2 name, 3 file) of node as str. */
static PyObject *field(const NodeTab *t, int64_t node, int f) {
  int64_t a = t->offs[node * 4 + f], b = t->offs[node * 4 + f + 1];
  if (a < 0 || b < a || b > t->blob_len) {
    PyErr_SetString(PyExc_ValueError, "node table offset out of range");
    return NULL;
  }
  return PyUnicode_DecodeUTF8(t->blob + a, b - a, "strict");
}

static const char HEXD[] = "0123456789abcdef";

static PyObject *uuid_str(const int32_t *w4) {
  char buf[36];
  char hex[32];
  for (int k = 0; k < 4; k++) {
    uint32_t v = (uint32_t)w4[k];
    for (int j = 7; j >= 0; j--) {
      hex[k * 8 + j] = HEXD[v & 0xF];
      v >>= 4;
    }
  }
  int p = 0;
  for (int i = 0; i < 32; i++) {
    if (i == 8 || i == 12 || i == 16 || i == 20) buf[p++] = '-';
    buf[p++] = hex[i];
  }
  return PyUnicode_FromStringAndSize(buf, 36);
}

static PyObject *make_target(const Factory *f, PyObject *sym,
                             PyObject *addr) {
  PyObject *t = f->tgt_t->tp_alloc(f->tgt_t, 0);
  if (!t) return NULL;
  if (dset(f->tgt_d[0], t, sym) < 0 || dset(f->tgt_d[1], t, addr) < 0) {
    Py_DECREF(t);
    return NULL;
  }
  return t;
}

/* Assemble one Op. Steals NO references; all borrowed/owned by caller.
 * effects/guards/params are owned dict refs passed in (steals them). */
static PyObject *make_op(const Factory *f, PyObject *op_id, PyObject *type,
                         PyObject *target /* stolen */,
                         PyObject *params /* stolen */,
                         PyObject *guards /* stolen */,
                         PyObject *effects /* stolen */, PyObject *prov) {
  PyObject *op = f->op_t->tp_alloc(f->op_t, 0);
  if (!op) goto fail;
  if (dset(f->op_d[0], op, op_id) < 0) goto fail_op;
  if (dset(f->op_d[1], op, ONE) < 0) goto fail_op;
  if (dset(f->op_d[2], op, type) < 0) goto fail_op;
  if (dset(f->op_d[3], op, target) < 0) goto fail_op;
  if (dset(f->op_d[4], op, params) < 0) goto fail_op;
  if (dset(f->op_d[5], op, guards) < 0) goto fail_op;
  if (dset(f->op_d[6], op, effects) < 0) goto fail_op;
  if (dset(f->op_d[7], op, prov) < 0) goto fail_op;
  Py_DECREF(target);
  Py_DECREF(params);
  Py_DECREF(guards);
  Py_DECREF(effects);
  return op;
fail_op:
  Py_DECREF(op);
fail:
  Py_XDECREF(target);
  Py_XDECREF(params);
  Py_XDECREF(guards);
  Py_XDECREF(effects);
  return NULL;
}

static PyObject *guards_for(PyObject *addr) {
  PyObject *g = PyDict_New();
  if (!g) return NULL;
  if (PyDict_SetItem(g, S_exists, Py_True) < 0 ||
      PyDict_SetItem(g, S_addressMatch, addr) < 0) {
    Py_DECREF(g);
    return NULL;
  }
  return g;
}

static PyObject *summary3(PyObject *prefix, PyObject *a, PyObject *b) {
  /* prefix + a + ARROW + b */
  PyObject *s1 = PyUnicode_Concat(prefix, a);
  if (!s1) return NULL;
  PyObject *s2 = PyUnicode_Concat(s1, ARROW);
  Py_DECREF(s1);
  if (!s2) return NULL;
  PyObject *s3 = PyUnicode_Concat(s2, b);
  Py_DECREF(s2);
  return s3;
}

static PyObject *effects_summary(PyObject *summary /* stolen */) {
  if (!summary) return NULL;
  PyObject *e = PyDict_New();
  if (!e) {
    Py_DECREF(summary);
    return NULL;
  }
  if (PyDict_SetItem(e, S_summary, summary) < 0) {
    Py_DECREF(summary);
    Py_DECREF(e);
    return NULL;
  }
  Py_DECREF(summary);
  return e;
}

/* Build op i of a stream, applying composed-row overrides when
 * addr_ov/file_ov/name_ov are non-NULL (borrowed, may be Py_None).
 * Override semantics mirror oplog_view._materialize_decoded exactly,
 * except ops are always built fresh (value-identical). */
static PyObject *build_op(const Stream *s, Py_ssize_t i, PyObject *prov,
                          const Factory *f, PyObject *addr_ov,
                          PyObject *file_ov, PyObject *name_ov) {
  int k = s->kind[i];
  PyObject *op_id = uuid_str(s->words + 4 * i);
  if (!op_id) return NULL;
  PyObject *result = NULL;
  int has_addr = addr_ov && addr_ov != Py_None;
  int has_file = file_ov && file_ov != Py_None;
  int has_name = name_ov && name_ov != Py_None;

  if (k == 0 || k == 1) { /* renameSymbol / moveDecl */
    int64_t an = s->a_slot[i], bn = s->b_slot[i];
    PyObject *a_sym = field(&s->bt, an, 0), *a_addr = field(&s->bt, an, 1);
    if (!a_sym || !a_addr) {
      Py_XDECREF(a_sym);
      Py_XDECREF(a_addr);
      goto done;
    }
    PyObject *t_addr = has_addr ? addr_ov : a_addr;
    PyObject *target = make_target(f, a_sym, t_addr);
    PyObject *guards = guards_for(a_addr);
    if (!target || !guards) {
      Py_XDECREF(target);
      Py_XDECREF(guards);
      Py_DECREF(a_sym);
      Py_DECREF(a_addr);
      goto done;
    }
    if (k == 0) { /* renameSymbol */
      PyObject *a_name = field(&s->bt, an, 2), *b_name = field(&s->st, bn, 2),
               *b_file = field(&s->st, bn, 3);
      if (!a_name || !b_name || !b_file) {
        Py_XDECREF(a_name);
        Py_XDECREF(b_name);
        Py_XDECREF(b_file);
        Py_DECREF(target);
        Py_DECREF(guards);
        Py_DECREF(a_sym);
        Py_DECREF(a_addr);
        goto done;
      }
      PyObject *params = PyDict_New();
      int ok = params && PyDict_SetItem(params, S_oldName, a_name) == 0 &&
               PyDict_SetItem(params, S_newName, b_name) == 0 &&
               PyDict_SetItem(params, S_file,
                              has_file ? file_ov : b_file) == 0;
      if (ok && has_file) /* rename + chained file: newFile then file */
        ok = PyDict_SetItem(params, S_newFile, file_ov) == 0;
      /* NOTE: _materialize_decoded sets newFile THEN overwrites file;
       * insertion order is oldName,newName,file,newFile — file was
       * already inserted above, so order matches. renameContext never
       * applies to renameSymbol. */
      PyObject *effects =
          ok ? effects_summary(summary3(SUM_ren_prefix, a_name, b_name))
             : NULL;
      Py_DECREF(a_name);
      Py_DECREF(b_name);
      Py_DECREF(b_file);
      Py_DECREF(a_sym);
      Py_DECREF(a_addr);
      if (!ok || !effects) {
        Py_XDECREF(params);
        Py_XDECREF(effects);
        Py_DECREF(target);
        Py_DECREF(guards);
        goto done;
      }
      result = make_op(f, op_id, T_renameSymbol, target, params, guards,
                       effects, prov);
    } else { /* moveDecl */
      PyObject *b_addr = field(&s->st, bn, 1), *a_file = field(&s->bt, an, 3),
               *b_file = field(&s->st, bn, 3);
      if (!b_addr || !a_file || !b_file) {
        Py_XDECREF(b_addr);
        Py_XDECREF(a_file);
        Py_XDECREF(b_file);
        Py_DECREF(target);
        Py_DECREF(guards);
        Py_DECREF(a_sym);
        Py_DECREF(a_addr);
        goto done;
      }
      PyObject *params = PyDict_New();
      int ok = params && PyDict_SetItem(params, S_oldAddress, a_addr) == 0 &&
               PyDict_SetItem(params, S_newAddress,
                              has_addr ? addr_ov : b_addr) == 0 &&
               PyDict_SetItem(params, S_oldFile, a_file) == 0 &&
               PyDict_SetItem(params, S_newFile,
                              has_file ? file_ov : b_file) == 0;
      if (ok && has_name)
        ok = PyDict_SetItem(params, S_renameContext, name_ov) == 0;
      PyObject *effects =
          ok ? effects_summary(summary3(SUM_mov_prefix, a_addr, b_addr))
             : NULL;
      Py_DECREF(b_addr);
      Py_DECREF(a_file);
      Py_DECREF(b_file);
      Py_DECREF(a_sym);
      Py_DECREF(a_addr);
      if (!ok || !effects) {
        Py_XDECREF(params);
        Py_XDECREF(effects);
        Py_DECREF(target);
        Py_DECREF(guards);
        goto done;
      }
      result = make_op(f, op_id, T_moveDecl, target, params, guards,
                       effects, prov);
    }
  } else { /* addDecl (2) / deleteDecl (3) */
    const NodeTab *tab = (k == 2) ? &s->st : &s->bt;
    int64_t node = (k == 2) ? s->b_slot[i] : s->a_slot[i];
    PyObject *sym = field(tab, node, 0), *addr = field(tab, node, 1),
             *fil = field(tab, node, 3);
    if (!sym || !addr || !fil) {
      Py_XDECREF(sym);
      Py_XDECREF(addr);
      Py_XDECREF(fil);
      goto done;
    }
    PyObject *t_addr = has_addr ? addr_ov : addr;
    PyObject *target = make_target(f, sym, t_addr);
    PyObject *params = PyDict_New();
    int ok = target && params && PyDict_SetItem(params, S_file, fil) == 0;
    if (ok && has_name)
      ok = PyDict_SetItem(params, S_renameContext, name_ov) == 0;
    PyObject *guards = ok ? PyDict_New() : NULL;
    PyObject *effects = NULL;
    if (ok && guards) {
      PyObject *sum = (k == 2) ? SUM_add : SUM_del;
      Py_INCREF(sum);
      effects = effects_summary(sum);
    }
    Py_DECREF(sym);
    Py_DECREF(addr);
    Py_DECREF(fil);
    if (!ok || !guards || !effects) {
      Py_XDECREF(target);
      Py_XDECREF(params);
      Py_XDECREF(guards);
      Py_XDECREF(effects);
      goto done;
    }
    result = make_op(f, op_id, (k == 2) ? T_addDecl : T_deleteDecl,
                     target, params, guards, effects, prov);
  }
done:
  Py_DECREF(op_id);
  return result;
}

/* ---- argument plumbing ---- */

typedef struct {
  Py_buffer kind, a_slot, b_slot, words, b_offs, s_offs;
  Py_buffer b_blob, s_blob;
  Stream s;
  Py_ssize_t n;
  int held;
} StreamArgs;

static int get_stream(PyObject *args, Py_ssize_t off, StreamArgs *sa) {
  PyObject *kind = PyTuple_GET_ITEM(args, off);
  PyObject *a_slot = PyTuple_GET_ITEM(args, off + 1);
  PyObject *b_slot = PyTuple_GET_ITEM(args, off + 2);
  PyObject *words = PyTuple_GET_ITEM(args, off + 3);
  PyObject *b_blob = PyTuple_GET_ITEM(args, off + 4);
  PyObject *b_offs = PyTuple_GET_ITEM(args, off + 5);
  PyObject *s_blob = PyTuple_GET_ITEM(args, off + 6);
  PyObject *s_offs = PyTuple_GET_ITEM(args, off + 7);
  memset(sa, 0, sizeof(*sa));
  if (PyObject_GetBuffer(kind, &sa->kind, PyBUF_C_CONTIGUOUS) < 0) return -1;
  if (PyObject_GetBuffer(a_slot, &sa->a_slot, PyBUF_C_CONTIGUOUS) < 0) goto f1;
  if (PyObject_GetBuffer(b_slot, &sa->b_slot, PyBUF_C_CONTIGUOUS) < 0) goto f2;
  if (PyObject_GetBuffer(words, &sa->words, PyBUF_C_CONTIGUOUS) < 0) goto f3;
  if (PyObject_GetBuffer(b_blob, &sa->b_blob, PyBUF_C_CONTIGUOUS) < 0) goto f4;
  if (PyObject_GetBuffer(b_offs, &sa->b_offs, PyBUF_C_CONTIGUOUS) < 0) goto f5;
  if (PyObject_GetBuffer(s_blob, &sa->s_blob, PyBUF_C_CONTIGUOUS) < 0) goto f6;
  if (PyObject_GetBuffer(s_offs, &sa->s_offs, PyBUF_C_CONTIGUOUS) < 0) goto f7;
  sa->n = sa->kind.len / 4;
  if (sa->a_slot.len != sa->kind.len || sa->b_slot.len != sa->kind.len ||
      sa->words.len != sa->kind.len * 4) {
    PyErr_SetString(PyExc_ValueError, "column length mismatch");
    goto f8;
  }
  sa->s.kind = (const int32_t *)sa->kind.buf;
  sa->s.a_slot = (const int32_t *)sa->a_slot.buf;
  sa->s.b_slot = (const int32_t *)sa->b_slot.buf;
  sa->s.words = (const int32_t *)sa->words.buf;
  sa->s.bt.blob = (const char *)sa->b_blob.buf;
  sa->s.bt.blob_len = sa->b_blob.len;
  sa->s.bt.offs = (const int64_t *)sa->b_offs.buf;
  sa->s.st.blob = (const char *)sa->s_blob.buf;
  sa->s.st.blob_len = sa->s_blob.len;
  sa->s.st.offs = (const int64_t *)sa->s_offs.buf;
  sa->held = 1;
  return 0;
f8:
  PyBuffer_Release(&sa->s_offs);
f7:
  PyBuffer_Release(&sa->s_blob);
f6:
  PyBuffer_Release(&sa->b_offs);
f5:
  PyBuffer_Release(&sa->b_blob);
f4:
  PyBuffer_Release(&sa->words);
f3:
  PyBuffer_Release(&sa->b_slot);
f2:
  PyBuffer_Release(&sa->a_slot);
f1:
  PyBuffer_Release(&sa->kind);
  return -1;
}

static void release_stream(StreamArgs *sa) {
  if (!sa->held) return;
  PyBuffer_Release(&sa->kind);
  PyBuffer_Release(&sa->a_slot);
  PyBuffer_Release(&sa->b_slot);
  PyBuffer_Release(&sa->words);
  PyBuffer_Release(&sa->b_blob);
  PyBuffer_Release(&sa->b_offs);
  PyBuffer_Release(&sa->s_blob);
  PyBuffer_Release(&sa->s_offs);
  sa->held = 0;
}

static PyObject *py_stream_ops(PyObject *self, PyObject *args) {
  (void)self;
  if (PyTuple_GET_SIZE(args) != 11) {
    PyErr_SetString(PyExc_TypeError, "stream_ops expects 11 args");
    return NULL;
  }
  StreamArgs sa;
  if (get_stream(args, 0, &sa) < 0) return NULL;
  PyObject *prov = PyTuple_GET_ITEM(args, 8);
  Factory fac;
  if (factory_init(&fac, PyTuple_GET_ITEM(args, 9),
                   PyTuple_GET_ITEM(args, 10)) < 0) {
    factory_clear(&fac);
    release_stream(&sa);
    return NULL;
  }
  PyObject *out = PyList_New(sa.n);
  if (!out) {
    factory_clear(&fac);
    release_stream(&sa);
    return NULL;
  }
  for (Py_ssize_t i = 0; i < sa.n; i++) {
    PyObject *op = build_op(&sa.s, i, prov, &fac, NULL, NULL, NULL);
    if (!op) {
      Py_DECREF(out);
      factory_clear(&fac);
      release_stream(&sa);
      return NULL;
    }
    PyList_SET_ITEM(out, i, op);
  }
  factory_clear(&fac);
  release_stream(&sa);
  return out;
}

static PyObject *py_composed_ops(PyObject *self, PyObject *args) {
  (void)self;
  if (PyTuple_GET_SIZE(args) != 25) {
    PyErr_SetString(PyExc_TypeError, "composed_ops expects 25 args");
    return NULL;
  }
  StreamArgs left, right;
  if (get_stream(args, 0, &left) < 0) return NULL;
  if (get_stream(args, 8, &right) < 0) {
    release_stream(&left);
    return NULL;
  }
  PyObject *sides = PyTuple_GET_ITEM(args, 16);
  PyObject *idxs = PyTuple_GET_ITEM(args, 17);
  PyObject *addr_ov = PyTuple_GET_ITEM(args, 18);
  PyObject *file_ov = PyTuple_GET_ITEM(args, 19);
  PyObject *name_ov = PyTuple_GET_ITEM(args, 20);
  PyObject *prov_l = PyTuple_GET_ITEM(args, 21);
  PyObject *prov_r = PyTuple_GET_ITEM(args, 22);
  Factory fac;
  int fac_ok = factory_init(&fac, PyTuple_GET_ITEM(args, 23),
                            PyTuple_GET_ITEM(args, 24)) == 0;
  PyObject *out = NULL;
  if (!fac_ok) {
    factory_clear(&fac);
    release_stream(&left);
    release_stream(&right);
    return NULL;
  }
  Py_buffer sides_b = {0}, idxs_b = {0};
  if (PyObject_GetBuffer(sides, &sides_b, PyBUF_C_CONTIGUOUS) < 0) goto done0;
  if (PyObject_GetBuffer(idxs, &idxs_b, PyBUF_C_CONTIGUOUS) < 0) goto done1;
  {
    Py_ssize_t n = sides_b.len / 4;
    const int32_t *sd = (const int32_t *)sides_b.buf;
    const int32_t *ix = (const int32_t *)idxs_b.buf;
    if (idxs_b.len != sides_b.len ||
        !PyList_Check(addr_ov) || !PyList_Check(file_ov) ||
        !PyList_Check(name_ov) || PyList_GET_SIZE(addr_ov) != n ||
        PyList_GET_SIZE(file_ov) != n || PyList_GET_SIZE(name_ov) != n) {
      PyErr_SetString(PyExc_ValueError, "composed row arrays mismatch");
      goto done2;
    }
    out = PyList_New(n);
    if (!out) goto done2;
    for (Py_ssize_t i = 0; i < n; i++) {
      const Stream *s = (sd[i] == 0) ? &left.s : &right.s;
      Py_ssize_t row = ix[i];
      Py_ssize_t limit = (sd[i] == 0) ? left.n : right.n;
      if (row < 0 || row >= limit) {
        PyErr_SetString(PyExc_IndexError, "composed ref out of range");
        Py_CLEAR(out);
        goto done2;
      }
      PyObject *op = build_op(
          s, row, (sd[i] == 0) ? prov_l : prov_r, &fac,
          PyList_GET_ITEM(addr_ov, i), PyList_GET_ITEM(file_ov, i),
          PyList_GET_ITEM(name_ov, i));
      if (!op) {
        Py_CLEAR(out);
        goto done2;
      }
      PyList_SET_ITEM(out, i, op);
    }
  }
done2:
  PyBuffer_Release(&idxs_b);
done1:
  PyBuffer_Release(&sides_b);
done0:
  factory_clear(&fac);
  release_stream(&left);
  release_stream(&right);
  return out;
}

static PyMethodDef Methods[] = {
    {"stream_ops", py_stream_ops, METH_VARARGS,
     "Build one op stream's Op objects from its columns."},
    {"composed_ops", py_composed_ops, METH_VARARGS,
     "Build the composed Op sequence from two streams' columns + "
     "per-row chain overrides."},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {PyModuleDef_HEAD_INIT,
                                       "semmerge_opfactory",
                                       NULL,
                                       -1,
                                       Methods,
                                       NULL,
                                       NULL,
                                       NULL,
                                       NULL};

static PyObject *intern(const char *s) { return PyUnicode_InternFromString(s); }

PyMODINIT_FUNC PyInit_semmerge_opfactory(void) {
  PyObject *m = PyModule_Create(&moduledef);
  if (!m) return NULL;
  S_id = intern("id");
  S_schemaVersion = intern("schemaVersion");
  S_type = intern("type");
  S_target = intern("target");
  S_params = intern("params");
  S_guards = intern("guards");
  S_effects = intern("effects");
  S_provenance = intern("provenance");
  S_symbolId = intern("symbolId");
  S_addressId = intern("addressId");
  S_oldName = intern("oldName");
  S_newName = intern("newName");
  S_file = intern("file");
  S_oldAddress = intern("oldAddress");
  S_newAddress = intern("newAddress");
  S_oldFile = intern("oldFile");
  S_newFile = intern("newFile");
  S_exists = intern("exists");
  S_addressMatch = intern("addressMatch");
  S_summary = intern("summary");
  S_renameContext = intern("renameContext");
  T_renameSymbol = intern("renameSymbol");
  T_moveDecl = intern("moveDecl");
  T_addDecl = intern("addDecl");
  T_deleteDecl = intern("deleteDecl");
  SUM_add = PyUnicode_FromString("add decl");
  SUM_del = PyUnicode_FromString("delete decl");
  SUM_ren_prefix = PyUnicode_FromString("rename ");
  SUM_mov_prefix = PyUnicode_FromString("move ");
  ARROW = PyUnicode_FromString("\xe2\x86\x92");
  ONE = PyLong_FromLong(1);
  return m;
}
