// semmerge native frontend — C++ port of the host declaration scanner.
//
// This is the TPU framework's native hot-path component, playing the
// role the Node.js TypeScript worker plays in the reference
// (reference workers/ts/src/{sast}.ts: parse + index): tokenize
// TypeScript/JavaScript source and index the five declaration kinds
// with the exact (symbolId, addressId) scheme of
// semantic_merge_tpu/frontend/{tokenizer,scanner}.py. The Python
// scanner is the semantic oracle; this library must match it
// bit-for-bit on ASCII sources (non-ASCII snapshots fall back to
// Python host-side — offsets are code-point based there, byte based
// here).
//
// C ABI (consumed via ctypes from semantic_merge_tpu/frontend/native.py):
//   char* smn_scan_snapshot(const char** paths, const char** contents,
//                           int n_files)
//     → malloc'd JSON array of decl-node records; caller frees with
//       smn_free. Two-pass semantics identical to scan_snapshot():
//       pass 1 collects declared type names across ALL files, pass 2
//       scans each file against that set.
//   void smn_free(char*)
//   int  smn_abi_version()
#include <cstdint>
#include <cstring>
#include <algorithm>
#include <atomic>
#include <functional>
#include <thread>
#include <cstdlib>
#include <cstdio>
#include <string>
#include <string_view>
#include <vector>
#include <unordered_set>

// ---------------------------------------------------------------------------
// SHA-256 (FIPS 180-4), enough for symbolId = first 16 hex chars.

namespace sha256 {

static const uint32_t K[64] = {
    0x428a2f98,0x71374491,0xb5c0fbcf,0xe9b5dba5,0x3956c25b,0x59f111f1,
    0x923f82a4,0xab1c5ed5,0xd807aa98,0x12835b01,0x243185be,0x550c7dc3,
    0x72be5d74,0x80deb1fe,0x9bdc06a7,0xc19bf174,0xe49b69c1,0xefbe4786,
    0x0fc19dc6,0x240ca1cc,0x2de92c6f,0x4a7484aa,0x5cb0a9dc,0x76f988da,
    0x983e5152,0xa831c66d,0xb00327c8,0xbf597fc7,0xc6e00bf3,0xd5a79147,
    0x06ca6351,0x14292967,0x27b70a85,0x2e1b2138,0x4d2c6dfc,0x53380d13,
    0x650a7354,0x766a0abb,0x81c2c92e,0x92722c85,0xa2bfe8a1,0xa81a664b,
    0xc24b8b70,0xc76c51a3,0xd192e819,0xd6990624,0xf40e3585,0x106aa070,
    0x19a4c116,0x1e376c08,0x2748774c,0x34b0bcb5,0x391c0cb3,0x4ed8aa4a,
    0x5b9cca4f,0x682e6ff3,0x748f82ee,0x78a5636f,0x84c87814,0x8cc70208,
    0x90befffa,0xa4506ceb,0xbef9a3f7,0xc67178f2};

static inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

struct Ctx {
  uint32_t h[8];
  uint64_t len = 0;
  uint8_t buf[64];
  size_t buflen = 0;
  Ctx() {
    h[0]=0x6a09e667; h[1]=0xbb67ae85; h[2]=0x3c6ef372; h[3]=0xa54ff53a;
    h[4]=0x510e527f; h[5]=0x9b05688c; h[6]=0x1f83d9ab; h[7]=0x5be0cd19;
  }
  void block(const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = (uint32_t(p[i*4]) << 24) | (uint32_t(p[i*4+1]) << 16) |
             (uint32_t(p[i*4+2]) << 8) | uint32_t(p[i*4+3]);
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr(w[i-15],7) ^ rotr(w[i-15],18) ^ (w[i-15] >> 3);
      uint32_t s1 = rotr(w[i-2],17) ^ rotr(w[i-2],19) ^ (w[i-2] >> 10);
      w[i] = w[i-16] + s0 + w[i-7] + s1;
    }
    uint32_t a=h[0],b=h[1],c=h[2],d=h[3],e=h[4],f=h[5],g=h[6],hh=h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr(e,6) ^ rotr(e,11) ^ rotr(e,25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K[i] + w[i];
      uint32_t S0 = rotr(a,2) ^ rotr(a,13) ^ rotr(a,22);
      uint32_t mj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + mj;
      hh=g; g=f; f=e; e=d+t1; d=c; c=b; b=a; a=t1+t2;
    }
    h[0]+=a; h[1]+=b; h[2]+=c; h[3]+=d; h[4]+=e; h[5]+=f; h[6]+=g; h[7]+=hh;
  }
  void update(const uint8_t* p, size_t n) {
    len += n;
    while (n) {
      size_t take = 64 - buflen; if (take > n) take = n;
      memcpy(buf + buflen, p, take);
      buflen += take; p += take; n -= take;
      if (buflen == 64) { block(buf); buflen = 0; }
    }
  }
  void final(uint8_t out[32]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t z = 0;
    while (buflen != 56) update(&z, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; i++) lenb[i] = uint8_t(bits >> (56 - 8*i));
    update(lenb, 8);
    for (int i = 0; i < 8; i++) {
      out[i*4]   = uint8_t(h[i] >> 24);
      out[i*4+1] = uint8_t(h[i] >> 16);
      out[i*4+2] = uint8_t(h[i] >> 8);
      out[i*4+3] = uint8_t(h[i]);
    }
  }
};

// First n_hex hex chars of sha256(data).
static std::string hex16(std::string_view data) {
  Ctx c;
  c.update(reinterpret_cast<const uint8_t*>(data.data()), data.size());
  uint8_t out[32];
  c.final(out);
  static const char* digits = "0123456789abcdef";
  std::string s;
  s.reserve(16);
  for (int i = 0; i < 8; i++) {  // 8 bytes → 16 hex chars
    s.push_back(digits[out[i] >> 4]);
    s.push_back(digits[out[i] & 0xf]);
  }
  return s;
}

}  // namespace sha256

// ---------------------------------------------------------------------------
// Tokenizer — port of semantic_merge_tpu/frontend/tokenizer.py.

enum TokType : uint8_t { T_IDENT, T_NUMBER, T_STRING, T_TEMPLATE, T_REGEX, T_PUNCT };

struct Token {
  TokType type;
  std::string_view text;
  int start;
  int end;
  int prev_end;
  bool nl_before;
};

// Longest-match-first operator table — EXACT order of tokenizer.py.
static const char* OPERATORS[] = {
    ">>>=", "...", "===", "!==", "**=", "<<=", ">>=", ">>>", "&&=", "||=", "?\?=",
    "=>", "==", "!=", "<=", ">=", "&&", "||", "??", "?.", "++", "--", "+=", "-=",
    "*=", "/=", "%=", "&=", "|=", "^=", "<<", ">>", "**",
    "{", "}", "(", ")", "[", "]", ";", ",", "<", ">", "+", "-", "*", "/", "%",
    "&", "|", "^", "!", "~", "?", ":", "=", ".", "@", "#",
};
static const int N_OPERATORS = sizeof(OPERATORS) / sizeof(OPERATORS[0]);

static inline bool is_ident_start(char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') || c == '_' || c == '$';
}
static inline bool is_digit(char c) { return c >= '0' && c <= '9'; }
static inline bool is_ident_part(char c) { return is_ident_start(c) || is_digit(c); }
static inline bool is_alnum(char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') || is_digit(c);
}

static const std::unordered_set<std::string_view> REGEX_ALLOWED_KEYWORDS = {
    "return", "typeof", "instanceof", "in", "of", "new", "delete", "void",
    "throw", "case", "do", "else", "yield", "await",
};

static bool regex_allowed(const std::vector<Token>& toks) {
  if (toks.empty()) return true;
  const Token& prev = toks.back();
  if (prev.type == T_NUMBER || prev.type == T_STRING || prev.type == T_TEMPLATE ||
      prev.type == T_REGEX)
    return false;
  if (prev.type == T_IDENT) return REGEX_ALLOWED_KEYWORDS.count(prev.text) != 0;
  return !(prev.text == ")" || prev.text == "]" || prev.text == "}" ||
           prev.text == "++" || prev.text == "--");
}

static int scan_string(std::string_view t, int i, char quote) {
  int n = int(t.size());
  i += 1;
  while (i < n) {
    char c = t[i];
    if (c == '\\') { i += 2; continue; }
    if (c == quote || c == '\n') return i + 1;
    i += 1;
  }
  return n;
}

static int scan_regex(std::string_view t, int i) {
  int n = int(t.size());
  i += 1;
  bool in_class = false;
  while (i < n) {
    char c = t[i];
    if (c == '\\') { i += 2; continue; }
    if (c == '[') in_class = true;
    else if (c == ']') in_class = false;
    else if (c == '/' && !in_class) {
      i += 1;
      while (i < n && is_ident_part(t[i])) i += 1;
      return i;
    } else if (c == '\n') return i;
    i += 1;
  }
  return n;
}

static int scan_template(std::string_view t, int i);

static int scan_substitution(std::string_view t, int i) {
  int n = int(t.size());
  int depth = 1;
  while (i < n) {
    char c = t[i];
    if (c == '\\') { i += 2; continue; }
    if (c == '\'' || c == '"') { i = scan_string(t, i, c); continue; }
    if (c == '`') { i = scan_template(t, i); continue; }
    if (c == '{') depth += 1;
    else if (c == '}') {
      depth -= 1;
      if (depth == 0) return i + 1;
    }
    i += 1;
  }
  return n;
}

static int scan_template(std::string_view t, int i) {
  int n = int(t.size());
  i += 1;
  while (i < n) {
    char c = t[i];
    if (c == '\\') { i += 2; continue; }
    if (c == '`') return i + 1;
    if (c == '$' && i + 1 < n && t[i + 1] == '{') {
      i = scan_substitution(t, i + 2);
      continue;
    }
    i += 1;
  }
  return n;
}

static const char* match_operator(std::string_view t, int i) {
  for (int k = 0; k < N_OPERATORS; k++) {
    const char* op = OPERATORS[k];
    size_t len = strlen(op);
    if (t.size() - size_t(i) >= len && memcmp(t.data() + i, op, len) == 0) return op;
  }
  return nullptr;
}

static std::vector<Token> tokenize(std::string_view text) {
  std::vector<Token> toks;
  toks.reserve(text.size() / 6 + 8);
  int i = 0;
  int n = int(text.size());
  int prev_end = 0;
  bool nl_before = false;
  while (i < n) {
    char c = text[i];
    if (c == ' ' || c == '\t' || c == '\r' || c == '\f' || c == '\v') { i += 1; continue; }
    if (c == '\n') { nl_before = true; i += 1; continue; }
    if (c == '/' && i + 1 < n) {
      if (text[i + 1] == '/') {
        size_t j = text.find('\n', i);
        i = (j == std::string_view::npos) ? n : int(j);
        continue;
      }
      if (text[i + 1] == '*') {
        size_t j = text.find("*/", i + 2);
        if (j == std::string_view::npos) { i = n; continue; }
        if (text.substr(i, j - i).find('\n') != std::string_view::npos) nl_before = true;
        i = int(j) + 2;
        continue;
      }
    }
    int start = i;
    Token tok;
    if (is_ident_start(c)) {
      while (i < n && is_ident_part(text[i])) i += 1;
      tok = {T_IDENT, text.substr(start, i - start), start, i, prev_end, nl_before};
    } else if (is_digit(c) || (c == '.' && i + 1 < n && is_digit(text[i + 1]))) {
      while (i < n && (is_alnum(text[i]) || text[i] == '.' || text[i] == '_')) i += 1;
      tok = {T_NUMBER, text.substr(start, i - start), start, i, prev_end, nl_before};
    } else if (c == '\'' || c == '"') {
      i = scan_string(text, i, c);
      tok = {T_STRING, text.substr(start, i - start), start, i, prev_end, nl_before};
    } else if (c == '`') {
      i = scan_template(text, i);
      tok = {T_TEMPLATE, text.substr(start, i - start), start, i, prev_end, nl_before};
    } else if (c == '/' && regex_allowed(toks)) {
      i = scan_regex(text, i);
      tok = {T_REGEX, text.substr(start, i - start), start, i, prev_end, nl_before};
    } else {
      const char* op = match_operator(text, i);
      if (op == nullptr) { i += 1; continue; }  // stray byte: skip
      i += int(strlen(op));
      tok = {T_PUNCT, text.substr(start, i - start), start, i, prev_end, nl_before};
    }
    toks.push_back(tok);
    prev_end = tok.end;
    nl_before = false;
  }
  return toks;
}

// ---------------------------------------------------------------------------
// Scanner — port of semantic_merge_tpu/frontend/scanner.py.

static const char* KIND_FUNCTION = "FunctionDeclaration";
static const char* KIND_CLASS = "ClassDeclaration";
static const char* KIND_INTERFACE = "InterfaceDeclaration";
static const char* KIND_ENUM = "EnumDeclaration";
static const char* KIND_VARS = "VariableStatement";

static const std::unordered_set<std::string_view> EXPRESSION_PREV = {
    "=", "(", "[", ",", ":", "?", "!", "&", "|", "+", "-", "*", "/", "%",
    "<", ">", "=>", "==", "===", "!=", "!==", "&&", "||", "??", "...",
    "+=", "-=", "*=", "/=", "?\?=", "&&=", "||=", ".", "?.",
};
static const std::unordered_set<std::string_view> EXPRESSION_PREV_IDENTS = {
    "return", "typeof", "new", "delete", "void", "in", "of", "instanceof",
    "yield", "await", "case", "do", "throw", "extends", "default",
};
static const std::unordered_set<std::string_view> DECL_MODIFIERS = {
    "export", "default", "declare", "async", "abstract", "public", "private",
    "protected",
};
static const std::unordered_set<std::string_view> PRIMITIVE_TYPES = {
    "string", "number", "boolean", "any", "unknown", "never", "void", "object",
    "undefined", "null", "bigint", "symbol", "this", "true", "false",
};

struct DeclNode {
  std::string symbolId;
  std::string addressId;
  const char* kind;
  std::string name;   // empty + has_name=false → null
  bool has_name;
  std::string file;
  int pos;
  int end;
  std::string signature;
};

using TokVec = std::vector<Token>;
using StrSet = std::unordered_set<std::string>;

static std::string normalize_path(std::string p) {
  for (auto& ch : p)
    if (ch == '\\') ch = '/';
  if (p.rfind("./", 0) == 0) p = p.substr(2);
  if (!p.empty() && p[0] == '/') p = p.substr(1);
  return p;
}

static bool is_expression_position(const TokVec& toks, int i) {
  int j = i - 1;
  while (j >= 0 && toks[j].type == T_IDENT && DECL_MODIFIERS.count(toks[j].text)) j -= 1;
  if (j < 0) return false;
  const Token& prev = toks[j];
  if (prev.type == T_PUNCT) return EXPRESSION_PREV.count(prev.text) != 0;
  if (prev.type == T_IDENT) return EXPRESSION_PREV_IDENTS.count(prev.text) != 0;
  return true;
}

static StrSet collect_type_names(const TokVec& toks) {
  StrSet names;
  int n = int(toks.size());
  for (int i = 0; i < n; i++) {
    const Token& t = toks[i];
    if (t.type != T_IDENT || i + 1 >= n) continue;
    const Token& nxt = toks[i + 1];
    bool head = (t.text == "class" || t.text == "interface" || t.text == "enum" ||
                 t.text == "type");
    if (head && nxt.type == T_IDENT) {
      if (t.text == "type" &&
          (i + 2 >= n || !(toks[i + 2].text == "=" || toks[i + 2].text == "<")))
        continue;
      if (t.text == "class" && is_expression_position(toks, i)) continue;
      names.insert(std::string(nxt.text));
    }
  }
  return names;
}

// Index of the `@` starting a (possibly dotted) decorator name ending
// just before j — `@Name` / `@ns.sub.Name` — or -1 (twin of
// scanner._decorator_start).
static int decorator_start(const TokVec& toks, int j) {
  int t = j - 1;
  if (t < 0 || toks[t].type != T_IDENT) return -1;
  while (t - 2 >= 0 && toks[t - 1].text == "." && toks[t - 2].type == T_IDENT)
    t -= 2;
  if (t - 1 >= 0 && toks[t - 1].text == "@") return t - 1;
  return -1;
}

static int full_start(const TokVec& toks, int i) {
  // Walk back over modifiers AND decorators: TS parses `@dec` as part
  // of the declaration node, so the node's pos starts before it
  // (twin of scanner._full_start).
  int j = i;
  while (j - 1 >= 0) {
    const Token& prev = toks[j - 1];
    if (prev.type == T_IDENT && DECL_MODIFIERS.count(std::string(prev.text))) {
      j -= 1;
      continue;
    }
    if (prev.text == ")") {  // @ Name( ... ) / @ ns.Name( ... )
      int k = j - 1, depth = 0;
      while (k >= 0) {
        if (toks[k].text == ")") depth += 1;
        else if (toks[k].text == "(") {
          depth -= 1;
          if (depth == 0) break;
        }
        k -= 1;
      }
      int start = decorator_start(toks, k);
      if (start >= 0) {
        j = start;
        continue;
      }
    }
    if (prev.type == T_IDENT) {
      int start = decorator_start(toks, j);
      if (start >= 0) {
        j = start;
        continue;
      }
    }
    break;
  }
  return toks[j].prev_end;
}

// (names, index_after) for a `<T, U extends X = Y>` list at i. Type
// parameters resolve lexically: the checker renders a type-parameter
// reference by its name even with no default lib, so the signature
// renderers treat these names as in-scope types (twin of
// scanner._type_param_names).
static int type_param_names(const TokVec& toks, int i,
                            std::vector<std::string>* names) {
  int n = int(toks.size());
  if (i < n && toks[i].text == "<") {
    int depth = 0;
    bool expecting = false;
    while (i < n) {
      const auto& t = toks[i].text;
      if (t == "<") {
        depth += 1;
        if (depth == 1) expecting = true;
      } else if (t == ">" || t == ">>" || t == ">>>") {
        depth -= int(t.size());  // count of '>' chars
        if (depth <= 0) return i + 1;
      } else if (depth == 1 && t == ",") {
        expecting = true;
      } else if (expecting && depth == 1 && toks[i].type == T_IDENT &&
                 t != "const" && t != "in" && t != "out") {
        if (names) names->push_back(std::string(t));
        expecting = false;
      }
      i += 1;
    }
  }
  return i;
}

static int skip_type_params(const TokVec& toks, int i) {
  return type_param_names(toks, i, nullptr);
}

static int matching_brace(const TokVec& toks, int i) {
  int depth = 0;
  int n = int(toks.size());
  while (i < n) {
    if (toks[i].text == "{") depth += 1;
    else if (toks[i].text == "}") {
      depth -= 1;
      if (depth == 0) return i;
    }
    i += 1;
  }
  return n - 1;
}

static int matching_paren(const TokVec& toks, int i) {
  int depth = 0;
  int n = int(toks.size());
  while (i < n) {
    if (toks[i].text == "(") depth += 1;
    else if (toks[i].text == ")") {
      depth -= 1;
      if (depth == 0) return i;
    }
    i += 1;
  }
  return n - 1;
}

static bool has_default_modifier(const TokVec& toks, int i) {
  int j = i - 1;
  while (j >= 0 && toks[j].type == T_IDENT && DECL_MODIFIERS.count(toks[j].text)) {
    if (toks[j].text == "default") return true;
    j -= 1;
  }
  return false;
}

// --- type display (typeToString emulation) ---------------------------------

static std::string render_type_text(const std::vector<std::string_view>& parts,
                                    const StrSet& declared);

static std::vector<std::vector<std::string_view>> split_top(
    const std::vector<std::string_view>& parts, std::string_view sep) {
  std::vector<std::vector<std::string_view>> out;
  out.emplace_back();
  int depth = 0;
  for (const auto& p : parts) {
    if (p == "(" || p == "[" || p == "{" || p == "<") depth += 1;
    else if (p == ")" || p == "]" || p == "}" || p == ">") depth -= 1;
    if (p == sep && depth == 0) out.emplace_back();
    else out.back().push_back(p);
  }
  return out;
}

static bool is_numeric_literal(std::string_view name) {
  size_t k = 0;
  while (k < name.size() && name[k] == '-') k += 1;  // lstrip("-")
  if (k == name.size()) return false;
  for (; k < name.size(); k++)
    if (!is_digit(name[k])) return false;
  return true;
}

// Python str.isidentifier() for the ASCII subset the tokenizer emits:
// letters/underscore start, letters/digits/underscore continue ('$' is
// an identifier char in JS but NOT in Python — parity with the oracle).
static bool is_identifier_text(std::string_view s) {
  if (s.empty()) return false;
  char c0 = s[0];
  if (!((c0 >= 'a' && c0 <= 'z') || (c0 >= 'A' && c0 <= 'Z') || c0 == '_'))
    return false;
  for (size_t k = 1; k < s.size(); k++) {
    char c = s[k];
    if (!((c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') || is_digit(c) ||
          c == '_'))
      return false;
  }
  return true;
}

static std::string join(const std::vector<std::string_view>& parts,
                        const char* sep) {
  std::string out;
  for (size_t k = 0; k < parts.size(); k++) {
    if (k) out += sep;
    out.append(parts[k].data(), parts[k].size());
  }
  return out;
}

static std::string render_type_text(const std::vector<std::string_view>& parts,
                                    const StrSet& declared) {
  if (parts.empty()) return "any";  // e.g. trailing comma's empty element
  // Union / intersection at top level.
  for (const char* op : {"|", "&"}) {
    auto pieces = split_top(parts, op);
    if (pieces.size() > 1) {
      std::string out;
      for (size_t k = 0; k < pieces.size(); k++) {
        if (k) { out += " "; out += op; out += " "; }
        out += render_type_text(pieces[k], declared);
      }
      return out;
    }
  }
  // Trailing [] — array type.
  if (parts.size() >= 2 && parts[parts.size() - 1] == "]" &&
      parts[parts.size() - 2] == "[") {
    std::vector<std::string_view> inner(parts.begin(), parts.end() - 2);
    std::string elem = render_type_text(inner, declared);
    if (elem.find(" | ") != std::string::npos || elem.find(" & ") != std::string::npos)
      return "(" + elem + ")[]";
    return elem + "[]";
  }
  // Parenthesized. (After the union check, no depth-0 "|" remains, so the
  // Python `_split_top(parts, "|") == [parts]` guard is always true here.)
  if (!parts.empty() && parts[0] == "(") {
    if (parts.back() == ")") {
      std::vector<std::string_view> inner(parts.begin() + 1, parts.end() - 1);
      return render_type_text(inner, declared);
    }
  }
  if (parts.size() == 1) {
    std::string_view name = parts[0];
    if (PRIMITIVE_TYPES.count(name) || is_numeric_literal(name) ||
        (!name.empty() && (name[0] == '\'' || name[0] == '"' || name[0] == '`')))
      return std::string(name);
    return declared.count(std::string(name)) ? std::string(name) : "any";
  }
  // Generic reference ``Name<...>`` — unresolved without a default lib.
  if (!parts.empty() && !PRIMITIVE_TYPES.count(parts[0]) && parts.size() >= 2 &&
      parts[1] == "<")
    return declared.count(std::string(parts[0])) ? std::string(parts[0]) : "any";
  // Qualified name ``Ns.Thing`` — namespaces are not indexed decl kinds,
  // so the no-default-lib checker cannot resolve the root: "any".
  if (parts.size() >= 3 && parts.size() % 2 == 1) {
    bool qualified = true;
    for (size_t k = 1; k < parts.size(); k += 2)
      if (parts[k] != ".") { qualified = false; break; }
    if (qualified)
      for (size_t k = 0; k < parts.size(); k += 2)
        if (!is_identifier_text(parts[k])) { qualified = false; break; }
    if (qualified) return "any";
  }
  // Tuple type ``[A, B]`` — render element-wise like the checker.
  if (!parts.empty() && parts[0] == "[" && parts.back() == "]" &&
      parts.size() > 2) {
    std::vector<std::string_view> inner(parts.begin() + 1, parts.end() - 1);
    auto elems = split_top(inner, ",");
    std::string out = "[";
    bool first = true;
    for (auto& elem : elems) {
      if (elem.empty()) continue;  // trailing comma's empty element drops
      if (!first) out += ", ";
      first = false;
      out += render_type_text(elem, declared);
    }
    return out + "]";
  }
  // Fallback display with checker-style punctuation spacing: no space
  // before ":,;.)]>", none after "([<.".
  std::vector<std::string> grouped;
  for (const auto& p : parts) {
    bool attach = false;
    if (!grouped.empty()) {
      char last = grouped.back().back();
      if (p == "," || p == ";" || p == ":" || p == ")" || p == "]" ||
          p == ">" || p == ".")
        attach = true;
      else if (last == '(' || last == '[' || last == '<' || last == '.')
        attach = true;
    }
    if (attach) grouped.back().append(p.data(), p.size());
    else grouped.emplace_back(p);
  }
  std::string out;
  for (size_t k = 0; k < grouped.size(); k++) {
    if (k) out += " ";
    out += grouped[k];
  }
  return out;
}

static std::string render_type(const std::vector<const Token*>& type_toks,
                               const StrSet& declared) {
  if (type_toks.empty()) return "any";
  std::vector<std::string_view> parts;
  parts.reserve(type_toks.size());
  for (const Token* t : type_toks) parts.push_back(t->text);
  return render_type_text(parts, declared);
}

// --- parameter / annotation parsing ----------------------------------------

static std::vector<const Token*> annotation_of(const std::vector<const Token*>& ptoks) {
  int depth = 0;
  int start = -1;
  for (size_t idx = 0; idx < ptoks.size(); idx++) {
    std::string_view t = ptoks[idx]->text;
    if (t == "(" || t == "[" || t == "{" || t == "<") depth += 1;
    else if (t == ")" || t == "]" || t == "}" || t == ">") depth -= 1;
    else if (depth == 0 && t == ":" && start < 0) start = int(idx) + 1;
    else if (depth == 0 && t == "=" && start >= 0)
      return {ptoks.begin() + start, ptoks.begin() + idx};
    else if (depth == 0 && t == "=" && start < 0)
      return {};
  }
  if (start >= 0) return {ptoks.begin() + start, ptoks.end()};
  return {};
}

static std::vector<std::string> parse_param_types(
    const std::vector<const Token*>& param_toks, const StrSet& declared) {
  std::vector<std::string> types;
  if (param_toks.empty()) return types;
  std::vector<std::vector<const Token*>> params;
  params.emplace_back();
  int depth = 0;
  for (const Token* t : param_toks) {
    std::string_view x = t->text;
    if (x == "(" || x == "[" || x == "{" || x == "<") depth += 1;
    else if (x == ")" || x == "]" || x == "}" || x == ">") depth -= 1;
    if (x == "," && depth == 0) params.emplace_back();
    else params.back().push_back(t);
  }
  for (const auto& ptoks : params) {
    if (ptoks.empty()) continue;
    auto ann = annotation_of(ptoks);
    types.push_back(ann.empty() ? "any" : render_type(ann, declared));
  }
  return types;
}

// A depth-0 "{" after one of these continues the type (object-literal
// type position); after a completed type atom it opens the body.
static const StrSet TYPE_EXPECTED_AFTER = {":", "|", "&", "(", ",", "<", "=>",
                                           "extends", "keyof", "readonly", "?"};

static std::pair<std::vector<const Token*>, int> collect_type_tokens(
    const TokVec& toks, int i, const StrSet& stop) {
  std::vector<const Token*> out;
  int depth = 0;
  int n = int(toks.size());
  bool expecting = true;  // start of annotation: a type is expected
  while (i < n) {
    const Token& t = toks[i];
    std::string txt(t.text);
    if (depth == 0 && stop.count(txt) && !(txt == "{" && expecting)) break;
    if (t.text == "(" || t.text == "[" || t.text == "<" || t.text == "{") depth += 1;
    else if (t.text == ")" || t.text == "]" || t.text == ">" || t.text == "}") {
      if (depth == 0) break;
      depth -= 1;
    }
    expecting = TYPE_EXPECTED_AFTER.count(txt) != 0;
    out.push_back(&t);
    i += 1;
  }
  return {out, i};
}

// --- node construction ------------------------------------------------------

static DeclNode mk_node(const std::string& path, const TokVec& toks, int start_i,
                        int end_i, const char* kind, const std::string& name,
                        bool has_name, const std::string& sig) {
  int pos = full_start(toks, start_i);
  int end = toks[std::min(end_i, int(toks.size()) - 1)].end;
  std::string address = path + "::" + (has_name ? name : std::string("anon")) +
                        "::" + std::to_string(pos);
  DeclNode node;
  node.symbolId = sha256::hex16(sig);
  node.addressId = address;
  node.kind = kind;
  node.name = name;
  node.has_name = has_name;
  node.file = path;
  node.pos = pos;
  node.end = end;
  node.signature = sig;
  return node;
}

// --- function declarations --------------------------------------------------

static bool scan_function(const std::string& path, const TokVec& toks, int i,
                          const StrSet& declared, DeclNode* out) {
  if (is_expression_position(toks, i)) return false;
  int n = int(toks.size());
  int j = i + 1;
  if (j < n && toks[j].text == "*") j += 1;  // generator
  std::string name;
  bool has_name = false;
  if (j < n && toks[j].type == T_IDENT) {
    name = std::string(toks[j].text);
    has_name = true;
    j += 1;
  }
  std::vector<std::string> tp_names;
  j = type_param_names(toks, j, &tp_names);
  if (j >= n || toks[j].text != "(") return false;
  if (!has_name && !has_default_modifier(toks, i)) return false;
  // The decl's own type parameters are lexically in scope for its
  // param/return annotations and render by name (checker semantics).
  StrSet local_owned;
  const StrSet* scope = &declared;
  if (!tp_names.empty()) {
    local_owned = declared;
    for (auto& nm : tp_names) local_owned.insert(nm);
    scope = &local_owned;
  }
  int params_start = j;
  int params_end = matching_paren(toks, params_start);
  std::vector<const Token*> ptoks;
  for (int k = params_start + 1; k < params_end; k++) ptoks.push_back(&toks[k]);
  auto param_types = parse_param_types(ptoks, *scope);
  int k = params_end + 1;
  std::string ret_type = "any";
  if (k < n && toks[k].text == ":") {
    static const StrSet stop = {"{", ";"};
    auto [type_toks, k2] = collect_type_tokens(toks, k + 1, stop);
    ret_type = render_type(type_toks, *scope);
    k = k2;
  }
  int end_idx;
  if (k < n && toks[k].text == "{") end_idx = matching_brace(toks, k);
  else if (k < n && toks[k].text == ";") end_idx = k;
  else end_idx = params_end;
  std::string sig = "fn(";
  for (size_t q = 0; q < param_types.size(); q++) {
    if (q) sig += ",";
    sig += param_types[q];
  }
  sig += ")->" + ret_type;
  *out = mk_node(path, toks, i, end_idx, KIND_FUNCTION, name, has_name, sig);
  return true;
}

// --- class / interface / enum -----------------------------------------------

static bool asi_break(const Token& prev, const Token& cur) {
  if (prev.type == T_PUNCT &&
      !(prev.text == ")" || prev.text == "]" || prev.text == "}"))
    return false;
  if (cur.type == T_PUNCT && !(cur.text == "[" || cur.text == "@" || cur.text == "#"))
    return false;
  static const std::unordered_set<std::string_view> member_heads = {
      "get", "set", "static", "readonly", "public", "private", "protected",
      "abstract", "async", "new"};
  if (prev.type == T_IDENT && member_heads.count(prev.text)) return false;
  return true;
}

static int member_end(const TokVec& toks, int i, int body_end, bool allow_method_body) {
  int depth = 0;
  bool seen_eq = false;
  int n = body_end;
  int start = i;  // the ASI check must not fire on the member's own first token
  while (i < n) {
    const Token& t = toks[i];
    if (t.text == "(" || t.text == "[") depth += 1;
    else if (t.text == ")" || t.text == "]") depth -= 1;
    else if (t.text == "{") {
      if (depth == 0 && !seen_eq && allow_method_body)
        return matching_brace(toks, i) + 1;
      depth += 1;
    } else if (t.text == "}") depth -= 1;
    else if (depth == 0) {
      if (t.text == "=") seen_eq = true;
      else if (t.text == ";" || t.text == ",") return i + 1;
      else if (t.nl_before && i > start && asi_break(toks[i - 1], t)) return i;
    }
    i += 1;
  }
  return n;
}

static int count_class_members(const TokVec& toks, int body_start, int body_end) {
  int count = 0;
  int i = body_start + 1;
  while (i < body_end) {
    if (toks[i].text == ";") { count += 1; i += 1; continue; }
    count += 1;
    i = member_end(toks, i, body_end, /*allow_method_body=*/true);
  }
  return count;
}

static int count_interface_members(const TokVec& toks, int body_start, int body_end) {
  int count = 0;
  int i = body_start + 1;
  while (i < body_end) {
    if (toks[i].text == ";" || toks[i].text == ",") { i += 1; continue; }
    count += 1;
    i = member_end(toks, i, body_end, /*allow_method_body=*/false);
  }
  return count;
}

static int count_enum_members(const TokVec& toks, int body_start, int body_end) {
  int count = 0;
  int depth = 0;
  bool has_content = false;
  for (int i = body_start + 1; i < body_end; i++) {
    const Token& t = toks[i];
    if (t.text == "(" || t.text == "[" || t.text == "{") depth += 1;
    else if (t.text == ")" || t.text == "]" || t.text == "}") depth -= 1;
    else if (t.text == "," && depth == 0) {
      if (has_content) count += 1;
      has_content = false;
      continue;
    }
    if (depth == 0 && t.text != ",") has_content = true;
  }
  if (has_content) count += 1;
  return count;
}

static bool scan_braced_decl(const std::string& path, const TokVec& toks, int i,
                             const char* kind, DeclNode* out) {
  if (is_expression_position(toks, i)) return false;
  int n = int(toks.size());
  int j = i + 1;
  std::string name;
  bool has_name = false;
  if (j < n && toks[j].type == T_IDENT && toks[j].text != "extends" &&
      toks[j].text != "implements") {
    name = std::string(toks[j].text);
    has_name = true;
    j += 1;
  }
  if (!has_name && (kind == KIND_INTERFACE || kind == KIND_ENUM)) return false;
  j = skip_type_params(toks, j);
  while (j < n && toks[j].text != "{") {
    if (toks[j].text == ";" || toks[j].text == ")") return false;
    j += 1;
  }
  if (j >= n) return false;
  int body_start = j;
  int body_end = matching_brace(toks, body_start);
  std::string sig;
  if (kind == KIND_CLASS)
    sig = "class{" + std::to_string(count_class_members(toks, body_start, body_end)) + "}";
  else if (kind == KIND_INTERFACE)
    sig = "iface{" + std::to_string(count_interface_members(toks, body_start, body_end)) + "}";
  else
    sig = "enum{" + std::to_string(count_enum_members(toks, body_start, body_end)) + "}";
  int start_i = i;
  if (kind == KIND_ENUM && i - 1 >= 0 && toks[i - 1].text == "const") start_i = i - 1;
  *out = mk_node(path, toks, start_i, body_end, kind, name, has_name, sig);
  return true;
}

// --- variable statements -----------------------------------------------------

static bool var_asi_break(const Token& prev, const Token& cur) {
  if (prev.type == T_PUNCT &&
      !(prev.text == ")" || prev.text == "]" || prev.text == "}"))
    return false;
  if (cur.type == T_PUNCT &&
      (cur.text == "+" || cur.text == "-" || cur.text == "*" || cur.text == "/" ||
       cur.text == "." || cur.text == "?." || cur.text == "=" || cur.text == "(" ||
       cur.text == "[" || cur.text == "`"))
    return false;
  if (cur.type == T_IDENT &&
      (cur.text == "instanceof" || cur.text == "in" || cur.text == "of" ||
       cur.text == "as"))
    return false;
  return true;
}

static bool scan_var_statement(const std::string& path, const TokVec& toks, int i,
                               DeclNode* out) {
  int n = int(toks.size());
  if (i + 1 < n && toks[i + 1].text == "enum") return false;  // const enum
  if (i + 1 >= n ||
      !(toks[i + 1].type == T_IDENT || toks[i + 1].text == "[" || toks[i + 1].text == "{"))
    return false;
  if (toks[i + 1].type == T_IDENT &&
      (toks[i + 1].text == "in" || toks[i + 1].text == "of" ||
       toks[i + 1].text == "instanceof"))
    return false;
  int j = i - 1;
  if (j >= 0 && toks[j].text == "(" && j - 1 >= 0 && toks[j - 1].type == T_IDENT &&
      (toks[j - 1].text == "for" || toks[j - 1].text == "await"))
    return false;
  if (is_expression_position(toks, i)) return false;
  int depth = 0;
  int declarators = 1;
  int k = i + 1;
  int end_idx = i;
  while (k < n) {
    const Token& t2 = toks[k];
    if (t2.text == "(" || t2.text == "[" || t2.text == "{") depth += 1;
    else if (t2.text == ")" || t2.text == "]") {
      depth -= 1;
      if (depth < 0) break;
    } else if (t2.text == "}") {
      depth -= 1;
      if (depth < 0) break;
    } else if (depth == 0) {
      if (t2.text == ";") { end_idx = k; break; }
      if (t2.text == ",") declarators += 1;
      else if (t2.nl_before && var_asi_break(toks[k - 1], t2)) break;
      else if (t2.type == T_IDENT && (t2.text == "of" || t2.text == "in") &&
               toks[k - 1].type == T_IDENT)
        return false;
    }
    end_idx = k;
    k += 1;
  }
  std::string sig = "vars{" + std::to_string(declarators) + "}";
  *out = mk_node(path, toks, i, end_idx, KIND_VARS, "", /*has_name=*/false, sig);
  return true;
}

// --- file scan ---------------------------------------------------------------

static void scan_tokens(const std::string& path, const TokVec& toks,
                        const StrSet& declared, std::vector<DeclNode>* nodes) {
  int n = int(toks.size());
  for (int i = 0; i < n; i++) {
    const Token& t = toks[i];
    if (t.type != T_IDENT) continue;
    std::string_view word = t.text;
    DeclNode node;
    bool ok = false;
    if (word == "function") ok = scan_function(path, toks, i, declared, &node);
    else if (word == "class") ok = scan_braced_decl(path, toks, i, KIND_CLASS, &node);
    else if (word == "interface") ok = scan_braced_decl(path, toks, i, KIND_INTERFACE, &node);
    else if (word == "enum") ok = scan_braced_decl(path, toks, i, KIND_ENUM, &node);
    else if (word == "var" || word == "let" || word == "const")
      ok = scan_var_statement(path, toks, i, &node);
    if (ok) nodes->push_back(std::move(node));
  }
}

// ---------------------------------------------------------------------------
// JSON output.

static void json_escape(const std::string& s, std::string* out) {
  for (unsigned char c : s) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\n': *out += "\\n"; break;
      case '\t': *out += "\\t"; break;
      case '\r': *out += "\\r"; break;
      case '\b': *out += "\\b"; break;
      case '\f': *out += "\\f"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          *out += buf;
        } else {
          out->push_back(char(c));
        }
    }
  }
}

static void append_node_json(const DeclNode& n, std::string* out) {
  *out += "{\"symbolId\":\"";
  json_escape(n.symbolId, out);
  *out += "\",\"addressId\":\"";
  json_escape(n.addressId, out);
  *out += "\",\"kind\":\"";
  *out += n.kind;
  *out += "\",\"name\":";
  if (n.has_name) {
    *out += "\"";
    json_escape(n.name, out);
    *out += "\"";
  } else {
    *out += "null";
  }
  *out += ",\"file\":\"";
  json_escape(n.file, out);
  *out += "\",\"pos\":" + std::to_string(n.pos);
  *out += ",\"end\":" + std::to_string(n.end);
  *out += ",\"signature\":\"";
  json_escape(n.signature, out);
  *out += "\"}";
}

// ---------------------------------------------------------------------------
// C ABI.

// ---------------------------------------------------------------------------
// Parallel helper: run fn(i) for i in [0, n) across a small thread pool.
// Per-file work (tokenize / scan) is independent; only the declared-set
// merge and output concatenation are sequential — the work-stealing
// parse/bind pool the reference designs but never builds (reference
// architecture.md "parallelism model": parallel per file/package).

static void parallel_for(int n, const std::function<void(int)>& fn) {
  unsigned hw = std::thread::hardware_concurrency();
  int n_threads = int(hw ? hw : 4);
  if (n_threads > n) n_threads = n;
  if (n_threads <= 1 || n < 32) {  // small snapshots: threads cost more
    for (int i = 0; i < n; i++) fn(i);
    return;
  }
  std::atomic<int> next{0};
  std::vector<std::thread> pool;
  pool.reserve(n_threads);
  for (int t = 0; t < n_threads; t++) {
    pool.emplace_back([&] {
      for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) fn(i);
    });
  }
  for (auto& th : pool) th.join();
}

extern "C" {

int smn_abi_version() { return 4; }

// Scan a snapshot: two passes exactly like scan_snapshot() — collect
// declared type names across all files, then scan each file in snapshot
// order. Per-file tokenize and scan run thread-parallel; node order
// stays deterministic (concatenation in snapshot order). Returns a
// malloc'd JSON array; free with smn_free.
char* smn_scan_snapshot(const char** paths, const char** contents, int n_files) {
  std::vector<std::string> sources(n_files);
  std::vector<std::string> norm_paths(n_files);
  std::vector<TokVec> toks(n_files);
  std::vector<StrSet> names(n_files);
  parallel_for(n_files, [&](int f) {
    sources[f] = contents[f];
    norm_paths[f] = normalize_path(paths[f]);
    toks[f] = tokenize(sources[f]);
    names[f] = collect_type_names(toks[f]);
  });
  StrSet declared;
  for (int f = 0; f < n_files; f++)
    for (auto& name : names[f]) declared.insert(name);
  std::vector<std::vector<DeclNode>> per_file(n_files);
  parallel_for(n_files, [&](int f) {
    scan_tokens(norm_paths[f], toks[f], declared, &per_file[f]);
  });
  std::string out = "[";
  bool first = true;
  for (int f = 0; f < n_files; f++) {
    for (auto& node : per_file[f]) {
      if (!first) out += ",";
      first = false;
      append_node_json(node, &out);
    }
  }
  out += "]";
  char* buf = static_cast<char*>(malloc(out.size() + 1));
  memcpy(buf, out.data(), out.size() + 1);
  return buf;
}

// Pass 1 only: per-file declared type names as a JSON array of sorted
// string arrays. Lets the host-side decl cache compute the snapshot's
// declared-set hash without falling back to the Python tokenizer.
char* smn_type_names(const char** contents, int n_files) {
  std::vector<std::vector<std::string>> per_file(n_files);
  parallel_for(n_files, [&](int f) {
    std::string src(contents[f]);
    TokVec toks = tokenize(src);
    for (auto& name : collect_type_names(toks)) per_file[f].push_back(name);
    std::sort(per_file[f].begin(), per_file[f].end());
  });
  std::string out = "[";
  for (int f = 0; f < n_files; f++) {
    if (f) out += ",";
    out += "[";
    for (size_t k = 0; k < per_file[f].size(); k++) {
      if (k) out += ",";
      out += "\"";
      json_escape(per_file[f][k], &out);
      out += "\"";
    }
    out += "]";
  }
  out += "]";
  char* buf = static_cast<char*>(malloc(out.size() + 1));
  memcpy(buf, out.data(), out.size() + 1);
  return buf;
}

// Combined cold-path entry: one tokenize pass yields BOTH the per-file
// declared type names (for the host decl cache's keys) and the decl
// nodes — a fully-cold cached scan costs exactly one native pass.
// Returns {"names": [[...], ...], "nodes": [...]}.
char* smn_scan_with_names(const char** paths, const char** contents, int n_files) {
  std::vector<std::string> sources(n_files);
  std::vector<std::string> norm_paths(n_files);
  std::vector<TokVec> toks(n_files);
  std::vector<std::vector<std::string>> names(n_files);
  parallel_for(n_files, [&](int f) {
    sources[f] = contents[f];
    norm_paths[f] = normalize_path(paths[f]);
    toks[f] = tokenize(sources[f]);
    for (auto& name : collect_type_names(toks[f])) names[f].push_back(name);
    std::sort(names[f].begin(), names[f].end());
  });
  StrSet declared;
  for (int f = 0; f < n_files; f++)
    for (auto& name : names[f]) declared.insert(name);
  std::vector<std::vector<DeclNode>> per_file(n_files);
  parallel_for(n_files, [&](int f) {
    scan_tokens(norm_paths[f], toks[f], declared, &per_file[f]);
  });
  std::string out = "{\"names\":[";
  for (int f = 0; f < n_files; f++) {
    if (f) out += ",";
    out += "[";
    for (size_t k = 0; k < names[f].size(); k++) {
      if (k) out += ",";
      out += "\"";
      json_escape(names[f][k], &out);
      out += "\"";
    }
    out += "]";
  }
  out += "],\"nodes\":[";
  bool first = true;
  for (int f = 0; f < n_files; f++) {
    for (auto& node : per_file[f]) {
      if (!first) out += ",";
      first = false;
      append_node_json(node, &out);
    }
  }
  out += "]}";
  char* buf = static_cast<char*>(malloc(out.size() + 1));
  memcpy(buf, out.data(), out.size() + 1);
  return buf;
}

// Columnar op-log serializer — the native twin of
// semantic_merge_tpu/ops/oplog_view.py OpStreamView.to_json(). The
// fused device path fetches op streams as int32 columns; this renders
// the canonical op-log JSON (the reference parity surface,
// semmerge/ops.py:106-121 shape) straight from those columns plus two
// node string tables, byte-identical to the Python serializer
// (fuzz-tested in tests/test_oplog_view.py).
//
//   kind   : n int32 diff kinds (0 rename, 1 move, 2 add, 3 delete)
//   a_slot : n int32 indices into the base node table (rename/move/delete)
//   b_slot : n int32 indices into the side node table (rename/move/add)
//   words  : n*4 uint32 op-id digest words; uuid hex = the words
//            rendered big-endian in order, dashes at 8/12/16/20
//   *_blob/*_offs: node tables — per node, 4 UTF-8 fields (symbolId,
//            addressId, name, file) as [offs[4i+k], offs[4i+k+1])
//            byte ranges of blob; offsets int64, 4*m+1 entries
//   prov   : the pre-rendered provenance JSON object (shared per stream)

static const char HEXD[] = "0123456789abcdef";

static inline void append_uuid(const uint32_t* w, std::string* out) {
  char buf[36];
  char hex[32];
  for (int k = 0; k < 4; k++) {
    uint32_t v = w[k];
    for (int j = 7; j >= 0; j--) { hex[k * 8 + j] = HEXD[v & 0xF]; v >>= 4; }
  }
  int p = 0;
  for (int i = 0; i < 32; i++) {
    if (i == 8 || i == 12 || i == 16 || i == 20) buf[p++] = '-';
    buf[p++] = hex[i];
  }
  out->append(buf, 36);
}

struct NodeTab {
  const char* blob;
  const int64_t* offs;
};

static inline void append_field(const NodeTab& t, int64_t node, int field,
                                std::string* out) {
  int64_t a = t.offs[node * 4 + field], b = t.offs[node * 4 + field + 1];
  const char* s = t.blob + a;
  int64_t len = b - a;
  // Fast path: no byte needs escaping (the overwhelming case for
  // identifiers/paths); single scan, bulk append.
  bool clean = true;
  for (int64_t i = 0; i < len; i++) {
    unsigned char c = (unsigned char)s[i];
    if (c < 0x20 || c == '"' || c == '\\') { clean = false; break; }
  }
  if (clean) { out->append(s, (size_t)len); return; }
  std::string tmp(s, (size_t)len);
  json_escape(tmp, out);
}

char* smn_oplog_json(int n,
                     const int32_t* kind, const int32_t* a_slot,
                     const int32_t* b_slot, const uint32_t* words,
                     const char* base_blob, const int64_t* base_offs,
                     const char* side_blob, const int64_t* side_offs,
                     const char* prov_json, int64_t* out_len) {
  NodeTab bt{base_blob, base_offs};
  NodeTab st{side_blob, side_offs};
  std::string prov(prov_json);
  std::string out;
  out.reserve((size_t)n * 420 + 2);
  out += "[";
  for (int i = 0; i < n; i++) {
    if (i) out += ",";
    out += "{\"id\":\"";
    append_uuid(words + (size_t)i * 4, &out);
    out += "\",\"schemaVersion\":1,\"type\":\"";
    int k = kind[i];
    int64_t a = a_slot[i], b = b_slot[i];
    switch (k) {
      case 0: {  // renameSymbol
        out += "renameSymbol\",\"target\":{\"symbolId\":\"";
        append_field(bt, a, 0, &out);
        out += "\",\"addressId\":\"";
        append_field(bt, a, 1, &out);
        out += "\"},\"params\":{\"oldName\":\"";
        append_field(bt, a, 2, &out);
        out += "\",\"newName\":\"";
        append_field(st, b, 2, &out);
        out += "\",\"file\":\"";
        append_field(st, b, 3, &out);
        out += "\"},\"guards\":{\"exists\":true,\"addressMatch\":\"";
        append_field(bt, a, 1, &out);
        out += "\"},\"effects\":{\"summary\":\"rename ";
        append_field(bt, a, 2, &out);
        out += "\xe2\x86\x92";  // U+2192 →
        append_field(st, b, 2, &out);
        out += "\"},\"provenance\":";
        break;
      }
      case 1: {  // moveDecl
        out += "moveDecl\",\"target\":{\"symbolId\":\"";
        append_field(bt, a, 0, &out);
        out += "\",\"addressId\":\"";
        append_field(bt, a, 1, &out);
        out += "\"},\"params\":{\"oldAddress\":\"";
        append_field(bt, a, 1, &out);
        out += "\",\"newAddress\":\"";
        append_field(st, b, 1, &out);
        out += "\",\"oldFile\":\"";
        append_field(bt, a, 3, &out);
        out += "\",\"newFile\":\"";
        append_field(st, b, 3, &out);
        out += "\"},\"guards\":{\"exists\":true,\"addressMatch\":\"";
        append_field(bt, a, 1, &out);
        out += "\"},\"effects\":{\"summary\":\"move ";
        append_field(bt, a, 1, &out);
        out += "\xe2\x86\x92";
        append_field(st, b, 1, &out);
        out += "\"},\"provenance\":";
        break;
      }
      case 2: {  // addDecl
        out += "addDecl\",\"target\":{\"symbolId\":\"";
        append_field(st, b, 0, &out);
        out += "\",\"addressId\":\"";
        append_field(st, b, 1, &out);
        out += "\"},\"params\":{\"file\":\"";
        append_field(st, b, 3, &out);
        out += "\"},\"guards\":{},\"effects\":{\"summary\":\"add decl\"},"
               "\"provenance\":";
        break;
      }
      default: {  // deleteDecl
        out += "deleteDecl\",\"target\":{\"symbolId\":\"";
        append_field(bt, a, 0, &out);
        out += "\",\"addressId\":\"";
        append_field(bt, a, 1, &out);
        out += "\"},\"params\":{\"file\":\"";
        append_field(bt, a, 3, &out);
        out += "\"},\"guards\":{},\"effects\":{\"summary\":\"delete decl\"},"
               "\"provenance\":";
        break;
      }
    }
    out += prov;
    out += "}";
  }
  out += "]";
  *out_len = (int64_t)out.size();
  char* buf = static_cast<char*>(malloc(out.size() + 1));
  memcpy(buf, out.data(), out.size() + 1);
  return buf;
}

void smn_free(char* p) { free(p); }

}  // extern "C"
