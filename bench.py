"""Benchmark harness — prints ONE JSON line for the driver.

Metric (per ``BASELINE.json``): files merged/sec/chip on a synthetic
multi-file TypeScript 3-way merge. The workload mirrors the reference's
measurement ladder rung 2-3 (100s of files, independent renames on side
A, cross-file moves on side B, a few adds/deletes). Baseline is the
pure-Python host path — the stand-in for the reference's per-file Node
worker (`workers/ts/src/{sast,diff,lift}.ts` + `semmerge/compose.py`),
which cannot run here (no Node in the image). ``vs_baseline`` is the
TPU-path speedup over that host path on the identical workload.

Since round 5 the timed unit runs merge → composed-stream consumption
(what the CLI's apply layer reads) → notes op-log JSON payloads (the
CLI's persisted deliverable) on BOTH paths, so the number cannot be
gamed by returning lazy objects. Since the columnar-applier round the
device path's consumption is the applier's real read: the shard-wise
apply-action plan built from the composed view's columns (chain decode
forced, params through the field tables) — the host path still
materializes its Op list, and parity gates both against identical
output.

Usage: ``python bench.py [--files N] [--decls N] [--json-only]``
"""
from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from semantic_merge_tpu.utils.jaxenv import enable_compile_cache  # noqa: E402

enable_compile_cache()

from semantic_merge_tpu.frontend.snapshot import Snapshot  # noqa: E402
from semantic_merge_tpu.obs import metrics as obs_metrics  # noqa: E402
from semantic_merge_tpu.obs import spans as obs_spans  # noqa: E402


_SIG_TYPES = ("string", "number", "boolean", "bigint", "symbol", "object",
              "unknown", "never", "void", "undefined", "null")


def _unique_params(idx: int, n_digits: int) -> str:
    """Param list whose *types* encode ``idx`` in base-11, so every decl
    gets a unique name-free structural signature (symbolId is computed
    from param/return types only — same-shape decls collide, a
    reference quirk the workload must avoid to stay per-file).
    ``n_digits`` must cover the largest index used."""
    digits = []
    for _ in range(n_digits):
        digits.append(_SIG_TYPES[idx % len(_SIG_TYPES)])
        idx //= len(_SIG_TYPES)
    assert idx == 0, "index exceeds signature capacity"
    return ", ".join(f"p{k}: {t}" for k, t in enumerate(digits))


def synth_repo(n_files: int, decls_per_file: int, divergent: bool = False):
    """Three snapshots of an ``n_files`` TS repo.

    Side A renames one function per even-indexed file; side B moves
    every odd-indexed file into ``lib/`` (a cross-file decl move, the
    flagship scenario of the reference's ``tests/e2e_basic.sh``); a few
    files gain or lose a declaration so every diff kind appears. With
    ``divergent``, side B renames a sprinkling of the functions side A
    also renamed — to a *different* name — the DivergentRename conflict
    workload of measurement-ladder rung 5.
    """
    total = n_files * decls_per_file
    n_digits = 1
    while len(_SIG_TYPES) ** n_digits < total:
        n_digits += 1
    base, left, right = [], [], []
    for i in range(n_files):
        path = f"src/mod{i:05d}.ts"
        decls = []
        for d in range(decls_per_file):
            params = _unique_params(i * decls_per_file + d, n_digits)
            decls.append(f"export function fn{i}_{d}({params}): number {{ return {d}; }}")
        content = "\n".join(decls) + "\n"
        base.append({"path": path, "content": content})

        if i % 2 == 0:
            left.append({"path": path,
                         "content": content.replace(f"function fn{i}_0(",
                                                    f"function renamed{i}_0(")})
        elif i % 17 == 0:
            left.append({"path": path, "content": content +
                         f"export function added{i}(x: string): string {{ return x; }}\n"})
        else:
            left.append({"path": path, "content": content})

        if divergent and i % 96 == 0:
            right.append({"path": path,
                          "content": content.replace(f"function fn{i}_0(",
                                                     f"function other{i}_0(")})
        elif i % 2 == 1:
            right.append({"path": f"lib/mod{i:05d}.ts", "content": content})
        elif i % 23 == 0:
            lines = content.splitlines(keepends=True)
            right.append({"path": path, "content": "".join(lines[1:])})
        else:
            right.append({"path": path, "content": content})
    return Snapshot(files=base), Snapshot(files=left), Snapshot(files=right)


def run_merge(backend, base, left, right):
    from semantic_merge_tpu.backends.base import run_merge as _rm
    return _rm(backend, base, left, right, base_rev="bench", seed="bench",
               timestamp="2026-01-01T00:00:00Z")


def serialize_payload(result) -> int:
    """Produce the notes op-log JSON payloads — the CLI's deliverable
    for a merge (cli.py cmd_semmerge → notes_put). Timed as part of
    every merge since round 5: the device path serializes columnar
    (ops/oplog_view.py, no Op objects), the host path from its Op
    lists — both are measured producing identical bytes, so
    ``vs_baseline`` compares output-to-output, not object-to-object."""
    from semantic_merge_tpu.core.ops import OpLog
    return (len(OpLog(result.op_log_left).to_json_bytes())
            + len(OpLog(result.op_log_right).to_json_bytes()))


def run_merge_to_payload(backend, base, left, right):
    result, composed, conflicts = run_merge(backend, base, left, right)
    # Serialize first: the notes payloads need only the two op streams,
    # so under SEMMERGE_SPLIT_FETCH the composed view's chain columns
    # keep streaming device→host during this work (the deferred-fetch
    # pipeline seam). Identical deliverables either way; this is a
    # schedule, not a shortcut.
    with obs_spans.span("serialize", layer="runtime"):
        n_bytes = serialize_payload(result)
    # Consume the composed stream the way the CLI's applier does. Since
    # the columnar-applier round that is the shard-wise apply-action
    # plan read straight off the view's columns (runtime/applier
    # consume_stream — chain decode forced, every param read through
    # the field tables, zero Op objects); object streams (the host
    # path, SEMMERGE_OBJECT_APPLY=1) still materialize every op. Both
    # paths pay their full apply-side consumption inside the timed
    # window — the number cannot be gamed by returning lazy objects.
    from semantic_merge_tpu.runtime.applier import consume_stream
    with obs_spans.span("compose_materialize", layer="ops"):
        consume_stream(composed)
    return result, composed, conflicts, n_bytes


def _interval_union(intervals):
    """Sorted disjoint union of ``(start, end)`` intervals."""
    out = []
    for s, e in sorted(intervals):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _covered_seconds(union, lo, hi):
    """Seconds of ``[lo, hi)`` covered by a sorted disjoint union."""
    total = 0.0
    for s, e in union:
        if e <= lo:
            continue
        if s >= hi:
            break
        total += min(e, hi) - max(s, lo)
    return total


def _tail_disjoint(phases: dict, recorder) -> dict:
    """Report the host-tail phases DISJOINTLY against the overlap pool.

    Phase totals are per-span wall sums. The shared tail pool executes
    its ``materialize_overlap`` shard jobs *during* the main thread's
    ``serialize``/``compose_materialize`` span windows (eager
    prefetch, ops/fused.py TailPlan), so the same wall instant used to
    land in two phases — once in the main-thread phase's wall, once in
    the worker's ``materialize_overlap`` record — and ``host_tail_ms``
    double-counted the overlapped stretch whenever the tail pipeline
    was on. Attribute overlapped instants to ``materialize_overlap``
    exclusively: each tail phase reports its wall MINUS the union of
    worker intervals intersecting its own window, so summing the tail
    trio with ``materialize_overlap`` counts every instant once."""
    rows = recorder.span_dicts()
    workers = _interval_union(
        (r["t_start"], r["t_start"] + r["seconds"])
        for r in rows if r["name"] == "materialize_overlap")
    if not workers:
        return phases
    out = dict(phases)
    for name in HOST_TAIL_PHASES:
        if name not in out:
            continue
        covered = sum(
            _covered_seconds(workers, r["t_start"],
                             r["t_start"] + r["seconds"])
            for r in rows if r["name"] == name)
        if covered > 0.0:
            out[name] = max(0.0, out[name] - covered)
    return out


def instrumented_phases(backend, base, left, right, repeats: int = 2):
    """Instrumented merge-to-payload runs; per-phase wall-times come
    from the shared obs metrics registry — the same spine the CLI's
    ``--trace`` reads — so BENCH ``phases_ms`` and CLI trace artifacts
    share one timing code path (no hand-rolled phase dicts). Activating
    a SpanRecorder switches the fused engine into detailed mode (kernel
    sync fences), exactly like a ``--trace`` CLI run. Tail phases are
    reported disjointly (:func:`_tail_disjoint`): pool-worker overlap
    time counts under ``materialize_overlap`` only, never a second time
    inside the main-thread phase wall it overlapped. Each phase
    reports its minimum over ``repeats`` runs — the same best-of
    posture as the wall-clock measurement (a single run's tail phases
    showed ~2× allocator/GC jitter on busy 1-core hosts)."""
    best: dict = {}
    for _ in range(max(1, repeats)):
        before = obs_metrics.phase_totals()
        recorder = obs_spans.SpanRecorder()
        with obs_spans.activated(recorder):
            run_merge_to_payload(backend, base, left, right)
        run_phases = _tail_disjoint(
            obs_metrics.phase_totals_since(before), recorder)
        for k, v in run_phases.items():
            best[k] = min(best.get(k, v), v)
    return best


#: Main-thread phases of the post-kernel host tail (the serial-Python
#: cost the pipelined-materialization round attacks). Their sum is the
#: BENCH ``host_tail_ms`` headline.
HOST_TAIL_PHASES = ("compose_decode", "serialize", "compose_materialize")


def host_tail_summary(phases: dict) -> dict:
    """Additive BENCH fields for the host-tail pipeline: the tail trio
    sum, the worker-side busy time recorded under ``materialize_overlap``
    (shard decode + materialize executed on the tail pool), and
    ``hidden_ms`` — worker time that did NOT surface in the main
    thread's ``compose_materialize`` wall, i.e. tail work genuinely
    overlapped behind serialization/transfer. On a single-core host the
    pipeline runs its shards lazily, so ``hidden_ms`` is ~0 by design."""
    from semantic_merge_tpu.ops.fused import resolve_host_workers
    tail_ms = sum(phases.get(k, 0.0) for k in HOST_TAIL_PHASES) * 1e3
    worker_ms = phases.get("materialize_overlap", 0.0) * 1e3
    visible_ms = phases.get("compose_materialize", 0.0) * 1e3
    return {
        "host_tail_ms": round(tail_ms, 1),
        "overlap": {
            "host_workers": resolve_host_workers(),
            "worker_ms": round(worker_ms, 1),
            "hidden_ms": round(max(0.0, worker_ms - visible_ms), 1),
        },
    }


def time_merge(backend, base, left, right, *, repeats: int = 3) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        run_merge_to_payload(backend, base, left, right)
        best = min(best, time.perf_counter() - t0)
    return best


def probe_roundtrip_ms(repeats: int = 5) -> float:
    """Median dispatch+fetch latency of a trivial device program — the
    floor any synchronous device interaction pays. Through the remote
    accelerator tunnel this measured ~65 ms (2026-07-29), which is the
    number that killed the two-program device path of rounds 2-3 and
    motivated the one-fetch fused merge program."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    x = jnp.zeros((8,), jnp.int32)
    f = jax.jit(lambda a, k: a + k)
    np.asarray(f(x, 0))  # compile
    times = []
    for k in range(1, repeats + 1):
        t0 = time.perf_counter()
        np.asarray(f(x, k))
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2] * 1e3


def synth_repo_sparse(n_files: int, decls_per_file: int, n_changed: int):
    """A large tree where only ``n_changed`` files differ — the
    reference's own budget scenario (its perf budgets assume ≤200
    changed files of a 1M-LOC monorepo, reference
    ``architecture.md:311-313``). Changed files alternate between a
    left-side rename and a right-side cross-file move."""
    total = n_files * decls_per_file
    n_digits = 1
    while len(_SIG_TYPES) ** n_digits < total:
        n_digits += 1
    step = max(1, n_files // n_changed)
    base, left, right = [], [], []
    for i in range(n_files):
        path = f"src/mod{i:05d}.ts"
        decls = []
        for d in range(decls_per_file):
            params = _unique_params(i * decls_per_file + d, n_digits)
            decls.append(f"export function fn{i}_{d}({params}): number {{ return {d}; }}")
        content = "\n".join(decls) + "\n"
        base.append({"path": path, "content": content})
        k = i // step
        is_changed = (i % step == 0) and k < n_changed
        if is_changed and k % 2 == 0:
            left.append({"path": path,
                         "content": content.replace(f"function fn{i}_0(",
                                                    f"function renamed{i}_0(")})
        else:
            left.append({"path": path, "content": content})
        if is_changed and k % 2 == 1:
            right.append({"path": f"lib/mod{i:05d}.ts", "content": content})
        else:
            right.append({"path": path, "content": content})
    return Snapshot(files=base), Snapshot(files=left), Snapshot(files=right)


def changed_paths(base, left, right) -> set:
    """The merge scope, computed the way the CLI's ``git diff
    --name-only`` union sees it: every path whose content differs (or
    exists on only one side) between base and either side."""
    base_m = {f["path"]: f["content"] for f in base.files}
    scope: set = set()
    for side in (left, right):
        side_m = {f["path"]: f["content"] for f in side.files}
        for p, c in side_m.items():
            if base_m.get(p) != c:
                scope.add(p)
        for p in base_m:
            if p not in side_m:
                scope.add(p)
    return scope


#: The extract/inline fixture pairs of the strict workload (the shapes
#: ``core.difflift.body_motions`` detects; see tests/test_motions.py).
#: Every fixture decl's structural signature is unique — within the
#: quartet and against the synthetic decls (which all return number) —
#: so the name-free symbolId join cannot cross-match them.
_X_BIG = ("export function xbig(s: string): string"
          " { return s.trim() + '!'; }\n")
_X_BIG_CALLS = ("export function xbig(s: string): string"
                " { return xhelper(s, 0); }\n")
_X_HELPER = ("export function xhelper(s: string, pad: number): string"
             " { return s.trim() + '!'; }\n")
_Y_UTIL = ("export function yutil(s: unknown): string"
           " { return s.trim(); }\n")
_Y_CALLER = ("export function ycaller(s: string, n: boolean): string"
             " { return yutil(s); }\n")
_Y_CALLER_INLINED = ("export function ycaller(s: string, n: boolean): string"
                     " { return s.trim(); }\n")


def synth_repo_strict(n_files: int, decls_per_file: int,
                      n_edits: int = 300):
    """The ``--strict-conflicts`` workload: the rung-5 tree shape, but
    the edits are statement-level — side A rewrites ``n_edits``
    function *bodies* (editStmtBlock extraction, ≥2-statement blocks so
    the motion-size floor keeps them), side B rewrites a disjoint
    handful, plus one extract pair (side A splits ``xbig``'s body into
    a new ``xhelper``) and one inline pair (side B folds ``yutil`` into
    ``ycaller``) — so the strict join, the body-motion pass, and
    statement lifting all run at repo scale."""
    total = n_files * decls_per_file
    n_digits = 1
    while len(_SIG_TYPES) ** n_digits < total:
        n_digits += 1
    step = max(1, n_files // max(1, n_edits))
    base, left, right = [], [], []
    for i in range(n_files):
        path = f"src/mod{i:05d}.ts"
        decls = []
        for d in range(decls_per_file):
            params = _unique_params(i * decls_per_file + d, n_digits)
            decls.append(f"export function fn{i}_{d}({params}): number "
                         f"{{ return {d}; }}")
        content = "\n".join(decls) + "\n"
        base.append({"path": path, "content": content})
        edited = content.replace(
            "{ return 0; }", f"{{ const t{i} = {i} % 7; return t{i} + 1; }}")
        if i % step == 0:
            left.append({"path": path, "content": edited})
            right.append({"path": path, "content": content})
        elif i % (step * 3) == 1:
            left.append({"path": path, "content": content})
            right.append({"path": path, "content": edited})
        else:
            left.append({"path": path, "content": content})
            right.append({"path": path, "content": content})
    for rows, xbig, xhelper, ycaller, yutil in (
            (base, _X_BIG, None, _Y_CALLER, _Y_UTIL),
            (left, _X_BIG_CALLS, _X_HELPER, _Y_CALLER, _Y_UTIL),
            (right, _X_BIG, None, _Y_CALLER_INLINED, "")):
        rows.append({"path": "src/xbig.ts", "content": xbig})
        if xhelper is not None:
            rows.append({"path": "src/xhelper.ts", "content": xhelper})
        rows.append({"path": "src/ycaller.ts", "content": ycaller})
        rows.append({"path": "src/yutil.ts", "content": yutil})
    return Snapshot(files=base), Snapshot(files=left), Snapshot(files=right)


def run_strict_bench(record: dict, args, json_only: bool = False) -> int:
    """The ``strict`` preset: measure what ``--strict-conflicts`` costs
    with a phase split, instead of leaving it unknown. The pipeline is
    the CLI's strict branch — ``build_and_diff`` with statement ops →
    ``detect_conflicts_strict`` (the ``strict_detect`` span) → compose —
    run to the same payload endpoint as the fused path, parity-gated
    device-vs-host, with the non-strict wall on the identical workload
    reported alongside so the strict premium is explicit."""
    from semantic_merge_tpu.backends.base import get_backend
    from semantic_merge_tpu.core.ops import OpLog
    from semantic_merge_tpu.core.strict_conflicts import \
        detect_conflicts_strict
    from semantic_merge_tpu.runtime.applier import consume_stream

    base, left, right = synth_repo_strict(args.files, args.decls)
    kw = dict(base_rev="bench", seed="bench",
              timestamp="2026-01-01T00:00:00Z")

    def strict_merge(backend):
        result = backend.build_and_diff(base, left, right,
                                        statement_ops=True, **kw)
        with obs_spans.span("strict_detect", layer="core",
                            n_a=len(result.op_log_left),
                            n_b=len(result.op_log_right)):
            ops_a, ops_b, conflicts = detect_conflicts_strict(
                result.op_log_left, result.op_log_right)
        composed, walk = backend.compose(ops_a, ops_b)
        with obs_spans.span("serialize", layer="runtime"):
            len(OpLog(result.op_log_left).to_json_bytes())
            len(OpLog(result.op_log_right).to_json_bytes())
        with obs_spans.span("compose_materialize", layer="ops"):
            consume_stream(composed)
        return result, composed, conflicts + walk

    # Parity gate (and jit warm-up) before anything is timed.
    res_t, comp_t, conf_t = strict_merge(get_backend("tpu"))
    res_h, comp_h, conf_h = strict_merge(get_backend("host"))
    parity = (
        [o.to_dict() for o in res_t.op_log_left]
        == [o.to_dict() for o in res_h.op_log_left]
        and [o.to_dict() for o in res_t.op_log_right]
        == [o.to_dict() for o in res_h.op_log_right]
        and [o.to_dict() for o in comp_t] == [o.to_dict() for o in comp_h]
        and [c.to_dict() for c in conf_t] == [c.to_dict() for c in conf_h])
    motions = sum(o.type in ("extractMethod", "inlineMethod")
                  for ops in (res_t.op_log_left, res_t.op_log_right)
                  for o in ops)

    tpu = get_backend("tpu")
    before = obs_metrics.phase_totals()
    with obs_spans.activated(obs_spans.SpanRecorder()):
        strict_merge(tpu)
    phases = obs_metrics.phase_totals_since(before)

    best_strict = best_plain = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        strict_merge(tpu)
        best_strict = min(best_strict, time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_merge_to_payload(tpu, base, left, right)
        best_plain = min(best_plain, time.perf_counter() - t0)

    import jax
    platform = jax.devices()[0].platform
    record["metric"] = (
        f"files merged/sec/chip (strict-conflicts 3-way TS merge, "
        f"{args.files} files x {args.decls} decls, parity="
        f"{'ok' if parity else 'FAIL'}, platform={platform})")
    record["value"] = round(args.files / best_strict, 2)
    record["vs_baseline"] = round(best_plain / best_strict, 3)
    record["strict_ms"] = round(best_strict * 1e3, 1)
    record["nonstrict_ms"] = round(best_plain * 1e3, 1)
    record["strict_conflicts"] = len(conf_t)
    record["strict_motion_ops"] = motions
    record["phases_ms"] = {k: round(v * 1e3, 1) for k, v in phases.items()}
    record["parity"] = bool(parity)
    if not json_only:
        print(f"# strict path:     {best_strict*1e3:8.1f} ms "
              f"({len(conf_t)} conflicts, {motions} motion ops)",
              file=sys.stderr)
        print(f"# non-strict path: {best_plain*1e3:8.1f} ms",
              file=sys.stderr)
        print("# phases: " + "  ".join(f"{k}={v*1e3:.1f}ms"
                                       for k, v in phases.items()),
              file=sys.stderr)
    emit_record(record)
    return 0 if parity else 1


def _tracecost_fleet_leg(record: dict, json_only: bool = False) -> bool:
    """The fleet leg of the ``tracecost`` preset: what the stitched
    observability plane costs a merge through a live 2-member fleet.
    Dark = stitching off, no trace artifacts, no OTLP. On = the full
    plane: members ship span trees, the router grafts and persists
    stitched artifacts, and the OTLP exporter streams them to a local
    collector sink. Both arms run the same fixed small workload (the
    fleet preset's 24-file service repo — the leg measures a relative
    overhead, not throughput), hedging off so every merge runs exactly
    once. Both fleets stay up for the whole measurement and samples
    are interleaved one-for-one (sequential arms read machine drift as
    overhead); the compared statistic is the per-arm median latency.
    Emits the additive ``fleet_trace_overhead_pct`` field and returns
    whether it stayed under the 2% budget."""
    import http.server
    import shutil
    import signal as signal_mod
    import socketserver
    import subprocess
    import tempfile
    import threading

    from semantic_merge_tpu.service import client as svc_client

    scratch = pathlib.Path(tempfile.mkdtemp(prefix="semmerge-tracefleet-"))
    repo = scratch / "repo"
    _build_service_repo(repo, 24, 4)

    child_env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.abspath(__file__))
    prior_pp = child_env.get("PYTHONPATH", "")
    child_env["PYTHONPATH"] = (f"{pkg_root}{os.pathsep}{prior_pp}"
                               if prior_pp else pkg_root)
    child_env.update({
        "SEMMERGE_DAEMON": "off",
        "SEMMERGE_FLEET_HEALTH_INTERVAL": "0.2",
        "SEMMERGE_SUPERVISE_BACKOFF": "0.1",
        "SEMMERGE_SERVICE_DRAIN_TIMEOUT": "2",
        "SEMMERGE_FLEET_HEDGE": "off",
    })
    for key in ("SEMMERGE_FAULT", "SEMMERGE_METRICS",
                "SEMMERGE_SERVICE_SOCKET", "SEMMERGE_FLEET",
                "SEMMERGE_FLEET_MEMBERS", "SEMMERGE_FLEET_HEDGE_MS",
                "SEMMERGE_FLEET_STITCH", "SEMMERGE_FLEET_TRACE_DIR",
                "SEMMERGE_OTLP_ENDPOINT", "SEMMERGE_OTLP_QUEUE"):
        child_env.pop(key, None)
    if os.environ.get("SEMMERGE_BENCH_PLATFORM") == "cpu":
        child_env["JAX_PLATFORMS"] = "cpu"

    # A local collector sink so the on arm pays the real HTTP export
    # path, not a connection-refused fast failure.
    class _Sink(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    class _Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
        daemon_threads = True

    sink = _Server(("127.0.0.1", 0), _Sink)
    sink_url = f"http://127.0.0.1:{sink.server_address[1]}"
    threading.Thread(target=sink.serve_forever, daemon=True).start()

    def teardown(proc):
        if proc is None or proc.poll() is not None:
            return
        proc.send_signal(signal_mod.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()

    def spawn(tag, extra_env):
        sock = str(scratch / f"fleet-{tag}.sock")
        env = dict(child_env)
        env.update(extra_env)
        log = open(sock + ".log", "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "semantic_merge_tpu", "fleet",
             "--socket", sock, "--members", "2"],
            stdin=subprocess.DEVNULL, stdout=log, stderr=log,
            cwd="/", env=env, start_new_session=True)
        log.close()
        return proc, sock

    def wait_up(tag, proc, sock):
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                return (f"{tag} router exited rc={proc.returncode} "
                        f"(log: {sock}.log)")
            try:
                status = svc_client.call_control("status", path=sock,
                                                 timeout=10)
            except Exception:
                status = None
            if status and status.get("members_up", 0) >= 2:
                return None
            time.sleep(0.2)
        return f"{tag} fleet not up (log: {sock}.log)"

    def merge(sock):
        """One routed merge; returns its wall seconds or None."""
        t0 = time.perf_counter()
        frame = svc_client.call_verb(
            "semmerge",
            {"argv": ["basebr", "brA", "brB", "--backend", "host"],
             "cwd": str(repo), "env": {},
             "idempotency_key": f"tc-{os.urandom(8).hex()}"},
            path=sock, timeout=180)
        if (frame.get("result") or {}).get("exit_code") != 0:
            return None
        return time.perf_counter() - t0

    def median(xs):
        xs = sorted(xs)
        mid = len(xs) // 2
        return (xs[mid] if len(xs) % 2
                else (xs[mid - 1] + xs[mid]) / 2.0)

    samples = 64
    arms = {"dark": {"SEMMERGE_FLEET_STITCH": "off"},
            "on": {"SEMMERGE_FLEET_TRACE_DIR": str(scratch / "traces"),
                   "SEMMERGE_OTLP_ENDPOINT": sink_url}}
    procs = {}
    try:
        err = None
        for tag, extra in arms.items():
            procs[tag] = spawn(tag, extra)
        for tag, (proc, sock) in procs.items():
            err = err or wait_up(tag, proc, sock)
        lat = {tag: [] for tag in arms}
        if err is None:
            for tag, (_, sock) in procs.items():
                for _ in range(4):  # warm the owner's merge path
                    if merge(sock) is None:
                        err = f"{tag} warm-up merge failed"
                        break
        if err is None:
            for _ in range(samples):
                for tag, (_, sock) in procs.items():
                    dt = merge(sock)
                    if dt is None:
                        err = f"{tag} timed merge failed"
                        break
                    lat[tag].append(dt)
                if err:
                    break
        if err is None and not list((scratch / "traces").glob("*.json")):
            err = "on arm produced no stitched trace artifacts"
        if err:
            prior = record.get("error")
            msg = f"tracecost fleet leg: {err}"
            record["error"] = f"{prior}; {msg}" if prior else msg
            return False
        dark_s, on_s = median(lat["dark"]), median(lat["on"])
        overhead = ((on_s - dark_s) / dark_s * 100.0
                    if dark_s > 0 else 0.0)
        ok = overhead < 2.0
        record["fleet_trace_overhead_pct"] = round(overhead, 3)
        record["fleet_trace_dark_ms"] = round(dark_s * 1e3, 1)
        record["fleet_trace_on_ms"] = round(on_s * 1e3, 1)
        if not ok:
            prior = record.get("error")
            msg = (f"fleet trace overhead {overhead:.2f}% exceeds "
                   f"the 2% budget")
            record["error"] = f"{prior}; {msg}" if prior else msg
        if not json_only:
            print(f"# fleet dark: {dark_s*1e3:8.1f} ms/merge   "
                  f"stitched+otlp: {on_s*1e3:8.1f} ms/merge   "
                  f"overhead: {overhead:+.2f}% "
                  f"(medians over {samples} interleaved merges/arm)",
                  file=sys.stderr)
        return ok
    finally:
        for proc, _sock in procs.values():
            teardown(proc)
        sink.shutdown()
        sink.server_close()
        shutil.rmtree(scratch, ignore_errors=True)


def run_tracecost_bench(record: dict, args, backend, base, left, right,
                        json_only: bool = False) -> int:
    """The ``tracecost`` preset: what always-on observability costs a
    rung-5 merge. Dark = flight ring disabled, no recorder (the
    pre-request-tracing fast path). On = the daemon's per-request
    posture: a request scope carrying a trace id and a (non-detailed)
    SpanRecorder, plus the flight ring at its default capacity. Asserts
    the overhead stays under 2% of dark wall time and emits the
    additive ``trace_overhead_pct`` field. A second, subprocess-shaped
    leg measures the fleet plane (stitching + OTLP export) against a
    dark fleet and emits ``fleet_trace_overhead_pct`` under the same
    2% budget — see ``_tracecost_fleet_leg``."""
    from semantic_merge_tpu.obs import flight as obs_flight

    repeats = 5
    # Warm compiles and caches so both arms measure steady state.
    run_merge_to_payload(backend, base, left, right)

    os.environ[obs_flight.ENV_RING] = "0"
    obs_flight.reset()
    dark_s = time_merge(backend, base, left, right, repeats=repeats)

    os.environ[obs_flight.ENV_RING] = str(obs_flight.DEFAULT_RING)
    obs_flight.reset()
    on_s = float("inf")
    for i in range(repeats):
        recorder = obs_spans.SpanRecorder(detailed=False)
        t0 = time.perf_counter()
        with obs_spans.request_scope(f"tracecost-{i}", recorder):
            run_merge_to_payload(backend, base, left, right)
        on_s = min(on_s, time.perf_counter() - t0)
    os.environ.pop(obs_flight.ENV_RING, None)
    obs_flight.reset()

    overhead_pct = (on_s - dark_s) / dark_s * 100.0 if dark_s > 0 else 0.0
    ok = overhead_pct < 2.0
    record["metric"] = (
        f"request-tracing overhead (rung-5 merge, {args.files} files x "
        f"{args.decls} decls, flight ring + per-request recorder on vs off)")
    record["value"] = round(overhead_pct, 3)
    record["unit"] = "pct"
    record["vs_baseline"] = round(on_s / dark_s, 4) if dark_s > 0 else 0.0
    record["trace_overhead_pct"] = round(overhead_pct, 3)
    record["trace_dark_ms"] = round(dark_s * 1e3, 1)
    record["trace_on_ms"] = round(on_s * 1e3, 1)
    if not ok:
        prior = record.get("error")
        msg = f"trace overhead {overhead_pct:.2f}% exceeds the 2% budget"
        record["error"] = f"{prior}; {msg}" if prior else msg
    if not json_only:
        print(f"# dark: {dark_s*1e3:8.1f} ms   traced: {on_s*1e3:8.1f} ms   "
              f"overhead: {overhead_pct:+.2f}%", file=sys.stderr)
    fleet_ok = _tracecost_fleet_leg(record, json_only=json_only)
    emit_record(record)
    return 0 if ok and fleet_ok else 1


def run_slocost_bench(record: dict, args, backend, base, left, right,
                      json_only: bool = False) -> int:
    """The ``slocost`` preset: what the SLO engine costs a rung-5
    merge. Dark = no engine (the pre-SLO fast path). On = the daemon's
    steady-state posture: a live SloEngine with the default merge
    objective, one ``observe()`` per merge plus a full ``evaluate()``
    per repeat — an upper bound, since the daemon's monitor thread
    evaluates every 5 s, not per request. Asserts the overhead stays
    under 2% of dark wall time and emits the additive
    ``slo_overhead_pct`` field."""
    from semantic_merge_tpu.obs import slo as obs_slo

    repeats = 5
    # Warm compiles and caches so both arms measure steady state.
    run_merge_to_payload(backend, base, left, right)

    dark_s = time_merge(backend, base, left, right, repeats=repeats)

    engine = obs_slo.SloEngine(
        obs_slo.parse_objectives("merge:p99<800ms,err<1%"))
    on_s = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        run_merge_to_payload(backend, base, left, right)
        engine.observe("semmerge", time.perf_counter() - t0)
        engine.evaluate()
        on_s = min(on_s, time.perf_counter() - t0)

    overhead_pct = (on_s - dark_s) / dark_s * 100.0 if dark_s > 0 else 0.0
    ok = overhead_pct < 2.0
    record["metric"] = (
        f"SLO-engine overhead (rung-5 merge, {args.files} files x "
        f"{args.decls} decls, observe+evaluate per merge vs no engine)")
    record["value"] = round(overhead_pct, 3)
    record["unit"] = "pct"
    record["vs_baseline"] = round(on_s / dark_s, 4) if dark_s > 0 else 0.0
    record["slo_overhead_pct"] = round(overhead_pct, 3)
    record["slo_dark_ms"] = round(dark_s * 1e3, 1)
    record["slo_on_ms"] = round(on_s * 1e3, 1)
    if not ok:
        prior = record.get("error")
        msg = f"SLO overhead {overhead_pct:.2f}% exceeds the 2% budget"
        record["error"] = f"{prior}; {msg}" if prior else msg
    if not json_only:
        print(f"# dark: {dark_s*1e3:8.1f} ms   slo-on: {on_s*1e3:8.1f} ms   "
              f"overhead: {overhead_pct:+.2f}%", file=sys.stderr)
    emit_record(record)
    return 0 if ok else 1


def _telcost_soak_leg(record: dict, rows: list,
                      json_only: bool = False) -> bool:
    """200-merge chaos-soak against a deliberately tight trace-store
    budget: ~10% of the traffic carries errored/degraded outcomes
    (protected keep reasons), the rest is subject to head sampling.
    Gates: the store's on-disk bytes stay at or under the budget after
    every write has landed, and 100% of the errored/degraded traces
    survive the pruning that the budget forces."""
    import random
    import shutil
    import tempfile

    from semantic_merge_tpu.obs import sampling as obs_sampling

    merges = 200
    rng = random.Random(20)
    scratch = pathlib.Path(tempfile.mkdtemp(prefix="semmerge-telcost-"))
    try:
        sampler = obs_sampling.SamplingPolicy(sample_n=4,
                                              minted_by="telcost")
        store = obs_sampling.TraceStore(scratch / "traces",
                                        budget_mb=0.25)
        protected_ids = []
        kept = 0
        for i in range(merges):
            tid = f"soak-{i:04d}"
            is_err = i % 17 == 0
            is_deg = i % 23 == 5
            seconds = rng.uniform(0.8, 1.6)
            decision = sampler.decide(tid, "semmerge", seconds,
                                      error=is_err, degraded=is_deg)
            if is_err or is_deg:
                protected_ids.append(tid)
            if decision.keep:
                kept += 1
                store.write(tid, {
                    "schema": 1, "kind": "trace", "trace_id": tid,
                    "verb": "semmerge", "outcome":
                        "error" if is_err else "ok",
                    "seconds": round(seconds, 6), "spans": rows,
                }, decision=decision)
        live = {p.stem for p in (scratch / "traces").glob("*.json")}
        retained = sum(1 for tid in protected_ids if tid in live)
        protected_pct = (100.0 * retained / len(protected_ids)
                         if protected_ids else 100.0)
        bytes_now = store.total_bytes()
        pruned = live != {f"soak-{i:04d}" for i in range(merges)
                          } and kept > len(live)
        ok = (bytes_now <= store.budget_bytes
              and protected_pct == 100.0 and pruned)
        record["telemetry_soak_bytes"] = bytes_now
        record["telemetry_soak_budget_bytes"] = store.budget_bytes
        record["telemetry_soak_protected_pct"] = round(protected_pct, 1)
        if not ok:
            prior = record.get("error")
            if bytes_now > store.budget_bytes:
                msg = (f"telcost soak: store {bytes_now}B over the "
                       f"{store.budget_bytes}B budget")
            elif protected_pct < 100.0:
                msg = (f"telcost soak: only {protected_pct:.1f}% of "
                       f"errored/degraded traces retained")
            else:
                msg = ("telcost soak: budget never forced a prune — "
                       "the leg measured nothing")
            record["error"] = f"{prior}; {msg}" if prior else msg
        if not json_only:
            print(f"# soak: {merges} merges, {kept} kept, "
                  f"{len(live)} on disk ({bytes_now}B / "
                  f"{store.budget_bytes}B budget), "
                  f"protected retained: {protected_pct:.1f}%",
                  file=sys.stderr)
        return ok
    finally:
        shutil.rmtree(scratch, ignore_errors=True)


def _telcost_triage_leg(record: dict, backend, base, left, right,
                        json_only: bool = False) -> bool:
    """Sustained injected-latency leg: real merges carry a real (slept)
    ``inject.lag`` span — 2 ms during warmup, 250 ms once the
    regression 'ships' — through the same recorder→phases→AnomalyTriage
    path the daemon runs per request. Gates: the sustained breach
    produces exactly one auto-captured triage bundle and its phase diff
    names ``inject.lag`` as the suspect."""
    import shutil
    import tempfile

    from semantic_merge_tpu.obs import anomaly as obs_anomaly

    warmup, sustain = 6, 3
    scratch = pathlib.Path(tempfile.mkdtemp(prefix="semmerge-telcost-"))
    try:
        triage = obs_anomaly.AnomalyTriage(min_n=warmup, sustain=sustain)
        bundles = []
        for i in range(warmup + sustain):
            lag = 0.25 if i >= warmup else 0.002
            recorder = obs_spans.SpanRecorder(detailed=False)
            tid = f"telcost-triage-{i}"
            t0 = time.perf_counter()
            with obs_spans.request_scope(tid, recorder):
                run_merge_to_payload(backend, base, left, right)
                with obs_spans.span("inject.lag", layer="bench"):
                    time.sleep(lag)
            total = time.perf_counter() - t0
            rows = recorder.span_dicts()
            phases: dict = {}
            for row in rows:
                name = str(row.get("name") or "?")
                try:
                    phases[name] = phases.get(name, 0.0) + \
                        float(row.get("seconds") or 0.0)
                except (TypeError, ValueError):
                    continue
            bundles.extend(triage.observe(tid, "semmerge", phases,
                                          seconds=total, spans=rows,
                                          root=str(scratch)))
        hits = [b for b in bundles if b.get("phase") == "inject.lag"]
        fired_once = len(hits) == 1
        named = bool(hits) and \
            hits[0].get("suspect_phase") == "inject.lag"
        captured = bool(hits) and hits[0].get("bundle") and \
            pathlib.Path(hits[0]["bundle"]).exists()
        ok = fired_once and named and captured
        record["telemetry_triage_fired"] = len(hits)
        if not ok:
            prior = record.get("error")
            if not fired_once:
                msg = (f"telcost triage: injected phase fired "
                       f"{len(hits)} bundles, expected exactly 1")
            elif not named:
                msg = ("telcost triage: bundle suspect is "
                       f"{hits[0].get('suspect_phase')!r}, not the "
                       "injected phase")
            else:
                msg = "telcost triage: bundle file was not written"
            record["error"] = f"{prior}; {msg}" if prior else msg
        if not json_only:
            where = hits[0]["bundle"] if captured else "none"
            print(f"# triage: {len(hits)} bundle(s) for inject.lag, "
                  f"suspect={hits[0].get('suspect_phase') if hits else None}"
                  f", bundle={'ok' if captured else where}",
                  file=sys.stderr)
        return ok
    finally:
        shutil.rmtree(scratch, ignore_errors=True)


def run_telcost_bench(record: dict, args, backend, base, left, right,
                      json_only: bool = False) -> int:
    """The ``telcost`` preset: what the full telemetry pipeline costs a
    rung-5 merge, plus two correctness legs over the same pipeline.

    Overhead leg — dark = bare merge, no recorder. On = the daemon's
    per-request posture end to end: non-detailed SpanRecorder, span→
    phase folding, sampling verdict, window rollup, anomaly
    observation, and the trace-store write for kept traces (mirrors
    ``MergeDaemon._finish_telemetry``). Asserts the overhead stays
    under 2% of dark wall time and emits ``telemetry_overhead_pct``.

    Soak leg — see :func:`_telcost_soak_leg` (200-merge chaos soak:
    store under budget, 100% errored/degraded retention). Triage leg —
    see :func:`_telcost_triage_leg` (sustained injected latency must
    produce one bundle whose diff names the injected phase)."""
    import shutil
    import tempfile

    from semantic_merge_tpu.obs import agg as obs_agg
    from semantic_merge_tpu.obs import anomaly as obs_anomaly
    from semantic_merge_tpu.obs import sampling as obs_sampling

    repeats = 5
    # Warm compiles and caches so both arms measure steady state.
    run_merge_to_payload(backend, base, left, right)

    dark_s = time_merge(backend, base, left, right, repeats=repeats)

    scratch = pathlib.Path(tempfile.mkdtemp(prefix="semmerge-telcost-"))
    try:
        window = obs_agg.WindowAggregator()
        sampler = obs_sampling.SamplingPolicy(sample_n=10,
                                              minted_by="telcost")
        triage = obs_anomaly.AnomalyTriage()
        store = obs_sampling.TraceStore(scratch / "traces")
        on_s = float("inf")
        last_rows: list = []
        for i in range(repeats):
            recorder = obs_spans.SpanRecorder(detailed=False)
            tid = f"telcost-{i}"
            t0 = time.perf_counter()
            with obs_spans.request_scope(tid, recorder):
                run_merge_to_payload(backend, base, left, right)
            rows = recorder.span_dicts()
            phases: dict = {}
            for row in rows:
                name = str(row.get("name") or "?")
                try:
                    phases[name] = phases.get(name, 0.0) + \
                        float(row.get("seconds") or 0.0)
                except (TypeError, ValueError):
                    continue
            flags = obs_sampling.outcome_flags(rows)
            total = time.perf_counter() - t0
            decision = sampler.decide(
                tid, "semmerge", total, error=flags["error"],
                degraded=flags["degraded"], breaker=flags["breaker"],
                resolver=flags["resolver"])
            window.observe("semmerge", total, error=flags["error"],
                           phases=phases)
            triage.observe(tid, "semmerge", phases, seconds=total,
                           spans=rows, root=str(scratch))
            if decision.keep:
                store.write(tid, {
                    "schema": 1, "kind": "trace", "trace_id": tid,
                    "verb": "semmerge", "outcome": "ok",
                    "seconds": round(total, 6), "spans": rows,
                }, decision=decision)
            on_s = min(on_s, time.perf_counter() - t0)
            last_rows = rows
    finally:
        shutil.rmtree(scratch, ignore_errors=True)

    overhead_pct = (on_s - dark_s) / dark_s * 100.0 if dark_s > 0 else 0.0
    ok = overhead_pct < 2.0
    record["metric"] = (
        f"telemetry-pipeline overhead (rung-5 merge, {args.files} files x "
        f"{args.decls} decls, sampling+window+anomaly+store on vs dark)")
    record["value"] = round(overhead_pct, 3)
    record["unit"] = "pct"
    record["vs_baseline"] = round(on_s / dark_s, 4) if dark_s > 0 else 0.0
    record["telemetry_overhead_pct"] = round(overhead_pct, 3)
    record["telemetry_dark_ms"] = round(dark_s * 1e3, 1)
    record["telemetry_on_ms"] = round(on_s * 1e3, 1)
    if not ok:
        prior = record.get("error")
        msg = (f"telemetry overhead {overhead_pct:.2f}% exceeds "
               f"the 2% budget")
        record["error"] = f"{prior}; {msg}" if prior else msg
    if not json_only:
        print(f"# dark: {dark_s*1e3:8.1f} ms   telemetry-on: "
              f"{on_s*1e3:8.1f} ms   overhead: {overhead_pct:+.2f}%",
              file=sys.stderr)
    soak_ok = _telcost_soak_leg(record, last_rows, json_only=json_only)
    triage_ok = _telcost_triage_leg(record, backend, base, left, right,
                                    json_only=json_only)
    emit_record(record)
    return 0 if ok and soak_ok and triage_ok else 1


def run_devtail_bench(record: dict, args, backend, base, left, right,
                      json_only: bool = False) -> int:
    """The ``devtail`` preset: what device-side op-log rendering and
    warm snapshot residency buy the rung-5 host tail. Three legs over
    one workload, coldest posture first:

      cold           render off, residency off — the PR-2 tail
                     pipeline as shipped (PERF_BASELINE's
                     ``tpu_r5_rung5`` tail: fetch + compose +
                     serialize ≈ 931 ms against a 102 ms kernel).
      resident-base  ``SEMMERGE_RESIDENCY_CACHE=on``: repeat merges of
                     the same base tree through FRESH Snapshot objects
                     (the daemon's request shape — object identity
                     never survives a request boundary), so only the
                     warm residency cache can skip the base side's
                     scan_encode+h2d.
      device-render  ``SEMMERGE_DEVICE_RENDER=require`` on top: op-log
                     payloads serialize from device-rendered byte
                     tensors; the host does one d2h copy + concat.

    Guarded (obs/perf.py GUARDED_FIELDS): ``host_tail_ms`` — the
    device-render leg's disjoint tail trio — and
    ``residency_hit_rate`` from the resident-base leg. ``d2h_bytes``
    (rendered rows × width summed over the leg's ``render.d2h`` spans)
    is reported so render-width regressions surface even when wall
    time hides them. Byte parity between the cold and device-render
    payloads is a gate, same as the headline presets."""
    import gc

    from semantic_merge_tpu.core.ops import OpLog
    from semantic_merge_tpu.frontend.snapshot import (Snapshot,
                                                      annotate_residency)
    from semantic_merge_tpu.service import residency

    def leg_env(render: str, resident: bool) -> None:
        os.environ["SEMMERGE_DEVICE_RENDER"] = render
        os.environ["SEMMERGE_RENDER_MIN_ROWS"] = "0"
        os.environ["SEMMERGE_RESIDENCY_CACHE"] = \
            "on" if resident else "off"

    def fresh_base() -> Snapshot:
        # Same tree, new object: the residency key (not object
        # identity, not the scan fingerprint fast path) must carry the
        # warm encoding across the "request" boundary.
        fb = Snapshot(files=base.files)
        annotate_residency(fb, "", "devtail-base")
        return fb

    def payload_bytes(result):
        return (OpLog(result.op_log_left).to_json_bytes(),
                OpLog(result.op_log_right).to_json_bytes())

    def instrumented(make_base, repeats: int = 2):
        """Best-of phase split (disjoint tail accounting) plus the
        max rendered-d2h volume observed across the runs."""
        best: dict = {}
        d2h = 0
        for _ in range(max(1, repeats)):
            gc.collect()
            before = obs_metrics.phase_totals()
            recorder = obs_spans.SpanRecorder()
            with obs_spans.activated(recorder):
                run_merge_to_payload(backend, make_base(), left, right)
            for k, v in _tail_disjoint(
                    obs_metrics.phase_totals_since(before),
                    recorder).items():
                best[k] = min(best.get(k, v), v)
            d2h = max(d2h, sum(
                int(r["meta"].get("rows", 0)) * int(r["meta"].get("width", 0))
                for r in recorder.span_dicts()
                if r["name"] == "render.d2h"))
        return best, d2h

    # --- Leg 1: cold (the shipped PR-2 tail pipeline). -----------------
    leg_env("off", resident=False)
    residency.cache().reset()
    res_c, *_ = run_merge_to_payload(backend, base, left, right)  # warm
    cold_payload = payload_bytes(res_c)
    cold_phases, _ = instrumented(lambda: base)
    cold_tail_ms = host_tail_summary(cold_phases)["host_tail_ms"]

    # --- Leg 2: resident base (warm snapshot residency). ---------------
    leg_env("off", resident=True)
    residency.cache().reset()
    resident_repeats = 12
    t_resident = float("inf")
    for _ in range(resident_repeats):
        t0 = time.perf_counter()
        run_merge_to_payload(backend, fresh_base(), left, right)
        t_resident = min(t_resident, time.perf_counter() - t0)
    rstats = residency.cache().stats()
    residency_hit_rate = rstats["hit_rate"]
    resident_phases, _ = instrumented(fresh_base)
    resident_tail_ms = host_tail_summary(resident_phases)["host_tail_ms"]

    # --- Leg 3: device render on top of residency. ---------------------
    leg_env("require", resident=True)
    try:
        res_r, *_ = run_merge_to_payload(backend, fresh_base(),
                                         left, right)  # warm compiles
        render_payload = payload_bytes(res_r)
        render_phases, d2h_bytes = instrumented(fresh_base)
    except Exception as exc:  # RenderFault under require is a failure
        record["error"] = f"device-render leg failed: {exc}"
        record["host_tail_cold_ms"] = cold_tail_ms
        record["residency_hit_rate"] = round(residency_hit_rate, 4)
        emit_record(record)
        return 1
    finally:
        leg_env("off", resident=False)
        residency.cache().reset()

    parity = render_payload == cold_payload
    tail = host_tail_summary(render_phases)
    render_tail_ms = tail["host_tail_ms"]

    import jax
    platform = jax.devices()[0].platform
    record["metric"] = (
        f"post-kernel host tail ms (cold vs resident-base vs "
        f"device-render, {args.files} files x {args.decls} decls, "
        f"parity={'ok' if parity else 'FAIL'}, platform={platform})")
    record["value"] = render_tail_ms
    record["unit"] = "ms"
    record["vs_baseline"] = round(
        cold_tail_ms / render_tail_ms, 3) if render_tail_ms > 0 else 0.0
    record["phases_ms"] = {k: round(v * 1e3, 1)
                           for k, v in render_phases.items()}
    record["phases_cold_ms"] = {k: round(v * 1e3, 1)
                                for k, v in cold_phases.items()}
    record["host_tail_cold_ms"] = cold_tail_ms
    record["host_tail_resident_ms"] = resident_tail_ms
    record["resident_merge_ms"] = round(t_resident * 1e3, 1)
    record["residency_hit_rate"] = round(residency_hit_rate, 4)
    record["residency_entries"] = rstats["entries"]
    record["d2h_bytes"] = int(d2h_bytes)
    record["parity"] = bool(parity)
    record.update(tail)
    if not json_only:
        print(f"# cold tail:     {cold_tail_ms:8.1f} ms", file=sys.stderr)
        print(f"# resident tail: {resident_tail_ms:8.1f} ms  "
              f"(hit rate {residency_hit_rate:.3f})", file=sys.stderr)
        print(f"# rendered tail: {render_tail_ms:8.1f} ms  "
              f"(d2h {d2h_bytes} B, parity: {parity})", file=sys.stderr)
        print("# render phases: " + "  ".join(
            f"{k}={v*1e3:.1f}ms" for k, v in sorted(render_phases.items())),
            file=sys.stderr)
    emit_record(record)
    return 0 if parity else 1


# BASELINE.json measurement ladder (rung 1 is the e2e pytest scenario).
# rung5i is the incremental scenario: repo-scale tree, change-scale work.
# strict measures the --strict-conflicts premium on a statement-edit
# workload (body edits + one extract/inline pair) with a phase split.
PRESETS = {
    "rung2": {"files": 100, "decls": 6},
    "rung3": {"files": 1000, "decls": 6},
    "rung4": {"files": 5000, "decls": 4},
    "rung5": {"files": 10000, "decls": 4, "conflicts": True},
    "rung5i": {"files": 10000, "decls": 4, "changed": 200},
    "strict": {"files": 10000, "decls": 4, "strict": True},
    "warmserve": {"files": 48, "decls": 4, "warmserve": True},
    "batchserve": {"files": 48, "decls": 4, "batchserve": True},
    "overload": {"files": 24, "decls": 4, "overload": True},
    "fleet": {"files": 24, "decls": 4, "fleet": True},
    # fleetwan: the cross-host fleet shape — remote members joined over
    # TCP, 20ms injected dial latency; gates the post-churn rehash miss
    # rate at <= 0.15.
    "fleetwan": {"files": 24, "decls": 4, "fleetwan": True},
    "tracecost": {"files": 10000, "decls": 4, "tracecost": True},
    "slocost": {"files": 10000, "decls": 4, "slocost": True},
    # telcost: the PR-20 telemetry pipeline (tail sampling + window
    # rollups + anomaly bank + trace store) on vs dark, plus the
    # chaos-soak and injected-latency triage legs; guards
    # telemetry_overhead_pct under the 2% budget.
    "telcost": {"files": 10000, "decls": 4, "telcost": True},
    # devtail: the rung-5 host-tail ladder — cold vs resident-base vs
    # device-render legs; guards host_tail_ms and residency_hit_rate.
    "devtail": {"files": 10000, "decls": 4, "conflicts": True,
                "devtail": True},
    # resolve: files = number of independently-resolvable
    # ConcurrentStmtEdit conflict files; the preset measures the
    # resolution tier's premium and per-gate cost, so the workload is
    # conflict-dense, not large.
    "resolve": {"files": 6, "decls": 1},
}

# Set by main() once the preset is resolved; emit_record stamps it into
# the trajectory row so BENCH_trajectory.jsonl is self-describing.
_EMIT_PRESET = None


def emit_record(record: dict) -> None:
    """The driver contract: exactly one JSON record line on stdout —
    plus a best-effort append to BENCH_trajectory.jsonl (see
    ``semantic_merge_tpu/obs/perf.py``), so every bench run leaves a
    machine-readable point on the perf trajectory."""
    print(json.dumps(record), flush=True)
    try:
        from semantic_merge_tpu.obs import perf as obs_perf
        obs_perf.append_trajectory(
            record, preset=_EMIT_PRESET,
            root=os.path.dirname(os.path.abspath(__file__)))
    except Exception:
        pass  # the trajectory is a courtesy, never a bench failure


def _emit_and_exit_on_watchdog(record: dict, seconds: float):
    """Arm a daemon timer that emits ``record`` and hard-exits if the
    bench wedges (e.g. backend discovery blocking on the accelerator
    relay — round 1's dryrun hung >9 min there). The caller mutates
    ``record`` in place as phases finish, so whatever was measured by
    the deadline still reaches the driver."""
    import threading

    def fire():
        msg = f"watchdog: bench exceeded {seconds:.0f}s"
        prior = record.get("error")
        record["error"] = f"{prior}; {msg}" if prior else msg
        emit_record(record)
        os._exit(1)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def run_cold_bench(record: dict, args, conflicts_expected: bool,
                   json_only: bool = False) -> int:
    """Driver-shaped cold-start measurement (``--cold``): every
    repetition forks a FRESH python process that imports JAX, builds
    the workload, initializes the backend, and runs one merge to the
    payload endpoint — what the L7 git merge driver pays per
    invocation. The persistent XLA compilation cache
    (JAX_COMPILATION_CACHE_DIR) is on, as in the CLI, so compiles are
    disk-warm after the first run; process/imports/caches are cold
    every time. Reference budget frame: cold ≤ 40 s / warm ≤ 10 s for
    a large-repo merge (reference architecture.md:311-313)."""
    child_code = (
        "import json, sys, time\n"
        "t0 = time.perf_counter()\n"
        "sys.path.insert(0, %r)\n"
        "import bench\n"
        "from semantic_merge_tpu.backends.base import get_backend\n"
        "t_import = time.perf_counter() - t0\n"
        "base, left, right = bench.synth_repo(%d, %d, divergent=%r)\n"
        "t1 = time.perf_counter()\n"
        "bk = get_backend('tpu')\n"
        "t_init = time.perf_counter() - t1\n"
        "t2 = time.perf_counter()\n"
        "bench.run_merge_to_payload(bk, base, left, right)\n"
        "t_merge = time.perf_counter() - t2\n"
        "print(json.dumps({'import_s': round(t_import, 3),\n"
        "                  'backend_init_s': round(t_init, 3),\n"
        "                  'merge_s': round(t_merge, 3)}))\n"
    ) % (os.path.dirname(os.path.abspath(__file__)),
         args.files, args.decls, conflicts_expected)
    import subprocess
    runs = []
    total_walls = []
    errors = []
    for _ in range(3):
        t0 = time.perf_counter()
        try:
            proc = subprocess.run([sys.executable, "-c", child_code],
                                  stdout=subprocess.PIPE, text=True,
                                  env=dict(os.environ), timeout=600)
        except subprocess.TimeoutExpired:
            errors.append("cold child timed out after 600s")
            continue
        total_walls.append(time.perf_counter() - t0)
        lines = proc.stdout.strip().splitlines()
        if proc.returncode != 0 or not lines:
            errors.append(f"cold child exit {proc.returncode}, "
                          f"{len(lines)} stdout lines")
            continue
        try:
            runs.append(json.loads(lines[-1]))
        except json.JSONDecodeError as exc:
            errors.append(f"cold child output unparseable: {exc}")
    if not runs:
        # Always emit a record — the driver contract (round 1 died
        # with rc=1 and no JSON).
        record["metric"] = "cold-start merge wall (fresh process/run)"
        record["unit"] = "seconds"
        record["error"] = "; ".join(errors) or "no cold run succeeded"
        emit_record(record)
        return 1
    if errors:
        record["error"] = "; ".join(errors)
    best = min(range(len(runs)), key=lambda i: runs[i]["merge_s"])
    import jax
    platform = jax.devices()[0].platform
    r = runs[best]
    record["metric"] = (
        f"cold-start merge wall (fresh process/run, {args.files} files x "
        f"{args.decls} decls, platform={platform})")
    record["value"] = round(r["merge_s"], 3)
    record["unit"] = "seconds"
    record["vs_baseline"] = 0.0
    record["cold_runs"] = runs
    record["process_wall_s"] = [round(w, 2) for w in total_walls]
    if not json_only:
        for i, (run, w) in enumerate(zip(runs, total_walls)):
            print(f"# cold run {i}: import={run['import_s']}s "
                  f"init={run['backend_init_s']}s merge={run['merge_s']}s "
                  f"process_total={w:.1f}s", file=sys.stderr)
    emit_record(record)
    return 0


def _build_service_repo(root, n_files: int, decls_per_file: int) -> None:
    """A real git repo for the service bench: base holds the synthetic
    module tree; brA edits the first half of the files, brB the second
    half (disjoint → clean merge, repeatable without --inplace)."""
    import subprocess

    def git(*argv):
        subprocess.run(["git", *argv], cwd=root, check=True,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    root.mkdir(parents=True)
    git("init", "-q", "-b", "main")
    git("config", "user.email", "bench@example.com")
    git("config", "user.name", "bench")
    base, _left, _right = synth_repo(n_files, decls_per_file)
    for f in base.files:
        p = root / f["path"]
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(f["content"])
    git("add", "-A")
    git("commit", "-q", "-m", "base")
    git("branch", "basebr")
    half = n_files // 2
    for branch, lo, hi in (("brA", 0, half), ("brB", half, n_files)):
        git("checkout", "-qb", branch)
        for i in range(lo, hi):
            p = root / f"src/mod{i:05d}.ts"
            p.write_text(p.read_text().replace("return 0;", "return 100;"))
        git("add", "-A")
        git("commit", "-q", "-m", f"edit {branch}")
        git("checkout", "-q", "main")


def run_warmserve_bench(record: dict, args, json_only: bool = False) -> int:
    """The ``warmserve`` preset: what the service daemon actually buys.
    Cold = one-shot CLI subprocesses (``SEMMERGE_DAEMON=off``) paying
    imports + backend init + cold caches per merge; warm = the same
    merge as protocol requests against one spawned daemon. Additive
    BENCH fields: ``cold_ms``/``warm_ms``/``warm_speedup`` plus the
    daemon's ``declcache_hit_rate`` and ``daemon_rss_mb`` from its
    status endpoint."""
    import shutil
    import subprocess
    import tempfile

    from semantic_merge_tpu.service import client as svc_client

    scratch = pathlib.Path(tempfile.mkdtemp(prefix="semmerge-warmserve-"))
    repo = scratch / "repo"
    sock = str(scratch / "daemon.sock")
    _build_service_repo(repo, args.files, args.decls)

    child_env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.abspath(__file__))
    prior_pp = child_env.get("PYTHONPATH", "")
    child_env["PYTHONPATH"] = (f"{pkg_root}{os.pathsep}{prior_pp}"
                               if prior_pp else pkg_root)
    child_env["SEMMERGE_DAEMON"] = "off"
    child_env.pop("SEMMERGE_FAULT", None)
    child_env.pop("SEMMERGE_METRICS", None)
    if os.environ.get("SEMMERGE_BENCH_PLATFORM") == "cpu":
        child_env["JAX_PLATFORMS"] = "cpu"
    merge_argv = ["semmerge", "basebr", "brA", "brB", "--backend", "host"]

    daemon = None
    try:
        cold_walls = []
        for _ in range(2):
            t0 = time.perf_counter()
            proc = subprocess.run(
                [sys.executable, "-m", "semantic_merge_tpu", *merge_argv],
                cwd=repo, env=child_env, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE, text=True, timeout=600)
            cold_walls.append(time.perf_counter() - t0)
            if proc.returncode != 0:
                record["error"] = (f"cold one-shot merge exit "
                                   f"{proc.returncode}: {proc.stderr[-500:]}")
                emit_record(record)
                return 1
        cold_s = min(cold_walls)

        log = open(sock + ".log", "ab")
        daemon = subprocess.Popen(
            [sys.executable, "-m", "semantic_merge_tpu", "serve",
             "--socket", sock],
            stdin=subprocess.DEVNULL, stdout=log, stderr=log,
            cwd="/", env=child_env, start_new_session=True)
        log.close()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            conn = svc_client._try_connect(sock, timeout=2.0)
            if conn is not None:
                svc_client._close(*conn)
                break
            if daemon.poll() is not None:
                record["error"] = (f"daemon exited rc={daemon.returncode} "
                                   f"during startup (log: {sock}.log)")
                emit_record(record)
                return 1
            time.sleep(0.1)
        else:
            record["error"] = "daemon did not come up within 120s"
            emit_record(record)
            return 1

        params = {"argv": merge_argv[1:], "cwd": str(repo), "env": {}}
        warm_walls = []
        for i in range(4):
            t0 = time.perf_counter()
            frame = svc_client.call_verb("semmerge", params, path=sock,
                                         timeout=600)
            wall = time.perf_counter() - t0
            result = frame.get("result") or {}
            if result.get("exit_code") != 0:
                record["error"] = f"warm request failed: {frame}"
                emit_record(record)
                return 1
            if i > 0:  # request 0 is the daemon's residual warm-up
                warm_walls.append(wall)
        warm_s = min(warm_walls)
        status = svc_client.call_control("status", path=sock, timeout=30)

        record["metric"] = (
            f"files merged/sec (warm service daemon vs one-shot CLI, "
            f"{args.files} files x {args.decls} decls, host backend)")
        record["value"] = round(args.files / warm_s, 2)
        record["vs_baseline"] = round(cold_s / warm_s, 3)
        record["cold_ms"] = round(cold_s * 1e3, 1)
        record["warm_ms"] = round(warm_s * 1e3, 1)
        record["warm_speedup"] = round(cold_s / warm_s, 3)
        record["declcache_hit_rate"] = round(
            float(status.get("declcache_hit_rate", 0.0)), 4)
        record["daemon_rss_mb"] = round(float(status.get("rss_mb", 0.0)), 1)
        if not json_only:
            print(f"# cold one-shot: {cold_s*1e3:8.1f} ms", file=sys.stderr)
            print(f"# warm daemon:   {warm_s*1e3:8.1f} ms "
                  f"({cold_s/warm_s:.1f}x)", file=sys.stderr)
            print(f"# declcache hit rate: "
                  f"{record['declcache_hit_rate']:.3f}  "
                  f"rss: {record['daemon_rss_mb']} MiB", file=sys.stderr)
        emit_record(record)
        return 0
    finally:
        if daemon is not None:
            try:
                svc_client.call_control("shutdown", path=sock, timeout=10)
                daemon.wait(timeout=30)
            except Exception:
                daemon.kill()
        shutil.rmtree(scratch, ignore_errors=True)


def _build_resolve_bench_repo(root, n_conflicts: int) -> None:
    """A git repo whose strict-mode merge yields ``n_conflicts``
    independent ``ConcurrentStmtEdit`` conflicts, every one resolvable:
    brA and brB edit *disjoint* lines of each function body, so the
    resolver's 3-way body merge is the unique winner for all of them —
    resolve-on exits 0 where resolve-off exits 1 on the identical
    workload. ConcurrentStmtEdit is the corpus category because its
    strict-mode detection is deterministic at any count; the parity
    walk's head-vs-head DivergentRename detection masks concurrent
    same-category conflicts by design (reference semantics), which
    would make a multi-conflict rename corpus flaky."""
    import subprocess

    def git(*argv):
        subprocess.run(["git", *argv], cwd=root, check=True,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    def tree(edit_a=False, edit_b=False):
        files = {}
        for i in range(n_conflicts):
            # Signatures are unique per file (i extra string params):
            # symbolId is a pure function of the type signature, so
            # same-signature decls across files would collapse into one
            # symbol and drop all edits but the last file's.
            pad = "".join(f", x{k}: string" for k in range(i))
            line1 = f"n = n + {i + 3};" if edit_a else f"n = n + {i + 1};"
            line2 = "n = n * 4;" if edit_b else "n = n * 2;"
            files[f"src/calc{i:03d}.ts"] = (
                f"export function calc{i}(n: number{pad}): number {{\n"
                f"  {line1}\n"
                f"  {line2}\n"
                f"  return n;\n"
                f"}}\n")
        return files

    def commit(files, msg):
        for path, content in files.items():
            p = root / path
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(content)
        git("add", "-A")
        git("commit", "-q", "-m", msg)

    root.mkdir(parents=True)
    git("init", "-q", "-b", "main")
    git("config", "user.email", "bench@example.com")
    git("config", "user.name", "bench")
    commit(tree(), "base")
    git("branch", "basebr")
    git("checkout", "-qb", "brA")
    commit(tree(edit_a=True), "edit first statement")
    git("checkout", "-q", "main")
    git("checkout", "-qb", "brB")
    commit(tree(edit_b=True), "edit second statement")
    git("checkout", "-q", "main")


def run_resolve_bench(record: dict, args, json_only: bool = False) -> int:
    """The ``resolve`` preset: what the conflict-resolution tier costs
    and buys on a conflict-dense merge. Baseline = ``--resolve`` off
    (the merge exits 1, conflict-as-result); measured = ``--resolve``
    auto on the identical repo (every conflict resolves, exit 0),
    parity-gated by the audit records themselves — every accepted
    resolution must show all four verify gates green, the second of
    which is the untouched-region parity check. Additive BENCH fields:
    ``resolution_rate``, ``resolve_on_ms`` / ``resolve_off_ms``, and
    the per-gate totals ``gate_recompose_ms`` / ``gate_parity_ms`` /
    ``gate_typecheck_ms`` / ``gate_format_ms`` read from the v2
    conflicts artifact."""
    import shutil
    import subprocess
    import tempfile

    scratch = pathlib.Path(tempfile.mkdtemp(prefix="semmerge-resolve-"))
    repo = scratch / "repo"
    _build_resolve_bench_repo(repo, args.files)

    child_env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.abspath(__file__))
    prior_pp = child_env.get("PYTHONPATH", "")
    child_env["PYTHONPATH"] = (f"{pkg_root}{os.pathsep}{prior_pp}"
                               if prior_pp else pkg_root)
    child_env["SEMMERGE_DAEMON"] = "off"
    for var in ("SEMMERGE_FAULT", "SEMMERGE_METRICS", "SEMMERGE_RESOLVE",
                "SEMMERGE_STRICT"):
        child_env.pop(var, None)
    if os.environ.get("SEMMERGE_BENCH_PLATFORM") == "cpu":
        child_env["JAX_PLATFORMS"] = "cpu"
    # Strict conflict detection: deterministic multi-conflict surfacing
    # (see _build_resolve_bench_repo on why the corpus needs it).
    merge_argv = ["semmerge", "basebr", "brA", "brB", "--backend", "host",
                  "--strict-conflicts"]
    artifact = repo / ".semmerge-conflicts.json"

    def one_shot(extra_argv):
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, "-m", "semantic_merge_tpu",
             *merge_argv, *extra_argv],
            cwd=repo, env=child_env, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True, timeout=600)
        return proc, time.perf_counter() - t0

    try:
        off_walls = []
        for _ in range(2):
            proc, wall = one_shot([])
            if proc.returncode != 1:
                record["error"] = (
                    f"resolve-off merge exit {proc.returncode} (want 1: "
                    f"conflict-as-result): {proc.stderr[-500:]}")
                emit_record(record)
                return 1
            off_walls.append(wall)
        legacy = json.loads(artifact.read_text())
        if not isinstance(legacy, list) or not legacy:
            record["error"] = ("resolve-off artifact is not the legacy "
                               "non-empty bare array")
            emit_record(record)
            return 1
        n_conflicts = len(legacy)

        on_walls, payload = [], None
        for _ in range(3):
            proc, wall = one_shot(["--resolve"])
            if proc.returncode != 0:
                record["error"] = (
                    f"resolve-on merge exit {proc.returncode} (want 0: "
                    f"verified suggestion): {proc.stderr[-500:]}")
                emit_record(record)
                return 1
            on_walls.append(wall)
            payload = json.loads(artifact.read_text())
        resolutions = payload.get("resolutions", [])
        accepted = sum(r.get("status") == "accepted" for r in resolutions)
        gate_ms = {g: 0.0 for g in ("recompose", "parity",
                                    "typecheck", "format")}
        parity_ok = bool(resolutions)
        for r in resolutions:
            for row in r.get("gates", []):
                if row.get("gate") in gate_ms:
                    gate_ms[row["gate"]] += float(row.get("ms", 0.0))
            if r.get("status") == "accepted" and not all(
                    row.get("ok") for row in r.get("gates", [])):
                parity_ok = False

        off_s, on_s = min(off_walls), min(on_walls)
        record["metric"] = (
            f"conflicts resolved/sec (resolution tier on vs off, "
            f"{n_conflicts} ConcurrentStmtEdit conflicts, host backend, "
            f"parity={'ok' if parity_ok else 'FAIL'})")
        record["value"] = round(n_conflicts / on_s, 2)
        record["unit"] = "conflicts/sec"
        record["vs_baseline"] = round(off_s / on_s, 3)
        record["parity"] = parity_ok
        record["resolution_rate"] = round(accepted / max(1, n_conflicts), 4)
        record["resolve_on_ms"] = round(on_s * 1e3, 1)
        record["resolve_off_ms"] = round(off_s * 1e3, 1)
        for gate, total in gate_ms.items():
            record[f"gate_{gate}_ms"] = round(total, 1)
        if not json_only:
            print(f"# resolve off: {off_s*1e3:8.1f} ms (exit 1, "
                  f"{n_conflicts} conflicts)", file=sys.stderr)
            print(f"# resolve on:  {on_s*1e3:8.1f} ms (exit 0, "
                  f"{accepted}/{n_conflicts} accepted)", file=sys.stderr)
            print("# gates: " + "  ".join(f"{g}={v:.1f}ms"
                                          for g, v in gate_ms.items()),
                  file=sys.stderr)
        emit_record(record)
        return 0 if (accepted == n_conflicts and parity_ok) else 1
    finally:
        shutil.rmtree(scratch, ignore_errors=True)


def run_batchserve_bench(record: dict, args, json_only: bool = False) -> int:
    """The ``batchserve`` preset: what continuous batching buys a WARM
    daemon under concurrent load — now along a **chips axis**. Phase 1
    (``chips=1``) pins ``SEMMERGE_MESH=off`` (the single-device batched
    program); phase 2 restarts the daemon mesh-on so the packed merge
    axis shards across every local chip (on a CPU host the mesh runs
    over 4 ``--xla_force_host_platform_device_count`` virtual devices).
    Parity gates the number three ways: batched-vs-unbatched inside
    phase 1, mesh-vs-single-device across the phases, and a one-shot
    (no daemon) CLI run — all must leave byte-identical git-notes
    op-log payloads. Additive BENCH fields: the phase-1 set
    (``serial_merges_per_sec``, ``batch_merges_per_sec_c4``/``_c16``,
    ``batch_speedup_c16``, ``batch_p50_ms``/``batch_p99_ms``,
    ``mean_batch_size``, ``batch_padding_waste_ratio``,
    ``batch_program_cache_hit_rate``) plus the chips axis: ``chips``,
    ``mesh_merges_per_sec_c16``, ``merges_per_sec_per_chip``,
    ``scaling_efficiency`` (mesh c16 rate over single-device c16 rate,
    per effective chip — virtual CPU devices add no hardware, so there
    the denominator is 1), ``mesh_p50_ms``/``mesh_p99_ms`` at matched
    concurrency. Exit 0 requires parity AND ``scaling_efficiency`` ≥
    0.7 whenever the mesh actually formed."""
    import shutil
    import statistics
    import subprocess
    import tempfile
    import threading

    from semantic_merge_tpu.service import client as svc_client

    scratch = pathlib.Path(tempfile.mkdtemp(prefix="semmerge-batchserve-"))
    repo = scratch / "repo"
    _build_service_repo(repo, args.files, args.decls)
    on_cpu = os.environ.get("SEMMERGE_BENCH_PLATFORM") == "cpu"

    base_env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.abspath(__file__))
    prior_pp = base_env.get("PYTHONPATH", "")
    base_env["PYTHONPATH"] = (f"{pkg_root}{os.pathsep}{prior_pp}"
                              if prior_pp else pkg_root)
    base_env["SEMMERGE_DAEMON"] = "off"
    base_env.pop("SEMMERGE_FAULT", None)
    base_env.pop("SEMMERGE_METRICS", None)
    base_env["SEMMERGE_SERVICE_WORKERS"] = "16"
    base_env.setdefault("SEMMERGE_BATCH_WINDOW_MS", "25")
    if on_cpu:
        base_env["JAX_PLATFORMS"] = "cpu"
    merge_argv = ["semmerge", "basebr", "brA", "brB", "--backend", "tpu"]

    def notes_blobs():
        blobs = []
        for rev in ("brA", "brB"):
            p = subprocess.run(
                ["git", "notes", "--ref", "semmerge", "show", rev],
                cwd=repo, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True)
            blobs.append((p.returncode, p.stdout))
        return blobs

    def request(sock, posture=None):
        env = {} if posture is None else {"SEMMERGE_BATCH": posture}
        t0 = time.perf_counter()
        frame = svc_client.call_verb(
            "semmerge",
            {"argv": merge_argv[1:], "cwd": str(repo), "env": env},
            path=sock, timeout=600)
        wall = time.perf_counter() - t0
        result = frame.get("result") or {}
        return result.get("exit_code"), wall, frame

    def drive(sock, concurrency: int, per_thread: int):
        """``concurrency`` client threads, ``per_thread`` requests
        each, released together; returns (walls, total_wall, errors)."""
        walls, errors = [], []
        lock = threading.Lock()
        barrier = threading.Barrier(concurrency)

        def worker():
            try:
                barrier.wait()
                for _ in range(per_thread):
                    code, wall, frame = request(sock)
                    with lock:
                        if code != 0:
                            errors.append(f"request exit {code}: {frame}")
                            return
                        walls.append(wall)
            except Exception as exc:
                with lock:
                    errors.append(f"client thread died: {exc}")

        threads = [threading.Thread(target=worker)
                   for _ in range(concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        return walls, time.perf_counter() - t0, errors

    def spawn(sock, mesh_posture):
        """Start one daemon phase; returns (proc, error_or_None)."""
        env = dict(base_env)
        env["SEMMERGE_MESH"] = mesh_posture
        if mesh_posture != "off" and on_cpu and \
                "xla_force_host_platform_device_count" not in \
                env.get("XLA_FLAGS", ""):
            # CPU container: the mesh phase runs over virtual host-
            # platform devices (they exercise the sharded program; they
            # add no hardware, so scaling_efficiency divides by 1).
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                " --xla_force_host_platform_device_count=4"
                                ).strip()
            # XLA:CPU aborts reloading AOT-cached multi-replica
            # executables; the persistent compile cache must sit this
            # phase out.
            env["SEMMERGE_NO_COMPILE_CACHE"] = "1"
        log = open(sock + ".log", "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "semantic_merge_tpu", "serve",
             "--socket", sock],
            stdin=subprocess.DEVNULL, stdout=log, stderr=log,
            cwd="/", env=env, start_new_session=True)
        log.close()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            conn = svc_client._try_connect(sock, timeout=2.0)
            if conn is not None:
                svc_client._close(*conn)
                return proc, None
            if proc.poll() is not None:
                return proc, (f"daemon exited rc={proc.returncode} during "
                              f"startup (log: {sock}.log)")
            time.sleep(0.1)
        proc.kill()
        return proc, "daemon did not come up within 120s"

    def teardown(proc, sock):
        if proc is None:
            return
        try:
            svc_client.call_control("shutdown", path=sock, timeout=10)
            proc.wait(timeout=30)
        except Exception:
            proc.kill()

    def fail(msg: str) -> int:
        record["error"] = msg
        emit_record(record)
        return 1

    daemon = None
    sock = cur_sock = str(scratch / "daemon.sock")
    try:
        # ----- phase 1: chips=1 (single-device batched program) -----
        daemon, err = spawn(sock, "off")
        if err:
            return fail(err)

        # Parity gate (doubles as warm-up of the B=1 batched program):
        # require-batched vs forced-unbatched, byte-identical notes.
        for posture in ("require", "require"):  # 2nd run is cache-warm
            code, _, frame = request(sock, posture)
            if code != 0:
                return fail(f"batched warm-up failed: {frame}")
        batched_notes = notes_blobs()
        code, _, frame = request(sock, "off")
        if code != 0:
            return fail(f"unbatched parity run failed: {frame}")
        parity = (notes_blobs() == batched_notes)

        # Untimed c16 burst: compiles the larger-B batched programs so
        # the timed sweep measures steady state, as the other presets do.
        _, _, errs = drive(sock, 16, 1)
        if errs:
            return fail(f"warm burst failed: {errs[0]}")

        walls1, total1, errs1 = drive(sock, 1, 6)
        walls4, total4, errs4 = drive(sock, 4, 4)
        walls16, total16, errs16 = drive(sock, 16, 2)
        for errs in (errs1, errs4, errs16):
            if errs:
                return fail(errs[0])
        serial_rate = len(walls1) / total1
        rate4 = len(walls4) / total4
        rate16 = len(walls16) / total16
        lat = sorted(walls16)
        p50 = statistics.median(lat)
        p99 = lat[min(len(lat) - 1, round(0.99 * (len(lat) - 1)))]

        status = svc_client.call_control("status", path=sock, timeout=30)
        batch = status.get("batch") or {}
        cache = batch.get("program_cache") or {}
        teardown(daemon, sock)
        daemon = None

        # ----- one-shot parity leg: no daemon, no batching, no mesh --
        env_one = dict(base_env)
        env_one.update({"SEMMERGE_MESH": "off", "SEMMERGE_BATCH": "off"})
        proc = subprocess.run(
            [sys.executable, "-m", "semantic_merge_tpu", *merge_argv],
            cwd=repo, env=env_one, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True)
        if proc.returncode != 0:
            return fail(f"one-shot parity run failed: {proc.stderr[-500:]}")
        parity = parity and (notes_blobs() == batched_notes)

        # ----- phase 2: chips=N (mesh-sharded batched program) -------
        # require on CPU (the phase forces 4 virtual devices, so the
        # contract is satisfiable by construction); auto on real
        # hardware, where the chip count is whatever the host has.
        sock2 = cur_sock = str(scratch / "daemon-mesh.sock")
        daemon, err = spawn(sock2, "require" if on_cpu else "auto")
        if err:
            return fail(err)
        for posture in ("require", "require"):
            code, _, frame = request(sock2, posture)
            if code != 0:
                return fail(f"mesh warm-up failed: {frame}")
        parity = parity and (notes_blobs() == batched_notes)
        _, _, errs = drive(sock2, 16, 1)
        if errs:
            return fail(f"mesh warm burst failed: {errs[0]}")
        mwalls16, mtotal16, merrs16 = drive(sock2, 16, 2)
        if merrs16:
            return fail(merrs16[0])
        parity = parity and (notes_blobs() == batched_notes)
        record["parity"] = bool(parity)
        mesh_rate16 = len(mwalls16) / mtotal16
        mlat = sorted(mwalls16)
        mp50 = statistics.median(mlat)
        mp99 = mlat[min(len(mlat) - 1, round(0.99 * (len(mlat) - 1)))]

        status2 = svc_client.call_control("status", path=sock2, timeout=30)
        mesh = (status2.get("batch") or {}).get("mesh") or {}
        meshed = int(mesh.get("mesh_dispatches") or 0) > 0
        shape = str(mesh.get("last_shape") or "batch=1")
        chips = int(shape.partition("=")[2] or 1) if meshed else 1
        # Virtual host-platform devices exercise the sharded program
        # but add no hardware: efficiency there is mesh-vs-off at
        # matched concurrency (denominator 1). On real chips it is the
        # per-chip share of the speedup.
        chips_effective = 1 if on_cpu else max(1, chips)
        scaling = (mesh_rate16 / rate16) / chips_effective if rate16 else 0.0
        efficiency_ok = (not meshed) or scaling >= 0.7

        record["metric"] = (
            f"merges/sec (continuous batching, warm daemon, concurrency "
            f"16 vs 1, chips={chips}, {args.files} files x {args.decls} "
            f"decls, parity={'ok' if parity else 'FAIL'})")
        record["value"] = round(rate16, 2)
        record["unit"] = "merges/sec"
        record["vs_baseline"] = round(rate16 / serial_rate, 3)
        record["serial_merges_per_sec"] = round(serial_rate, 2)
        record["batch_merges_per_sec_c4"] = round(rate4, 2)
        record["batch_merges_per_sec_c16"] = round(rate16, 2)
        record["batch_speedup_c16"] = round(rate16 / serial_rate, 3)
        record["batch_p50_ms"] = round(p50 * 1e3, 1)
        record["batch_p99_ms"] = round(p99 * 1e3, 1)
        record["mean_batch_size"] = round(
            float(batch.get("mean_batch_size", 0.0)), 3)
        record["batch_padding_waste_ratio"] = round(
            float(batch.get("padding_waste_ratio", 0.0)), 4)
        record["batch_program_cache_hit_rate"] = round(
            float(cache.get("hit_rate", 0.0)), 4)
        record["chips"] = chips
        record["mesh_merges_per_sec_c16"] = round(mesh_rate16, 2)
        record["merges_per_sec_per_chip"] = round(
            mesh_rate16 / max(1, chips), 2)
        record["scaling_efficiency"] = round(scaling, 3)
        record["mesh_p50_ms"] = round(mp50 * 1e3, 1)
        record["mesh_p99_ms"] = round(mp99 * 1e3, 1)
        if not json_only:
            print(f"# serial (c1):  {serial_rate:6.2f} merges/sec",
                  file=sys.stderr)
            print(f"# batched (c4): {rate4:6.2f} merges/sec",
                  file=sys.stderr)
            print(f"# batched (c16):{rate16:6.2f} merges/sec "
                  f"({rate16 / serial_rate:.1f}x serial)  "
                  f"p50={p50 * 1e3:.0f}ms p99={p99 * 1e3:.0f}ms",
                  file=sys.stderr)
            print(f"# mean batch size: {record['mean_batch_size']}  "
                  f"padding waste: {record['batch_padding_waste_ratio']}  "
                  f"program cache hit rate: "
                  f"{record['batch_program_cache_hit_rate']}",
                  file=sys.stderr)
            print(f"# mesh (c16, chips={chips}): {mesh_rate16:6.2f} "
                  f"merges/sec  per-chip={record['merges_per_sec_per_chip']}"
                  f"  efficiency={scaling:.2f}  "
                  f"p50={mp50 * 1e3:.0f}ms p99={mp99 * 1e3:.0f}ms",
                  file=sys.stderr)
        emit_record(record)
        return 0 if (parity and efficiency_ok) else 1
    finally:
        teardown(daemon, cur_sock)
        shutil.rmtree(scratch, ignore_errors=True)


def run_overload_bench(record: dict, args, json_only: bool = False) -> int:
    """The ``overload`` preset: what the resilience machinery costs and
    buys. One daemon, deliberately constrained (2 workers, queue of 2,
    breaker threshold 3 / cooldown 1s), driven through four phases:

    1. sequential baseline          -> ``baseline_p99_ms``
    2. 16-thread burst              -> ``overload_p99_ms`` (accepted
       requests), ``overload_shed_rate`` (typed rejections w/
       ``retry_after_ms`` over the whole burst)
    3. wedge the host rung until the breaker opens, then measure
       skip-without-attempt merges   -> ``breaker_open_latency_ms``
    4. clear the fault, time half-open probe -> closed
                                    -> ``breaker_recovery_s``

    plus ``steady_rss_mb`` from the daemon's final status. All additive
    BENCH fields; headline value = accepted merges/sec under the burst.
    """
    import shutil
    import subprocess
    import tempfile
    import threading

    from semantic_merge_tpu.service import client as svc_client

    scratch = pathlib.Path(tempfile.mkdtemp(prefix="semmerge-overload-"))
    repo = scratch / "repo"
    sock = str(scratch / "daemon.sock")
    _build_service_repo(repo, args.files, args.decls)

    child_env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.abspath(__file__))
    prior_pp = child_env.get("PYTHONPATH", "")
    child_env["PYTHONPATH"] = (f"{pkg_root}{os.pathsep}{prior_pp}"
                               if prior_pp else pkg_root)
    child_env["SEMMERGE_DAEMON"] = "off"
    child_env.pop("SEMMERGE_FAULT", None)
    child_env.pop("SEMMERGE_METRICS", None)
    child_env["SEMMERGE_SERVICE_WORKERS"] = "2"
    child_env["SEMMERGE_SERVICE_QUEUE"] = "2"
    child_env["SEMMERGE_BREAKER_THRESHOLD"] = "3"
    child_env["SEMMERGE_BREAKER_COOLDOWN"] = "1.0"
    if os.environ.get("SEMMERGE_BENCH_PLATFORM") == "cpu":
        child_env["JAX_PLATFORMS"] = "cpu"
    merge_argv = ["semmerge", "basebr", "brA", "brB", "--backend", "host"]

    def request(env=None):
        t0 = time.perf_counter()
        frame = svc_client.call_verb(
            "semmerge",
            {"argv": merge_argv[1:], "cwd": str(repo), "env": env or {}},
            path=sock, timeout=600)
        return frame, time.perf_counter() - t0

    def breaker_state(status):
        return ((status.get("resilience") or {})
                .get("breakers") or {}).get("host")

    daemon = None
    try:
        log = open(sock + ".log", "ab")
        daemon = subprocess.Popen(
            [sys.executable, "-m", "semantic_merge_tpu", "serve",
             "--socket", sock],
            stdin=subprocess.DEVNULL, stdout=log, stderr=log,
            cwd="/", env=child_env, start_new_session=True)
        log.close()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            conn = svc_client._try_connect(sock, timeout=2.0)
            if conn is not None:
                svc_client._close(*conn)
                break
            if daemon.poll() is not None:
                record["error"] = (f"daemon exited rc={daemon.returncode} "
                                   f"during startup (log: {sock}.log)")
                emit_record(record)
                return 1
            time.sleep(0.1)
        else:
            record["error"] = "daemon did not come up within 120s"
            emit_record(record)
            return 1

        # Phase 1 — sequential baseline (first request is the warm-up).
        baseline_walls = []
        for i in range(9):
            frame, wall = request()
            if (frame.get("result") or {}).get("exit_code") != 0:
                record["error"] = f"baseline merge failed: {frame}"
                emit_record(record)
                return 1
            if i > 0:
                baseline_walls.append(wall)
        baseline_walls.sort()
        baseline_p99 = baseline_walls[
            min(len(baseline_walls) - 1,
                int(len(baseline_walls) * 0.99))]

        # Phase 2 — 16-thread burst of 4 requests each against 2
        # workers + queue of 2: admission control must shed the
        # overflow with typed retry_after_ms rejections while accepted
        # requests keep a bounded p99.
        accepted_walls, rejected, other_errors = [], [], []
        lock = threading.Lock()
        barrier = threading.Barrier(16)

        def burst_worker():
            try:
                barrier.wait()
                for _ in range(4):
                    frame, wall = request()
                    err = frame.get("error") or {}
                    with lock:
                        if (frame.get("result") or {}).get("exit_code") == 0:
                            accepted_walls.append(wall)
                        elif isinstance(err.get("retry_after_ms"), int):
                            rejected.append(err)
                        else:
                            other_errors.append(str(frame)[:200])
            except Exception as exc:
                with lock:
                    other_errors.append(f"client thread died: {exc}")

        threads = [threading.Thread(target=burst_worker)
                   for _ in range(16)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        burst_wall = time.perf_counter() - t0
        if other_errors:
            record["error"] = ("burst produced undocumented failures: "
                               + "; ".join(other_errors[:3]))
            emit_record(record)
            return 1
        total_burst = len(accepted_walls) + len(rejected)
        accepted_walls.sort()
        overload_p99 = accepted_walls[
            min(len(accepted_walls) - 1,
                int(len(accepted_walls) * 0.99))] if accepted_walls else 0.0

        # Phase 3 — wedge the host rung until the breaker opens, then
        # measure the skip-without-attempt path (degrade to the textual
        # floor with no doomed rung attempt burning latency).
        fault_env = {"SEMMERGE_FAULT": "scan:raise"}
        opened = False
        for _ in range(10):
            request(fault_env)
            status = svc_client.call_control("status", path=sock,
                                             timeout=30)
            if breaker_state(status) == "open":
                opened = True
                break
        if not opened:
            record["error"] = ("host-rung breaker did not open after 10 "
                               "consecutive injected failures")
            emit_record(record)
            return 1
        open_walls = []
        for _ in range(6):
            frame, wall = request(fault_env)
            if (frame.get("result") or {}).get("exit_code") == 0:
                open_walls.append(wall)
        open_walls.sort()
        breaker_open_ms = (open_walls[len(open_walls) // 2] * 1e3
                           if open_walls else 0.0)

        # Phase 4 — clear the fault and time open -> half-open probe ->
        # closed (the 1s cooldown dominates; the probe itself is one
        # successful merge).
        t0 = time.perf_counter()
        recovery_s = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            frame, _ = request()
            status = svc_client.call_control("status", path=sock,
                                             timeout=30)
            if breaker_state(status) == "closed":
                recovery_s = time.perf_counter() - t0
                break
            time.sleep(0.2)
        if recovery_s is None:
            record["error"] = ("breaker did not close within 30s of the "
                               "fault clearing")
            emit_record(record)
            return 1

        status = svc_client.call_control("status", path=sock, timeout=30)
        record["metric"] = (
            f"accepted merges/sec under 16-thread overload burst "
            f"(2 workers, queue 2, {args.files} files x {args.decls} "
            f"decls, host backend)")
        record["value"] = round(len(accepted_walls) / burst_wall, 2)
        record["unit"] = "merges/sec"
        record["vs_baseline"] = round(
            baseline_p99 / overload_p99, 3) if overload_p99 else 0.0
        record["overload_shed_rate"] = round(
            len(rejected) / total_burst, 4) if total_burst else 0.0
        record["overload_p99_ms"] = round(overload_p99 * 1e3, 1)
        record["baseline_p99_ms"] = round(baseline_p99 * 1e3, 1)
        record["breaker_open_latency_ms"] = round(breaker_open_ms, 1)
        record["breaker_recovery_s"] = round(recovery_s, 3)
        record["steady_rss_mb"] = round(float(status.get("rss_mb", 0.0)), 1)
        if not json_only:
            print(f"# baseline p99: {record['baseline_p99_ms']:8.1f} ms",
                  file=sys.stderr)
            print(f"# overload p99: {record['overload_p99_ms']:8.1f} ms  "
                  f"shed rate: {record['overload_shed_rate']:.3f} "
                  f"({len(rejected)}/{total_burst})", file=sys.stderr)
            print(f"# breaker-open p50: "
                  f"{record['breaker_open_latency_ms']:.1f} ms  "
                  f"recovery: {record['breaker_recovery_s']:.2f} s  "
                  f"rss: {record['steady_rss_mb']} MiB", file=sys.stderr)
        emit_record(record)
        return 0
    finally:
        if daemon is not None:
            try:
                svc_client.call_control("shutdown", path=sock, timeout=10)
                daemon.wait(timeout=30)
            except Exception:
                daemon.kill()
        shutil.rmtree(scratch, ignore_errors=True)


def run_fleet_bench(record: dict, args, json_only: bool = False) -> int:
    """The ``fleet`` preset: what the consistent-hash router buys and
    costs. Four phases, all subprocess-shaped (router + member daemons
    spawned; the parent needs no accelerator):

    1. throughput sweep at members in {1, 2, 3} (hedging off so every
       merge runs exactly once) -> ``fleet_merges_per_sec_m1/2/3``;
       headline value = merges/sec at 3 members, ``vs_baseline`` = the
       m3/m1 scaling ratio.
    2. SIGKILL the rendezvous owner of one repo mid-fleet and time
       until that repo's next merge lands on the rehashed owner
       -> ``fleet_failover_recovery_s``.
    3. rendezvous rehash quality, measured over a 240-key population:
       mean fraction of keys whose owner changes when one of three
       members is lost -> ``fleet_rehash_miss_rate`` (a plain
       mod-N ring would score ~1.0; rendezvous ~1/3).
    4. fresh hedge-enabled fleet: wedge one repo's owner (single
       worker + injected execute hang), fire reads at it, and report
       ``fleet_hedge_win_rate`` = hedge wins / hedges launched.
    """
    import shutil
    import signal as signal_mod
    import subprocess
    import tempfile
    import threading

    from semantic_merge_tpu.fleet import hashring
    from semantic_merge_tpu.service import client as svc_client

    scratch = pathlib.Path(tempfile.mkdtemp(prefix="semmerge-fleet-"))
    n_repos = 4
    repos = []
    for i in range(n_repos):
        repo = scratch / f"repo{i}"
        _build_service_repo(repo, args.files, args.decls)
        repos.append(repo)

    child_env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.abspath(__file__))
    prior_pp = child_env.get("PYTHONPATH", "")
    child_env["PYTHONPATH"] = (f"{pkg_root}{os.pathsep}{prior_pp}"
                               if prior_pp else pkg_root)
    child_env.update({
        "SEMMERGE_DAEMON": "off",
        "SEMMERGE_FLEET_HEALTH_INTERVAL": "0.2",
        "SEMMERGE_SUPERVISE_BACKOFF": "0.1",
        # One worker per member: the m1 -> m3 sweep then measures ring
        # fan-out, not intra-member parallelism, and phase 4's wedge
        # deterministically occupies the owner.
        "SEMMERGE_SERVICE_WORKERS": "1",
        "SEMMERGE_SERVICE_DRAIN_TIMEOUT": "2",
    })
    for key in ("SEMMERGE_FAULT", "SEMMERGE_METRICS",
                "SEMMERGE_SERVICE_SOCKET", "SEMMERGE_FLEET",
                "SEMMERGE_FLEET_MEMBERS", "SEMMERGE_FLEET_HEDGE",
                "SEMMERGE_FLEET_HEDGE_MS"):
        child_env.pop(key, None)
    if os.environ.get("SEMMERGE_BENCH_PLATFORM") == "cpu":
        child_env["JAX_PLATFORMS"] = "cpu"

    def spawn_router(sock, members, extra_env=None):
        env = dict(child_env)
        env.update(extra_env or {})
        log = open(sock + ".log", "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "semantic_merge_tpu", "fleet",
             "--socket", sock, "--members", str(members)],
            stdin=subprocess.DEVNULL, stdout=log, stderr=log,
            cwd="/", env=env, start_new_session=True)
        log.close()
        return proc

    def wait_fleet(sock, proc, members, timeout=240.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                return None, (f"router exited rc={proc.returncode} "
                              f"during startup (log: {sock}.log)")
            try:
                status = svc_client.call_control("status", path=sock,
                                                 timeout=10)
            except Exception:
                status = None
            if status and status.get("fleet") \
                    and status.get("members_up", 0) >= members:
                return status, None
            time.sleep(0.2)
        return None, f"fleet of {members} not up within {timeout:g}s " \
                     f"(log: {sock}.log)"

    def call(sock, repo, *, extra_env=None, inplace=False, timeout=180):
        argv = ["basebr", "brA", "brB", "--backend", "host"]
        if inplace:
            argv.insert(3, "--inplace")
        return svc_client.call_verb(
            "semmerge",
            {"argv": argv, "cwd": str(repo), "env": extra_env or {},
             "idempotency_key": f"bench-{os.urandom(8).hex()}"},
            path=sock, timeout=timeout)

    def teardown(proc, sock):
        if proc is None or proc.poll() is not None:
            return
        proc.send_signal(signal_mod.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()

    def fail(msg: str) -> int:
        record["error"] = msg
        emit_record(record)
        return 1

    def sweep(sock, total, concurrency):
        """``total`` clean merges round-robined over the repos from
        ``concurrency`` client threads; returns (merges/sec, errors)."""
        work = [repos[i % n_repos] for i in range(total)]
        lock = threading.Lock()
        errors = []

        def worker():
            while True:
                with lock:
                    if not work:
                        return
                    repo = work.pop()
                try:
                    frame = call(sock, repo)
                except Exception as exc:
                    with lock:
                        errors.append(f"sweep request died: {exc}")
                    return
                if (frame.get("result") or {}).get("exit_code") != 0:
                    with lock:
                        errors.append(f"sweep merge failed: "
                                      f"{str(frame)[:200]}")

        threads = [threading.Thread(target=worker)
                   for _ in range(concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        wall = time.perf_counter() - t0
        return (total / wall if wall else 0.0), errors

    def counter_total(status, name):
        metric = ((status or {}).get("metrics") or {}) \
            .get("counters", {}).get(name, {})
        return sum(s["value"] for s in metric.get("series", []))

    router = sock = None
    try:
        # ----- phase 1: throughput sweep, hedging off -----
        rates = {}
        for n in (1, 2, 3):
            sock = str(scratch / f"fleet-m{n}.sock")
            router = spawn_router(sock, n,
                                  {"SEMMERGE_FLEET_HEDGE": "off"})
            status, err = wait_fleet(sock, router, n)
            if err:
                return fail(err)
            for repo in repos:  # warm every member's first-merge path
                frame = call(sock, repo)
                if (frame.get("result") or {}).get("exit_code") != 0:
                    return fail(f"warm-up merge failed at m{n}: "
                                f"{str(frame)[:200]}")
            rate, errors = sweep(sock, total=24, concurrency=6)
            if errors:
                return fail(f"m{n} sweep: " + "; ".join(errors[:3]))
            rates[n] = rate
            record[f"fleet_merges_per_sec_m{n}"] = round(rate, 2)
            if not json_only:
                print(f"# fleet m{n}: {rate:6.2f} merges/sec",
                      file=sys.stderr)
            if n < 3:
                teardown(router, sock)
                router = None

        # ----- phase 2: failover recovery on the 3-member fleet -----
        status, err = wait_fleet(sock, router, 3)
        if err:
            return fail(err)
        ring = [m["id"] for m in status.get("members", [])
                if m.get("in_ring")]
        victim_id = hashring.owner(hashring.repo_key(str(repos[0])), ring)
        victim_pid = next((m["pid"] for m in status["members"]
                           if m["id"] == victim_id and m.get("pid")),
                          None)
        if victim_pid is None:
            return fail(f"owner {victim_id} of repo0 has no live pid")
        t0 = time.perf_counter()
        os.kill(victim_pid, signal_mod.SIGKILL)
        recovery_s = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            try:
                frame = call(sock, repos[0], timeout=60)
            except Exception:
                time.sleep(0.1)
                continue
            if (frame.get("result") or {}).get("exit_code") == 0:
                recovery_s = time.perf_counter() - t0
                break
            time.sleep(0.1)
        if recovery_s is None:
            return fail("repo0 merge did not recover within 120s of "
                        "its owner's SIGKILL")
        record["fleet_failover_recovery_s"] = round(recovery_s, 3)
        status = svc_client.call_control("status", path=sock, timeout=30)
        failovers = counter_total(status, "fleet_failovers_total")
        teardown(router, sock)
        router = None

        # ----- phase 3: rendezvous rehash quality (analytic) -----
        ids = [f"m{i}" for i in range(3)]
        keys = [f"/bench/repo-{i:03d}" for i in range(240)]
        moved = 0
        for gone in ids:
            survivors = [m for m in ids if m != gone]
            moved += sum(1 for k in keys
                         if hashring.owner(k, ids)
                         != hashring.owner(k, survivors))
        miss_rate = moved / (len(keys) * len(ids))
        record["fleet_rehash_miss_rate"] = round(miss_rate, 4)

        # ----- phase 4: hedge win rate on a fresh hedge-enabled fleet --
        sock = str(scratch / "fleet-hedge.sock")
        router = spawn_router(sock, 3,
                              {"SEMMERGE_FLEET_HEDGE_MS": "50"})
        status, err = wait_fleet(sock, router, 3)
        if err:
            return fail(err)
        for repo in repos:
            call(sock, repo)  # warm (may hedge; counters reset below)
        status = svc_client.call_control("status", path=sock, timeout=30)
        hedges0 = counter_total(status, "fleet_hedges_total")
        wins0 = counter_total(status, "fleet_hedge_wins_total")
        # Wedge repo1's owner: --inplace never hedges, so the injected
        # 20s execute hang pins the owner's single worker.
        def wedge_owner():
            try:
                call(sock, repos[1], inplace=True, timeout=60,
                     extra_env={"SEMMERGE_FAULT":
                                "service:execute:hang=20"})
            except Exception:
                pass  # torn down mid-hang by design

        wedge = threading.Thread(target=wedge_owner, daemon=True)
        wedge.start()
        time.sleep(0.5)
        hedge_ok = 0
        for _ in range(4):
            frame = call(sock, repos[1], timeout=60)
            if (frame.get("result") or {}).get("exit_code") == 0:
                hedge_ok += 1
        status = svc_client.call_control("status", path=sock, timeout=30)
        hedges = counter_total(status, "fleet_hedges_total") - hedges0
        wins = counter_total(status, "fleet_hedge_wins_total") - wins0
        if hedges < 1 or hedge_ok < 1:
            return fail(f"wedged owner produced no hedges "
                        f"(hedges={hedges}, ok={hedge_ok})")
        win_rate = wins / hedges if hedges else 0.0
        record["fleet_hedge_win_rate"] = round(win_rate, 4)

        record["metric"] = (
            f"merges/sec through a 3-member fleet router (rendezvous "
            f"affinity, hedging off, {n_repos} repos x {args.files} "
            f"files x {args.decls} decls, host backend, 1 worker/member)")
        record["value"] = round(rates[3], 2)
        record["unit"] = "merges/sec"
        record["vs_baseline"] = round(
            rates[3] / rates[1], 3) if rates[1] else 0.0
        if not json_only:
            print(f"# failover recovery: {recovery_s:6.3f} s "
                  f"(failovers counted: {failovers:.0f})",
                  file=sys.stderr)
            print(f"# rehash miss rate: {miss_rate:.3f} "
                  f"(mod-N ring would be ~1.0)", file=sys.stderr)
            print(f"# hedge win rate: {win_rate:.3f} "
                  f"({wins:.0f}/{hedges:.0f} hedges, "
                  f"{hedge_ok}/4 wedged reads served)", file=sys.stderr)
        emit_record(record)
        return 0
    finally:
        teardown(router, sock)
        shutil.rmtree(scratch, ignore_errors=True)


def run_fleetwan_bench(record: dict, args, json_only: bool = False) -> int:
    """The ``fleetwan`` preset: the cross-host fleet shape on a TCP
    loopback with injected per-dial latency (the ``net:slow`` seam,
    20 ms — a same-region WAN RTT). A router with no local members
    fronts 3 standalone daemons joined over ``serve --join``; every
    router->member dial pays the lag. Four measurements:

    1. warm throughput through the laggy transport
       -> ``fleetwan_merges_per_sec`` (headline);
    2. elastic churn — one TCP join + one drain; after the incremental
       handoff prewarms moved keys, one merge per repo must land warm
       -> ``fleetwan_rehash_miss_rate`` = cold dispatches / repos,
       hard-gated at <= 0.15 (an unassisted rendezvous rehash faults
       ~1/N of the keyspace in cold) and guarded in PERF_BASELINE.json;
    3. SIGKILL the rendezvous owner of one repo, time until that
       repo's next merge lands on the rehashed owner
       -> ``fleetwan_failover_recovery_ms``;
    4. a second, heartbeat-quiet fleet (health interval 5 s vs 0.2 s)
       isolates the probe plane's throughput cost on the same laggy
       transport -> ``fleetwan_heartbeat_overhead_pct``.
    """
    import shutil
    import signal as signal_mod
    import subprocess
    import tempfile
    import threading

    from semantic_merge_tpu.fleet import hashring
    from semantic_merge_tpu.service import client as svc_client

    scratch = pathlib.Path(tempfile.mkdtemp(prefix="semmerge-fleetwan-"))
    lag_s = 0.02
    miss_gate = 0.15
    n_repos = 8
    repos = []
    for i in range(n_repos):
        repo = scratch / f"repo{i}"
        _build_service_repo(repo, args.files, args.decls)
        repos.append(repo)

    child_env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.abspath(__file__))
    prior_pp = child_env.get("PYTHONPATH", "")
    child_env["PYTHONPATH"] = (f"{pkg_root}{os.pathsep}{prior_pp}"
                               if prior_pp else pkg_root)
    child_env.update({
        "SEMMERGE_DAEMON": "off",
        "SEMMERGE_SERVICE_WORKERS": "1",
        "SEMMERGE_SERVICE_DRAIN_TIMEOUT": "2",
    })
    for key in ("SEMMERGE_FAULT", "SEMMERGE_METRICS",
                "SEMMERGE_SERVICE_SOCKET", "SEMMERGE_FLEET",
                "SEMMERGE_FLEET_MEMBERS", "SEMMERGE_FLEET_HEDGE",
                "SEMMERGE_FLEET_HEDGE_MS"):
        child_env.pop(key, None)
    if os.environ.get("SEMMERGE_BENCH_PLATFORM") == "cpu":
        child_env["JAX_PLATFORMS"] = "cpu"

    def spawn_router(sock, health_interval):
        # The lag is injected in the ROUTER's env only: its dials to
        # members (dispatch, heartbeats, handoff prewarms) all pay it —
        # the member daemons and the bench client stay unlagged.
        env = dict(child_env)
        env.update({
            "SEMMERGE_FLEET_HEDGE": "off",
            "SEMMERGE_FLEET_HEALTH_INTERVAL": health_interval,
            "SEMMERGE_FAULT": "net:slow:lag",
            "SEMMERGE_FAULT_NET_SLOW_S": f"{lag_s}",
        })
        log = open(sock + ".log", "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "semantic_merge_tpu", "fleet",
             "--socket", sock, "--members", "0"],
            stdin=subprocess.DEVNULL, stdout=log, stderr=log,
            cwd="/", env=env, start_new_session=True)
        log.close()
        return proc

    def spawn_member(router_sock, member_id):
        env = dict(child_env)
        env["SEMMERGE_FLEET_JOIN_INTERVAL"] = "0.5"
        log = open(str(scratch / f"member-{member_id}.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "semantic_merge_tpu", "serve",
             "--socket", "tcp://127.0.0.1:0", "--join", router_sock,
             "--member-id", member_id],
            stdin=subprocess.DEVNULL, stdout=log, stderr=log,
            cwd="/", env=env, start_new_session=True)
        log.close()
        return proc

    def fleet_status(sock, timeout=10):
        try:
            return svc_client.call_control("status", path=sock,
                                           timeout=timeout)
        except Exception:
            return None

    def wait_ring(sock, proc, want_ids, timeout=240.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                return (f"router exited rc={proc.returncode} "
                        f"(log: {sock}.log)")
            status = fleet_status(sock)
            ring = {m["id"] for m in (status or {}).get("members", [])
                    if m.get("in_ring")}
            if status and status.get("fleet") and want_ids <= ring:
                return None
            time.sleep(0.2)
        return (f"ring never reached {sorted(want_ids)} within "
                f"{timeout:g}s (log: {sock}.log)")

    def call(sock, repo, timeout=180):
        return svc_client.call_verb(
            "semmerge",
            {"argv": ["basebr", "brA", "brB", "--backend", "host"],
             "cwd": str(repo), "env": {},
             "idempotency_key": f"bench-{os.urandom(8).hex()}"},
            path=sock, timeout=timeout)

    def warm(sock):
        for repo in repos:
            frame = call(sock, repo)
            if (frame.get("result") or {}).get("exit_code") != 0:
                return f"warm-up merge failed: {str(frame)[:200]}"
        return None

    def sweep(sock, total, concurrency):
        work = [repos[i % n_repos] for i in range(total)]
        lock = threading.Lock()
        errors = []

        def worker():
            while True:
                with lock:
                    if not work:
                        return
                    repo = work.pop()
                try:
                    frame = call(sock, repo)
                except Exception as exc:
                    with lock:
                        errors.append(f"sweep request died: {exc}")
                    return
                if (frame.get("result") or {}).get("exit_code") != 0:
                    with lock:
                        errors.append(f"sweep merge failed: "
                                      f"{str(frame)[:200]}")

        threads = [threading.Thread(target=worker)
                   for _ in range(concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        wall = time.perf_counter() - t0
        return (total / wall if wall else 0.0), errors

    def counter_total(status, name):
        metric = ((status or {}).get("metrics") or {}) \
            .get("counters", {}).get(name, {})
        return sum(s["value"] for s in metric.get("series", []))

    def teardown(proc, sock):
        if proc is None or proc.poll() is not None:
            return
        proc.send_signal(signal_mod.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()

    def fail(msg: str) -> int:
        record["error"] = msg
        emit_record(record)
        return 1

    router_a = router_b = None
    sock_a = str(scratch / "wan-a.sock")
    sock_b = str(scratch / "wan-b.sock")
    member_procs = {}
    try:
        # ----- phase 1: warm throughput through the laggy transport --
        router_a = spawn_router(sock_a, health_interval="0.2")
        for mid in ("t0", "t1", "t2"):
            member_procs[mid] = spawn_member(sock_a, mid)
        err = wait_ring(sock_a, router_a, {"t0", "t1", "t2"})
        if err:
            return fail(err)
        err = warm(sock_a)
        if err:
            return fail(err)
        rate_hb, errors = sweep(sock_a, total=24, concurrency=6)
        if errors:
            return fail("fleetwan sweep: " + "; ".join(errors[:3]))
        record["fleetwan_merges_per_sec"] = round(rate_hb, 2)
        if not json_only:
            print(f"# fleetwan ({lag_s*1e3:.0f} ms lag): "
                  f"{rate_hb:6.2f} merges/sec", file=sys.stderr)

        # ----- phase 2: churn — one join + one drain, miss rate ------
        member_procs["t3"] = spawn_member(sock_a, "t3")
        err = wait_ring(sock_a, router_a, {"t1", "t2", "t3"})
        if err:
            return fail(err)
        ack = svc_client.call_control("drain", params={"member": "t0"},
                                      path=sock_a, timeout=30)
        if not (ack or {}).get("ok"):
            return fail(f"drain of t0 not acked: {ack!r}")
        # The affinity handoff prewarms moved keys off the churn path
        # (a background thread); wait for the handoff counter to go
        # quiet before sampling, so the measurement sees the rebalanced
        # steady state, not the rebalance itself.
        settle_deadline = time.monotonic() + 120
        last = (-1.0, time.monotonic())
        while time.monotonic() < settle_deadline:
            status = fleet_status(sock_a, timeout=30)
            now_total = counter_total(status, "fleet_handoffs_total")
            if now_total != last[0]:
                last = (now_total, time.monotonic())
            elif time.monotonic() - last[1] >= 1.5:
                break
            time.sleep(0.25)
        status = fleet_status(sock_a, timeout=30)
        misses0 = counter_total(status, "fleet_affinity_misses_total")
        for repo in repos:
            frame = call(sock_a, repo)
            if (frame.get("result") or {}).get("exit_code") != 0:
                return fail(f"post-churn merge failed: "
                            f"{str(frame)[:200]}")
        status = fleet_status(sock_a, timeout=30)
        misses = counter_total(status, "fleet_affinity_misses_total") \
            - misses0
        miss_rate = misses / n_repos
        record["fleetwan_rehash_miss_rate"] = round(miss_rate, 4)
        record["fleetwan_handoffs_total"] = counter_total(
            status, "fleet_handoffs_total")
        if not json_only:
            print(f"# rehash miss rate after join+drain: "
                  f"{miss_rate:.3f} ({misses:.0f}/{n_repos} cold; "
                  f"gate {miss_gate})", file=sys.stderr)

        # ----- phase 3: failover recovery on the laggy transport -----
        status = fleet_status(sock_a, timeout=30)
        ring = [m["id"] for m in (status or {}).get("members", [])
                if m.get("in_ring")]
        victim_id = hashring.owner(hashring.repo_key(str(repos[0])),
                                   ring)
        victim = member_procs.get(victim_id)
        if victim is None:
            return fail(f"owner {victim_id!r} of repo0 is not a "
                        f"spawned member")
        t0 = time.perf_counter()
        os.kill(victim.pid, signal_mod.SIGKILL)
        recovery_s = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            try:
                frame = call(sock_a, repos[0], timeout=60)
            except Exception:
                time.sleep(0.1)
                continue
            if (frame.get("result") or {}).get("exit_code") == 0:
                recovery_s = time.perf_counter() - t0
                break
            time.sleep(0.1)
        if recovery_s is None:
            return fail("repo0 merge did not recover within 120s of "
                        "its owner's SIGKILL")
        record["fleetwan_failover_recovery_ms"] = round(
            recovery_s * 1e3, 1)
        if not json_only:
            print(f"# failover recovery: {recovery_s*1e3:8.1f} ms",
                  file=sys.stderr)
        teardown(router_a, sock_a)
        router_a = None

        # ----- phase 4: heartbeat overhead vs a quiet fleet ----------
        router_b = spawn_router(sock_b, health_interval="5")
        for mid in ("q0", "q1", "q2"):
            member_procs[mid] = spawn_member(sock_b, mid)
        err = wait_ring(sock_b, router_b, {"q0", "q1", "q2"})
        if err:
            return fail(err)
        err = warm(sock_b)
        if err:
            return fail(err)
        rate_quiet, errors = sweep(sock_b, total=24, concurrency=6)
        if errors:
            return fail("fleetwan quiet sweep: "
                        + "; ".join(errors[:3]))
        overhead = (max(0.0, (rate_quiet - rate_hb) / rate_quiet * 100)
                    if rate_quiet > 0 else 0.0)
        record["fleetwan_quiet_merges_per_sec"] = round(rate_quiet, 2)
        record["fleetwan_heartbeat_overhead_pct"] = round(overhead, 2)
        if not json_only:
            print(f"# heartbeat overhead: {overhead:5.2f}% "
                  f"({rate_quiet:.2f} merges/sec with probes quiet)",
                  file=sys.stderr)

        record["metric"] = (
            f"merges/sec through a TCP-loopback fleet with "
            f"{lag_s*1e3:.0f} ms injected dial latency (3 remote "
            f"members joined via announce, rendezvous affinity, "
            f"hedging off, {n_repos} repos x {args.files} files x "
            f"{args.decls} decls, host backend, 1 worker/member)")
        record["value"] = round(rate_hb, 2)
        record["unit"] = "merges/sec"
        record["vs_baseline"] = round(
            rate_hb / rate_quiet, 3) if rate_quiet else 0.0
        if miss_rate > miss_gate:
            return fail(f"fleetwan rehash miss rate {miss_rate:.3f} "
                        f"exceeds the {miss_gate} gate — the affinity "
                        f"handoff is not prewarming moved keys")
        emit_record(record)
        return 0
    finally:
        teardown(router_a, sock_a)
        teardown(router_b, sock_b)
        for proc in member_procs.values():
            if proc.poll() is None:
                proc.send_signal(signal_mod.SIGTERM)
        for proc in member_procs.values():
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
        shutil.rmtree(scratch, ignore_errors=True)


def run_incremental_bench(record: dict, args, n_changed: int,
                          json_only: bool = False) -> int:
    """The rung5i scenario: a 10k-file tree where only ``n_changed``
    files differ. Times three protocols, each on a FRESH backend per
    repeat (cold interner/decl/snapshot caches, warm jit — the shape a
    new merge arriving at a long-lived worker sees):

    - device path, scope-restricted snapshots (what the CLI does with
      ``[engine] incremental = true``, the default);
    - device path, full-tree snapshots (the round-4 behavior);
    - host oracle, full-tree snapshots (the baseline denominator).

    Parity gate: the restricted device merge must produce op logs and
    composed ops byte-identical to the full-scan host oracle."""
    import gc

    from semantic_merge_tpu.backends.base import get_backend

    base, left, right = synth_repo_sparse(args.files, args.decls, n_changed)
    scope = changed_paths(base, left, right)
    base_r, left_r, right_r = (base.restrict(scope), left.restrict(scope),
                               right.restrict(scope))

    # Parity gate (also warms every jit variant the timed runs need).
    res_t, comp_t, conf_t = run_merge(get_backend("tpu"), base_r, left_r, right_r)
    res_h, comp_h, conf_h = run_merge(get_backend("host"), base, left, right)
    parity = (
        [o.to_dict() for o in res_t.op_log_left] == [o.to_dict() for o in res_h.op_log_left]
        and [o.to_dict() for o in res_t.op_log_right] == [o.to_dict() for o in res_h.op_log_right]
        and [o.to_dict() for o in comp_t] == [o.to_dict() for o in comp_h]
        and [c.to_dict() for c in conf_t] == [c.to_dict() for c in conf_h]
    )
    run_merge(get_backend("tpu"), base, left, right)  # warm full-scan shapes

    def time_cold(name, b, l, r, repeats=3):
        best = float("inf")
        for _ in range(repeats):
            bk = get_backend(name)
            gc.collect()
            t0 = time.perf_counter()
            run_merge_to_payload(bk, b, l, r)
            best = min(best, time.perf_counter() - t0)
        return best

    t_inc = time_cold("tpu", base_r, left_r, right_r)
    t_full_dev = time_cold("tpu", base, left, right)
    t_full_host = time_cold("host", base, left, right)

    phases = instrumented_phases(get_backend("tpu"), base_r, left_r, right_r)

    import jax
    platform = jax.devices()[0].platform
    files_per_sec = args.files / t_inc
    record["metric"] = (
        f"files merged/sec/chip (synthetic 3-way TS merge, {args.files} "
        f"files x {args.decls} decls, {n_changed} changed, incremental "
        f"scope, parity={'ok' if parity else 'FAIL'}, platform={platform})")
    record["value"] = round(files_per_sec, 2)
    record["vs_baseline"] = round(t_full_host / t_inc, 3)
    record["vs_full_scan_device"] = round(t_full_dev / t_inc, 3)
    record["incremental_ms"] = round(t_inc * 1e3, 1)
    record["full_scan_device_ms"] = round(t_full_dev * 1e3, 1)
    record["full_scan_host_ms"] = round(t_full_host * 1e3, 1)
    record["phases_ms"] = {k: round(v * 1e3, 1) for k, v in phases.items()}
    record["parity"] = bool(parity)
    record.update(host_tail_summary(phases))
    if not json_only:
        print(f"# incremental ({len(scope)} files in scope): "
              f"{t_inc*1e3:8.1f} ms", file=sys.stderr)
        print(f"# full-scan device: {t_full_dev*1e3:8.1f} ms "
              f"({t_full_dev/t_inc:.1f}x slower)", file=sys.stderr)
        print(f"# full-scan host:   {t_full_host*1e3:8.1f} ms", file=sys.stderr)
        print("# phases: " + "  ".join(f"{k}={v*1e3:.1f}ms"
                                       for k, v in phases.items()),
              file=sys.stderr)
    emit_record(record)
    return 0 if parity else 1


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--files", type=int, default=None,
                        help="Override the workload size (default: the "
                             "rung-5 preset — BASELINE.json's 10k-file "
                             "north-star config)")
    parser.add_argument("--decls", type=int, default=6)
    parser.add_argument("--preset", choices=sorted(PRESETS),
                        help="BASELINE.json ladder rung (overrides --files/--decls)")
    parser.add_argument("--json-only", action="store_true")
    parser.add_argument("--cold", action="store_true",
                        help="Fork a fresh process per merge (driver-shaped "
                             "cold start; persistent compile cache on)")
    parser.add_argument("--watchdog", type=float,
                        default=float(os.environ.get("BENCH_WATCHDOG", "900")),
                        help="seconds before the bench force-emits and exits")
    args = parser.parse_args()
    conflicts_expected = False
    n_changed = None
    strict_mode = False
    tracecost_mode = False
    slocost_mode = False
    telcost_mode = False
    devtail_mode = False
    if args.preset is None and args.files is None:
        # The headline number is measured where BASELINE.json defines
        # it: the 10k-file DivergentRename monorepo merge (rung 5).
        args.preset = "rung5"
    if args.preset:
        p = PRESETS[args.preset]
        args.files, args.decls = p["files"], p["decls"]
        conflicts_expected = p.get("conflicts", False)
        n_changed = p.get("changed")
        strict_mode = p.get("strict", False)
        tracecost_mode = p.get("tracecost", False)
        slocost_mode = p.get("slocost", False)
        telcost_mode = p.get("telcost", False)
        devtail_mode = p.get("devtail", False)
    elif args.files is None:
        args.files = 512
    global _EMIT_PRESET
    _EMIT_PRESET = args.preset

    record = {
        "metric": f"files merged/sec/chip (synthetic 3-way TS merge, "
                  f"{args.files} files x {args.decls} decls)",
        "value": 0.0,
        "unit": "files/sec",
        "vs_baseline": 0.0,
    }
    _emit_and_exit_on_watchdog(record, args.watchdog)

    if args.preset == "warmserve":
        # Entirely subprocess-shaped (one-shot CLIs + a spawned daemon):
        # the parent needs no accelerator, no backend, no GC tuning.
        return run_warmserve_bench(record, args, json_only=args.json_only)
    if args.preset == "batchserve":
        # Same shape: all merges run inside the spawned daemon.
        return run_batchserve_bench(record, args, json_only=args.json_only)
    if args.preset == "overload":
        # Same shape again: admission control, breakers, and RSS are
        # all exercised inside the spawned daemon.
        return run_overload_bench(record, args, json_only=args.json_only)
    if args.preset == "fleet":
        # Router + member daemons are all subprocesses; the parent
        # needs no accelerator.
        return run_fleet_bench(record, args, json_only=args.json_only)
    if args.preset == "fleetwan":
        # Same shape over TCP with injected dial latency.
        return run_fleetwan_bench(record, args, json_only=args.json_only)
    if args.preset == "resolve":
        # One-shot CLI subprocesses on the host backend: the parent
        # needs no accelerator.
        return run_resolve_bench(record, args, json_only=args.json_only)

    # Accelerator acquisition, hardened (round 1 died here with rc=1 and
    # no JSON): probe the relay-backed TPU plugin in a throwaway
    # subprocess (a hang there cannot wedge the bench), retrying once;
    # on failure pin this process to host CPU — the device path is still
    # exercised (XLA-on-CPU), the record says so in "error".
    from semantic_merge_tpu.utils.jaxenv import accelerator_available, force_cpu

    if os.environ.get("SEMMERGE_BENCH_PLATFORM") == "cpu":
        plat = None  # explicit local-iteration override: skip the probe
    else:
        plat = accelerator_available(timeout=120.0, retries=1)
    if plat is None:
        force_cpu()
        record["error"] = ("no accelerator: TPU/relay backend failed to "
                           "initialise after 2 probes; measured on host CPU")

    from semantic_merge_tpu.backends.base import get_backend

    if n_changed is None and not strict_mode and not args.cold:
        base, left, right = synth_repo(args.files, args.decls,
                                       divergent=conflicts_expected)

    # Same GC posture as the CLI entry point (utils/gctune): default
    # thresholds cost ~40% of warm merge wall at the 5k rung. Applied
    # before the parity/warm runs so BOTH paths are measured under it.
    from semantic_merge_tpu.utils.gctune import tune_for_merge
    tune_for_merge()

    if args.cold:
        # Cold mode never uses the parent's backends — children build
        # their own; skip parent-side backend init entirely.
        return run_cold_bench(record, args, conflicts_expected,
                              json_only=args.json_only)

    try:
        tpu = get_backend("tpu")
    except Exception as exc:  # in-process init can still fail post-probe
        force_cpu()
        record["error"] = f"tpu backend init failed in-process: {exc}"
        tpu = get_backend("tpu")
    host = get_backend("host")

    if n_changed is not None:
        return run_incremental_bench(record, args, n_changed,
                                     json_only=args.json_only)
    if strict_mode:
        return run_strict_bench(record, args, json_only=args.json_only)
    if tracecost_mode:
        return run_tracecost_bench(record, args, tpu, base, left, right,
                                   json_only=args.json_only)
    if slocost_mode:
        return run_slocost_bench(record, args, tpu, base, left, right,
                                 json_only=args.json_only)
    if telcost_mode:
        return run_telcost_bench(record, args, tpu, base, left, right,
                                 json_only=args.json_only)
    if devtail_mode:
        return run_devtail_bench(record, args, tpu, base, left, right,
                                 json_only=args.json_only)

    # Parity gate: the bench number is meaningless if the device path
    # diverges from the oracle. Also warms compiles and the fused
    # path's capacity hint, so the timed runs measure steady state.
    res_t, comp_t, conf_t = run_merge(tpu, base, left, right)
    res_h, comp_h, conf_h = run_merge(host, base, left, right)
    parity = (
        [o.to_dict() for o in res_t.op_log_left] == [o.to_dict() for o in res_h.op_log_left]
        and [o.to_dict() for o in res_t.op_log_right] == [o.to_dict() for o in res_h.op_log_right]
        and [o.to_dict() for o in comp_t] == [o.to_dict() for o in comp_h]
        and [c.to_dict() for c in conf_t] == [c.to_dict() for c in conf_h]
    )

    # Phase split (VERDICT r3 #1a): one instrumented warm merge per
    # path, read back from the shared obs metrics registry. The fused
    # device path reports scan_encode/h2d/kernel/fetch/materialize/
    # compose_decode; the host path build_and_diff/compose.
    tpu_phases = instrumented_phases(tpu, base, left, right)
    host_phases = instrumented_phases(host, base, left, right)

    tpu_s = time_merge(tpu, base, left, right)
    host_s = time_merge(host, base, left, right)

    import jax
    platform = jax.devices()[0].platform
    try:
        rtt_ms = round(probe_roundtrip_ms(), 1)
    except Exception:
        rtt_ms = None

    conflicts_ok = (len(conf_t) > 0) if conflicts_expected else True

    files_per_sec = args.files / tpu_s
    vs_baseline = (args.files / tpu_s) / (args.files / host_s)
    record["metric"] = (
        "files merged/sec/chip (synthetic 3-way TS merge, "
        f"{args.files} files x {args.decls} decls, parity="
        f"{'ok' if parity else 'FAIL'}, platform={platform})")
    record["value"] = round(files_per_sec, 2)
    record["vs_baseline"] = round(vs_baseline, 3)
    record["phases_ms"] = {k: round(v * 1e3, 1) for k, v in tpu_phases.items()}
    record["host_phases_ms"] = {k: round(v * 1e3, 1)
                                for k, v in host_phases.items()}
    record["parity"] = bool(parity)
    record.update(host_tail_summary(tpu_phases))
    if rtt_ms is not None:
        record["device_roundtrip_ms"] = rtt_ms
    if not conflicts_ok:
        record["error"] = (record.get("error", "") +
                           " preset declares conflicts but none were produced").strip()
    if not args.json_only:
        print(f"# tpu path:  {tpu_s*1e3:8.1f} ms  ({args.files/tpu_s:9.1f} files/s)",
              file=sys.stderr)
        print(f"# host path: {host_s*1e3:8.1f} ms  ({args.files/host_s:9.1f} files/s)",
              file=sys.stderr)
        print(f"# composed ops: {len(comp_t)}  conflicts: {len(conf_t)}  parity: {parity}",
              file=sys.stderr)
        print(f"# tpu phases:  " + "  ".join(
            f"{k}={v*1e3:.1f}ms" for k, v in tpu_phases.items()), file=sys.stderr)
        print(f"# host phases: " + "  ".join(
            f"{k}={v*1e3:.1f}ms" for k, v in host_phases.items()), file=sys.stderr)
        if rtt_ms is not None:
            print(f"# device round trip: {rtt_ms} ms", file=sys.stderr)
    emit_record(record)
    return 0 if (parity and conflicts_ok) else 1


def _safe_main() -> int:
    """Never let the driver see a crash without a JSON record."""
    try:
        return main()
    except SystemExit:
        raise
    except BaseException as exc:  # noqa: BLE001 — the record IS the contract
        import traceback
        traceback.print_exc(file=sys.stderr)
        emit_record({
            "metric": "files merged/sec/chip (synthetic 3-way TS merge)",
            "value": 0.0,
            "unit": "files/sec",
            "vs_baseline": 0.0,
            "error": f"{type(exc).__name__}: {exc}",
        })
        return 1


if __name__ == "__main__":
    sys.exit(_safe_main())
