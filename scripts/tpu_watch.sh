#!/bin/bash
# Round-5 TPU window watcher: probe the relay every 5 min; the moment a
# real accelerator initialises, run the bench ladder (rung5/4/3) and save
# BENCH_tpu_r5_<rung>.json + append raw output to BENCHLOG_tpu_r5.txt.
# Exits after a successful ladder capture.
cd /root/repo || exit 1
OUT=/root/repo/BENCHLOG_tpu_r5.txt
while true; do
  # Relay-wedge avoidance (see .claude/skills/verify): killing a jax
  # process mid-init under CPU contention can wedge the relay for
  # hours. Skip the probe while tests/benches are running. Only count
  # processes whose argv[0] is a python binary — the build driver's
  # own cmdline quotes "pytest"/"bench.py" and must not match.
  busy=0
  for p in $(pgrep -f "pytest|bench\.py" 2>/dev/null); do
    first=$(tr '\0' '\n' < "/proc/$p/cmdline" 2>/dev/null | head -1)
    case "$first" in
      *python*) busy=1; break ;;
    esac
  done
  if [ "$busy" = "1" ]; then
    echo "[$(date -u +%H:%M:%S)] busy (pytest/bench running); skipping probe" >> "$OUT"
    sleep 300
    continue
  fi
  echo "[$(date -u +%H:%M:%S)] probing relay..." >> "$OUT"
  if timeout 600 python -c "
import jax
d = jax.devices()
assert d and d[0].platform != 'cpu', d
print('PLATFORM', d[0].platform)
" >> "$OUT" 2>&1; then
    echo "[$(date -u +%H:%M:%S)] accelerator up — running ladder" >> "$OUT"
    ok=1
    # Headline rung FIRST so a brief window still captures the number
    # that matters; then the rest of the ladder + the incremental and
    # cold-start scenarios.
    for rung in rung5 rung4 rung3 rung5i; do
      echo "=== $rung $(date -u +%H:%M:%S) ===" >> "$OUT"
      if timeout 1200 python bench.py --preset "$rung" >> "$OUT" 2>&1; then
        # copy the last JSON line to a per-rung artifact
        grep -h '^{' "$OUT" | tail -1 > "BENCH_tpu_r5_${rung}.json"
        # a cpu-fallback run does not count as a capture
        if grep -q '"platform=cpu"\|platform=cpu' "BENCH_tpu_r5_${rung}.json"; then
          ok=0
        fi
      else
        [ "$rung" = "rung5i" ] || ok=0
      fi
    done
    echo "=== cold rung3 $(date -u +%H:%M:%S) ===" >> "$OUT"
    if timeout 1200 python bench.py --preset rung3 --cold >> "$OUT" 2>&1; then
      grep -h '^{' "$OUT" | tail -1 > "BENCH_tpu_r5_cold.json"
    fi
    if [ "$ok" = "1" ]; then
      echo "[$(date -u +%H:%M:%S)] ladder captured — watcher done" >> "$OUT"
      exit 0
    fi
    echo "[$(date -u +%H:%M:%S)] ladder incomplete; will retry" >> "$OUT"
  fi
  sleep 300
done
