#!/usr/bin/env python3
"""Chaos/soak harness for the supervised merge service (ISSUE 9).

Drives a ``semmerge serve --supervise`` daemon with concurrent mixed
traffic — clean ``--inplace`` merges, fault-injected merges that must
degrade to the byte-exact textual rung, strict-mode requests that
must surface documented typed exits, and resolver-enabled merges of
genuinely conflicting repos that must land on the search resolver's
verified suggestion — while SIGKILLing the daemon at randomized
points mid-soak. The supervisor must bring it back on the
same socket; harness workers ride through the outage with bounded
idempotent retries, exactly like the real client.

Invariants checked (the acceptance bar):

- **No corrupted or duplicated commits**: after the soak (plus one
  clean settling merge per repo), every repo's work tree is byte-exact
  against the known merge result, with no journal/stage/lock debris.
- **Byte-identical responses or documented typed exits**: every
  response is a result with exit 0 (clean / degraded) or the request
  shape's documented typed exit; nothing else.
- **Self-healing observable**: daemon pid changes across kills;
  restarts appear in the supervisor's metrics dump.
- **Bounded memory**: final daemon RSS stays under the hard watermark.

Run standalone::

    python scripts/chaos_soak.py --requests 200 --repos 8 \
        --concurrency 8 --kills 2 --seed 1 --json

Exit 0 when every invariant holds, 1 otherwise. The tier-1 smoke
(``tests/test_chaos.py``) imports :func:`run_soak` directly; the
slow-marked full soak runs a longer schedule with memory pressure.

``--fleet`` switches to the ISSUE 14 kill-drill: a ``semmerge fleet``
router fronting N member daemons takes the same byte-exact traffic
while random members — and, separately, the router itself — are
SIGKILLed mid-stream. The replacement router reclaims the orphaned
members and replays its dispatch WAL; :func:`audit_wal` then walks the
full retained journal history to prove every effect was accounted for
exactly once (no duplicate ``--inplace`` effect from a replay or a
failover re-dispatch).

The cross-host legs (ISSUE 19) ride on the fleet drill:

- ``--tcp-members N`` adds N *standalone* daemons that join the router
  over real TCP (``serve --join``) — the two-host-simulated shape; a
  router SIGKILL also proves remote members re-announce themselves to
  the replacement.
- ``--partitions K`` SIGSTOPs a TCP member K times: the connection
  stays up but reads never complete (true half-open), so only the
  application-level heartbeat can eject it — counted as a
  ``reason="partition"`` failover. Traffic keeps settling byte-exact
  on the survivors; SIGCONT heals the member and it rejoins.
- ``--churn`` performs one elastic join and one drain mid-load: a
  fresh TCP member announces itself into a warm ring (moved keys are
  handed off), and a serving member is drained (``reason="drain"``,
  never a failure eject).
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import pathlib
import random
import signal
import socket as socketlib
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from semantic_merge_tpu.service import protocol  # noqa: E402

#: The merged tree every soak repo must converge on (A renames foo->bar
#: in util.ts, B adds extra.ts and appends to notes.txt — disjoint
#: edits, so semantic and textual rungs agree byte-for-byte).
EXPECTED_TREE = {
    "src/util.ts": "export function bar(n: number): number {\n"
                   "  return n;\n}\n",
    "notes.txt": "hello\nworld\n",
    "extra.ts": "export function extra(s: string): string { return s; }\n",
}

#: Engine artifacts excluded from tree comparison. Postmortem bundles
#: are expected debris of fault-injected traffic: every degradation and
#: fault escape dumps one (see "Flight recorder", runbook).
ARTIFACTS = {".semmerge-conflicts.json", ".semmerge-trace.json",
             ".semmerge-events.jsonl", ".semmerge-journal.json",
             ".semmerge-postmortem"}

#: The tree the *conflict* repos must converge on once the resolver
#: tier picks the evidence-backed rename (A renamed foo->bar and
#: rewrote the call site; B renamed the declaration only, so keepA
#: wins 2:1 on whole-word reference counts).
RESOLVED_TREE = {
    "src/util.ts": "export function bar(n: number): number {\n"
                   "  return n;\n}\n"
                   "export function use(s: string): number {\n"
                   "  return bar(s.length);\n}\n",
}

#: Request shapes: (name, request env overlay, documented exit codes).
#: Fault-injected non-strict merges must land on the textual rung
#: (exit 0); strict ones surface the scan's ParseFault (10) — or, once
#: the chaos traffic has tripped the host-rung circuit breaker, the
#: breaker-open WorkerFault (12). The ``resolve`` shape runs against
#: the conflict-repo pool with the resolution tier enabled and must
#: merge clean (exit 0) on the resolver's verified suggestion — or,
#: while the host-rung breaker is open, degrade to the textual rung
#: where the rename genuinely conflicts (documented exit 1,
#: conflict-as-result). Anything else fails the soak.
RESOLVE_ENV = {"SEMMERGE_RESOLVE": "auto"}
SHAPES = [
    ("clean", {}, {0}),
    ("degrade-scan", {"SEMMERGE_FAULT": "scan:raise"}, {0}),
    ("degrade-apply", {"SEMMERGE_FAULT": "apply:fault"}, {0}),
    ("strict-scan", {"SEMMERGE_FAULT": "scan:fault",
                     "SEMMERGE_STRICT": "1"}, {10, 12}),
    ("resolve", dict(RESOLVE_ENV), {0, 1}),
]


def _git(args, cwd):
    subprocess.run(["git", *args], cwd=cwd, check=True,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def build_repo(root: pathlib.Path) -> pathlib.Path:
    root.mkdir(parents=True)
    _git(["init", "-q", "-b", "main"], root)
    _git(["config", "user.email", "t@example.com"], root)
    _git(["config", "user.name", "t"], root)
    env = dict(os.environ,
               GIT_AUTHOR_DATE="2024-01-01T00:00:00Z",
               GIT_COMMITTER_DATE="2024-01-01T00:00:00Z")

    def commit(msg):
        subprocess.run(["git", "add", "-A"], cwd=root, check=True,
                       stdout=subprocess.DEVNULL)
        subprocess.run(["git", "commit", "-q", "-m", msg], cwd=root,
                       check=True, env=env, stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL)

    (root / "src").mkdir()
    (root / "src/util.ts").write_text(
        "export function foo(n: number): number {\n  return n;\n}\n")
    (root / "notes.txt").write_text("hello\n")
    commit("base")
    _git(["branch", "basebr"], root)
    _git(["checkout", "-qb", "brA"], root)
    (root / "src/util.ts").write_text(EXPECTED_TREE["src/util.ts"])
    commit("rename foo->bar")
    _git(["checkout", "-q", "main"], root)
    _git(["checkout", "-qb", "brB"], root)
    (root / "extra.ts").write_text(EXPECTED_TREE["extra.ts"])
    (root / "notes.txt").write_text(EXPECTED_TREE["notes.txt"])
    commit("add extra + edit notes")
    _git(["checkout", "-q", "main"], root)
    return root


def build_conflict_repo(root: pathlib.Path) -> pathlib.Path:
    """A repo whose merge genuinely conflicts (DivergentRename) but
    carries asymmetric reference evidence, so the search resolver
    settles it deterministically onto :data:`RESOLVED_TREE`."""
    root.mkdir(parents=True)
    _git(["init", "-q", "-b", "main"], root)
    _git(["config", "user.email", "t@example.com"], root)
    _git(["config", "user.name", "t"], root)
    env = dict(os.environ,
               GIT_AUTHOR_DATE="2024-01-01T00:00:00Z",
               GIT_COMMITTER_DATE="2024-01-01T00:00:00Z")

    def commit(msg):
        subprocess.run(["git", "add", "-A"], cwd=root, check=True,
                       stdout=subprocess.DEVNULL)
        subprocess.run(["git", "commit", "-q", "-m", msg], cwd=root,
                       check=True, env=env, stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL)

    (root / "src").mkdir()
    (root / "src/util.ts").write_text(
        "export function foo(n: number): number {\n"
        "  return n;\n}\n"
        "export function use(s: string): number {\n"
        "  return foo(s.length);\n}\n")
    commit("base")
    _git(["branch", "basebr"], root)
    _git(["checkout", "-qb", "brA"], root)
    (root / "src/util.ts").write_text(RESOLVED_TREE["src/util.ts"])
    commit("rename foo->bar, rewrite call site")
    _git(["checkout", "-q", "main"], root)
    _git(["checkout", "-qb", "brB"], root)
    (root / "src/util.ts").write_text(
        "export function baz(n: number): number {\n"
        "  return n;\n}\n"
        "export function use(s: string): number {\n"
        "  return foo(s.length);\n}\n")
    commit("rename foo->baz declaration only")
    _git(["checkout", "-q", "main"], root)
    return root


def tree_errors(root: pathlib.Path,
                expected: Optional[Dict[str, str]] = None) -> List[str]:
    """Byte-exactness + debris check for one settled repo."""
    errors = []
    if expected is None:
        expected = EXPECTED_TREE
    for rel, want in expected.items():
        p = root / rel
        if not p.is_file():
            errors.append(f"{root.name}: missing {rel}")
        elif p.read_text() != want:
            errors.append(f"{root.name}: {rel} bytes differ")
    for debris in (".semmerge-journal.json", ".semmerge-stage",
                   ".semmerge-inplace.lock",
                   ".semmerge-inplace.lock.breaker"):
        if (root / debris).exists():
            errors.append(f"{root.name}: leftover {debris}")
    extra = hashlib.sha256()  # unexpected tracked-tree files
    for p in sorted(root.rglob("*")):
        if not p.is_file():
            continue
        rel = p.relative_to(root).as_posix()
        if rel.startswith(".git/") or rel.split("/")[0] in ARTIFACTS:
            continue
        if rel not in expected:
            errors.append(f"{root.name}: unexpected file {rel}")
        extra.update(rel.encode())
    return errors


# ---------------------------------------------------------------------------
# Wire plumbing (the harness IS a client: idempotent bounded retries)
# ---------------------------------------------------------------------------

class Transport(Exception):
    """Connection-level failure: daemon dead/respawning. Retryable."""


def _request_once(sock_path: str, params: Dict[str, Any],
                  timeout: float = 120.0) -> Dict[str, Any]:
    s = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
    s.settimeout(timeout)
    try:
        s.connect(sock_path)
        rfile = s.makefile("r", encoding="utf-8")
        wfile = s.makefile("w", encoding="utf-8")
        protocol.write_message(wfile, {"id": 1, "method": "semmerge",
                                       "params": params})
        resp = protocol.read_message(rfile)
    except (OSError, protocol.ProtocolError) as exc:
        raise Transport(str(exc)) from exc
    finally:
        try:
            s.close()
        except OSError:
            pass
    if resp is None:
        raise Transport("connection closed before a response (daemon "
                        "killed mid-request)")
    return resp


def request(sock_path: str, repo: pathlib.Path, shape_env: Dict[str, str],
            stats: Dict[str, Any], deadline_s: float = 180.0) -> Dict:
    """One merge request with the real client's resilience posture:
    an idempotency key pinned across attempts, transport failures
    retried until the supervisor brings the daemon back, typed
    ``retry_after_ms`` rejections honored."""
    params = {
        "argv": ["basebr", "brA", "brB", "--inplace", "--backend", "host"],
        "cwd": str(repo),
        "env": shape_env,
        "idempotency_key": f"{os.getpid():x}-{os.urandom(8).hex()}",
    }
    deadline = time.monotonic() + deadline_s
    attempt = 0
    while True:
        try:
            resp = _request_once(sock_path, params)
        except Transport as exc:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"daemon never came back within {deadline_s:g}s: "
                    f"{exc}") from exc
            attempt += 1
            with stats["lock"]:
                stats["transport_retries"] += 1
            time.sleep(min(0.2 * (2 ** min(attempt, 4)), 2.0))
            continue
        err = resp.get("error")
        if err and isinstance(err.get("retry_after_ms"), int) \
                and "exit_code" in err:
            if time.monotonic() > deadline:
                return resp
            with stats["lock"]:
                stats["shed_retries"] += 1
            time.sleep(err["retry_after_ms"] / 1000.0)
            continue
        return resp


# ---------------------------------------------------------------------------
# Supervised daemon lifecycle
# ---------------------------------------------------------------------------

def spawn_supervised(sock_path: str, dump_path: pathlib.Path,
                     extra_env: Optional[Dict[str, str]] = None,
                     workers: int = 8) -> subprocess.Popen:
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": str(REPO_ROOT),
        "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
        "SEMMERGE_DAEMON": "off",
        "SEMMERGE_METRICS": str(dump_path),
        "SEMMERGE_SUPERVISE_BACKOFF": "0.1",
        "SEMMERGE_SERVICE_WORKERS": str(workers),
    })
    env.pop("SEMMERGE_FAULT", None)
    env.pop("SEMMERGE_STRICT", None)
    env.pop("SEMMERGE_RESOLVE", None)
    if extra_env:
        env.update(extra_env)
    log = open(sock_path + ".log", "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "semantic_merge_tpu", "serve",
         "--supervise", "--socket", sock_path],
        stdin=subprocess.DEVNULL, stdout=log, stderr=log,
        cwd="/", env=env, start_new_session=True)
    log.close()
    return proc


def control(sock_path: str, method: str,
            params: Optional[Dict[str, Any]] = None,
            timeout: float = 5.0) -> Optional[dict]:
    """One control-verb round trip (status/drain/leave/...); ``None``
    on any transport failure — callers poll."""
    s = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
    s.settimeout(timeout)
    try:
        s.connect(sock_path)
        rfile = s.makefile("r", encoding="utf-8")
        wfile = s.makefile("w", encoding="utf-8")
        protocol.write_message(wfile, {"id": 1, "method": method,
                                       "params": params or {}})
        resp = protocol.read_message(rfile)
        return (resp or {}).get("result")
    except (OSError, protocol.ProtocolError):
        return None
    finally:
        try:
            s.close()
        except OSError:
            pass


def daemon_status(sock_path: str, timeout: float = 5.0) -> Optional[dict]:
    return control(sock_path, "status", timeout=timeout)


def wait_daemon(sock_path: str, sup: subprocess.Popen,
                timeout: float = 180.0) -> dict:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if sup.poll() is not None:
            raise RuntimeError(f"supervisor exited rc={sup.returncode} "
                               f"(log: {sock_path}.log)")
        status = daemon_status(sock_path)
        if status:
            return status
        time.sleep(0.2)
    raise RuntimeError(f"daemon not up within {timeout:g}s "
                       f"(log: {sock_path}.log)")


# ---------------------------------------------------------------------------
# The soak
# ---------------------------------------------------------------------------

def run_soak(workdir: pathlib.Path, *, requests: int = 200, repos: int = 8,
             concurrency: int = 8, kills: int = 2, seed: int = 1,
             hard_mb: float = 4096.0,
             extra_env: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    """Run the full scenario; returns the report (see module doc)."""
    rng = random.Random(seed)
    workdir = pathlib.Path(workdir)
    workdir.mkdir(parents=True, exist_ok=True)
    repo_paths = [build_repo(workdir / f"repo{i}") for i in range(repos)]
    # A smaller pool of genuinely-conflicting repos serviced only by
    # the resolver-enabled shape (resolver-off traffic against them
    # would exit 1 and break the byte-exact settling invariant).
    conflict_paths = [build_conflict_repo(workdir / f"crepo{i}")
                      for i in range(max(1, repos // 4))]
    sock = str(workdir / "chaos.sock")
    dump = workdir / "supervisor-metrics.json"
    env = {"SEMMERGE_RSS_HARD_MB": str(hard_mb)}
    env.update(extra_env or {})
    sup = spawn_supervised(sock, dump, extra_env=env)

    stats: Dict[str, Any] = {
        "lock": threading.Lock(), "transport_retries": 0,
        "shed_retries": 0, "outcomes": {}, "bad_responses": [],
        "kills": 0, "pids_seen": set(),
    }
    report: Dict[str, Any] = {"requests": requests, "errors": []}
    t0 = time.monotonic()
    try:
        status = wait_daemon(sock, sup)
        stats["pids_seen"].add(status["pid"])

        # The request schedule: shapes spread over repos (the resolve
        # shape over the conflict-repo pool), kill points scattered
        # through the middle of the run.
        schedule = []
        for _ in range(requests):
            shape = SHAPES[rng.randrange(len(SHAPES))]
            pool = conflict_paths if shape[0] == "resolve" else repo_paths
            schedule.append((pool[rng.randrange(len(pool))], shape))
        kill_points = sorted(rng.sample(
            range(requests // 4, max(requests // 4 + kills, 3 * requests // 4)),
            kills)) if kills else []
        done = {"n": 0}
        sem = threading.Semaphore(concurrency)
        threads: List[threading.Thread] = []

        def fire(repo: pathlib.Path, shape) -> None:
            name, shape_env, allowed = shape
            try:
                resp = request(sock, repo, dict(shape_env), stats)
            except RuntimeError as exc:
                with stats["lock"]:
                    stats["bad_responses"].append(f"{name}: {exc}")
                return
            finally:
                sem.release()
            code = None
            if "result" in resp:
                code = resp["result"].get("exit_code")
            elif "error" in resp:
                code = resp["error"].get("exit_code")
            with stats["lock"]:
                stats["outcomes"].setdefault(name, {}).setdefault(
                    str(code), 0)
                stats["outcomes"][name][str(code)] += 1
                if code not in allowed:
                    stats["bad_responses"].append(
                        f"{name}: exit {code!r} not in documented {allowed} "
                        f"({resp.get('error') or ''})")

        for i, (repo, shape) in enumerate(schedule):
            if kill_points and i == kill_points[0]:
                kill_points.pop(0)
                status = daemon_status(sock)
                if status:
                    try:
                        os.kill(status["pid"], signal.SIGKILL)
                        with stats["lock"]:
                            stats["kills"] += 1
                    except OSError:
                        pass
            sem.acquire()
            t = threading.Thread(target=fire, args=(repo, shape))
            t.start()
            threads.append(t)
            done["n"] = i + 1
        for t in threads:
            t.join(timeout=300)

        # Settle: one clean merge per repo resolves any journal left by
        # a SIGKILL mid-commit, then the tree must be byte-exact.
        # Conflict repos settle with the resolution tier enabled and
        # must land on the resolver's verified suggestion.
        final = wait_daemon(sock, sup)
        stats["pids_seen"].add(final["pid"])
        for repo in repo_paths + conflict_paths:
            is_conflict = repo in conflict_paths
            settle_env = dict(RESOLVE_ENV) if is_conflict else {}
            settle_by = time.monotonic() + 60.0
            while True:
                resp = request(sock, repo, dict(settle_env), stats)
                code = (resp.get("result") or resp.get("error") or {}) \
                    .get("exit_code")
                if code == 0:
                    break
                # A conflict repo's settle can land while the fault
                # traffic's host-rung breaker is still open (textual
                # rung, where the rename genuinely conflicts: exit 1).
                # Wait out the breaker cooldown and retry.
                if not (is_conflict and code == 1
                        and time.monotonic() < settle_by):
                    report["errors"].append(
                        f"{repo.name}: settling merge exited {code!r}")
                    break
                time.sleep(1.0)
        for repo in repo_paths:
            report["errors"].extend(tree_errors(repo))
        for repo in conflict_paths:
            report["errors"].extend(tree_errors(repo, RESOLVED_TREE))

        final = daemon_status(sock) or final
        counters = (final.get("metrics") or {}).get("counters", {})

        def _counter_total(name):
            series = counters.get(name, {}).get("series")
            if series is None:
                return None
            return sum(s["value"] for s in series)

        # Breaker/shedding state of the (possibly respawned) daemon —
        # proves the resilience machinery was live during the chaos.
        report["breaker_transitions"] = _counter_total(
            "breaker_transitions_total")
        report["shed_total"] = _counter_total("service_shed_total")
        # Resolver activity in the surviving daemon's lifetime; the
        # resolver-settled merges above guarantee at least one
        # accepted resolution even right after a respawn.
        report["resolutions_total"] = _counter_total("resolutions_total")
        report["breakers"] = (final.get("resilience") or {}).get("breakers")
        report["final_rss_mb"] = final.get("rss_mb")
        if report["final_rss_mb"] is None \
                or report["final_rss_mb"] >= hard_mb:
            report["errors"].append(
                f"final RSS {report['final_rss_mb']} outside the "
                f"{hard_mb:g} MiB hard watermark")
        report["served_total"] = final.get("served_total")
    finally:
        # Orderly shutdown so the supervisor's metrics dump lands.
        if sup.poll() is None:
            sup.send_signal(signal.SIGTERM)
            try:
                sup.wait(timeout=60)
            except subprocess.TimeoutExpired:
                sup.kill()
                sup.wait(timeout=10)

    report["elapsed_s"] = round(time.monotonic() - t0, 3)
    report["outcomes"] = stats["outcomes"]
    report["transport_retries"] = stats["transport_retries"]
    report["shed_retries"] = stats["shed_retries"]
    report["kills"] = stats["kills"]
    report["daemon_pids_seen"] = len(stats["pids_seen"])
    report["errors"].extend(stats["bad_responses"])
    if stats["kills"] and report["daemon_pids_seen"] < 2:
        report["errors"].append(
            "daemon was SIGKILLed but no respawned pid was ever observed")
    try:
        metrics = json.loads(dump.read_text())
        series = metrics.get("counters", {}).get(
            "supervisor_restarts_total", {}).get("series", [])
        report["supervisor_restarts"] = sum(s["value"] for s in series)
    except (OSError, ValueError):
        report["supervisor_restarts"] = None
    if stats["kills"] and not report["supervisor_restarts"]:
        report["errors"].append(
            "supervisor restarts not observable in the metrics dump")
    report["ok"] = not report["errors"]
    return report


# ---------------------------------------------------------------------------
# Fleet soak (ISSUE 14): member + router SIGKILLs, WAL replay audit
# ---------------------------------------------------------------------------

#: Fleet-soak request shapes: only exit-0 traffic, because the fleet
#: invariant under test is exactly-once *effects* — every settled tree
#: byte-exact, no duplicate inplace effect from a WAL replay or a
#: failover re-dispatch.
FLEET_SHAPES = [
    ("clean", {}, {0}),
    ("degrade-scan", {"SEMMERGE_FAULT": "scan:raise"}, {0}),
]


def spawn_fleet_router(sock_path: str, *, members: int = 3,
                       extra_env: Optional[Dict[str, str]] = None
                       ) -> subprocess.Popen:
    """Start a ``semmerge fleet`` router fronting ``members`` supervised
    member daemons."""
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": str(REPO_ROOT),
        "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
        "SEMMERGE_DAEMON": "off",
        "SEMMERGE_FLEET_HEALTH_INTERVAL": "0.2",
        "SEMMERGE_SUPERVISE_BACKOFF": "0.1",
    })
    for key in ("SEMMERGE_FAULT", "SEMMERGE_STRICT", "SEMMERGE_RESOLVE",
                "SEMMERGE_METRICS", "SEMMERGE_SERVICE_SOCKET"):
        env.pop(key, None)
    if extra_env:
        env.update(extra_env)
    log = open(sock_path + ".log", "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "semantic_merge_tpu", "fleet",
         "--socket", sock_path, "--members", str(members)],
        stdin=subprocess.DEVNULL, stdout=log, stderr=log,
        cwd="/", env=env, start_new_session=True)
    log.close()
    return proc


def wait_fleet(sock_path: str, router: subprocess.Popen,
               min_members: int, timeout: float = 240.0) -> dict:
    """Wait until the router answers ``status`` with ``fleet: true``
    and at least ``min_members`` ring members."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if router.poll() is not None:
            raise RuntimeError(f"fleet router exited rc="
                               f"{router.returncode} "
                               f"(log: {sock_path}.log)")
        status = daemon_status(sock_path)
        if status and status.get("fleet") \
                and status.get("members_up", 0) >= min_members:
            return status
        time.sleep(0.2)
    raise RuntimeError(f"fleet not up within {timeout:g}s "
                       f"(log: {sock_path}.log)")


def spawn_tcp_member(router_sock: str, workdir: pathlib.Path,
                     member_id: str,
                     extra_env: Optional[Dict[str, str]] = None
                     ) -> subprocess.Popen:
    """Start a *standalone* member daemon on an ephemeral TCP port that
    announces itself to the router (``serve --join``) — the two-host-
    simulated shape: the router reaches it only over the TCP member
    transport, and it re-announces itself after router restarts or
    healed partitions."""
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": str(REPO_ROOT),
        "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
        "SEMMERGE_DAEMON": "off",
        "SEMMERGE_FLEET_JOIN_INTERVAL": "0.5",
    })
    for key in ("SEMMERGE_FAULT", "SEMMERGE_STRICT", "SEMMERGE_RESOLVE",
                "SEMMERGE_METRICS", "SEMMERGE_SERVICE_SOCKET"):
        env.pop(key, None)
    if extra_env:
        env.update(extra_env)
    log_path = pathlib.Path(workdir) / f"member-{member_id}.log"
    log = open(log_path, "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "semantic_merge_tpu", "serve",
         "--socket", "tcp://127.0.0.1:0", "--join", router_sock,
         "--member-id", member_id],
        stdin=subprocess.DEVNULL, stdout=log, stderr=log,
        cwd="/", env=env, start_new_session=True)
    log.close()
    return proc


def wait_member(sock_path: str, member_id: str, *, in_ring: bool,
                timeout: float = 120.0) -> dict:
    """Wait until the router's view of ``member_id`` reaches (or, for
    ``in_ring=False``, leaves) the ring."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status = daemon_status(sock_path)
        view = next((m for m in (status or {}).get("members", [])
                     if m.get("id") == member_id), None)
        if in_ring and view is not None and view.get("in_ring"):
            return view
        if not in_ring and (view is None or not view.get("in_ring")):
            return view or {}
        time.sleep(0.2)
    raise RuntimeError(
        f"member {member_id} never became "
        f"{'ring member' if in_ring else 'ejected'} within {timeout:g}s")


def audit_wal(wal_dir: str) -> List[str]:
    """Exactly-once accounting over the full retained WAL history.

    Invariants: only documented record kinds; every ``dispatch`` and
    ``ack`` names a journaled request; retries and carried-forward
    replays of one key always journal the *same* request (same verb +
    params — two different requests under one idempotency key would be
    a duplicate-effect hazard); acks never outnumber the journaled
    incarnations of their key.
    """
    from semantic_merge_tpu.fleet import wal as fleet_wal
    errors: List[str] = []
    records = fleet_wal.read_records(wal_dir)
    if not records:
        return [f"wal: no records found under {wal_dir}"]
    requests: Dict[str, List[dict]] = {}
    dispatches: Dict[str, int] = {}
    acks: Dict[str, int] = {}
    for rec in records:
        kind, key = rec.get("kind"), rec.get("key")
        if kind not in fleet_wal.RECORD_KINDS:
            errors.append(f"wal: undocumented record kind {kind!r}")
            continue
        if not isinstance(key, str) or not key:
            errors.append(f"wal: {kind} record without a key")
            continue
        if kind == "request":
            requests.setdefault(key, []).append(rec)
        elif kind == "dispatch":
            dispatches[key] = dispatches.get(key, 0) + 1
        else:
            acks[key] = acks.get(key, 0) + 1
    for key in set(dispatches) | set(acks):
        if key not in requests:
            errors.append(f"wal: key {key} dispatched/acked but never "
                          f"journaled")
    for key, recs in requests.items():
        shapes = {json.dumps({"verb": r.get("verb"),
                              "params": r.get("params")},
                             sort_keys=True) for r in recs}
        if len(shapes) > 1:
            errors.append(f"wal: key {key} journaled with "
                          f"{len(shapes)} different payloads — "
                          f"duplicate-effect hazard")
        if acks.get(key, 0) > len(recs):
            errors.append(f"wal: key {key} acked {acks[key]}x for "
                          f"{len(recs)} journaled incarnation(s)")
    return errors


#: Requests carved out of the budget for each special (churn /
#: partition) phase so ``requests`` stays the total fired.
_PHASE_BURST = 4


def run_fleet_soak(workdir: pathlib.Path, *, requests: int = 40,
                   repos: int = 6, concurrency: int = 6,
                   members: int = 3, member_kills: int = 2,
                   router_kills: int = 1, seed: int = 1,
                   tcp_members: int = 0, partitions: int = 0,
                   churn: bool = False) -> Dict[str, Any]:
    """Fleet kill-drill: randomized member SIGKILLs plus a router
    SIGKILL mid-stream (the replacement router reclaims the orphaned
    members, replays the WAL, and keeps serving). Every request must
    settle byte-exact with documented exits only; the WAL history must
    account for every effect exactly once.

    ``tcp_members`` adds standalone daemons joined over real TCP;
    ``partitions`` SIGSTOPs one of them (half-open link: the heartbeat,
    not the dial, must eject it — a ``reason="partition"`` failover)
    while traffic keeps settling on the survivors, then SIGCONTs it and
    waits for the rejoin; ``churn`` performs one elastic TCP join and
    one drain mid-load."""
    if partitions and tcp_members < 1:
        raise ValueError("--partitions needs at least one --tcp-members "
                         "(the half-open victim is a TCP member)")
    special_phases = partitions + (1 if churn else 0)
    main_requests = requests - _PHASE_BURST * special_phases
    kill_events = (["member"] * member_kills + ["router"] * router_kills)
    if main_requests < len(kill_events) + 2:
        raise ValueError(f"requests={requests} too small for "
                         f"{special_phases} special phase(s) plus "
                         f"{len(kill_events)} kill(s)")
    rng = random.Random(seed)
    workdir = pathlib.Path(workdir)
    workdir.mkdir(parents=True, exist_ok=True)
    repo_paths = [build_repo(workdir / f"repo{i}") for i in range(repos)]
    sock = str(workdir / "fleet.sock")
    wal_dir = sock + ".semmerge-fleet-wal"
    router_env: Dict[str, str] = {}
    if partitions:
        # Partition ejection is heartbeat-paced: tighten the deadline so
        # the half-open victim is detected in ~3 probes, not ~3×2s.
        router_env["SEMMERGE_FLEET_HEARTBEAT_TIMEOUT"] = "0.75"
    router = spawn_fleet_router(sock, members=members,
                                extra_env=router_env)
    tcp_procs: Dict[str, subprocess.Popen] = {}
    stopped: Dict[str, subprocess.Popen] = {}

    stats: Dict[str, Any] = {
        "lock": threading.Lock(), "transport_retries": 0,
        "shed_retries": 0, "outcomes": {}, "bad_responses": [],
        "member_kills": 0, "router_kills": 0,
        "partitions": 0, "joins": 0, "drains": 0,
        "router_pids_seen": set(), "member_pids_seen": set(),
    }
    report: Dict[str, Any] = {"requests": requests, "errors": []}
    t0 = time.monotonic()
    try:
        status = wait_fleet(sock, router, min_members=members)
        stats["router_pids_seen"].add(status["pid"])
        for i in range(tcp_members):
            mid = f"t{i}"
            tcp_procs[mid] = spawn_tcp_member(sock, workdir, mid)
            wait_member(sock, mid, in_ring=True)
        status = daemon_status(sock) or status
        for m in status.get("members", []):
            if m.get("pid"):
                stats["member_pids_seen"].add(m["pid"])

        schedule = []
        for _ in range(main_requests):
            shape = FLEET_SHAPES[rng.randrange(len(FLEET_SHAPES))]
            schedule.append((repo_paths[rng.randrange(repos)], shape))
        lo = main_requests // 4
        hi = max(lo + len(kill_events), 3 * main_requests // 4)
        kill_points = sorted(
            zip(rng.sample(range(lo, hi), len(kill_events)),
                rng.sample(kill_events, len(kill_events))))
        sem = threading.Semaphore(concurrency)
        threads: List[threading.Thread] = []

        def fire(repo: pathlib.Path, shape) -> None:
            name, shape_env, allowed = shape
            try:
                resp = request(sock, repo, dict(shape_env), stats)
            except RuntimeError as exc:
                with stats["lock"]:
                    stats["bad_responses"].append(f"{name}: {exc}")
                return
            finally:
                sem.release()
            code = None
            if "result" in resp:
                code = resp["result"].get("exit_code")
            elif "error" in resp:
                code = resp["error"].get("exit_code")
            with stats["lock"]:
                stats["outcomes"].setdefault(name, {}).setdefault(
                    str(code), 0)
                stats["outcomes"][name][str(code)] += 1
                if code not in allowed:
                    stats["bad_responses"].append(
                        f"{name}: exit {code!r} not in documented "
                        f"{allowed} ({resp.get('error') or ''})")

        def launch(repo: pathlib.Path, shape) -> None:
            sem.acquire()
            t = threading.Thread(target=fire, args=(repo, shape))
            t.start()
            threads.append(t)

        def drain_inflight() -> None:
            for t in threads:
                t.join(timeout=300)
            del threads[:]

        def burst(n: int) -> None:
            for _ in range(n):
                shape = FLEET_SHAPES[rng.randrange(len(FLEET_SHAPES))]
                launch(repo_paths[rng.randrange(repos)], shape)
            drain_inflight()

        for i, (repo, shape) in enumerate(schedule):
            while kill_points and i == kill_points[0][0]:
                _, what = kill_points.pop(0)
                if what == "member":
                    # Only supervised members are SIGKILL fodder — a
                    # killed remote has no supervisor to bring it back.
                    # Poll briefly: the kill point may land right after
                    # a router respawn, before any child is back up.
                    victim_deadline = time.monotonic() + 60.0
                    while time.monotonic() < victim_deadline:
                        status = daemon_status(sock)
                        live = [m for m in
                                (status or {}).get("members", [])
                                if m.get("pid") and m.get("in_ring")
                                and not m.get("remote")]
                        if not live:
                            time.sleep(0.2)
                            continue
                        victim = live[rng.randrange(len(live))]
                        try:
                            os.kill(victim["pid"], signal.SIGKILL)
                        except OSError:
                            time.sleep(0.2)
                            continue
                        with stats["lock"]:
                            stats["member_kills"] += 1
                        break
                else:
                    try:
                        os.kill(router.pid, signal.SIGKILL)
                        router.wait(timeout=10)
                        with stats["lock"]:
                            stats["router_kills"] += 1
                    except OSError:
                        pass
                    router = spawn_fleet_router(sock, members=members,
                                                extra_env=router_env)
            launch(repo, shape)
        drain_inflight()

        if churn:
            # One elastic join + one drain mid-load: the newcomer
            # announces itself into a warm ring (moved keys handed
            # off), serves a burst, then is drained — a deliberate
            # leave, never a failure eject.
            cj = spawn_tcp_member(sock, workdir, "cj0")
            tcp_procs["cj0"] = cj
            wait_member(sock, "cj0", in_ring=True)
            stats["joins"] += 1
            burst(_PHASE_BURST // 2)
            ack = control(sock, "drain", {"member": "cj0"}, timeout=10.0)
            if not (ack or {}).get("ok"):
                report["errors"].append(
                    f"drain of churn member not acked: {ack!r}")
            wait_member(sock, "cj0", in_ring=False)
            stats["drains"] += 1
            burst(_PHASE_BURST - _PHASE_BURST // 2)

        for p in range(partitions):
            # Half-open partition: SIGSTOP keeps the victim's sockets
            # accepting (kernel backlog) while reads never complete, so
            # only the application-level heartbeat can detect it. Drain
            # in-flight work first — the drill measures detection and
            # failover, not a 600s dispatch stall.
            victim_id = f"t{p % tcp_members}"
            victim = tcp_procs[victim_id]
            try:
                os.kill(victim.pid, signal.SIGSTOP)
            except OSError:
                continue
            stopped[victim_id] = victim
            stats["partitions"] += 1
            try:
                wait_member(sock, victim_id, in_ring=False)
                burst(_PHASE_BURST)
            finally:
                try:
                    os.kill(victim.pid, signal.SIGCONT)
                except OSError:
                    pass
                stopped.pop(victim_id, None)
            wait_member(sock, victim_id, in_ring=True)

        expected_up = members + tcp_members
        final = wait_fleet(sock, router, min_members=expected_up)
        stats["router_pids_seen"].add(final["pid"])
        for m in final.get("members", []):
            if m.get("pid"):
                stats["member_pids_seen"].add(m["pid"])
        for repo in repo_paths:
            resp = request(sock, repo, {}, stats)
            code = (resp.get("result") or resp.get("error") or {}) \
                .get("exit_code")
            if code != 0:
                report["errors"].append(
                    f"{repo.name}: settling merge exited {code!r}")
        for repo in repo_paths:
            report["errors"].extend(tree_errors(repo))

        final = daemon_status(sock) or final
        counters = (final.get("metrics") or {}).get("counters", {})

        def _counter_total(name, **labels):
            series = counters.get(name, {}).get("series")
            if series is None:
                return None
            return sum(s["value"] for s in series
                       if all((s.get("labels") or {}).get(k) == v
                              for k, v in labels.items()))

        report["failovers_total"] = _counter_total("fleet_failovers_total")
        report["partition_failovers"] = _counter_total(
            "fleet_failovers_total", reason="partition")
        report["drain_failovers"] = _counter_total(
            "fleet_failovers_total", reason="drain")
        report["joins_total"] = _counter_total("fleet_joins_total")
        report["handoffs_total"] = _counter_total("fleet_handoffs_total")
        report["rehash_moves_total"] = _counter_total(
            "fleet_rehash_moves_total")
        report["wal_replayed_total"] = _counter_total(
            "fleet_wal_replayed_total")
        report["members_up"] = final.get("members_up")
        report["wal_open"] = (final.get("wal") or {}).get("open")
        if report["wal_open"] != 0:
            report["errors"].append(
                f"{report['wal_open']} WAL entries still open after "
                f"settling — journaled effects unaccounted for")
    finally:
        for proc in stopped.values():
            try:
                os.kill(proc.pid, signal.SIGCONT)
            except OSError:
                pass
        for proc in tcp_procs.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in tcp_procs.values():
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
        if router.poll() is None:
            router.send_signal(signal.SIGTERM)
            try:
                router.wait(timeout=60)
            except subprocess.TimeoutExpired:
                router.kill()
                router.wait(timeout=10)

    report["errors"].extend(audit_wal(wal_dir))
    report["elapsed_s"] = round(time.monotonic() - t0, 3)
    report["outcomes"] = stats["outcomes"]
    report["transport_retries"] = stats["transport_retries"]
    report["shed_retries"] = stats["shed_retries"]
    report["member_kills"] = stats["member_kills"]
    report["router_kills"] = stats["router_kills"]
    report["tcp_members"] = tcp_members
    report["partitions"] = stats["partitions"]
    report["churn_joins"] = stats["joins"]
    report["churn_drains"] = stats["drains"]
    report["router_pids_seen"] = len(stats["router_pids_seen"])
    report["member_pids_seen"] = len(stats["member_pids_seen"])
    report["errors"].extend(stats["bad_responses"])
    if stats["member_kills"] and not report.get("failovers_total"):
        report["errors"].append(
            "members were SIGKILLed but no fleet failover was counted")
    if stats["router_kills"] and report["router_pids_seen"] < 2:
        report["errors"].append(
            "router was SIGKILLed but no replacement pid was observed")
    if stats["partitions"] and not report.get("partition_failovers"):
        report["errors"].append(
            "a member was partitioned (SIGSTOP) but no "
            'reason="partition" failover was counted')
    if stats["drains"] and not report.get("drain_failovers"):
        report["errors"].append(
            'a member was drained but no reason="drain" failover was '
            "counted")
    if (tcp_members or stats["joins"]) and not report.get("joins_total"):
        report["errors"].append(
            "TCP members joined but fleet_joins_total stayed zero")
    report["ok"] = not report["errors"]
    return report


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="Chaos/soak the supervised merge service")
    parser.add_argument("--requests", type=int, default=200)
    parser.add_argument("--repos", type=int, default=8)
    parser.add_argument("--concurrency", type=int, default=8)
    parser.add_argument("--kills", type=int, default=2)
    parser.add_argument("--seed", type=int, default=1)
    parser.add_argument("--hard-mb", type=float, default=4096.0)
    parser.add_argument("--fleet", action="store_true",
                        help="Run the fleet kill-drill shape instead "
                             "(router + N members, member/router "
                             "SIGKILLs, WAL replay audit)")
    parser.add_argument("--members", type=int, default=3,
                        help="Fleet members (with --fleet)")
    parser.add_argument("--router-kills", type=int, default=1,
                        help="Router SIGKILLs mid-stream (with --fleet)")
    parser.add_argument("--tcp-members", type=int, default=0,
                        help="Standalone members joined over TCP "
                             "(with --fleet)")
    parser.add_argument("--partitions", type=int, default=0,
                        help="SIGSTOP partitions of a TCP member "
                             "(with --fleet; needs --tcp-members)")
    parser.add_argument("--churn", action="store_true",
                        help="One elastic TCP join + one drain "
                             "mid-load (with --fleet)")
    parser.add_argument("--workdir", default=None,
                        help="Scratch dir (default: a fresh temp dir)")
    parser.add_argument("--json", action="store_true",
                        help="Emit the full report as JSON")
    args = parser.parse_args(argv)
    if args.workdir:
        workdir = pathlib.Path(args.workdir)
    else:
        import tempfile
        workdir = pathlib.Path(tempfile.mkdtemp(prefix="semmerge-chaos-"))
    if args.fleet:
        report = run_fleet_soak(
            workdir, requests=args.requests, repos=args.repos,
            concurrency=args.concurrency, members=args.members,
            member_kills=args.kills, router_kills=args.router_kills,
            seed=args.seed, tcp_members=args.tcp_members,
            partitions=args.partitions, churn=args.churn)
    else:
        report = run_soak(workdir, requests=args.requests,
                          repos=args.repos,
                          concurrency=args.concurrency, kills=args.kills,
                          seed=args.seed, hard_mb=args.hard_mb)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    elif args.fleet:
        print(f"fleet soak: {report['requests']} requests, "
              f"{report['member_kills']} member kills, "
              f"{report['router_kills']} router kills, "
              f"{report['partitions']} partitions, "
              f"{report['churn_joins']} joins, "
              f"{report['churn_drains']} drains, "
              f"{report['transport_retries']} transport retries, "
              f"{report['elapsed_s']}s -> "
              f"{'OK' if report['ok'] else 'FAIL'}")
        for err in report["errors"]:
            print(f"  {err}", file=sys.stderr)
    else:
        print(f"soak: {report['requests']} requests, "
              f"{report['kills']} kills, "
              f"{report['transport_retries']} transport retries, "
              f"rss {report.get('final_rss_mb')} MiB, "
              f"{report['elapsed_s']}s -> "
              f"{'OK' if report['ok'] else 'FAIL'}")
        for err in report["errors"]:
            print(f"  {err}", file=sys.stderr)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
