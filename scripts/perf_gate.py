#!/usr/bin/env python3
"""Standalone perf-regression gate over the checked-in bench snapshots.

CI face of the sentinel in ``semantic_merge_tpu/obs/perf.py``:

    # compare every checked-in BENCH_*.json against PERF_BASELINE.json
    python scripts/perf_gate.py

    # compare specific snapshots, custom tolerances
    python scripts/perf_gate.py BENCH_r05.json --tolerance-pct 5

    # (re)generate the committed baseline from the current snapshots
    python scripts/perf_gate.py --record

Exit codes: 0 all compared entries within tolerance, 1 at least one
regression, 2 usage/IO problems (missing baseline, unreadable
snapshot). New snapshots with no baseline entry are reported but never
fail the gate — record them first.
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys

_REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_REPO_ROOT))

from semantic_merge_tpu.obs import perf as obs_perf  # noqa: E402


def _default_snapshots() -> list[pathlib.Path]:
    return sorted(p for p in _REPO_ROOT.glob("BENCH_*.json")
                  if p.name != obs_perf.BASELINE_NAME)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="perf_gate",
        description="Compare bench snapshots against PERF_BASELINE.json")
    parser.add_argument("snapshots", nargs="*",
                        help="BENCH_*.json files (default: every "
                             "BENCH_*.json at the repo root)")
    parser.add_argument("--baseline",
                        default=str(_REPO_ROOT / obs_perf.BASELINE_NAME))
    parser.add_argument("--tolerance-pct", type=float,
                        default=obs_perf.DEFAULT_TOLERANCE_PCT)
    parser.add_argument("--phase-tolerance-pct", type=float,
                        default=obs_perf.DEFAULT_PHASE_TOLERANCE_PCT)
    parser.add_argument("--record", action="store_true",
                        help="Write/refresh the baseline from the "
                             "snapshots instead of comparing")
    parser.add_argument("--json", action="store_true",
                        help="Emit findings as JSON")
    args = parser.parse_args(argv)

    paths = [pathlib.Path(s) for s in args.snapshots] \
        or _default_snapshots()
    if not paths:
        print("perf_gate: no BENCH_*.json snapshots found",
              file=sys.stderr)
        return 2
    entries = {}
    for path in paths:
        try:
            record = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, ValueError) as exc:
            print(f"perf_gate: cannot read {path}: {exc}",
                  file=sys.stderr)
            return 2
        entries[obs_perf.record_key(path)] = obs_perf.normalize_record(
            record, source=path.name)

    baseline_path = pathlib.Path(args.baseline)
    if args.record:
        existing = {}
        if baseline_path.is_file():
            existing = obs_perf.load_baseline(baseline_path)["entries"]
        existing.update(entries)
        obs_perf.save_baseline(baseline_path, existing)
        print(f"perf_gate: recorded {len(entries)} entries into "
              f"{baseline_path}")
        return 0

    if not baseline_path.is_file():
        print(f"perf_gate: no baseline at {baseline_path} "
              f"(generate one with --record)", file=sys.stderr)
        return 2
    try:
        baseline = obs_perf.load_baseline(baseline_path)
    except (OSError, ValueError) as exc:
        print(f"perf_gate: unreadable baseline: {exc}", file=sys.stderr)
        return 2
    ok, findings = obs_perf.compare_many(
        entries, baseline, tolerance_pct=args.tolerance_pct,
        phase_tolerance_pct=args.phase_tolerance_pct)
    if args.json:
        print(json.dumps({"ok": ok, "findings": findings}, indent=2))
    else:
        print(f"perf_gate: {'OK' if ok else 'REGRESSION'} "
              f"({len(entries)} snapshots vs {baseline_path.name})")
        print(obs_perf.format_findings(findings))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
